"""TPU device executor: one logical plan -> ONE static-shape XLA program.

This is the engine half the reference delegates to Spark + spark-rapids
(`nds/power_run_gpu.template:35` enables the plugin; all GPU execution is
external to the reference repo). Here the execution layer is ours and is
designed TPU-first (SURVEY.md §7):

- **Masked fixed-capacity dataflow.** XLA wants static shapes, SQL produces
  data-dependent cardinalities. Resolution: every relation is a set of
  fixed-capacity device arrays plus a boolean presence mask. Filters AND
  the mask instead of compacting; every operator's output capacity is a
  *compile-time* function of its inputs' capacities, so the entire query
  traces into a single jit-compiled XLA program — no host round-trips, no
  recompiles within a scale factor.
- **Joins are gather joins.** Every equi-join in the TPC workloads has a
  side that is unique on the join keys (star schema). The unique side is
  sorted once (`lax.sort`), probes are `searchsorted` + gather — O(n log n)
  vectorized, no dynamic hash tables. Multi-column keys are bit-packed into
  one int64 using value bounds computed on the host at trace time.
- **Grouping is sort-based.** Rows sort by (presence, keys...) via a
  stable multi-operand `lax.sort`; group boundaries come from adjacent-row
  comparison; aggregates are `segment_sum/min/max` with
  `indices_are_sorted=True`. Output capacity = input capacity; the unused
  tail is masked.
- **Strings never reach the device.** Columns are dictionary-encoded
  (sorted dictionary => code order == lexicographic order,
  `nds_tpu/io/host_table.py`); LIKE / IN / comparisons against literals are
  evaluated once on the host dictionary producing boolean lookup tables the
  device gathers through. Cross-column string ops go through a union
  dictionary remap.
- **Decimals are scaled int64** end to end (+,-,*,compare exact; division
  and AVG via float64), mirroring the reference's use_decimal=True path
  (`nds/nds_schema.py:43-47`) with the `--floats` epsilon mode as the
  alternative.

The differential oracle for all of this is `cpu_exec.CpuExecutor`
(reference analog: CPU Spark as ground truth, `nds/nds_validate.py:48-114`).
"""

from __future__ import annotations

import os

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
# the deployment sitecustomize may pin jax to a remote TPU plugin
# regardless of JAX_PLATFORMS; NDS_TPU_PLATFORM wins when set (used by
# CLI drivers and CI to run the engine on the local cpu backend)
if os.environ.get("NDS_TPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["NDS_TPU_PLATFORM"])

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from nds_tpu.analysis import jitsan  # noqa: E402
from nds_tpu.engine import kernels as KX  # noqa: E402
from nds_tpu.engine.cpu_exec import ResultTable, like_mask  # noqa: E402
from nds_tpu.engine.types import (  # noqa: E402
    BoolType, DateType, DecimalType, DType, FloatType, IntType, StringType,
)
from nds_tpu.io.host_table import HostTable  # noqa: E402
from nds_tpu.obs import costs as obs_costs  # noqa: E402
from nds_tpu.obs import memwatch  # noqa: E402
from nds_tpu.obs import metrics as obs_metrics  # noqa: E402
from nds_tpu.obs.trace import get_tracer  # noqa: E402
from nds_tpu.sql import ir  # noqa: E402
from nds_tpu.sql import plan as P  # noqa: E402

I64_MAX = np.iinfo(np.int64).max
I64_MIN = np.iinfo(np.int64).min




class DeviceExecError(RuntimeError):
    pass


class DVal:
    """One evaluated column on device: array + optional validity, plus
    host-side metadata (string dictionary; integer value bounds used for
    join-key bit packing)."""

    __slots__ = ("arr", "valid", "sdict", "lo", "hi")

    def __init__(self, arr, valid=None, sdict=None, lo=None, hi=None):
        self.arr = arr
        self.valid = valid
        self.sdict = sdict
        self.lo = lo
        self.hi = hi

    def with_arrays(self, arr, valid):
        return DVal(arr, valid, self.sdict, self.lo, self.hi)


def _pred_sig(e) -> str:
    """Canonical predicate signature with column bindings normalized out
    (scan filters are single-table by construction, so the alias carries
    no meaning — q-pairs filtering the same table identically under
    different aliases must share one reduced view)."""
    import dataclasses
    if isinstance(e, ir.ColRef):
        return f"col:{e.name}"
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        parts = [type(e).__name__]
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (list, tuple)):
                parts.append(
                    "[" + ",".join(_pred_sig(x) for x in v) + "]")
            else:
                parts.append(_pred_sig(v))
        return "(" + " ".join(parts) + ")"
    return repr(e)


def _ir_children(e):
    """Every IR node reachable one step below e, descending through
    arbitrarily nested lists/tuples (CaseIR.whens is a list of (IR, IR)
    TUPLES — a flat isinstance walk silently skips everything inside a
    CASE arm)."""
    import dataclasses
    for f in dataclasses.fields(e):
        stack = [getattr(e, f.name)]
        while stack:
            v = stack.pop()
            if isinstance(v, (list, tuple)):
                stack.extend(v)
            elif isinstance(v, ir.IR):
                yield v


def _touches_float(e) -> bool:
    """True if evaluating e involves float compute anywhere (FloatType
    values or division, which routes decimals through floats)."""
    if isinstance(e, ir.IR):
        if isinstance(getattr(e, "dtype", None), FloatType):
            return True
        if isinstance(e, ir.Arith) and e.op == "/":
            return True
        return any(_touches_float(c) for c in _ir_children(e))
    return False


_EXACT_FLOAT_NODES = (
    ir.ColRef, ir.Lit, ir.Arith, ir.Cmp, ir.BoolOp, ir.Not, ir.Neg,
    ir.CaseIR, ir.LikeIR, ir.InListIR, ir.IsNullIR, ir.ExtractIR,
    ir.SubstrIR, ir.StrMapIR, ir.ConcatIR, ir.CastIR)


def _float_exact_safe(e) -> bool:
    """Host f64 reduction of a float-touching predicate is only sound
    when every node in it evaluates bit-identically between numpy and
    the device f64 path. IEEE +,-,*,/ comparisons and the
    string/date/case nodes above are exact on both; anything NOT in
    the whitelist (a future transcendental, say) must refuse host
    reduction rather than silently drop rows the device re-filter can
    never resurrect (advisor finding, round 4)."""
    if isinstance(e, ir.IR):
        if not isinstance(e, _EXACT_FLOAT_NODES):
            return False
        return all(_float_exact_safe(c) for c in _ir_children(e))
    return True


# peak memory bandwidth per backend kind, GB/s — the roofline ceiling
# for this engine's scan-dominated programs (published specs: TPU v4
# 1228 GB/s HBM2e, v5e 819, v5p 2765; the CPU figure is a typical
# single-socket DDR envelope and is overridable for a measured value)
_PEAK_MEM_GBPS = {"tpu v4": 1228.0, "tpu v5 lite": 819.0,
                  "tpu v5e": 819.0, "tpu v5": 2765.0, "tpu v6 lite": 1640.0,
                  "cpu": 25.0}


def _peak_mem_gbps() -> float | None:
    """Roofline peak for the ACTIVE backend: env override first
    (NDS_TPU_PEAK_GBPS, for measured numbers), then measured numbers
    from ``ndsperf --calibrate`` (configs/platform_peaks.json, via
    obs/costs), then the builtin device-kind lookup.
    Never initializes a backend (tunnel-down safety: utils/report.py)."""
    env = os.environ.get("NDS_TPU_PEAK_GBPS")
    if env:
        try:
            return float(env)
        except ValueError:  # telemetry stays best-effort on a typo
            return None
    try:
        from jax._src import xla_bridge as _xb
        if not getattr(_xb, "_backends", None):
            return None
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        return None
    measured = obs_costs.calibrated_mem_gbps(kind)
    if measured is not None:
        return measured
    for prefix, gbps in sorted(_PEAK_MEM_GBPS.items(),
                               key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return gbps
    return _PEAK_MEM_GBPS.get("cpu") if kind == "cpu" else None


class _ReducedScan:
    """A survivor-reduced view of one table for one scan-filter signature:
    host row indices of the survivors plus a power-of-two padded capacity
    (pow2 padding lets signatures with similar survivor counts share
    program shapes across slack retries and maintenance deltas)."""

    __slots__ = ("prefix", "table", "nrows", "capacity", "idx")

    def __init__(self, prefix: str, table: str, nrows: int, idx):
        self.prefix = prefix
        self.table = table
        self.nrows = nrows
        self.idx = idx
        c = 1
        while c < max(nrows, 1):
            c <<= 1
        self.capacity = c


class DCtx:
    """One relation during trace: capacity (static), presence mask (traced),
    and columns keyed by (binding, name)."""

    def __init__(self, n: int, row):
        self.n = n
        self.row = row
        self.cols: dict[tuple, DVal] = {}

    def gather(self, idx, clear_valid=None) -> "DCtx":
        """New ctx with every column gathered at idx (same capacity as idx).
        clear_valid, if given, is ANDed into every column's validity
        (used to null out the build side of outer joins)."""
        out = DCtx(idx.shape[0], None)
        for k, dv in self.cols.items():
            arr = jnp.take(dv.arr, idx, axis=0)
            valid = None if dv.valid is None else jnp.take(dv.valid, idx)
            if clear_valid is not None:
                valid = clear_valid if valid is None else (valid & clear_valid)
            out.cols[k] = dv.with_arrays(arr, valid)
        return out

    def merge(self, other: "DCtx") -> "DCtx":
        assert self.n == other.n
        out = DCtx(self.n, self.row)
        out.cols.update(self.cols)
        out.cols.update(other.cols)
        return out


def _ok(dv: DVal, row):
    """Row-presence AND value-validity for a column."""
    return row if dv.valid is None else (row & dv.valid)


def _scale_of(t: DType) -> int:
    return t.scale if isinstance(t, DecimalType) else 0


def _to_float(arr, t: DType, fdt=None):
    """Float compute dtype: f64 (default) is emulated on TPU but matches
    the CPU oracle exactly; `engine.precision` selects f32/bf16 in
    floats mode for native VPU arithmetic (the reference's
    variableFloatAgg tradeoff). fdt comes from the trace."""
    fdt = fdt or jnp.float64
    if isinstance(t, DecimalType):
        return arr.astype(fdt) / (10.0 ** t.scale)
    return arr.astype(fdt)


def _rescale(arr, from_s: int, to_s: int):
    if from_s == to_s:
        return arr
    if to_s > from_s:
        return arr.astype(jnp.int64) * (10 ** (to_s - from_s))
    return arr.astype(jnp.int64) // (10 ** (from_s - to_s))


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _ss(ks, q, side="left"):
    """searchsorted via the sort method. XLA lowers the default binary
    search as ~log2(n) serial gather passes over the full array — ~200ms
    (i32) to ~800ms (i64) per call at 1.8M rows on TPU, measured. One
    native sort of the concatenation is ~10ms, so every probe-scale
    searchsorted in the engine goes through here."""
    # ndslint: waive[NDS112] -- central chokepoint: operand width is the caller's (all hot callers narrow via _narrow_key/bounds), and method="sort" already sidesteps the emulated-bisection pathology
    return jnp.searchsorted(ks, q, side=side, method="sort")


# segmented inclusive scan: shared with every scan-based kernel
# (engine/kernels.py owns the implementation)
_seg_scan = KX.seg_scan


def _epoch_days_to_civil(days):
    """Hinnant's algorithm: epoch days -> (year, month, day), integer ops
    only so it vectorizes onto the VPU."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _narrow_key(dv: DVal):
    """int64 sort keys whose host bounds fit int32 narrow to int32 —
    TPU sorts i32 natively but emulates s64. Negation headroom (for
    descending keys) is why the bound check excludes INT32_MIN."""
    arr = dv.arr
    if (arr.dtype == jnp.int64 and dv.lo is not None
            and dv.hi is not None and -2**31 < dv.lo
            and dv.hi < 2**31 - 1):
        return arr.astype(jnp.int32)
    return arr


def _plan_bindings(node: P.Node) -> set:
    """All binding names produced anywhere inside a plan subtree."""
    out = set()
    for n in P.walk_plan(node):
        b = getattr(n, "binding", "")
        if b:
            out.add(b)
    return out


def _expr_bindings(e: ir.IR) -> set:
    return {x.binding for x in ir.walk(e) if isinstance(x, ir.ColRef)}


class DeviceExecutor:
    """Executes logical plans on jax devices. One instance should live for
    a whole session: it owns the device buffer pool (columns uploaded once,
    the transcode/load analog) and the per-query compile cache."""

    def __init__(self, tables: dict[str, HostTable],
                 float_dtype=None):
        self.tables = tables
        self.float_dtype = float_dtype  # None -> float64 (exact oracle)
        self._buffers: dict[str, jnp.ndarray] = {}
        self._bounds: dict[tuple, tuple] = {}
        self._compiled: dict[object, tuple] = {}
        # columnar encoding state (nds_tpu/columnar/): buffer key ->
        # EncSpec for every ENCODED upload (the trace's decode reads
        # it), and the raw host bytes that buffer set replaces (the
        # per-query compression_ratio numerator). Lives and dies with
        # the corresponding _buffers entries.
        self._enc_specs: dict[str, object] = {}
        self._raw_nbytes: dict[str, float] = {}
        # tables whose buffers are swapped in-place per chunk by the
        # partial-agg loop upload RAW (the swap rebuilds plain value
        # buffers; an encoded chunk-0 program would misread them)
        self._no_encode: set = set()
        # survivor-reduced scan views keyed by (table, filter signature);
        # values are _ReducedScan or the "full" no-reduction marker.
        # (NOT named _reduced: ChunkedExecutor already uses that name
        # for its phase-B executor cache)
        self._scan_views: dict[tuple, object] = {}
        # memoized string-dictionary unions keyed by the (left, right)
        # dictionary identities: every execution of every join over the
        # same two string columns otherwise recomputes np.union1d + two
        # searchsorteds on the host. Entries pin both dictionaries
        # (id-recycling cannot serve a stale union)
        self._union_cache: dict[tuple, tuple] = {}
        # perf accounting for the last execute(): compile/execute/
        # materialize wall-clock ms (the breakdown the reference leaves to
        # the Spark UI; here it feeds the JSON summaries directly).
        # last_query_span is the span-tree form of the same bill
        # (obs.query_timings reads it; last_timings stays as the legacy
        # scrape surface)
        self.last_timings: dict[str, float] = {}
        self.last_query_span = None
        # host-staged plan splitting (engine/staging.py): key -> the
        # once-computed (orig_planned, [(sub_planned, temp_name), ...],
        # main_planned). orig_planned pins the caller's plan object:
        # the key is its id(), and a recycled address must never serve
        # another query's staged split (advisor finding, round 5)
        self._stage_plans: dict[object, tuple] = {}
        self._stage_fps: dict[str, str] = {}  # temp -> content md5
        # pending sub-program bills keyed by query key (async
        # interleaving: another query's _finish must not consume
        # or clear this query's pending bill)
        self._stage_timings: dict[object, dict] = {}

    # ------------------------------------------------------------------ API

    DEFAULT_SLACK = 2.0

    # plans whose deduplicated node count exceeds this split into
    # multiple programs with host-staged intermediates (None = off).
    # The single-chip default keeps the widest templates (q64) from
    # multi-hour cold compiles; DistributedExecutor tightens it —
    # 8-device shard_map compile memory is the binding constraint
    # (VERDICT r4: q64/q72 exceeded 130 GB host RAM).
    STAGE_WEIGHT: int | None = int(os.environ.get("NDS_TPU_STAGE", "56"))

    def _register_staged(self, temp: str, table) -> None:
        """(Re-)register a staged temp table, invalidating this
        executor's per-table caches when the content changed (base-table
        DML between runs changes the sub-result; stale device buffers
        would silently serve the old rows). Content is fingerprinted so
        the steady-state bench path keeps its warmed buffers."""
        import hashlib
        h = hashlib.md5()
        for name in sorted(table.columns):
            col = table.columns[name]
            arr = np.ascontiguousarray(col.values)
            h.update(name.encode())
            h.update(str(arr.shape).encode())
            # FULL content, not a prefix: a same-shape sub-result whose
            # change lies past any prefix must invalidate the staged
            # buffers (ADVICE r5); hashing is linear and cheap next to
            # the sub-program that produced the rows. The contiguous
            # array feeds hashlib via the buffer protocol — no bytes
            # copy of a possibly-GB column
            h.update(arr)
        fp = h.hexdigest()
        if self._stage_fps.get(temp) == fp:
            return
        self._stage_fps[temp] = fp
        # ndslint: waive[NDS119] -- executor-internal staged temp table scoped to one plan step and torn down by _unregister_staged; never visible to the session catalog or the DML journal
        self.tables[temp] = table
        self._drop_col_buffers(temp + ".")
        for k in [k for k in self._bounds if k[0] == temp]:
            del self._bounds[k]
        for k in [k for k in self._scan_views if k[0] == temp]:
            del self._scan_views[k]

    def _drop_col_buffers(self, prefix: str) -> None:
        """Free every device buffer under a key prefix together with
        its encoding bookkeeping (a stale EncSpec surviving its buffer
        would mis-decode whatever re-uploads under the same key)."""
        for d in (self._buffers, self._enc_specs, self._raw_nbytes):
            for k in [k for k in d if k.startswith(prefix)]:
                del d[k]

    def invalidate_tables(self, names) -> None:
        """Scoped DML invalidation: drop ONLY the mutated tables'
        device buffers, host-cached bounds/sorted verdicts, and reduced
        scan views. Everything else — other tables' buffers, the whole
        compiled-program cache — survives: programs key on content
        fingerprints (segment-granular digests for delta tables), so a
        stale entry can never be SERVED for mutated content, and
        unaffected queries re-dispatch their warm programs at 0
        compiles."""
        for t in set(names):
            self._drop_col_buffers(f"{t}.")
            for k in [k for k in self._bounds if k[0] == t]:
                del self._bounds[k]
            for ck in [ck for ck in self._scan_views if ck[0] == t]:
                old = self._scan_views.pop(ck)
                if isinstance(old, _ReducedScan):
                    self._drop_col_buffers(old.prefix + ".")

    def _staged_effective(self, planned: P.PlannedQuery, key):
        """Resolve plan splitting for `planned`: execute + register any
        stage tables (every call — the timed run must pay for its
        sub-programs too, and DML may have changed their inputs), then
        return the plan the main program compiles from. Accumulates the
        sub-programs' timing bill under this key so last_timings can
        report the WHOLE query, not just the final program. No-op below
        STAGE_WEIGHT."""
        if not self.STAGE_WEIGHT:
            return planned
        from nds_tpu.engine import staging
        plans = self._stage_plans.get(key)
        if (plans is not None and plans[0] is not planned
                and not plans[1] and isinstance(key, tuple)
                and len(key) == 2 and key[0] == "param"):
            # literal-variant re-dispatch of a shared parameterized
            # program: the digest IS the key (identical canonical
            # plan), the split produced no temps — rebind the split to
            # THIS variant's plan object, keeping the compiled entry
            # (eviction below is for id()-recycling, which a digest key
            # cannot suffer)
            plans = (planned, [], planned)
            self._stage_plans[key] = plans
        if plans is not None and plans[2] is planned:
            # overflow-retry re-dispatch of the staged MAIN plan
            # (_finish retries with `planned`, which for a staged query
            # IS the cached main): the temps are registered and the
            # sub-program bill is already parked in _stage_timings —
            # re-running the subs would only waste the retry
            return planned
        if plans is not None and plans[0] is not planned:
            # stale entry: id() recycling (the pinning ref was evicted)
            # or the caller rebound this key to a new plan — either way
            # the cached split belongs to ANOTHER plan object. Its
            # compiled programs (main AND recursive sub-program keys)
            # are just as stale as the split itself
            self._evict_query_state(key)
            plans = None
        if plans is None:
            subs, main = [], planned
            base_digest = None
            while staging.plan_weight(main) > self.STAGE_WEIGHT:
                cut = staging.choose_cut(main)
                if cut is None:
                    break
                if base_digest is None:
                    # DETERMINISTIC temp names (plan-digest + index, not
                    # a process counter): the staged main plan's scan
                    # buffer keys embed them, and the persistent AOT
                    # plan cache (nds_tpu/cache/) can only hit across
                    # processes when identical plans stage identical
                    # names. Distinct plans get distinct digests, so
                    # names stay collision-free; re-splits after
                    # eviction re-mint the SAME names and
                    # _register_staged's content fingerprint keeps the
                    # buffers honest
                    from nds_tpu.cache.fingerprint import plan_digest
                    base_digest = plan_digest(planned)
                temp = staging.stage_temp_name(base_digest, len(subs))
                sub, main = staging.build_stage(main, cut, temp)
                subs.append((sub, temp))
            plans = (planned, subs, main)
            self._stage_plans[key] = plans
        _orig, subs, main = plans
        agg = {}
        tracer = get_tracer()
        for i, (sub, temp) in enumerate(subs):
            with tracer.span("stage.sub", temp=temp, index=i):
                # recursive: an oversized sub-program splits again here
                rt = self.execute(sub, key=(key, "__stage__", i))
            for k, v in self.last_timings.items():
                if k in ("compile_ms", "execute_ms", "materialize_ms",
                         "bytes_scanned", "bytes_scanned_raw",
                         "ops_est"):
                    agg[k] = agg.get(k, 0.0) + v
                elif k == "__kernels":
                    kacc = agg.setdefault("__kernels", {})
                    for kn, cnt in v.items():
                        kacc[kn] = kacc.get(kn, 0) + cnt
            self._register_staged(temp, staging.result_to_host_table(
                temp, rt))
        if subs:
            agg["staged_programs"] = len(subs)
            self._stage_timings[key] = agg
            obs_metrics.counter("staged_subprograms_total").inc(len(subs))
        return main

    @staticmethod
    def _stage_key_derives_from(k: object, base: object) -> bool:
        """True when k is a recursive staged-sub-program key rooted at
        base ((base, "__stage__", i) and deeper)."""
        while isinstance(k, tuple) and len(k) == 3 and k[1] == "__stage__":
            k = k[0]
            if k == base:
                return True
        return False

    def _unregister_staged(self, temp: str) -> None:
        """Free everything _register_staged created for a temp table:
        the host table, its fingerprint, and its per-table caches
        (device buffers, bounds, scan views)."""
        # ndslint: waive[NDS119] -- tear-down of the executor-internal staged temp registered above; the session catalog never saw it
        self.tables.pop(temp, None)
        self._stage_fps.pop(temp, None)
        self._drop_col_buffers(temp + ".")
        for k in [k for k in self._bounds if k[0] == temp]:
            del self._bounds[k]
        for k in [k for k in self._scan_views if k[0] == temp]:
            del self._scan_views[k]

    def _evict_query_state(self, key: object) -> None:
        """Drop the staging state tied to a compile-cache key being
        evicted — including the recursive sub-program entries keyed off
        it — so _stage_plans/_stage_timings/_compiled never hold a
        stale split for a plan whose pinning ref is gone (and never
        grow unboundedly across a long run). The evicted split's temp
        tables and their host/device caches free here too: a DIFFERENT
        plan rebound to this key would stage different digest-named
        temps, and eviction+rerun cycles must not leak the old
        intermediates."""
        for d in (self._stage_plans, self._stage_timings, self._compiled):
            for k in [key] + [k for k in d
                              if self._stage_key_derives_from(k, key)]:
                entry = d.pop(k, None)
                if d is self._stage_plans and entry is not None:
                    for _sub, temp in entry[1]:
                        self._unregister_staged(temp)

    def _merge_stage_timings(self, timings: dict,
                             key: object = None) -> None:
        """Fold the accumulated sub-program bill into the main
        program's timings and recompute the bandwidth-derived metrics
        over the WHOLE query (staging targets exactly the queries where
        dropping the sub bill would misreport the roofline)."""
        agg = self._stage_timings.pop(key, None)
        if not agg:
            return
        for k, v in agg.items():
            if k == "__kernels":
                kacc = timings.setdefault("__kernels", {})
                for kn, cnt in v.items():
                    kacc[kn] = kacc.get(kn, 0) + cnt
            else:
                timings[k] = timings.get(k, 0.0) + v
        bs = timings.get("bytes_scanned", 0.0)
        if bs and timings.get("execute_ms", 0) > 0:
            timings["scan_gbps"] = bs / (timings["execute_ms"] / 1000) / 1e9
            peak = _peak_mem_gbps()
            if peak:
                timings["roofline_frac"] = round(
                    timings["scan_gbps"] / peak, 4)
                timings["roofline_peak_gbps"] = peak
        if bs and timings.get("ops_est"):
            timings["ops_per_byte"] = round(
                timings["ops_est"] / bs, 4)
        if bs and timings.get("bytes_scanned_raw"):
            # whole-query ratio: the folded sub-programs' raw bytes
            # count too (staging targets exactly the big queries)
            timings["compression_ratio"] = round(
                timings["bytes_scanned_raw"] / bs, 4)

    def execute(self, planned: P.PlannedQuery, key: object = None):
        return self.execute_async(planned, key).result()

    def execute_async(self, planned: P.PlannedQuery,
                      key: object = None) -> "_AsyncResult":
        """Dispatch a query without blocking on its completion. jax's
        async dispatch returns device futures immediately, so a caller
        can keep N queries in flight (`engine.concurrent_tasks`, the
        analog of spark.rapids.sql.concurrentGpuTasks,
        `nds/power_run_gpu.template:38`) and overlap device execution
        with host-side materialization of earlier results."""
        from nds_tpu.resilience import faults, watchdog
        faults.fault_point("device.execute",
                           executor=type(self).__name__)
        # engine-side heartbeat: a query inside compile/execute still
        # shows liveness to the hang watchdog at every dispatch
        watchdog.beat("engine", phase="device.execute",
                      executor=type(self).__name__)
        planned = self._plan_for_dispatch(planned)
        key = key if key is not None else self._plan_key(planned)
        orig = planned
        tracer = get_tracer()
        # a failed query must never inherit the previous query's span
        # (query_timings would serve stale numbers into its summary)
        self.last_query_span = None
        # explicitly-owned query span: the async half (_finish) may run
        # after other queries dispatched, so stack discipline can't own it
        qspan = tracer.begin("device.execute",
                             executor=type(self).__name__)
        try:
            return self._dispatch_traced(planned, orig, key, tracer,
                                         qspan)
        except BaseException as exc:
            # nested staged sub-programs set last_query_span on THEIR
            # success; a failing main program must not leave a sub's
            # span masquerading as the whole query's
            self.last_query_span = None
            # release the accounted scan bytes a failed dispatch added
            # (pop: a pre-upload failure — or a stale dict from the
            # previous, already-released query — releases 0)
            memwatch.sub_live(
                (self.last_timings or {}).pop("__live_bytes", 0.0))
            if qspan and qspan.t1 is None:
                qspan.set(error=f"{type(exc).__name__}: {exc}").end()
            raise

    def _plan_for_dispatch(self, planned):
        """Pre-dispatch plan normalization hook. Base rule:
        parameterized plans heavy enough to SPLIT (engine/staging.py)
        fall back to their inlined-literal form — staged temps
        re-encode dictionaries and carry value-dependent content, so a
        shared parameterized program cannot span a staging cut (and
        the temp tables' content digests would defeat the shared
        fingerprint anyway). The sharded executor overrides to always
        inline."""
        from nds_tpu.sql import params as sqlparams
        if not sqlparams.has_params(planned) or not self.STAGE_WEIGHT:
            return planned
        from nds_tpu.engine import staging
        if staging.plan_weight(planned) > self.STAGE_WEIGHT:
            return sqlparams.inline(planned)
        return planned

    # entry bound for the per-query compile cache: power runs hold at
    # most 125 statements, but a serving workload cycles an unbounded
    # population of plan objects through id-keyed entries — without a
    # bound the pinned plans + compiled programs grow for the process
    # lifetime (compactor entries and in-flight staged sub-keys are
    # exempt from eviction)
    MAX_COMPILED = 256

    def _plan_key(self, planned):
        """Compile-cache key: plan identity for ordinary plans; for
        PARAMETERIZED plans the shared canonical-digest key
        (sql/params.plan_key — the same key the server batches on), so
        every literal variant of one template lands on ONE in-process
        compiled entry."""
        from nds_tpu.sql import params as sqlparams
        return sqlparams.plan_key(planned) or id(planned)

    def _bound_compiled(self, active_key) -> None:
        """FIFO-evict query-level entries past MAX_COMPILED (and their
        staged state, via _evict_query_state). Never evicts the entry
        being dispatched, compactor programs, or staged sub-entries
        (those die with their base key)."""
        def evictable(k) -> bool:
            if k == active_key:
                return False
            if isinstance(k, tuple) and k and k[0] == "__compact__":
                return False
            if isinstance(k, tuple) and len(k) == 3 \
                    and k[1] == "__stage__":
                return False
            return True

        while len(self._compiled) > self.MAX_COMPILED:
            victim = next((k for k in self._compiled if evictable(k)),
                          None)
            if victim is None:
                return
            self._evict_query_state(victim)

    def _dispatch_traced(self, planned, orig, key, tracer, qspan):
        import time as _time
        with tracer.attach(qspan):
            planned = self._staged_effective(planned, key)
            from nds_tpu.analysis import plan_verify
            if plan_verify.verify_enabled():
                # post-staging verification: _staged_effective has run
                # and registered every sub-program temp, so the staged
                # main plan's StagedScan nodes must now resolve against
                # this executor's table registry
                plan_verify.assert_valid(planned, tables=self.tables,
                                         label="staged plan")
            timings = {"compile_ms": 0.0}
            self.last_timings = timings
            # the cache entry holds a strong ref to the plan: id()-keyed
            # entries must keep THE CALLER'S plan object alive (its id is
            # the key — a recycled address could serve another query's
            # compiled program), plus the staged main plan actually
            # compiled
            entry = self._compiled.setdefault(
                key, {"slack": self.DEFAULT_SLACK, "ref": (orig, planned)})
            self._bound_compiled(key)
            if "compiled" not in entry:
                self._compile_or_load(planned, entry, timings, tracer)
            bufs = self._collect_buffers(planned)
            pvals = self._collect_params(planned)
            # bytes the query reads from HBM-resident scan buffers: the
            # roofline denominator (achieved GB/s lands in scan_gbps at
            # _finish) so wins/losses are judged against memory
            # bandwidth, not only against a host CPU
            timings["bytes_scanned"] = float(
                sum(b.nbytes for b in bufs.values()))
            self._attach_compression(timings, bufs)
            self._attach_delta(timings, planned)
            obs_metrics.counter("device_executions_total").inc()
            obs_metrics.counter("bytes_scanned_total").inc(
                timings["bytes_scanned"])
            # memory HWM (obs/memwatch): scan buffers go live here and
            # release in _finish; the device-stats sample around the
            # execute bracket dominates the accounting when available.
            # __live_bytes is the release token: every release POPS it,
            # so the success/failure paths can never double-release
            # (stripped from all published timings)
            memwatch.add_live(timings["bytes_scanned"])
            timings["__live_bytes"] = timings["bytes_scanned"]
            memwatch.sample_device()
            # compiler-truth cost billing (obs/costs): per dispatch,
            # before the execute bracket opens so the memoized
            # extraction never inflates device.run
            obs_costs.record_program(type(self).__name__,
                                     entry["compiled"])
            # ndslint: waive[NDS102] -- execute bracket opens here; _finish_traced closes it after device_get
            t1 = _time.perf_counter()
            # jitsan dispatch scope (analysis/jitsan): armed windows
            # count the crossing and forbid implicit h2d — bufs/pvals
            # are device-resident by the staging above
            with jitsan.dispatch(type(self).__name__):
                row, outs, overflow = (entry["compiled"](bufs, pvals)
                                       if pvals is not None
                                       else entry["compiled"](bufs))
        return _AsyncResult(self, planned, key, entry, timings, t1,
                            (row, outs, overflow), qspan)

    def _attach_compression(self, timings: dict, bufs: dict) -> None:
        """Per-query compression accounting (nds_tpu/columnar/):
        ``bytes_scanned`` already measures the ENCODED buffer bytes
        (the sum above counts what is actually resident); this adds
        the raw bytes those buffers replace and the resulting
        compression_ratio. Emitted only under an active mode so
        ``columnar.encode=off`` summaries stay byte-identical."""
        from nds_tpu import columnar
        if not columnar.enabled():
            return
        raw = 0.0
        for k, b in bufs.items():
            base = k[:-2] if k.endswith(("#v", "#x")) else k
            if base in self._enc_specs:
                if k == base:
                    raw += self._raw_nbytes.get(base, float(b.nbytes))
            else:
                raw += float(b.nbytes)
        timings["bytes_scanned_raw"] = raw
        if timings.get("bytes_scanned") and raw:
            timings["compression_ratio"] = round(
                raw / timings["bytes_scanned"], 4)

    def _attach_delta(self, timings: dict, planned) -> None:
        """Per-query delta accounting (columnar/delta.py): how many
        append-only segments and deleted-row mask entries rode under
        the tables THIS query scanned. Emitted only when a scanned
        table actually carries delta state, so pre-maintenance (and
        delta-free) summaries stay byte-identical — the ndsreport
        delta column keys off the field's presence."""
        from nds_tpu.columnar import delta
        scanned = {node.table
                   for root in [planned.root, *planned.scalar_subplans]
                   for node in P.walk_plan(root)
                   if isinstance(node, P.Scan)}
        segments = appended = masked = 0
        hit = False
        for t in sorted(scanned):
            rep = delta.delta_report(self.tables.get(t))
            if rep is None:
                continue
            hit = True
            segments += rep["segments"]
            appended += rep["appended_rows"]
            masked += rep["masked_rows"]
        if hit:
            timings["delta_segments"] = float(segments)
            timings["delta_appended_rows"] = float(appended)
            timings["delta_masked_rows"] = float(masked)

    # ------------------------------------------------- plan cache (AOT)

    def _fingerprint_parts(self) -> dict:
        """Executor-family facts every plan fingerprint folds in —
        anything (beyond the plan and the tables) that changes the
        traced program. Subclasses extend."""
        return {
            "float_dtype": str(self.float_dtype),
            "scan_reduce": bool(
                self.SCAN_REDUCE and os.environ.get(
                    "NDS_TPU_SCAN_REDUCE", "1") != "0"),
            "stage_weight": self.STAGE_WEIGHT,
        }

    def _fingerprint_roots(self) -> list:
        """Plan trees OUTSIDE the PlannedQuery that still shape the
        program (the partial-agg executor's merge plan)."""
        return []

    def _plan_fingerprint(self, planned, slack: float):
        """(cache, fingerprint) for this staged plan at this slack, or
        (None, None) when caching is off. A fingerprint failure is a
        warned cache miss, never a query failure."""
        from nds_tpu.cache import aot as cache_aot
        return cache_aot.try_fingerprint(
            type(self).__name__,
            {"slack": slack, **self._fingerprint_parts()},
            planned=planned, tables=self.tables,
            extra_roots=self._fingerprint_roots())

    def _compile_or_load(self, planned, entry: dict, timings: dict,
                         tracer) -> None:
        """Fill ``entry['compiled']``/``entry['side']`` for a plan: a
        verified plan-cache hit deserializes the persisted executable
        (0 compiles, ``compile_ms`` stays 0, ``cache_load_ms``
        recorded); otherwise compile as always and persist for the
        next process."""
        import time as _time
        from nds_tpu.cache import aot as cache_aot
        pc, fp = self._plan_fingerprint(planned, entry["slack"])
        if fp:
            with tracer.span("cache.load", fp=fp[:12]):
                bufs = self._collect_buffers(planned)
                pvals = self._collect_params(planned)
                hit = cache_aot.load_cached(
                    pc, fp, type(self).__name__, timings,
                    args=((bufs, pvals) if pvals is not None
                          else (bufs,)))
            if hit is not None:
                entry["compiled"], extra = hit
                entry["side"] = {"dicts": extra.get("dicts"),
                                 "kernels": extra.get("kernels"),
                                 "ops_est": extra.get("ops_est")}
                # an overflow retry served from another process's
                # persisted recompile consumed no compile here
                entry.pop("recompile", None)
                return
        # ndslint: waive[NDS102] -- raw bracket feeds compile_ms; the span records it too
        t0 = _time.perf_counter()
        with tracer.span("device.compile", slack=entry["slack"]):
            jitted, side = self._compile(planned, entry["slack"])
            bufs = self._collect_buffers(planned)
            pvals = self._collect_params(planned)
            # AOT-compile now so compile cost is attributed
            # separately from steady-state execution (fresh when the
            # blob will persist: see lower_and_compile)
            lower_args = ((bufs, pvals) if pvals is not None
                          else (bufs,))
            entry["compiled"] = cache_aot.lower_and_compile(
                jitted, *lower_args, fresh=cache_aot.fresh_for(pc, fp),
                kind=type(self).__name__)
        entry["side"] = side
        timings["compile_ms"] += (
            # ndslint: waive[NDS102,NDS103] -- .compile() is synchronous; the execute bracket closes via device_get in _finish_traced
            _time.perf_counter() - t0) * 1000
        # overflow retries recompile the SAME query: count them
        # apart from first compiles (distributed executor
        # semantics, README counter contract)
        obs_metrics.counter(
            "recompiles_total" if entry.pop("recompile", False)
            else "compiles_total").inc()
        if fp:
            cache_aot.persist(pc, fp, type(self).__name__,
                              entry["compiled"],
                              {"dicts": side.get("dicts"),
                               "kernels": side.get("kernels"),
                               "ops_est": side.get("ops_est")},
                              meta={"slack": entry["slack"]})

    # capacity at or above which results compact ON DEVICE before the
    # host transfer: a masked full-capacity result of a 576k-slot query
    # with 39 valid rows is ~8MB of dead bytes — at remote-tunnel
    # bandwidth (~11MB/s measured) the transfer dwarfs the compute.
    # Below the threshold the extra dispatch round-trips cost more than
    # they save.
    COMPACT_MIN_ROWS = 1 << 17

    def _compactor(self, row_d, outs_d, timings: dict):
        """AOT-compiled presence-compaction program: one stable sort
        moves valid rows to the front; the host then transfers only a
        power-of-two prefix covering the valid count. First-use compile
        is attributed to compile_ms (the executor's AOT contract), not
        the execution bracket."""
        import time as _time
        n = row_d.shape[0]
        sig = tuple((a.dtype.name, v.dtype.name) for a, v in outs_d)
        key = ("__compact__", n, sig)
        cf = self._compiled.get(key)
        if cf is None:
            def fn(row, outs):
                iota = jnp.arange(n, dtype=jnp.int32)
                k = jnp.where(row, 0, 1).astype(jnp.int32)
                _, perm = lax.sort([k, iota], num_keys=1,
                                   is_stable=True)
                cnt = jnp.sum(row)
                outs2 = [(jnp.take(a, perm, axis=0),
                          jnp.take(v, perm, axis=0)) for a, v in outs]
                return cnt, jnp.take(row, perm), outs2
            # ndslint: waive[NDS102] -- compactor compile bracket (attributed to compile_ms)
            t0 = _time.perf_counter()
            avatars = (jax.ShapeDtypeStruct(row_d.shape, row_d.dtype),
                       [(jax.ShapeDtypeStruct(a.shape, a.dtype),
                         jax.ShapeDtypeStruct(v.shape, v.dtype))
                        for a, v in outs_d])
            from nds_tpu.cache import aot as cache_aot
            pc, fp = cache_aot.try_fingerprint(
                "compact", {"n": n, "sig": sig,
                            "donate": KX.donate_enabled()})
            # the masked full-capacity result arrays are single-use by
            # construction (the compaction replaces them): donate, so
            # the biggest intermediate of the query stops
            # double-buffering
            KX.silence_donation_warnings()
            cf, _extra, hit = cache_aot.cached_compile(
                pc, fp, "compact",
                lambda: KX.donate_jit(fn, (0, 1)), avatars,
                timings=timings)
            # ndslint: waive[NDS102,NDS103] -- .compile() is synchronous; no device work is in flight here
            dt = (_time.perf_counter() - t0) * 1000
            if not hit:
                timings["compile_ms"] = (timings.get("compile_ms", 0.0)
                                         + dt)
            # hit or miss, the bracket is fingerprint + compile-or-load
            # time, not device execution: _finish_traced shifts the
            # execute window past it (a hit's deserialize cost is
            # already billed to cache_load_ms by load_cached)
            timings["__compact_compile_ms"] = dt
            self._compiled[key] = cf
        return cf

    def _finalize_timings(self, timings: dict, key: object) -> None:
        """Shared tail of every executor's timing bill: roofline
        derivation (achieved scan bandwidth vs the active backend's
        peak memory bandwidth — the denominator that turns "N GB/s"
        into "is it actually fast", VERDICT r4 weak #6), staged
        sub-program fold, and the last_timings publication."""
        bs = timings.get("bytes_scanned", 0.0)
        if bs and timings.get("execute_ms", 0) > 0:
            timings["scan_gbps"] = (
                bs / (timings["execute_ms"] / 1000) / 1e9)
            peak = _peak_mem_gbps()
            if peak:
                timings["roofline_frac"] = round(
                    timings["scan_gbps"] / peak, 4)
                timings["roofline_peak_gbps"] = peak
        if bs and timings.get("ops_est"):
            # arithmetic intensity of the compiled program: traced
            # row-slots per scanned byte — the ops/byte model the
            # ndsreport roofline column pairs with roofline_frac
            timings["ops_per_byte"] = round(
                timings["ops_est"] / bs, 4)
        self._merge_stage_timings(timings, key)
        self.last_timings = timings

    def _finish(self, planned, key, entry, timings, t1, devs,
                attempt: int = 0, span=None):
        """Blocking half of execute_async: one device->host round trip
        for execution + result (a separate block_until_ready +
        int(overflow) + device_get costs 2-3 tunnel RTTs per query on
        remote-attached TPUs), then overflow-retry with doubled slack.
        Large-capacity results compact on device first (see
        COMPACT_MIN_ROWS)."""
        tracer = get_tracer()
        try:
            return self._finish_traced(planned, key, entry, timings,
                                       t1, devs, attempt, span, tracer)
        except BaseException as exc:
            # failed queries still close their span (with the error
            # attached) so trace durations stay truthful; and a staged
            # sub's span must not survive as the failed query's
            self.last_query_span = None
            if span and span.t1 is None:
                span.set(error=f"{type(exc).__name__}: {exc}").end()
            raise
        finally:
            # the dispatch's accounted scan bytes release when the
            # query completes either way (overflow retries re-add
            # through execute_async and release through THEIR finish;
            # pop makes a second release a no-op)
            memwatch.sub_live(timings.pop("__live_bytes", 0.0))

    def _finish_traced(self, planned, key, entry, timings, t1, devs,
                       attempt, span, tracer):
        import time as _time
        row_d, outs_d, overflow_d = devs
        n = row_d.shape[0]
        if n >= self.COMPACT_MIN_ROWS and outs_d:
            cf = self._compactor(row_d, outs_d, timings)
            # first-use compactor compile must not count as execution
            t1 += timings.pop("__compact_compile_ms", 0.0) / 1000
            obs_costs.record_program("compact", cf)
            with jitsan.dispatch("compact"):
                cnt_d, row2, outs2 = cf(row_d, outs_d)
            cnt_h, overflow_h = jax.device_get((cnt_d, overflow_d))
            if int(overflow_h) == 0:
                C = 1
                while C < max(int(cnt_h), 1):
                    C <<= 1
                C = min(C, n)
                row_h, outs_h = jax.device_get(
                    (row2[:C], [(a[:C], v[:C]) for a, v in outs2]))
            else:
                row_h = outs_h = None
        else:
            row_h, outs_h, overflow_h = jax.device_get(devs)
        # ndslint: waive[NDS102] -- bracket endpoint after device_get; becomes the device.run span via begin(t0=t1).end(t=t2)
        t2 = _time.perf_counter()
        if int(overflow_h) == 0:
            # the execute bracket closed at t2 (device_get blocks until
            # ready); record it as a span with the measured endpoints
            tracer.begin("device.run", parent=span, t0=t1).end(t=t2)
            with tracer.attach(span), tracer.span("device.materialize"):
                out = self._materialize(planned, row_h, outs_h,
                                        entry["side"])
            # ndslint: waive[NDS102] -- host materialize endpoint; the device.materialize span brackets the same region
            t3 = _time.perf_counter()
            # post-materialize allocator sample: results + scan buffers
            # are all resident here, the per-query memory peak
            memwatch.sample_device()
            timings["execute_ms"] = (t2 - t1) * 1000
            timings["materialize_ms"] = (t3 - t2) * 1000
            side = entry.get("side") or {}
            if side.get("ops_est"):
                timings["ops_est"] = float(side["ops_est"])
            if side.get("kernels"):
                # dunder: a dict, not part of the numeric timings
                # vocabulary (engineTimings strips it; report.py
                # publishes it as the summary's "kernels" block)
                timings["__kernels"] = dict(side["kernels"])
            self._finalize_timings(timings, key)
            if span:
                # dunder keys are internal accounting state (e.g. the
                # __live_bytes release token), not part of the
                # published timings vocabulary
                span.set(timings={k: v for k, v in timings.items()
                                  if not k.startswith("__")}).end()
                self.last_query_span = span
            return out
        if attempt >= 3:
            if span:
                span.set(error="join expansion overflow").end()
            raise DeviceExecError("join expansion overflow after retries")
        # M:N join capacity exceeded: recompile with doubled slack
        # (recovered task-level failure -> listener chain, the
        # CompletedWithTaskFailures analog of `Manager.notifyAll`)
        from nds_tpu.utils.report import TaskFailureCollector
        TaskFailureCollector.notify(
            f"join expansion overflow: retry with slack "
            f"{entry['slack'] * 2}")
        obs_metrics.counter("slack_retries_total").inc()
        entry.pop("compiled", None)
        entry["recompile"] = True
        entry["slack"] *= 2
        if span:
            span.set(overflow_retry=True, slack=entry["slack"]).end()
        nxt = self.execute_async(planned, key)
        # engineTimings must report the FULL compile bill across retries
        nxt.timings["compile_ms"] += timings.get("compile_ms", 0.0)
        return self._finish(planned, key, nxt.entry, nxt.timings, nxt.t1,
                            nxt.devs, attempt + 1, span=nxt.span)

    def _compile(self, planned: P.PlannedQuery,
                 slack: float = DEFAULT_SLACK):
        from nds_tpu.sql import params as sqlparams
        side = {}

        def _run(bufs, params):
            tr = _Trace(self, bufs, slack, params=params)
            row, outs, dicts = tr.run_query(planned)
            side["dicts"] = dicts
            side["kernels"] = dict(tr.kernels)
            side["ops_est"] = int(tr.ops_est)
            return row, outs, tr.total_overflow()

        if sqlparams.has_params(planned):
            # hoisted literals ride as a second runtime-input pytree:
            # one compiled program serves every literal variant
            def fn(bufs, params):
                return _run(bufs, params)
        else:
            def fn(bufs):
                return _run(bufs, None)

        # ndslint: waive[NDS111] -- builds the traced callable only; AOT lower+compile routes through cache.aot (_compile_or_load)
        return jax.jit(fn), side

    def _collect_params(self, planned: P.PlannedQuery):
        """Device inputs for a parameterized plan's hoisted literals
        (sql/params.bind_params), or None for ordinary plans."""
        from nds_tpu.sql import params as sqlparams
        if not sqlparams.has_params(planned):
            return None
        return {k: jnp.asarray(v) for k, v in
                sqlparams.bind_params(planned, self.tables).items()}

    # -------------------------------------------------------------- buffers

    def _collect_buffers(self, planned: P.PlannedQuery) -> dict:
        bufs = {}
        roots = [planned.root] + list(planned.scalar_subplans)
        for root in roots:
            for node in P.walk_plan(root):
                if isinstance(node, P.Scan):
                    rv = self.scan_view(node)
                    for name, _dt in node.output:
                        if rv is not None:
                            self._upload_reduced(bufs, rv, name)
                        else:
                            self._upload(bufs, node.table, name)
                    if rv is None:
                        # delta deleted-row bitmask rides along as a
                        # bool buffer the scan's row gate consumes
                        # (reduced views already gathered it out)
                        self._upload_live(bufs, node.table)
        return bufs

    def _upload_live(self, bufs: dict, table: str) -> None:
        from nds_tpu.columnar import delta
        live = delta.live_mask(self.tables[table])
        if live is None:
            return
        key = f"{table}.__live"
        if key not in self._buffers:
            self._buffers[key] = jnp.asarray(live)
        bufs[key] = self._buffers[key]

    # ------------------------------------------- filtered scan reduction
    #
    # The static-shape engine otherwise builds every gather join at the
    # scanned table's FULL capacity even when pushed-down filters keep a
    # few percent of rows (customer_demographics at 1.92M rows with 2-3%
    # survival was the whole NDS single-chip loss: q4/q10/q18). This is
    # the role build-side sizing plays behind spark-rapids'
    # concurrentGpuTasks tuning (`nds/power_run_gpu.template:38`): at
    # compile time the scan's filter conjunction is evaluated ONCE on
    # the host (per-predicate fallback, like chunked_exec's keep-mask),
    # and when few enough rows survive, the scan reads a reduced
    # power-of-two-capacity buffer set instead — shrinking every
    # downstream operator's compile-time capacity. Filters are still
    # re-applied on device, so a host-eval miss can only lose the
    # shrink, never correctness.

    SCAN_REDUCE = True          # subclasses with pre-reduced tables opt out
    REDUCE_MIN_ROWS = 1 << 14   # below this, full capacity is already cheap
    REDUCE_MAX_FRAC = 0.5       # only shrink when survivors fit in half
    MAX_SCAN_VIEWS = 96         # bound host+device copies across a power run

    def scan_view(self, node):
        """_ReducedScan for this scan's (table, filters), or None for the
        full-table path. Deterministic per signature; cached."""
        if not self.SCAN_REDUCE or os.environ.get(
                "NDS_TPU_SCAN_REDUCE", "1") == "0":
            return None
        t = self.tables[node.table]
        if not node.filters or t.nrows < self.REDUCE_MIN_ROWS:
            return None
        # binding-normalized signature: the same table+filter pair under
        # different query aliases must share one reduced buffer set
        sig = "&".join(sorted(_pred_sig(f) for f in node.filters))
        ck = (node.table, sig)
        hit = self._scan_views.get(ck)
        if hit is not None:
            return hit if isinstance(hit, _ReducedScan) else None
        keep = self._host_keep_mask(node, t)
        s = 0 if keep is None else int(keep.sum())
        if keep is None or s > t.nrows * self.REDUCE_MAX_FRAC:
            self._scan_views[ck] = "full"
            return None
        # deterministic digest (NOT hash(): per-process randomization
        # would rename buffer keys and miss the persistent XLA cache
        # across processes/driver runs)
        import hashlib
        h = hashlib.md5(sig.encode()).hexdigest()[:8]
        rv = _ReducedScan(f"{node.table}@{h}", node.table, s,
                          np.nonzero(keep)[0])
        while len(self._scan_views) >= self.MAX_SCAN_VIEWS:
            old = self._scan_views.pop(next(iter(self._scan_views)))
            if isinstance(old, _ReducedScan):
                self._drop_col_buffers(old.prefix + ".")
        self._scan_views[ck] = rv
        return rv

    def _host_keep_mask(self, node, t: HostTable):
        """Vectorized host evaluation of the scan's filters via the CPU
        evaluator. Predicates it cannot evaluate (scalar-subquery refs,
        q32/q92 shape) simply don't reduce. None = nothing evaluable."""
        from nds_tpu.engine import cpu_exec as cx
        ctx = cx.Context(t.nrows)
        for name, _dt in node.output:
            col = t.columns[name]
            # ndslint: waive[NDS116] -- host-side scan-reduction planning (compile-time filter eval via the CPU evaluator), not device dataflow; nothing decoded here reaches a device buffer
            arr = col.decode() if col.is_string else col.values
            ctx.put((node.binding, name), np.asarray(arr), col.null_mask)
        # ndslint: waive[NDS110] -- expression-evaluation helper inside the device scan path, not a placement: only eval()/like_mask run, never execute()
        helper = cx.CpuExecutor(self.tables)
        from nds_tpu.columnar import delta
        live = delta.live_mask(t)
        # seed from the delta deleted-row bitmask: a reduced view then
        # physically excludes deleted rows and needs no runtime gate
        keep = np.ones(t.nrows, dtype=bool) if live is None \
            else live.copy()
        handled = 1 if live is not None else 0
        for pred in node.filters:
            # under reduced-precision compute (f32/bf16 floats mode) a
            # float predicate can legitimately flip near a boundary
            # between host float64 and device float32 — a row the host
            # drops is gone for good, so float-touching predicates only
            # filter on device there. Exact f64 mode reduces only on
            # predicates whose every op is IEEE-exact on both sides
            # (_float_exact_safe; all of today's ops qualify).
            if _touches_float(pred) and (
                    self.float_dtype is not None
                    or not _float_exact_safe(pred)):
                continue
            try:
                m, mv = helper.eval(pred, ctx)
            except Exception:  # noqa: BLE001 - per-predicate fallback
                continue
            m = np.asarray(m).astype(bool)
            if mv is not None:
                m = m & mv
            keep &= m
            handled += 1
        return keep if handled else None

    def _reduced_to_device(self, arr: np.ndarray):
        """Device placement for reduced-scan buffers; DistributedExecutor
        overrides to build replicated global arrays in multiprocess
        mode."""
        return jnp.asarray(arr)

    # encoded upload is the default; executors whose buffer layout the
    # columnar subsystem does not understand yet (the sharded SPMD
    # shard/pad layout) opt out wholesale and keep raw uploads even
    # when the mode is on
    COLUMNAR_UPLOAD = True

    def _upload_reduced(self, bufs: dict, rv: "_ReducedScan",
                        name: str) -> None:
        key = f"{rv.prefix}.{name}"
        if key not in self._buffers:
            from nds_tpu import columnar
            col = self.tables[rv.table].columns[name]
            vals = col.values[rv.idx]
            nulls = (None if col.null_mask is None
                     else col.null_mask[rv.idx])
            pad = rv.capacity - rv.nrows
            if pad:
                vals = np.concatenate(
                    [vals, np.zeros(pad, dtype=vals.dtype)])
                if nulls is not None:
                    nulls = np.concatenate(
                        [nulls, np.zeros(pad, dtype=bool)])
            # reduced views re-plan their encoding on the SURVIVOR
            # rows (runs/bounds differ from the base column; the pad
            # tail is gated by the row mask, so its zeros must not
            # drag the bitpack bounds down to 0 and forfeit the
            # shrink on exactly the hot filtered-scan path); the spec
            # lives with the buffers and evicts with them
            spec = (columnar.plan_padded(vals, nulls, rv.nrows,
                                         is_string=col.is_string)
                    if self.COLUMNAR_UPLOAD and columnar.enabled()
                    else None)
            if spec is not None:
                for sfx, arr in columnar.encode_values(
                        spec, vals, nulls, nrows=rv.nrows).items():
                    self._buffers[key + sfx] = self._reduced_to_device(
                        arr)
                self._enc_specs[key] = spec
                self._raw_nbytes[key] = float(
                    columnar.raw_nbytes(vals, nulls))
            else:
                self._buffers[key] = self._reduced_to_device(vals)
                if nulls is not None:
                    self._buffers[key + "#v"] = self._reduced_to_device(
                        nulls)
        for sfx in ("", "#v", "#x"):
            if key + sfx in self._buffers:
                bufs[key + sfx] = self._buffers[key + sfx]

    def _upload(self, bufs: dict, table: str, name: str) -> None:
        self._pool_upload(self._buffers, bufs, table, name)

    def _pool_upload(self, pool: dict, bufs: dict, table: str,
                     name: str) -> None:
        """One host->device column placement into ``pool`` (shared by
        the chunked engine's phase-B executors, whose pool choice
        differs). Under an active columnar mode the column uploads in
        its ENCODED form (nds_tpu/columnar/); the spec registers on
        THIS executor even when a sibling sharing the pool already
        placed the buffers — specs are deterministic per content+mode,
        so the recomputed choice always matches the resident bytes."""
        key = f"{table}.{name}"
        col = self.tables[table].columns[name]
        from nds_tpu import columnar
        spec = (columnar.column_spec(col)
                if (self.COLUMNAR_UPLOAD and columnar.enabled()
                    and table not in self._no_encode)
                else None)
        if key not in pool:
            if spec is not None:
                for sfx, arr in columnar.encode_column(
                        spec, col).items():
                    pool[key + sfx] = jnp.asarray(arr)
            else:
                pool[key] = jnp.asarray(col.values)
                if col.null_mask is not None:
                    pool[key + "#v"] = jnp.asarray(col.null_mask)
        if spec is not None:
            self._enc_specs[key] = spec
            self._raw_nbytes[key] = float(
                columnar.raw_nbytes(col.values, col.null_mask))
        for sfx in ("", "#v", "#x"):
            if key + sfx in pool:
                bufs[key + sfx] = pool[key + sfx]

    def col_is_sorted(self, table: str, name: str) -> bool:
        """Host-cached: column is non-null and nondecreasing. The
        generators emit surrogate keys in ascending order, so most star
        dimensions' PKs qualify — their gather-join build sort (the
        whole-table lax.sort per compiled program, 1.92M rows for
        customer_demographics) is then skipped entirely."""
        ck = (table, name, "sorted")
        if ck not in self._bounds:
            col = self.tables[table].columns[name]
            ok = (col.null_mask is None and not col.is_string
                  and np.issubdtype(col.values.dtype, np.number)
                  and (len(col.values) < 2
                       or bool(np.all(np.diff(col.values) >= 0))))
            self._bounds[ck] = ok
        return self._bounds[ck]

    def col_bounds(self, table: str, name: str):
        """Host-side (min,max) of an integer-typed column, for key packing."""
        ck = (table, name)
        if ck not in self._bounds:
            col = self.tables[table].columns[name]
            if col.is_string:
                self._bounds[ck] = (0, max(len(col.dictionary) - 1, 0))
            elif np.issubdtype(col.values.dtype, np.integer):
                vals = col.values
                if col.null_mask is not None:
                    vals = vals[col.null_mask]
                if len(vals) == 0:
                    self._bounds[ck] = (0, 0)
                else:
                    self._bounds[ck] = (int(vals.min()), int(vals.max()))
            else:
                self._bounds[ck] = (None, None)
        return self._bounds[ck]

    # ---------------------------------------------------------- materialize

    def _materialize(self, planned: P.PlannedQuery, row, outs, side):
        # inputs are already host-side (execute() batches the transfer);
        # device_get is a no-op passthrough for numpy but kept so direct
        # callers with device arrays still work
        row, outs = jax.device_get((row, outs))
        row = np.asarray(row)
        idx = np.nonzero(row)[0]
        arrs, valids, dtypes = [], [], []
        for (arr, valid), (name, dt), sd in zip(
                outs, planned.root.output, side["dicts"]):
            a = np.asarray(arr)[idx]
            v = np.asarray(valid)[idx]
            if sd is not None:
                a = sd[np.clip(a, 0, len(sd) - 1)]
                a = np.asarray(a, dtype=object)
            arrs.append(a)
            valids.append(None if v.all() else v)
            dtypes.append(dt)
        names = planned.column_names or [n for n, _ in planned.root.output]
        return ResultTable(names, arrs, dtypes, valids)


class _AsyncResult:
    """Handle for an in-flight query: dispatch happened, completion and
    materialization wait until result()."""

    __slots__ = ("ex", "planned", "key", "entry", "timings", "t1",
                 "devs", "span")

    def __init__(self, ex, planned, key, entry, timings, t1, devs,
                 span=None):
        self.ex = ex
        self.planned = planned
        self.key = key
        self.entry = entry
        self.timings = timings
        self.t1 = t1
        self.devs = devs
        self.span = span

    def result(self):
        return self.ex._finish(self.planned, self.key, self.entry,
                               self.timings, self.t1, self.devs,
                               span=self.span)


class _Trace:
    """Interprets a plan while being traced by jax.jit. All python control
    flow here runs at trace time; host-side numpy work (dictionary
    predicate tables, key bounds) becomes XLA constants."""

    def __init__(self, ex: DeviceExecutor, bufs: dict,
                 slack: float = 2.0, params: "dict | None" = None):
        self.ex = ex
        self.bufs = bufs
        self.slack = slack
        # hoisted-literal runtime inputs (sql/params.py): slot -> traced
        # array; empty for ordinary plans
        self.params = params or {}
        # float compute dtype (engine.precision); distributed executors
        # without the attribute inherit the exact-f64 default
        self.fdt = getattr(ex, "float_dtype", None) or jnp.float64
        self.scalars: dict[int, tuple] = {}
        self._cache: dict[int, DCtx] = {}
        self._overflows: list = []
        # kernel-use accounting (engine/kernels.py): which kernel each
        # hot operator actually compiled with, counted at trace time
        # and published per query (BenchReport "kernels" block)
        self.kernels: dict[str, int] = {}
        # ops estimate: total row-slots processed across plan nodes —
        # the numerator of the per-query ops/byte model ndsreport's
        # roofline column reads
        self.ops_est: int = 0

    def _note(self, kernel: str) -> None:
        self.kernels[kernel] = self.kernels.get(kernel, 0) + 1

    def total_overflow(self):
        if not self._overflows:
            return jnp.zeros((), jnp.int64)
        tot = self._overflows[0].astype(jnp.int64)
        for o in self._overflows[1:]:
            tot = tot + o.astype(jnp.int64)
        return tot

    def run_query(self, planned: P.PlannedQuery):
        for i, sub in enumerate(planned.scalar_subplans):
            ctx = self.run(sub)
            name, dt = sub.output[0]
            dv = ctx.cols[(sub.binding, name)]
            pos = jnp.argmax(ctx.row)
            v = dv.arr[pos]
            ok = ctx.row[pos]
            if dv.valid is not None:
                ok = ok & dv.valid[pos]
            self.scalars[i] = (v, ok, dv.sdict, dt)
        ctx = self.run(planned.root)
        root = planned.root
        outs, dicts = [], []
        for name, _dt in root.output:
            dv = ctx.cols[(root.binding, name)]
            valid = dv.valid if dv.valid is not None else jnp.ones(
                ctx.n, dtype=bool)
            outs.append((dv.arr, valid))
            dicts.append(dv.sdict)
        return ctx.row, outs, dicts

    # ----------------------------------------------------------- plan nodes

    def stash(self, node: P.Node, ctx: DCtx) -> None:
        """The trace's one node-result cache write point. id()-keying
        is sound here (and only here): the cache dies with this trace,
        and the traced PlannedQuery pins every node for that whole
        lifetime — no address can recycle while its entry is live."""
        # ndslint: waive[NDS101] -- trace-scoped; the traced plan pins its nodes
        self._cache[id(node)] = ctx

    def run(self, node: P.Node) -> DCtx:
        nid = id(node)
        if nid in self._cache:
            return self._cache[nid]
        ctx = getattr(self, "_run_" + type(node).__name__.lower())(node)
        # ops/byte model numerator: row-slots this node's context holds
        # (deduplicated — shared CTE bodies count once via the cache)
        self.ops_est += int(getattr(ctx, "n", 0))
        self.stash(node, ctx)
        return ctx

    def _run_scan(self, node: P.Scan) -> DCtx:
        t = self.ex.tables[node.table]
        rv = self.ex.scan_view(node)
        if rv is not None:
            n, nrows, prefix = rv.capacity, rv.nrows, rv.prefix
        else:
            n, nrows, prefix = max(t.nrows, 1), t.nrows, node.table
        row = jnp.arange(n, dtype=jnp.int32) < nrows
        live = self.bufs.get(f"{node.table}.__live") if rv is None \
            else None
        if live is not None:
            # delta deleted-row bitmask: DF_*-deleted rows leave every
            # scan's row population before predicates run (base column
            # buffers stay resident and encoded — deletion is one bool
            # AND, not a re-upload)
            row = row & live
        ctx = DCtx(n, row)
        for name, _dt in node.output:
            col = t.columns[name]
            key = f"{prefix}.{name}"
            spec = self.ex._enc_specs.get(key)
            if spec is not None:
                # encoded buffer set (nds_tpu/columnar/): the decode
                # traces INTO this program, so XLA fuses the unpack
                # into every consumer and the full-width values never
                # round-trip through HBM
                from nds_tpu.columnar import device as columnar_dev
                arr, valid = columnar_dev.decode(spec, self.bufs, key)
            else:
                arr = self.bufs[key]
                valid = self.bufs.get(key + "#v")
            if arr.shape[0] == 0:
                arr = jnp.zeros((1,), dtype=arr.dtype)
                valid = None
            lo, hi = self.ex.col_bounds(node.table, name)
            sdict = col.dictionary if col.is_string else None
            ctx.cols[(node.binding, name)] = DVal(arr, valid, sdict, lo, hi)
        for pred in node.filters:
            # re-applied even on a reduced view (host-eval misses lose
            # only the shrink; unhandled predicates still filter here)
            ctx = self._apply_filter(ctx, pred)
        # runtime marker for the presorted-build fast path: this ctx's
        # arrays are in host storage order with a prefix row mask.
        # Contexts rebuilt elsewhere (hash exchanges, merges) never set
        # it, so a static plan check alone can't mistake an exchanged
        # build side for a sorted one. A live mask breaks the
        # prefix-row-mask property the fast path assumes.
        ctx.pristine = not node.filters and live is None
        return ctx

    def _apply_filter(self, ctx: DCtx, pred: ir.IR) -> DCtx:
        dv = self.eval(pred, ctx)
        m = dv.arr.astype(bool)
        if dv.valid is not None:
            m = m & dv.valid
        out = DCtx(ctx.n, ctx.row & m)
        out.cols = ctx.cols
        return out

    def _run_derivedscan(self, node: P.DerivedScan) -> DCtx:
        child = self.run(node.child)
        cb = node.child.binding
        out = DCtx(child.n, child.row)
        for name, _dt in node.child.output:
            out.cols[(node.binding, name)] = child.cols[(cb, name)]
        return out

    def _run_stagedscan(self, node: P.StagedScan) -> DCtx:
        """Host-staged intermediate (engine/staging.py): scan the temp
        table, then restore each column's original (binding, name)
        address so the ancestors' expressions resolve unchanged."""
        inner = self.run(node.child)
        sb = node.child.binding
        out = DCtx(inner.n, inner.row)
        for b, name, mangled, _dt in node.cols:
            out.cols[(b, name)] = inner.cols[(sb, mangled)]
        out.pristine = getattr(inner, "pristine", False)
        return out

    def _run_filter(self, node: P.Filter) -> DCtx:
        return self._apply_filter(self.run(node.child), node.predicate)

    def _run_project(self, node: P.Project) -> DCtx:
        ctx = self.run(node.child)
        out = DCtx(ctx.n, ctx.row)
        for name, e in node.exprs:
            dv = self.eval(e, ctx)
            if dv.arr.ndim == 0:
                dv = dv.with_arrays(
                    jnp.broadcast_to(dv.arr, (ctx.n,)),
                    None if dv.valid is None
                    else jnp.broadcast_to(dv.valid, (ctx.n,)))
            out.cols[(node.binding, name)] = dv
        return out

    # -------------------------------------------------------------- joins

    def _join_key_arrays(self, lvals, rvals, lctx, rctx):
        """Align key pairs (string dictionary union, decimal rescale), then
        bit-pack multi-column keys into one int64 per side.
        Returns (lkey, lok, rkey, rok, span): span is the host-known
        (lo, hi) value range of the combined key — the dense-kernel
        feasibility input (engine/kernels.py) — or None when either
        side lacks bounds."""
        lok = lctx.row
        rok = rctx.row
        if len(lvals) == 1 and lvals[0].sdict is None \
                and rvals[0].sdict is None:
            lv, rv = lvals[0], rvals[0]
            lk, rk = lv.arr.astype(jnp.int64), rv.arr.astype(jnp.int64)
            span = None
            if (lv.lo is not None and rv.lo is not None
                    and lv.hi is not None and rv.hi is not None):
                span = (min(lv.lo, rv.lo), max(lv.hi, rv.hi))
                # int32 keys sort/search natively on TPU; int64 is
                # emulated
                if span[0] > -2**31 and span[1] < 2**31 - 1:
                    lk, rk = lk.astype(jnp.int32), rk.astype(jnp.int32)
            return lk, _ok(lv, lok), rk, _ok(rv, rok), span
        lks, rks, widths = [], [], []
        for lv, rv in zip(lvals, rvals):
            la, ra, lo, hi = self._align_pair(lv, rv)
            lok = _ok(lv, lok)
            rok = _ok(rv, rok)
            lks.append((la, lo, hi))
            rks.append((ra, lo, hi))
            span = hi - lo
            widths.append(max(span.bit_length(), 1))
        if sum(widths) > 62:
            raise DeviceExecError(
                f"join key too wide to pack: {widths} bits")
        lkey = self._pack(lks, widths)
        rkey = self._pack(rks, widths)
        if sum(widths) <= 30:
            lkey = lkey.astype(jnp.int32)
            rkey = rkey.astype(jnp.int32)
        # packed keys normalize each part to [0, hi-lo], so the combined
        # key lives in [0, 2^sum(widths))
        return lkey, lok, rkey, rok, (0, (1 << sum(widths)) - 1)

    @staticmethod
    def _pack(keys, widths):
        acc = None
        for (arr, lo, hi), w in zip(keys, widths):
            norm = jnp.clip(arr.astype(jnp.int64) - lo, 0, hi - lo)
            acc = norm if acc is None else ((acc << w) | norm)
        return acc

    # bound on memoized dictionary unions (each entry pins two host
    # dictionaries plus two host remap tables): ``columnar.
    # dict_union_cap`` / NDS_TPU_DICT_UNION_CAP — a serving workload
    # cycling many table pairs thrashed the old hard 256 silently
    @staticmethod
    def _union_cap() -> int:
        from nds_tpu import columnar
        return columnar.dict_union_cap()

    def _dict_union(self, lsd, rsd):
        """Memoized string-dictionary union for one (left, right)
        dictionary pair: np.union1d + the two searchsorted remaps run
        ONCE per pair per executor instead of once per execution of
        every join over the same two string columns. The cache holds
        HOST arrays only — a jnp array minted here would be a
        trace-local constant, and replaying it into a later trace
        desyncs that program's hoisted-constant inputs. Returns
        (union[np str], lmap[device], rmap[device])."""
        ex = self.ex
        key = (id(lsd), id(rsd))
        hit = ex._union_cache.get(key)
        if hit is None or hit[0] is not lsd or hit[1] is not rsd:
            union = np.union1d(lsd.astype(str), rsd.astype(str))
            lmap = np.searchsorted(union, lsd.astype(str))
            rmap = np.searchsorted(union, rsd.astype(str))
            cap = self._union_cap()
            while len(ex._union_cache) >= cap:
                ex._union_cache.pop(next(iter(ex._union_cache)))
            # the stored tuple pins both keyed dictionaries, and the
            # identity re-check above rejects any recycled address
            hit = (lsd, rsd, union, lmap, rmap)
            ex._union_cache[key] = hit
        return hit[2], jnp.asarray(hit[3]), jnp.asarray(hit[4])

    def _align_pair(self, lv: DVal, rv: DVal):
        """Make one key pair comparable as integers; returns
        (l_arr, r_arr, lo, hi) with host-known bounds."""
        if lv.sdict is not None or rv.sdict is not None:
            if lv.sdict is None or rv.sdict is None:
                raise DeviceExecError("string vs non-string join key")
            if lv.sdict is rv.sdict or (
                    len(lv.sdict) == len(rv.sdict)
                    and np.array_equal(lv.sdict, rv.sdict)):
                hi = max(len(lv.sdict) - 1, 0)
                return lv.arr, rv.arr, 0, hi
            union, lmap, rmap = self._dict_union(lv.sdict, rv.sdict)
            return (jnp.take(lmap, lv.arr), jnp.take(rmap, rv.arr),
                    0, max(len(union) - 1, 0))
        la, ra = lv.arr, rv.arr
        if (lv.lo is None or lv.hi is None or rv.lo is None
                or rv.hi is None):
            raise DeviceExecError(
                "join key without host bounds (needed for packing)")
        return la, ra, min(lv.lo, rv.lo), max(lv.hi, rv.hi)

    def _presorted_build(self, right: P.Node, right_keys) -> bool:
        """True when the build side is a bare unfiltered Scan whose
        single join key is a host-proven sorted non-null column: then
        the row mask is the scan's prefix and the key array is already
        in sort order, so _build_lookup's whole-table sort is a no-op
        to skip. Filters (mid-array masks), multi-column packs, strings
        and reduced views all disqualify."""
        if not isinstance(right, P.Scan) or right.filters:
            return False
        if len(right_keys) != 1:
            return False
        k = right_keys[0]
        if not isinstance(k, ir.ColRef) or k.binding != right.binding:
            return False
        # col_is_sorted is the single source of eligibility: it already
        # rejects strings, nullable and non-numeric columns
        return self.ex.col_is_sorted(right.table, k.name)

    @staticmethod
    def _build_lookup(key, ok):
        """Sort build keys (invalid rows to the sentinel end). Explicit
        int32 iota operand: jnp.argsort would carry an int64 index
        operand under x64, pushing the whole sort onto the TPU's
        emulated 64-bit path."""
        sentinel = jnp.iinfo(key.dtype).max
        k = jnp.where(ok, key, sentinel)
        iota = jnp.arange(k.shape[0], dtype=jnp.int32)
        ks, order = lax.sort([k, iota], num_keys=1, is_stable=True)
        return ks, order

    @staticmethod
    def _probe(ks, order, pkey, pok):
        n = ks.shape[0]
        pos = jnp.clip(_ss(ks, pkey), 0, n - 1)
        hit = (jnp.take(ks, pos) == pkey) & pok
        return jnp.take(order, pos), hit

    def _full_join(self, node: P.Join, lctx, rctx, lkey, lok, rkey,
                   rok) -> DCtx:
        """FULL OUTER over unique keys on BOTH sides (q51/q97 join
        grouped CTEs on their group keys): capacity = |L| + |R|. Slots
        [0, |L|) hold every left row with the right side gathered (null
        where unmatched); slots [|L|, |L|+|R|) hold only the right rows
        with no left match, left side null-extended."""
        if not node.right_unique:
            raise DeviceExecError(
                "FULL OUTER JOIN requires unique join keys")
        ks, order = self._build_lookup(rkey, rok)
        ridx, hit = self._probe(ks, order, lkey, lok)
        ks2, order2 = self._build_lookup(lkey, lok)
        _lidx, rhit = self._probe(ks2, order2, rkey, rok)
        unmatched_r = rctx.row & ~rhit

        falsev = jnp.zeros(rctx.n, dtype=bool)
        out = DCtx(lctx.n + rctx.n,
                   jnp.concatenate([lctx.row, unmatched_r]))
        gathered = rctx.gather(ridx, clear_valid=hit)
        for k, dv in lctx.cols.items():
            # left columns: present in block A, null in block B
            pad = jnp.zeros((rctx.n,) + dv.arr.shape[1:], dv.arr.dtype)
            arr = jnp.concatenate([dv.arr, pad])
            lv = dv.valid if dv.valid is not None else jnp.ones(
                lctx.n, dtype=bool)
            out.cols[k] = dv.with_arrays(
                arr, jnp.concatenate([lv, falsev]))
        for k, dv in rctx.cols.items():
            g = gathered.cols[k]
            arr = jnp.concatenate([g.arr, dv.arr])
            gv = g.valid if g.valid is not None else hit
            dvv = dv.valid if dv.valid is not None else jnp.ones(
                rctx.n, dtype=bool)
            out.cols[k] = dv.with_arrays(
                arr, jnp.concatenate([gv, dvv]))
        return out

    def _run_join(self, node: P.Join) -> DCtx:
        lctx, rctx = self.run(node.left), self.run(node.right)
        if not node.left_keys:
            return self._cross_join(node, lctx, rctx)
        lvals = [self.eval(k, lctx) for k in node.left_keys]
        rvals = [self.eval(k, rctx) for k in node.right_keys]
        lkey, lok, rkey, rok, span = self._join_key_arrays(
            lvals, rvals, lctx, rctx)
        if node.kind == "full":
            return self._full_join(node, lctx, rctx, lkey, lok, rkey,
                                   rok)
        if node.right_unique:
            # gather join: probe from the left, build on the unique
            # right. The planner's kernel choice (engine/kernels.py)
            # picks the probe machinery; infeasible choices (missing
            # bounds, oversized domain) demote to the sort path and the
            # demotion shows in the per-query kernel counts
            ridx = hit = None
            if (node.kernel == KX.JOIN_MATMUL
                    and rctx.n <= 4 * KX.MATMUL_MAX_BUILD):
                ridx, hit = KX.matmul_probe_join(rkey, rok, lkey, lok)
                self._note("join.matmul")
            elif node.kernel in (KX.JOIN_MATMUL, KX.JOIN_DIRECT):
                dom = (None if span is None
                       else KX.domain_of(span[0], span[1]))
                if KX.direct_feasible(dom, rctx.n):
                    ridx, hit = KX.direct_lookup_join(
                        rkey, rok, lkey, lok, int(span[0]), dom)
                    self._note("join.direct")
            if ridx is None:
                if (getattr(rctx, "pristine", False)
                        and self._presorted_build(node.right,
                                                  node.right_keys)):
                    # host-proven sorted PK build on a pristine scan
                    # ctx: rok is the scan's prefix mask, so masked
                    # tail rows -> sentinel keeps ks ascending with NO
                    # device sort
                    sentinel = jnp.iinfo(rkey.dtype).max
                    ks = jnp.where(rok, rkey, sentinel)
                    order = jnp.arange(rkey.shape[0], dtype=jnp.int32)
                    self._note("join.presorted")
                else:
                    ks, order = self._build_lookup(rkey, rok)
                    self._note("join.sortmerge")
                ridx, hit = self._probe(ks, order, lkey, lok)
            if node.kind == "left":
                out = DCtx(lctx.n, lctx.row)
                out.cols.update(lctx.cols)
                gathered = rctx.gather(ridx, clear_valid=hit)
                out.cols.update(gathered.cols)
                if node.residual is not None:
                    resid = self.eval(node.residual, out)
                    rk = resid.arr.astype(bool)
                    if resid.valid is not None:
                        rk = rk & resid.valid
                    keep = hit & rk
                    out2 = DCtx(lctx.n, lctx.row)
                    out2.cols.update(lctx.cols)
                    out2.cols.update(rctx.gather(ridx, clear_valid=keep).cols)
                    return out2
                return out
            out = DCtx(lctx.n, lctx.row & hit)
            out.cols.update(lctx.cols)
            out.cols.update(rctx.gather(ridx).cols)
            if node.residual is not None:
                out = self._apply_filter(out, node.residual)
            return out
        # right side not unique
        if node.kind == "inner":
            K = max(int(self.slack * max(lctx.n, rctx.n)), 1)
            if (node.kernel == KX.JOIN_PARTITIONED
                    and min(lctx.n, rctx.n) >= 2 * KX.NPART):
                # radix-partitioned sort-merge (engine/kernels.py):
                # per-partition sort depth is log(n/R) and all R sorts
                # batch into one lax.sort — the q21-class large-by-
                # large path. part_slack rides the executor's overflow
                # retry (doubled slack grows partition AND output
                # capacity together)
                part_slack = max(2.0, self.slack)
                lidx2, ridx, present, over = KX.partitioned_mn_join(
                    lkey, lok, rkey, rok, K, part_slack)
                self._overflows.append(over)
                self._note("join.partitioned")
                out = DCtx(int(lidx2.shape[0]), present)
                out.cols.update(lctx.gather(lidx2).cols)
                out.cols.update(rctx.gather(ridx).cols)
                if node.residual is not None:
                    out = self._apply_filter(out, node.residual)
                return out
            # generic M:N join: sort the left side by key, find each
            # right row's match RANGE via two searchsorteds, expand into
            # a fixed-capacity slot array (cumsum offsets -> slot->pair
            # mapping). Capacity = slack * max(|L|, |R|); overflow is
            # counted in-program and the executor retries with doubled
            # slack — the static-shape answer to data-dependent join
            # cardinality (SURVEY §7 hard part 2)
            self._note("join.sortmerge")
            ks, order = self._build_lookup(lkey, lok)
            lo = _ss(ks, rkey, side="left")
            hi = _ss(ks, rkey, side="right")
            cnt = jnp.where(rok, hi - lo, 0).astype(jnp.int64)
            offs = jnp.cumsum(cnt)
            total = offs[-1]
            slots = jnp.arange(K, dtype=jnp.int32)
            # slot->pair search runs on int32: offsets clamp to K+1
            # (order-preserving for every slot < K <= INT32_MAX, and
            # the clamped values can never be selected), keeping the
            # searchsorted sort native — an int64 offs sort is emulated
            # on TPU and was the q16-class M:N cost center
            if K + 1 >= 2**31:  # pragma: no cover - absurd capacity
                raise DeviceExecError(f"join capacity {K} exceeds int32")
            offs32 = jnp.minimum(offs, K + 1).astype(jnp.int32)
            ridx = jnp.clip(_ss(offs32, slots, side="right"),
                            0, rctx.n - 1)
            prev = jnp.where(ridx > 0, jnp.take(offs32, ridx - 1), 0)
            within = slots - prev
            lpos = jnp.clip(jnp.take(lo, ridx) + within, 0, lctx.n - 1)
            lidx2 = jnp.take(order, lpos)
            present = slots < jnp.minimum(total, K)
            self._overflows.append(jnp.maximum(total - K, 0))
            out = DCtx(K, present)
            out.cols.update(lctx.gather(lidx2).cols)
            out.cols.update(rctx.gather(ridx).cols)
            if node.residual is not None:
                out = self._apply_filter(out, node.residual)
            return out
        # left outer: probe from the right against a unique left
        # (FK-side expansion; the planner orients star joins the other
        # way, this path serves customer LEFT JOIN orders plans, q13)
        self._note("join.sortmerge")
        ks, order = self._build_lookup(lkey, lok)
        lidx, hit = self._probe(ks, order, rkey, rok)
        # left outer with expansion: block A = matched right rows with
        # gathered left columns; block B = left rows with no surviving match
        presentA = rctx.row & hit
        if node.residual is not None:
            combined = DCtx(rctx.n, presentA)
            combined.cols.update(rctx.cols)
            combined.cols.update(lctx.gather(lidx).cols)
            resid = self.eval(node.residual, combined)
            rk = resid.arr.astype(bool)
            if resid.valid is not None:
                rk = rk & resid.valid
            presentA = presentA & rk
        scat = jnp.zeros(lctx.n, dtype=jnp.int32).at[lidx].max(
            presentA.astype(jnp.int32))
        matched = scat > 0
        n_out = rctx.n + lctx.n
        out = DCtx(n_out, jnp.concatenate(
            [presentA, lctx.row & ~matched]))
        gatheredA = lctx.gather(lidx)
        for k, dv in lctx.cols.items():
            ga = gatheredA.cols[k]
            arr = jnp.concatenate([ga.arr, dv.arr])
            valid = None
            if ga.valid is not None or dv.valid is not None:
                gav = ga.valid if ga.valid is not None else jnp.ones(
                    rctx.n, bool)
                dvv = dv.valid if dv.valid is not None else jnp.ones(
                    lctx.n, bool)
                valid = jnp.concatenate([gav, dvv])
            out.cols[k] = dv.with_arrays(arr, valid)
        falses = jnp.zeros(lctx.n, dtype=bool)
        for k, dv in rctx.cols.items():
            arr = jnp.concatenate(
                [dv.arr, jnp.zeros(lctx.n, dtype=dv.arr.dtype)])
            av = dv.valid if dv.valid is not None else jnp.ones(rctx.n, bool)
            out.cols[k] = dv.with_arrays(arr, jnp.concatenate([av, falses]))
        return out

    def _cross_join(self, node: P.Join, lctx: DCtx, rctx: DCtx) -> DCtx:
        if lctx.n * rctx.n > 1 << 24:
            raise DeviceExecError(
                f"cross join too large: {lctx.n} x {rctx.n}")
        li = jnp.repeat(jnp.arange(lctx.n, dtype=jnp.int32), rctx.n)
        ri = jnp.tile(jnp.arange(rctx.n, dtype=jnp.int32), lctx.n)
        out = lctx.gather(li).merge(rctx.gather(ri))
        out.row = jnp.take(lctx.row, li) & jnp.take(rctx.row, ri)
        if node.residual is not None:
            out = self._apply_filter(out, node.residual)
        return out

    def _run_semijoin(self, node: P.SemiJoin) -> DCtx:
        lctx, rctx = self.run(node.left), self.run(node.right)
        lvals = [self.eval(k, lctx) for k in node.left_keys]
        rvals = [self.eval(k, rctx) for k in node.right_keys]
        if not node.left_keys:
            raise DeviceExecError("semi join without keys")
        lkey, lok, rkey, rok, span = self._join_key_arrays(
            lvals, rvals, lctx, rctx)
        dom = None if span is None else KX.domain_of(span[0], span[1])
        want_bitmask = (node.kernel == KX.SEMI_BITMASK
                        and KX.direct_feasible(dom, rctx.n))
        if node.residual is None:
            if want_bitmask:
                # EXISTS as a dense membership bitmap: one scatter on
                # the build, one gather on the probe — no sort anywhere
                exists = KX.bitmask_semi(rkey, rok, lkey, lok,
                                         int(span[0]), dom)
                self._note("semi.bitmask")
            else:
                ks, order = self._build_lookup(rkey, rok)
                _idx, hit = self._probe(ks, order, lkey, lok)
                exists = hit
                self._note("semi.sortmerge")
        else:
            exists = self._exists_with_residual(
                node, lctx, rctx, lkey, lok, rkey, rok,
                dom if want_bitmask else None,
                None if span is None else int(span[0]))
        keep = (lctx.row & ~exists) if node.anti else (lctx.row & exists)
        out = DCtx(lctx.n, keep)
        out.cols = lctx.cols
        return out

    def _exists_with_residual(self, node, lctx, rctx, lkey, lok, rkey,
                              rok, dom=None, key_lo=None):
        """EXISTS with a cross-side residual of the q21 shape
        `r.col <> l.col`: exists a right row with the key and a DIFFERENT
        (non-NULL) col value  <=>  the per-key [min, max] of col over
        right rows is not exactly [l.col, l.col].

        Two formulations: when the kernel choice is ``bitmask`` and the
        key domain is dense enough (``dom``/``key_lo`` from the
        caller), the min/max tables build by scatter into domain-sized
        arrays and the probe is three gathers — no sort at all
        (engine/kernels.keyed_minmax_semi, the q21 EXISTS-chain path).
        Otherwise one 2-key native sort of (key, col) makes col sorted
        within each key run, so min/max are gathers at the run's ends —
        still no row expansion and no emulated 64-bit sorts."""
        e = node.residual
        if not (isinstance(e, ir.Cmp) and e.op == "<>"):
            raise DeviceExecError(
                f"unsupported semi-join residual: {e!r}")
        rbinds = _plan_bindings(node.right)
        if _expr_bindings(e.left) <= rbinds:
            r_ir, l_ir = e.left, e.right
        elif _expr_bindings(e.right) <= rbinds:
            r_ir, l_ir = e.right, e.left
        else:
            raise DeviceExecError("residual does not split by side")
        lcol = self.eval(l_ir, lctx)
        rcol = self.eval(r_ir, rctx)
        la, ra, lo, hi = self._align_pair(lcol, rcol)
        lok2 = _ok(lcol, lok)
        # rows whose col is NULL can never satisfy `<>` — exclude them
        # from the build entirely (the count-difference formulation this
        # replaces over-counted such rows)
        rok2 = _ok(rcol, rok)
        rcol_n = ra
        lcol_n = la
        if (rkey.dtype == jnp.int32 and -2**31 < lo
                and hi < 2**31 - 1):
            rcol_n = ra.astype(jnp.int32)
            lcol_n = la.astype(jnp.int32)
        if dom is not None and jnp.issubdtype(rcol_n.dtype, jnp.integer):
            self._note("semi.minmax")
            return lok2 & KX.keyed_minmax_semi(
                rkey, rok2, rcol_n, lkey, lok2, lcol_n, key_lo, dom)
        self._note("semi.sortmerge")
        k_sent = jnp.iinfo(rkey.dtype).max
        rkey_s = jnp.where(rok2, rkey, k_sent)
        sk, sc = lax.sort([rkey_s, rcol_n], num_keys=2, is_stable=False)
        pos_l = _ss(sk, lkey, side="left")
        pos_r = _ss(sk, lkey, side="right")
        n = sk.shape[0]
        cmin = jnp.take(sc, jnp.clip(pos_l, 0, n - 1))
        cmax = jnp.take(sc, jnp.clip(pos_r - 1, 0, n - 1))
        has_key = pos_r > pos_l
        differs = (cmin != lcol_n) | (cmax != lcol_n)
        return lok & lok2 & has_key & differs

    # --------------------------------------------------------- aggregation

    def _run_aggregate(self, node: P.Aggregate) -> DCtx:
        ctx = self.run(node.child)
        b = node.binding
        if not node.group_keys:
            out = DCtx(1, jnp.ones(1, dtype=bool))
            for name, spec in node.aggs:
                arr, valid, sdict = self._agg_global(spec, ctx)
                lo, hi = self._agg_bounds(spec, ctx)
                out.cols[(b, name)] = DVal(arr, valid, sdict, lo, hi)
            return out
        keyvals = [self.eval(e, ctx) for _, e in node.group_keys]
        perm, gid, first_s, present_s, ngroups = self._group_ids(ctx, keyvals)
        G = self._group_capacity(ctx.n, keyvals)
        gid = jnp.minimum(gid, G - 1)
        out_row = jnp.arange(G, dtype=jnp.int32) < ngroups
        out = DCtx(G, out_row)
        # first sorted position per group (n for empty groups): gid is
        # sorted, so this is a sorted search, not a segment_min scatter
        starts2 = _ss(gid, jnp.arange(G, dtype=gid.dtype))
        starts = jnp.clip(starts2, 0, ctx.n - 1)
        for (kname, _kexpr), kv in zip(node.group_keys, keyvals):
            arr_s = jnp.take(kv.arr, perm)
            arr_g = jnp.take(arr_s, starts)
            valid_g = None
            if kv.valid is not None:
                valid_g = jnp.take(jnp.take(kv.valid, perm), starts)
            out.cols[(b, kname)] = kv.with_arrays(arr_g, valid_g)
        for name, spec in node.aggs:
            arr, valid, sdict = self._agg_grouped(
                spec, ctx, perm, gid, present_s, G, starts2,
                kernel=node.kernel)
            lo, hi = self._agg_bounds(spec, ctx)
            out.cols[(b, name)] = DVal(arr, valid, sdict, lo, hi)
        return out

    @staticmethod
    def _seg_sum(data, starts2, G):
        """Per-segment sum over the SORTED row space via inclusive-cumsum
        differences. segment_sum lowers to scatter-add (~160ms for i64 at
        1.8M rows on TPU, measured); cumsum runs at memory speed.
        starts2[g] = first sorted row of group g, n for empty groups;
        rows outside any real group must carry data == 0.

        Integer sums stay exact. Float sums pick up cancellation error
        bounded by ulp(global prefix): at SF100 scale (6e8 rows of ~1e9
        squared values) that is ~512 absolute against per-group sums of
        ~1e10+ — orders below the benchmark's float validation epsilon
        (`utils/validate_core.py`), and float aggregation order is
        already unspecified (the reference gates it behind
        `spark.rapids.sql.variableFloatAgg.enabled`)."""
        n = data.shape[0]
        csum = jnp.cumsum(data)
        nxt = jnp.concatenate(
            [starts2[1:], jnp.full((1,), n, starts2.dtype)])
        end = jnp.clip(nxt - 1, 0, n - 1)
        hi = jnp.take(csum, end)
        lo = jnp.where(starts2 > 0,
                       jnp.take(csum, jnp.clip(starts2 - 1, 0, n - 1)),
                       jnp.zeros((), csum.dtype))
        return hi - lo

    def _agg_bounds(self, spec: P.AggSpec, ctx: DCtx):
        """Host-known value bounds of an aggregate output (lets downstream
        joins against aggregate results bit-pack their keys, q2)."""
        if spec.func == "count":
            return 0, ctx.n
        dv = None
        if spec.arg is not None:
            dv = self.eval(spec.arg, ctx)  # cached via column DVals
        if dv is None or dv.lo is None or dv.hi is None:
            return None, None
        if spec.func in ("min", "max"):
            return dv.lo, dv.hi
        if spec.func == "sum" and not isinstance(spec.dtype, FloatType):
            return min(0, dv.lo) * ctx.n, max(0, dv.hi) * ctx.n
        return None, None

    @staticmethod
    def _group_capacity(n: int, keyvals) -> int:
        """Static bound on distinct groups: min(rows, product of key
        domains). Collapses the post-aggregation capacity for
        small-domain keys (q1: returnflag x linestatus -> ~6 slots
        instead of the scan's millions), which shrinks every downstream
        sort — the big TPU win since s64 sorts are emulated."""
        prod = 1
        for kv in keyvals:
            if kv.sdict is not None:
                dom = max(len(kv.sdict), 1)
            elif kv.lo is not None and kv.hi is not None:
                dom = max(int(kv.hi) - int(kv.lo) + 1, 1)
            else:
                return n
            if kv.valid is not None:
                dom += 1  # a NULL key forms one extra group
            prod *= dom
            if prod >= n:
                return n
        return max(min(prod, n), 1)

    def _group_ids(self, ctx: DCtx, keyvals):
        """Stable sort rows by (presence, key validity+values...); returns
        (perm, gid per sorted row, first-flag, presence per sorted row,
        ngroups). Present rows sort to the front."""
        n = ctx.n
        ops = [jnp.where(ctx.row, 0, 1).astype(jnp.int32)]
        key_ops = []
        for kv in keyvals:
            if kv.valid is not None:
                vop = jnp.where(kv.valid, 0, 1).astype(jnp.int32)
                ops.append(vop)
                key_ops.append(len(ops) - 1)
            arr = _narrow_key(kv)
            filled = jnp.where(_ok(kv, ctx.row), arr,
                               jnp.zeros((), dtype=arr.dtype))
            ops.append(filled)
            key_ops.append(len(ops) - 1)
        ops.append(jnp.arange(n, dtype=jnp.int32))
        sorted_ops = lax.sort(ops, num_keys=len(ops) - 1, is_stable=True)
        perm = sorted_ops[-1]
        present_s = jnp.take(ctx.row, perm)
        iota = jnp.arange(n, dtype=jnp.int32)
        diff = jnp.zeros(n, dtype=bool).at[0].set(True)
        for i in key_ops:
            o = sorted_ops[i]
            diff = diff | jnp.concatenate(
                [jnp.ones(1, bool), o[1:] != o[:-1]])
        first_s = present_s & (diff | (iota == 0))
        gid = jnp.cumsum(first_s.astype(jnp.int32)) - 1
        gid = jnp.clip(gid, 0, n - 1)
        ngroups = jnp.sum(first_s)
        return perm, gid, first_s, present_s, ngroups

    def _agg_arg(self, spec: P.AggSpec, ctx: DCtx):
        if spec.arg is None:
            return None
        return self.eval(spec.arg, ctx)

    def _agg_global(self, spec: P.AggSpec, ctx: DCtx):
        dv = self._agg_arg(spec, ctx)
        if spec.func == "count":
            if dv is None:
                cnt = jnp.sum(ctx.row)
                return (cnt.reshape(1).astype(jnp.int64),
                        jnp.ones(1, bool), None)
            w = _ok(dv, ctx.row)
            if spec.distinct:
                # sentinel-FREE distinct: validity is its own sort
                # operand, so no value (INT32_MAX, +inf, a bool True)
                # can collide with the invalid marker; _narrow_key
                # keeps the value operand on the native i32 sort path
                arr = _narrow_key(dv)
                iv = jnp.where(w, 0, 1).astype(jnp.int32)
                iv_s, v_s = lax.sort([iv, arr], num_keys=2,
                                     is_stable=False)
                w_s = iv_s == 0  # valid rows form the sorted prefix
                newv = jnp.concatenate(
                    [jnp.ones(1, bool), v_s[1:] != v_s[:-1]])
                cnt = jnp.sum(newv & w_s)
            else:
                cnt = jnp.sum(w)
            return (cnt.reshape(1).astype(jnp.int64),
                    jnp.ones(1, bool), None)
        w = _ok(dv, ctx.row)
        cnt = jnp.sum(w)
        valid = (cnt > 0).reshape(1)
        if spec.func == "sum":
            if isinstance(spec.dtype, FloatType):
                s = jnp.sum(jnp.where(w, dv.arr.astype(self.fdt), 0.0))
            else:
                s = jnp.sum(jnp.where(w, dv.arr.astype(jnp.int64), 0))
            return s.reshape(1), valid, None
        if spec.func == "avg":
            f = _to_float(dv.arr, spec.arg.dtype, self.fdt)
            s = jnp.sum(jnp.where(w, f, 0.0))
            return (s / jnp.maximum(cnt, 1)).reshape(1), valid, None
        if spec.func in ("min", "max"):
            if jnp.issubdtype(dv.arr.dtype, jnp.floating):
                fill = jnp.inf if spec.func == "min" else -jnp.inf
                masked = jnp.where(w, dv.arr, fill)
            else:
                fill = I64_MAX if spec.func == "min" else I64_MIN
                masked = jnp.where(w, dv.arr.astype(jnp.int64), fill)
            red = jnp.min(masked) if spec.func == "min" else jnp.max(masked)
            return red.reshape(1), valid, dv.sdict
        if spec.func in ("stddev_samp", "stddev"):
            f = _to_float(dv.arr, spec.arg.dtype, self.fdt)
            s1 = jnp.sum(jnp.where(w, f, 0.0))
            s2 = jnp.sum(jnp.where(w, f * f, 0.0))
            c = cnt.astype(self.fdt)
            var = (s2 - s1 * s1 / jnp.maximum(c, 1)) / jnp.maximum(
                c - 1, 1)
            sd = jnp.sqrt(jnp.maximum(var, 0.0))
            return (jnp.where(cnt > 1, sd, jnp.nan).reshape(1),
                    valid, None)
        raise DeviceExecError(spec.func)

    def _agg_grouped(self, spec: P.AggSpec, ctx: DCtx, perm, gid,
                     present_s, G, starts2, kernel: str = ""):
        dv = self._agg_arg(spec, ctx)
        if spec.func == "count" and spec.distinct:
            return self._count_distinct_grouped(
                spec, ctx, perm, gid, present_s, G)
        if dv is None:  # count(*)
            cnt = self._seg_sum(present_s.astype(jnp.int32), starts2,
                                G).astype(jnp.int64)
            return cnt, None, None
        arr_s = jnp.take(dv.arr, perm)
        w = present_s
        if dv.valid is not None:
            w = w & jnp.take(dv.valid, perm)
        # counts fit int32 (<= capacity); widen only the G-sized result
        cnt = self._seg_sum(w.astype(jnp.int32), starts2,
                            G).astype(jnp.int64)
        if spec.func == "count":
            return cnt, None, None
        valid = cnt > 0
        if spec.func == "sum":
            if isinstance(spec.dtype, FloatType):
                data = jnp.where(w, arr_s.astype(self.fdt), 0.0)
            else:
                data = jnp.where(w, arr_s.astype(jnp.int64), 0)
            return self._seg_sum(data, starts2, G), valid, None
        if spec.func == "avg":
            f = _to_float(arr_s, spec.arg.dtype, self.fdt)
            s = self._seg_sum(jnp.where(w, f, 0.0), starts2, G)
            return s / jnp.maximum(cnt, 1).astype(self.fdt), valid, None
        if spec.func in ("min", "max"):
            isf = jnp.issubdtype(arr_s.dtype, jnp.floating)
            if isf:
                fill = jnp.inf if spec.func == "min" else -jnp.inf
                data = jnp.where(w, arr_s, fill)
            else:
                # stay int32 when host bounds allow: segment_min/max
                # scatter i64 is emulated on TPU
                arr_i = arr_s.astype(jnp.int64)
                if (dv.lo is not None and dv.hi is not None
                        and -2**31 < dv.lo and dv.hi < 2**31 - 1):
                    arr_i = arr_s.astype(jnp.int32)
                fill = (jnp.iinfo(arr_i.dtype).max if spec.func == "min"
                        else jnp.iinfo(arr_i.dtype).min)
                data = jnp.where(w, arr_i, fill)
            if kernel == KX.AGG_SEGSCAN:
                # scan-based grouped min/max over the sorted gids: a
                # segmented scan + a gather at segment ends, riding the
                # same group sort every other AggSpec of this node
                # amortizes — no scatter (segment_min/max emulates
                # element-at-a-time for 64-bit operands on TPU)
                op = (jnp.minimum if spec.func == "min"
                      else jnp.maximum)
                red = KX.seg_reduce_at_ends(op, data, gid, starts2)
                self._note("agg.segscan")
            else:
                seg = (jax.ops.segment_min if spec.func == "min"
                       else jax.ops.segment_max)
                red = seg(data, gid, num_segments=G,
                          indices_are_sorted=True)
                self._note("agg.scatter")
            if not isf and not isinstance(spec.dtype,
                                          (FloatType, DecimalType)):
                red = red.astype(arr_s.dtype)
            elif not isf:
                red = red.astype(jnp.int64)
            return red, valid, dv.sdict
        if spec.func in ("stddev_samp", "stddev"):
            f = _to_float(arr_s, spec.arg.dtype, self.fdt)
            s1 = self._seg_sum(jnp.where(w, f, 0.0), starts2, G)
            s2 = self._seg_sum(jnp.where(w, f * f, 0.0), starts2, G)
            c = cnt.astype(self.fdt)
            var = (s2 - s1 * s1 / jnp.maximum(c, 1)) / jnp.maximum(
                c - 1, 1)
            sd = jnp.sqrt(jnp.maximum(var, 0.0))
            return jnp.where(cnt > 1, sd, jnp.nan), valid, None
        raise DeviceExecError(spec.func)

    def _count_distinct_grouped(self, spec, ctx, perm, gid, present_s, G):
        """Re-sort by (presence, gid, value); count first occurrences of
        (gid, value) among valid rows."""
        dv = self.eval(spec.arg, ctx)
        n = ctx.n
        # narrowed when bounds fit: keeps the 5-operand sort below on
        # the native i32 TPU sort path
        val = _narrow_key(dv)
        if val.dtype not in (jnp.int32, jnp.int64):
            val = dv.arr.astype(jnp.int64)
        w0 = _ok(dv, ctx.row)
        # group id per ORIGINAL row: scatter sorted gid back through perm
        gid_orig = jnp.zeros(n, dtype=gid.dtype).at[perm].set(gid)
        # valid rows sort before invalid within each group so the
        # first-occurrence flag below can't be shadowed by a NULL row
        ops = [jnp.where(ctx.row, 0, 1).astype(jnp.int32),
               gid_orig,
               jnp.where(w0, 0, 1).astype(jnp.int32),
               jnp.where(w0, val, 0), jnp.arange(n, dtype=jnp.int32)]
        sorted_ops = lax.sort(ops, num_keys=4, is_stable=True)
        perm2 = sorted_ops[-1]
        g2 = sorted_ops[1]
        v2 = sorted_ops[3]
        w2 = jnp.take(w0, perm2)
        newpair = jnp.concatenate(
            [jnp.ones(1, bool), (g2[1:] != g2[:-1]) | (v2[1:] != v2[:-1])])
        flag = w2 & newpair
        starts2 = _ss(g2, jnp.arange(G, dtype=g2.dtype))
        cnt = self._seg_sum(flag.astype(jnp.int32), starts2,
                            G).astype(jnp.int64)
        return cnt, None, None

    # ------------------------------------------------------------- windows

    def _run_window(self, node: P.Window) -> DCtx:
        """Sort-based window evaluation: ONE multi-operand lax.sort into
        partition-major/order-minor space, then segmented scans/segment
        reductions, scattered back through the permutation. Stays inside
        the single XLA program (no host round trips)."""
        ctx = self.run(node.child)
        out = DCtx(ctx.n, ctx.row)
        out.cols.update(ctx.cols)
        for name, spec in node.specs:
            out.cols[(node.binding, name)] = self._window_col(spec, ctx)
        return out

    def _window_col(self, spec: P.WindowSpec, ctx: DCtx) -> DVal:
        n = ctx.n
        iota = jnp.arange(n, dtype=jnp.int32)
        ops = [jnp.where(ctx.row, 0, 1).astype(jnp.int32)]
        part_ops = []
        for p in spec.partition:
            dv = self.eval(p, ctx)
            if dv.valid is not None:
                vop = jnp.where(dv.valid, 0, 1).astype(jnp.int32)
                ops.append(vop)
                part_ops.append(len(ops) - 1)
            arr = _narrow_key(dv)
            filled = jnp.where(_ok(dv, ctx.row), arr,
                               jnp.zeros((), dtype=arr.dtype))
            ops.append(filled)
            part_ops.append(len(ops) - 1)
        order_ops = []
        for e, asc, nulls_first in spec.order:
            dv = self.eval(e, ctx)
            if dv.valid is not None:
                rank = (jnp.where(dv.valid, 1, 0) if nulls_first
                        else jnp.where(dv.valid, 0, 1))
                ops.append(rank.astype(jnp.int32))
                order_ops.append(len(ops) - 1)
            arr = _narrow_key(dv)
            if jnp.issubdtype(arr.dtype, jnp.bool_):
                arr = arr.astype(jnp.int32)
            key = arr if asc else -arr
            if dv.valid is not None:
                key = jnp.where(dv.valid, key, jnp.zeros((), key.dtype))
            ops.append(key)
            order_ops.append(len(ops) - 1)
        ops.append(iota)
        sorted_ops = lax.sort(ops, num_keys=len(ops) - 1, is_stable=True)
        perm = sorted_ops[-1]
        present_s = jnp.take(ctx.row, perm)
        part_start = jnp.zeros(n, dtype=bool).at[0].set(True)
        for i in part_ops:
            o = sorted_ops[i]
            part_start = part_start | jnp.concatenate(
                [jnp.ones(1, bool), o[1:] != o[:-1]])
        start_pos = lax.cummax(jnp.where(part_start, iota, 0))
        pid = jnp.cumsum(part_start.astype(jnp.int32)) - 1

        def scatter(res_sorted, valid_sorted=None, lo=None, hi=None):
            arr = jnp.zeros(n, res_sorted.dtype).at[perm].set(res_sorted)
            valid = None
            if valid_sorted is not None:
                valid = jnp.zeros(n, bool).at[perm].set(valid_sorted)
            return DVal(arr, valid, None, lo, hi)

        if spec.func in ("rank", "dense_rank", "row_number"):
            if spec.func == "row_number":
                return scatter((iota - start_pos + 1).astype(jnp.int64),
                               lo=1, hi=n)
            change = part_start
            for i in order_ops:
                o = sorted_ops[i]
                change = change | jnp.concatenate(
                    [jnp.ones(1, bool), o[1:] != o[:-1]])
            if spec.func == "dense_rank":
                c = jnp.cumsum(change.astype(jnp.int64))
                cstart = lax.cummax(jnp.where(part_start, c, 0))
                return scatter(c - cstart + 1, lo=1, hi=n)
            lastchg = lax.cummax(jnp.where(change, iota, 0))
            return scatter((lastchg - start_pos + 1).astype(jnp.int64),
                           lo=1, hi=n)

        # aggregate windows
        if spec.arg is not None:
            dv = self.eval(spec.arg, ctx)
            w = jnp.take(_ok(dv, ctx.row), perm)
            vals = jnp.take(dv.arr, perm)
        else:  # count(*)
            w = present_s
            vals = jnp.ones(n, dtype=jnp.int64)
        running = bool(spec.order)
        is_f = isinstance(spec.dtype, FloatType)
        if spec.func == "avg":
            vals = _to_float(vals, spec.arg.dtype, self.fdt)
        elif is_f:
            vals = vals.astype(self.fdt)
        else:
            vals = vals.astype(jnp.int64)
        G = n
        # per-row partition total, scatter-free: inclusive cumsum
        # differenced at the partition's bounding rows (start_pos is the
        # running partition start; the next start comes from a reversed
        # cummin). segment_sum over n segments is a scatter — emulated
        # and slow for 64-bit operands on TPU.
        nstart = jnp.where(part_start, iota, n)
        nxt = jnp.concatenate(
            [lax.cummin(nstart, reverse=True)[1:],
             jnp.full((1,), n, jnp.int32)])
        pend = jnp.clip(nxt - 1, 0, n - 1)

        def part_total(data):
            csum = jnp.cumsum(data)
            hi = jnp.take(csum, pend)
            lo = jnp.where(start_pos > 0,
                           jnp.take(csum, jnp.clip(start_pos - 1, 0, n - 1)),
                           jnp.zeros((), csum.dtype))
            return hi - lo

        if spec.func == "count":
            src = w.astype(jnp.int32)
            if running:
                res = _seg_scan(lambda a, b: a + b, src, part_start)
            else:
                res = part_total(src)
            return self._window_range_fix(
                spec, scatter, res.astype(jnp.int64), None, part_start,
                order_ops, sorted_ops, pid, running)
        cnt_src = w.astype(jnp.int32)
        if running:
            cnt = _seg_scan(lambda a, b: a + b, cnt_src, part_start)
        else:
            cnt = part_total(cnt_src)
        valid = cnt > 0
        if spec.func in ("sum", "avg"):
            data = jnp.where(w, vals, jnp.zeros((), vals.dtype))
            if running:
                res = _seg_scan(lambda a, b: a + b, data, part_start)
            else:
                res = part_total(data)
            if spec.func == "avg":
                res = res.astype(self.fdt) / jnp.maximum(cnt, 1)
        elif spec.func in ("min", "max"):
            if jnp.issubdtype(vals.dtype, jnp.floating):
                fill = jnp.inf if spec.func == "min" else -jnp.inf
            else:
                fill = I64_MAX if spec.func == "min" else I64_MIN
            data = jnp.where(w, vals, fill)
            op = jnp.minimum if spec.func == "min" else jnp.maximum
            if running:
                res = _seg_scan(op, data, part_start)
            else:
                # whole-partition min/max via the segmented scan's
                # value at the partition's last row (pend is already
                # per-row) — replaces the segment_min/max scatter
                res = KX.part_reduce_broadcast(op, data, part_start,
                                               pend)
        else:
            raise DeviceExecError(f"window func {spec.func}")
        return self._window_range_fix(
            spec, scatter, res, valid, part_start, order_ops, sorted_ops,
            pid, running)

    def _window_range_fix(self, spec, scatter, res, valid, part_start,
                          order_ops, sorted_ops, pid, running):
        """SQL default frame with ORDER BY is RANGE ..CURRENT ROW: peer
        (order-key-tied) rows share the value at the peer group's LAST
        row. 'cum' (ROWS) keeps the per-row running value."""
        if running and spec.frame is None:
            n = res.shape[0]
            change = part_start
            for i in order_ops:
                o = sorted_ops[i]
                change = change | jnp.concatenate(
                    [jnp.ones(1, bool), o[1:] != o[:-1]])
            # each peer group's last row via a reversed running-min
            # over future change positions — no segment_max scatter
            last = KX.last_of_group(change, n)
            res = jnp.take(res, last)
            if valid is not None:
                valid = jnp.take(valid, last)
        return scatter(res, valid)

    # ------------------------------------------------------- sort and misc

    def _run_sort(self, node: P.Sort) -> DCtx:
        ctx = self.run(node.child)
        n = ctx.n
        ops = [jnp.where(ctx.row, 0, 1).astype(jnp.int32)]
        for e, asc, nulls_first in node.keys:
            dv = self.eval(e, ctx)
            if dv.valid is not None:
                rank = jnp.where(dv.valid, 1, 0) if nulls_first \
                    else jnp.where(dv.valid, 0, 1)
                ops.append(rank.astype(jnp.int32))
            arr = _narrow_key(dv)
            if jnp.issubdtype(arr.dtype, jnp.bool_):
                arr = arr.astype(jnp.int32)
            key = arr if asc else -arr  # negation stays in range: bounds checked
            if dv.valid is not None:
                key = jnp.where(dv.valid, key, jnp.zeros((), key.dtype))
            ops.append(key)
        ops.append(jnp.arange(n, dtype=jnp.int32))
        sorted_ops = lax.sort(ops, num_keys=len(ops) - 1, is_stable=True)
        perm = sorted_ops[-1]
        out = ctx.gather(perm)
        out.row = jnp.take(ctx.row, perm)
        return out

    def _compact(self, ctx: DCtx) -> DCtx:
        """Stable-sort present rows to the front (needed before Limit when
        the child didn't already order them)."""
        ops = [jnp.where(ctx.row, 0, 1).astype(jnp.int32),
               jnp.arange(ctx.n, dtype=jnp.int32)]
        sorted_ops = lax.sort(ops, num_keys=1, is_stable=True)
        perm = sorted_ops[-1]
        out = ctx.gather(perm)
        out.row = jnp.take(ctx.row, perm)
        return out

    def _run_limit(self, node: P.Limit) -> DCtx:
        ctx = self.run(node.child)
        if not isinstance(node.child, P.Sort):
            ctx = self._compact(ctx)
        cap = min(node.count, ctx.n)
        out = DCtx(cap, ctx.row[:cap])
        for k, dv in ctx.cols.items():
            out.cols[k] = dv.with_arrays(
                dv.arr[:cap],
                None if dv.valid is None else dv.valid[:cap])
        return out

    def _run_distinct(self, node: P.Distinct) -> DCtx:
        ctx = self.run(node.child)
        b = node.binding
        keyvals = [ctx.cols[(b, name)] for name, _ in node.output]
        perm, gid, first_s, present_s, ngroups = self._group_ids(ctx, keyvals)
        G = ctx.n
        starts = jnp.clip(_ss(gid, jnp.arange(G, dtype=gid.dtype)),
                          0, ctx.n - 1)
        out = DCtx(G, jnp.arange(G, dtype=jnp.int32) < ngroups)
        for (name, _dt), kv in zip(node.output, keyvals):
            arr_g = jnp.take(jnp.take(kv.arr, perm), starts)
            valid_g = None
            if kv.valid is not None:
                valid_g = jnp.take(jnp.take(kv.valid, perm), starts)
            out.cols[(b, name)] = kv.with_arrays(arr_g, valid_g)
        return out

    def _run_setop(self, node: P.SetOp) -> DCtx:
        lctx, rctx = self.run(node.left), self.run(node.right)
        lb, rb = node.left.binding, node.right.binding
        if node.kind.startswith("union"):
            out = DCtx(lctx.n + rctx.n,
                       jnp.concatenate([lctx.row, rctx.row]))
            for (lname, _), (rname, _) in zip(node.left.output,
                                              node.right.output):
                lv = lctx.cols[(lb, lname)]
                rv = rctx.cols[(rb, rname)]
                la, ra = lv.arr, rv.arr
                sdict = lv.sdict
                if lv.sdict is not None or rv.sdict is not None:
                    la, ra, sdict = self._union_dict(lv, rv)
                if la.dtype != ra.dtype:
                    tgt = jnp.promote_types(la.dtype, ra.dtype)
                    la, ra = la.astype(tgt), ra.astype(tgt)
                arr = jnp.concatenate([la, ra])
                valid = None
                if lv.valid is not None or rv.valid is not None:
                    lvv = lv.valid if lv.valid is not None else jnp.ones(
                        lctx.n, bool)
                    rvv = rv.valid if rv.valid is not None else jnp.ones(
                        rctx.n, bool)
                    valid = jnp.concatenate([lvv, rvv])
                out.cols[(lb, lname)] = DVal(
                    arr, valid, sdict,
                    None if (lv.lo is None or rv.lo is None)
                    else min(lv.lo, rv.lo),
                    None if (lv.hi is None or rv.hi is None)
                    else max(lv.hi, rv.hi))
            if node.kind == "union":
                # distinct over the concatenated context, inline
                keyvals = [out.cols[(lb, name)]
                           for name, _ in node.left.output]
                perm, gid, first_s, present_s, ngroups = self._group_ids(
                    out, keyvals)
                G = out.n
                starts = jnp.clip(_ss(gid, jnp.arange(G, dtype=gid.dtype)),
                                  0, G - 1)
                dctx = DCtx(G, jnp.arange(G, dtype=jnp.int32) < ngroups)
                for (name, _dt), kv in zip(node.left.output, keyvals):
                    arr_g = jnp.take(jnp.take(kv.arr, perm), starts)
                    valid_g = None
                    if kv.valid is not None:
                        valid_g = jnp.take(jnp.take(kv.valid, perm), starts)
                    dctx.cols[(lb, name)] = kv.with_arrays(arr_g, valid_g)
                return dctx
            return out
        # INTERSECT / EXCEPT: whole-row membership against the right
        # side. Rows pack into one int64 (pair-aligned per column, plus a
        # validity bit so NULLs compare equal, the SQL set-op rule); the
        # probe is a sorted-membership check. A Distinct above (planner-
        # inserted) provides the set semantics.
        lvals = [lctx.cols[(lb, name)] for name, _ in node.left.output]
        rvals = [rctx.cols[(rb, name)] for name, _ in node.right.output]
        lkey = jnp.zeros(lctx.n, dtype=jnp.int64)
        rkey = jnp.zeros(rctx.n, dtype=jnp.int64)
        total_w = 0
        for lv, rv in zip(lvals, rvals):
            la, ra, lo, hi = self._align_pair(lv, rv)
            w = max((hi - lo).bit_length(), 1)
            ln = jnp.clip(la.astype(jnp.int64) - lo, 0, hi - lo)
            rn = jnp.clip(ra.astype(jnp.int64) - lo, 0, hi - lo)
            if lv.valid is not None or rv.valid is not None:
                lval = (lv.valid if lv.valid is not None
                        else jnp.ones(lctx.n, bool))
                rval = (rv.valid if rv.valid is not None
                        else jnp.ones(rctx.n, bool))
                ln = jnp.where(lval, ln, 0) | (
                    lval.astype(jnp.int64) << w)
                rn = jnp.where(rval, rn, 0) | (
                    rval.astype(jnp.int64) << w)
                w += 1
            total_w += w
            if total_w > 62:
                raise DeviceExecError(
                    f"set-op row too wide to pack ({total_w} bits)")
            lkey = (lkey << w) | ln
            rkey = (rkey << w) | rn
        sent = I64_MAX
        if total_w <= 30:
            # packed whole-row keys fit int32: the membership sort and
            # search run on TPU's native i32 path instead of emulated
            # 64-bit (NDS112)
            lkey = lkey.astype(jnp.int32)
            rkey = rkey.astype(jnp.int32)
            sent = 2**31 - 1
        # ndslint: waive[NDS112] -- keys narrow to int32 above whenever the pack fits 30 bits; wider whole-row packs genuinely need int64
        ks = jnp.sort(jnp.where(rctx.row, rkey, sent))
        pos = jnp.clip(_ss(ks, lkey), 0, rctx.n - 1)
        hit = jnp.take(ks, pos) == lkey
        keep = hit if node.kind == "intersect" else ~hit
        out = DCtx(lctx.n, lctx.row & keep)
        out.cols = lctx.cols
        return out

    def _union_dict(self, lv: DVal, rv: DVal):
        if lv.sdict is None or rv.sdict is None:
            raise DeviceExecError("union of string and non-string column")
        if lv.sdict is rv.sdict or (
                len(lv.sdict) == len(rv.sdict)
                and np.array_equal(lv.sdict, rv.sdict)):
            return lv.arr, rv.arr, lv.sdict
        union, lmap, rmap = self._dict_union(lv.sdict, rv.sdict)
        return (jnp.take(lmap, lv.arr), jnp.take(rmap, rv.arr),
                union.astype(object))

    # ---------------------------------------------------------- expressions

    def eval(self, e: ir.IR, ctx: DCtx) -> DVal:
        if isinstance(e, ir.ColRef):
            return ctx.cols[(e.binding, e.name)]
        if isinstance(e, ir.Lit):
            return self._eval_lit(e, ctx)
        if isinstance(e, ir.ScalarRef):
            v, ok, sdict, _dt = self.scalars[e.plan_id]
            return DVal(jnp.broadcast_to(v, (ctx.n,)),
                        jnp.broadcast_to(ok, (ctx.n,)), sdict)
        if isinstance(e, ir.ParamRef):
            return self._eval_param(e, ctx)
        if isinstance(e, ir.DictParamIR):
            return self._eval_dict_param(e, ctx)
        if isinstance(e, ir.InListParamIR):
            return self._eval_inlist_param(e, ctx)
        if isinstance(e, ir.Arith):
            return self._eval_arith(e, ctx)
        if isinstance(e, ir.Cmp):
            return self._eval_cmp(e, ctx)
        if isinstance(e, ir.BoolOp):
            vals = [self.eval(a, ctx) for a in e.args]
            out = vals[0].arr.astype(bool)
            valid = vals[0].valid
            for dv in vals[1:]:
                if e.op == "and":
                    out = out & dv.arr.astype(bool)
                else:
                    out = out | dv.arr.astype(bool)
                valid = _and_valid(valid, dv.valid)
            return DVal(out, valid)
        if isinstance(e, ir.Not):
            dv = self.eval(e.operand, ctx)
            return DVal(~dv.arr.astype(bool), dv.valid)
        if isinstance(e, ir.Neg):
            dv = self.eval(e.operand, ctx)
            lo = None if dv.hi is None else -dv.hi
            hi = None if dv.lo is None else -dv.lo
            return DVal(-dv.arr, dv.valid, None, lo, hi)
        if isinstance(e, ir.CaseIR):
            return self._eval_case(e, ctx)
        if isinstance(e, ir.LikeIR):
            dv = self.eval(e.operand, ctx)
            if dv.sdict is None:
                raise DeviceExecError("LIKE over non-string")
            table = like_mask(dv.sdict, e.pattern)
            if e.negated:
                table = ~table
            return DVal(jnp.take(jnp.asarray(table), dv.arr), dv.valid)
        if isinstance(e, ir.InListIR):
            return self._eval_inlist(e, ctx)
        if isinstance(e, ir.IsNullIR):
            dv = self.eval(e.operand, ctx)
            if dv.valid is None:
                isnull = jnp.zeros(ctx.n, dtype=bool)
            else:
                isnull = ~dv.valid
            return DVal(~isnull if e.negated else isnull, None)
        if isinstance(e, ir.ExtractIR):
            dv = self.eval(e.operand, ctx)
            y, m, d = _epoch_days_to_civil(dv.arr)
            if e.part == "year":
                return DVal(y, dv.valid, None, 1970, 2199)
            if e.part == "month":
                return DVal(m, dv.valid, None, 1, 12)
            if e.part == "day":
                return DVal(d, dv.valid, None, 1, 31)
            raise DeviceExecError(f"extract {e.part}")
        if isinstance(e, ir.StrMapIR):
            return self._eval_strmap(e, ctx)
        if isinstance(e, ir.ConcatIR):
            return self._eval_concat(e, ctx)
        if isinstance(e, ir.SubstrIR):
            return self._eval_substr(e, ctx)
        if isinstance(e, ir.CastIR):
            return self._eval_cast(e, ctx)
        raise DeviceExecError(f"cannot eval {e!r}")

    def _eval_param(self, e: ir.ParamRef, ctx: DCtx) -> DVal:
        """A hoisted scalar literal: broadcast of the runtime input. No
        value bounds (unlike an inlined Lit) — consumers needing bounds
        fall back to their general paths, identically for every
        variant."""
        v = self.params[f"p{e.index}"]
        if isinstance(e.dtype, FloatType):
            v = v.astype(self.fdt)
        return DVal(jnp.broadcast_to(v, (ctx.n,)), None)

    def _eval_dict_param(self, e: ir.DictParamIR, ctx: DCtx) -> DVal:
        """A hoisted string predicate: boolean membership table over
        the operand's dictionary, bound per request on the host
        (sql/params.bind_params replicates the dictionary transform
        chain, so table length must match the traced dictionary)."""
        dv = self.eval(e.operand, ctx)
        if dv.sdict is None:
            raise DeviceExecError("dict-param predicate over "
                                  "non-string operand")
        tab = self.params[f"d{e.index}"]
        if tab.shape[0] != len(dv.sdict):
            raise DeviceExecError(
                f"dict-param table length {tab.shape[0]} != traced "
                f"dictionary length {len(dv.sdict)} for "
                f"{e.table}.{e.column}")
        if e.negated:
            tab = ~tab
        return DVal(jnp.take(tab, dv.arr), dv.valid)

    def _eval_inlist_param(self, e: ir.InListParamIR, ctx: DCtx) -> DVal:
        """A hoisted numeric IN-list: fixed-width vector input, any-of
        equality (the same compare chain the inlined path unrolls)."""
        dv = self.eval(e.operand, ctx)
        vals = self.params[f"v{e.index}"]
        m = jnp.zeros(ctx.n, dtype=bool)
        for i in range(e.width):
            m = m | (dv.arr == vals[i])
        return DVal(~m if e.negated else m, dv.valid)

    def _eval_lit(self, e: ir.Lit, ctx: DCtx) -> DVal:
        if isinstance(e.dtype, StringType):
            # string literals only appear inside comparisons, which bind
            # them against a dictionary; standalone use keeps the raw value
            if e.value is None:  # NULL string (rolled-up group key)
                return DVal(jnp.zeros(ctx.n, jnp.int32),
                            jnp.zeros(ctx.n, dtype=bool),
                            np.array([""], dtype=object), 0, 0)
            return DVal(jnp.zeros(ctx.n, jnp.int32), None,
                        np.array([e.value], dtype=object), 0, 0)
        v = e.value
        if v is None:
            if isinstance(e.dtype, FloatType):
                return DVal(jnp.zeros(ctx.n, self.fdt),
                            jnp.zeros(ctx.n, dtype=bool))
            dt = jnp.int32 if isinstance(e.dtype, DateType) else jnp.int64
            return DVal(jnp.zeros(ctx.n, dt),
                        jnp.zeros(ctx.n, dtype=bool), None, 0, 0)
        if isinstance(e.dtype, FloatType):
            arr = jnp.full(ctx.n, float(v), dtype=self.fdt)
            return DVal(arr, None)
        iv = int(v)
        dtype = jnp.int64
        if isinstance(e.dtype, (IntType,)) and e.dtype.bits <= 32 \
                and -2**31 <= iv < 2**31:
            dtype = jnp.int32
        if isinstance(e.dtype, DateType):
            dtype = jnp.int32
        return DVal(jnp.full(ctx.n, iv, dtype=dtype), None, None, iv, iv)

    def _eval_arith(self, e: ir.Arith, ctx: DCtx) -> DVal:
        l = self.eval(e.left, ctx)
        r = self.eval(e.right, ctx)
        valid = _and_valid(l.valid, r.valid)
        lt, rt = e.left.dtype, e.right.dtype
        if isinstance(e.dtype, DateType):
            return DVal(l.arr + r.arr, valid)
        if e.op == "/":
            la = _to_float(l.arr, lt, self.fdt)
            ra = _to_float(r.arr, rt, self.fdt)
            return DVal(la / ra, valid)
        if isinstance(e.dtype, FloatType):
            return DVal(_apply(e.op, _to_float(l.arr, lt, self.fdt),
                               _to_float(r.arr, rt, self.fdt)), valid)
        if isinstance(e.dtype, DecimalType):
            if e.op == "*":
                return DVal(l.arr.astype(jnp.int64) * r.arr.astype(jnp.int64),
                            valid)
            s = e.dtype.scale
            la = _rescale(l.arr, _scale_of(lt), s)
            ra = _rescale(r.arr, _scale_of(rt), s)
            return DVal(_apply(e.op, la, ra), valid)
        out = _apply(e.op, l.arr, r.arr)
        lo = hi = None
        if (l.lo is not None and r.lo is not None
                and l.hi is not None and r.hi is not None):
            if e.op == "+":
                lo, hi = l.lo + r.lo, l.hi + r.hi
            elif e.op == "-":
                lo, hi = l.lo - r.hi, l.hi - r.lo
            elif e.op == "*":
                cands = [l.lo * r.lo, l.lo * r.hi, l.hi * r.lo, l.hi * r.hi]
                lo, hi = min(cands), max(cands)
        return DVal(out, valid, None, lo, hi)

    def _eval_cmp(self, e: ir.Cmp, ctx: DCtx) -> DVal:
        lt, rt = e.left.dtype, e.right.dtype
        if isinstance(lt, StringType) or isinstance(rt, StringType):
            return self._string_cmp(e, ctx)
        l = self.eval(e.left, ctx)
        r = self.eval(e.right, ctx)
        valid = _and_valid(l.valid, r.valid)
        la, ra = l.arr, r.arr
        if isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            if isinstance(lt, FloatType) or isinstance(rt, FloatType):
                la, ra = (_to_float(la, lt, self.fdt),
                          _to_float(ra, rt, self.fdt))
            else:
                s = max(_scale_of(lt), _scale_of(rt))
                la = _rescale(la.astype(jnp.int64), _scale_of(lt), s)
                ra = _rescale(ra.astype(jnp.int64), _scale_of(rt), s)
        elif isinstance(lt, FloatType) or isinstance(rt, FloatType):
            la, ra = (_to_float(la, lt, self.fdt),
                          _to_float(ra, rt, self.fdt))
        return DVal(_cmp(e.op, la, ra), valid)

    def _string_cmp(self, e: ir.Cmp, ctx: DCtx) -> DVal:
        lit, col_ir, flipped = None, None, False
        if isinstance(e.right, ir.Lit):
            lit, col_ir = e.right.value, e.left
        elif isinstance(e.left, ir.Lit):
            lit, col_ir, flipped = e.left.value, e.right, True
        if lit is not None:
            dv = self.eval(col_ir, ctx)
            if dv.sdict is None:
                raise DeviceExecError("string compare on non-dict column")
            vals = dv.sdict.astype(str)
            op = e.op
            if flipped:
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
            table = _np_cmp(op, vals, str(lit))
            return DVal(jnp.take(jnp.asarray(table), dv.arr), dv.valid)
        l = self.eval(e.left, ctx)
        r = self.eval(e.right, ctx)
        valid = _and_valid(l.valid, r.valid)
        la, ra, _sd = self._union_dict(l, r)
        return DVal(_cmp(e.op, la, ra), valid)

    def _eval_case(self, e: ir.CaseIR, ctx: DCtx) -> DVal:
        if isinstance(e.dtype, StringType):
            return self._eval_case_string(e, ctx)
        conds, vals, branch_valids = [], [], []
        for c, v in e.whens:
            cdv = self.eval(c, ctx)
            cm = cdv.arr.astype(bool)
            if cdv.valid is not None:
                cm = cm & cdv.valid
            vdv = self.eval(v, ctx)
            conds.append(cm)
            vals.append(self._coerce(vdv, v.dtype, e.dtype))
            branch_valids.append(vdv.valid)
        if e.else_ is not None:
            edv = self.eval(e.else_, ctx)
            default = self._coerce(edv, e.else_.dtype, e.dtype)
            valid = edv.valid  # else-branch validity; refined per row below
        else:
            if isinstance(e.dtype, FloatType):
                default = jnp.zeros(ctx.n, self.fdt)
            else:
                default = jnp.zeros(ctx.n, jnp.int64)
            valid = jnp.zeros(ctx.n, dtype=bool)  # no branch -> NULL
        out = default
        # the result's validity is the SELECTED branch's validity
        need_valid = valid is not None or any(
            bv is not None for bv in branch_valids)
        if need_valid and valid is None:
            valid = jnp.ones(ctx.n, dtype=bool)
        for c, v, bv in zip(reversed(conds), reversed(vals),
                            reversed(branch_valids)):
            out = jnp.where(c, v, out)
            if need_valid:
                bvv = bv if bv is not None else jnp.ones(ctx.n, bool)
                valid = jnp.where(c, bvv, valid)
        return DVal(out, valid)

    def _eval_case_string(self, e: ir.CaseIR, ctx: DCtx) -> DVal:
        """String-valued CASE: union the branch dictionaries on the host,
        remap every branch's codes, then where-chain over int codes —
        strings still never reach the device."""
        branches = []       # (cond_mask, DVal)
        for c, v in e.whens:
            cdv = self.eval(c, ctx)
            cm = cdv.arr.astype(bool)
            if cdv.valid is not None:
                cm = cm & cdv.valid
            branches.append((cm, self.eval(v, ctx)))
        else_dv = (self.eval(e.else_, ctx) if e.else_ is not None
                   else DVal(jnp.zeros(ctx.n, jnp.int32),
                             jnp.zeros(ctx.n, dtype=bool),
                             np.array([""], dtype=object)))
        dvals = [dv for _, dv in branches] + [else_dv]
        for dv in dvals:
            if dv.sdict is None:
                raise DeviceExecError(
                    "string CASE branch without dictionary")
        union = np.array(sorted(set().union(
            *[set(dv.sdict.astype(str)) for dv in dvals])), dtype=object)
        remapped = []
        for dv in dvals:
            table = jnp.asarray(np.searchsorted(
                union.astype(str), dv.sdict.astype(str)).astype(np.int32))
            arr = jnp.take(table, dv.arr)
            if arr.ndim == 0:
                arr = jnp.broadcast_to(arr, (ctx.n,))
            remapped.append(arr)
        out = remapped[-1]
        valid = (else_dv.valid if else_dv.valid is not None
                 else jnp.ones(ctx.n, dtype=bool))
        for (cm, dv), arr in zip(reversed(branches),
                                 reversed(remapped[:-1])):
            out = jnp.where(cm, arr, out)
            bv = (dv.valid if dv.valid is not None
                  else jnp.ones(ctx.n, dtype=bool))
            valid = jnp.where(cm, bv, valid)
        return DVal(out, valid, union, 0, max(len(union) - 1, 0))

    def _coerce(self, dv: DVal, src: DType, dst: DType):
        if repr(src) == repr(dst):
            return dv.arr
        if isinstance(dst, FloatType):
            return _to_float(dv.arr, src, self.fdt)
        if isinstance(dst, DecimalType):
            return _rescale(dv.arr.astype(jnp.int64), _scale_of(src),
                            dst.scale)
        return dv.arr

    def _eval_inlist(self, e: ir.InListIR, ctx: DCtx) -> DVal:
        dv = self.eval(e.operand, ctx)
        if dv.sdict is not None:
            table = np.isin(dv.sdict.astype(str),
                            np.array([str(v) for v in e.values]))
            if e.negated:
                table = ~table
            return DVal(jnp.take(jnp.asarray(table), dv.arr), dv.valid)
        vals = e.values
        if isinstance(e.operand.dtype, DecimalType):
            s = e.operand.dtype.scale
            vals = [int(round(float(x) * 10 ** s)) for x in vals]
        m = jnp.zeros(ctx.n, dtype=bool)
        for v in vals:
            m = m | (dv.arr == v)
        return DVal(~m if e.negated else m, dv.valid)

    def _rewrite_dict(self, dv: DVal, fn) -> DVal:
        """Apply a per-entry string transform to a dictionary-encoded
        value: codes stay on device; the host-side dictionary is
        rewritten, DEDUPED (entries may collide, e.g. upper('abc') ==
        upper('ABC') — grouping hashes codes, so equal strings must
        share a code), re-sorted, and codes remapped."""
        if dv.sdict is None:
            raise DeviceExecError("string transform over non-string")
        newvals = np.array([fn(s) for s in dv.sdict.astype(str)],
                           dtype=object)
        uniq, inverse = np.unique(newvals.astype(str),
                                  return_inverse=True)
        table = jnp.asarray(inverse.astype(np.int32))
        return DVal(jnp.take(table, dv.arr), dv.valid,
                    uniq.astype(object), 0, max(len(uniq) - 1, 0))

    def _eval_strmap(self, e: ir.StrMapIR, ctx: DCtx) -> DVal:
        dv = self.eval(e.operand, ctx)
        f = str.upper if e.op == "upper" else str.lower
        return self._rewrite_dict(dv, f)

    def _eval_concat(self, e: ir.ConcatIR, ctx: DCtx) -> DVal:
        """Literal ⊕ column concat as a dictionary rewrite (q5's
        'store' || s_store_id ids)."""
        dv = self.eval(e.operand, ctx)
        return self._rewrite_dict(
            dv, lambda s: e.prefix + s + e.suffix)

    def _eval_substr(self, e: ir.SubstrIR, ctx: DCtx) -> DVal:
        dv = self.eval(e.operand, ctx)
        if dv.sdict is None:
            raise DeviceExecError("substr over non-string")
        lo = e.start - 1
        hi = None if e.length is None else lo + e.length
        subs = np.array([s[lo:hi] for s in dv.sdict.astype(str)],
                        dtype=object)
        newdict, remap = np.unique(subs.astype(str), return_inverse=True)
        table = jnp.asarray(remap.astype(np.int32))
        return DVal(jnp.take(table, dv.arr), dv.valid,
                    newdict.astype(object), 0, max(len(newdict) - 1, 0))

    def _eval_cast(self, e: ir.CastIR, ctx: DCtx) -> DVal:
        dv = self.eval(e.operand, ctx)
        src = e.operand.dtype
        if isinstance(e.dtype, FloatType):
            return DVal(_to_float(dv.arr, src, self.fdt), dv.valid)
        if isinstance(e.dtype, IntType):
            if isinstance(src, DecimalType):
                return DVal((dv.arr // 10 ** src.scale).astype(jnp.int64),
                            dv.valid)
            return DVal(dv.arr.astype(jnp.int64), dv.valid, None,
                        dv.lo, dv.hi)
        if isinstance(e.dtype, DecimalType):
            s = e.dtype.scale
            if isinstance(src, DecimalType):
                return DVal(_rescale(dv.arr, src.scale, s), dv.valid)
            if isinstance(src, IntType):
                return DVal(dv.arr.astype(jnp.int64) * 10 ** s, dv.valid)
            return DVal(jnp.round(dv.arr * 10 ** s).astype(jnp.int64),
                        dv.valid)
        raise DeviceExecError(f"cast to {e.dtype}")


def _apply(op, l, r):
    if op == "+":
        return l + r
    if op == "-":
        return l - r
    if op == "*":
        return l * r
    if op == "%":
        return l % r
    raise DeviceExecError(op)


def _cmp(op, l, r):
    if op == "=":
        return l == r
    if op == "<>":
        return l != r
    if op == "<":
        return l < r
    if op == "<=":
        return l <= r
    if op == ">":
        return l > r
    if op == ">=":
        return l >= r
    raise DeviceExecError(op)


def _np_cmp(op, vals, lit):
    if op == "=":
        return vals == lit
    if op == "<>":
        return vals != lit
    if op == "<":
        return vals < lit
    if op == "<=":
        return vals <= lit
    if op == ">":
        return vals > lit
    if op == ">=":
        return vals >= lit
    raise DeviceExecError(op)


PRECISIONS = {"f64": None, "f32": "float32", "bf16": "bfloat16"}


def make_device_factory(precision: str = "f64"):
    """Session executor factory that keeps ONE DeviceExecutor per table
    registry, preserving its device buffers and compile cache across
    queries (the load-once, query-many lifecycle of a power run,
    `nds/nds_power.py:184-322`).

    precision selects the on-device float compute dtype
    (`engine.precision`): f64 matches the CPU oracle exactly (emulated
    on TPU); f32/bf16 run native on the VPU at reduced precision — the
    floats-mode analog of the reference's variableFloatAgg tradeoff."""
    if precision not in PRECISIONS:
        raise ValueError(f"unknown engine.precision {precision!r}")
    fdt = PRECISIONS[precision]
    holder: dict = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            ex = DeviceExecutor(
                tables, None if fdt is None else getattr(jnp, fdt))
            holder["ex"] = ex
        return ex

    # DML invalidation hooks (Session.invalidate): a wholesale
    # invalidate drops the executor; the SCOPED variant keeps it —
    # only the mutated tables' buffers/bounds/scan-views go, and every
    # other table's warm buffers and the whole compile cache survive
    factory.invalidate = holder.clear

    def invalidate_tables(names):
        ex = holder.get("ex")
        if ex is not None:
            ex.invalidate_tables(names)

    factory.invalidate_tables = invalidate_tables
    return factory
