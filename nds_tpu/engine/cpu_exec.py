"""CPU oracle executor: interprets logical plans with numpy/pandas.

Role: the differential-validation ground truth. The reference's oracle is
the same workload run on CPU Spark, compared row-by-row with epsilon
(`nds/nds_validate.py:48-114`); here the oracle is an independent
interpretation of the same logical plan — separate code path from the
device engine (no jax, no static shapes, no dictionary tricks for
evaluation: strings are materialized), so engine bugs don't cancel out.

Decimals stay scaled int64 through +,-,* and comparisons (exact); division
and AVG go through float64, matching the IR type policy.
"""

from __future__ import annotations

import re

import numpy as np
import pandas as pd

from nds_tpu.engine.types import (
    DateType, DecimalType, DType, FloatType, IntType, StringType,
)
from nds_tpu.io.host_table import HostTable
from nds_tpu.sql import ir
from nds_tpu.sql import plan as P


class ExecError(RuntimeError):
    pass


class Context:
    """One relation's materialized columns keyed by (binding, name)."""

    def __init__(self, nrows: int):
        self.nrows = nrows
        self.cols: dict[tuple, np.ndarray] = {}
        self.valid: dict[tuple, np.ndarray | None] = {}

    def put(self, key, arr, valid=None):
        self.cols[key] = arr
        self.valid[key] = valid

    def take(self, idx: np.ndarray, matched: np.ndarray | None = None,
             only_bindings: set | None = None) -> "Context":
        out = Context(len(idx))
        for k, v in self.cols.items():
            if only_bindings is not None and k[0] not in only_bindings:
                continue
            arr = v[idx]
            val = self.valid[k]
            val = val[idx] if val is not None else None
            if matched is not None:
                val = matched if val is None else (val & matched)
            out.put(k, arr, val)
        return out

    def merge(self, other: "Context") -> "Context":
        assert self.nrows == other.nrows
        out = Context(self.nrows)
        out.cols.update(self.cols)
        out.cols.update(other.cols)
        out.valid.update(self.valid)
        out.valid.update(other.valid)
        return out

    def mask(self, m: np.ndarray) -> "Context":
        idx = np.nonzero(m)[0]
        return self.take(idx)


def _scale_of(t: DType) -> int:
    return t.scale if isinstance(t, DecimalType) else 0


def _to_float(arr: np.ndarray, t: DType) -> np.ndarray:
    if isinstance(t, DecimalType):
        return arr.astype(np.float64) / 10**t.scale
    return arr.astype(np.float64)


def _like_to_segments(pattern: str):
    """'%a%b' -> (anchored_start, anchored_end, [segments])."""
    segs = pattern.split("%")
    return (not pattern.startswith("%"), not pattern.endswith("%"),
            [s for s in segs if s])


def like_mask(values: np.ndarray, pattern: str) -> np.ndarray:
    """Vectorized SQL LIKE over a unicode array ('_' not needed by the
    benchmark patterns; falls back to regex if present)."""
    vals = values.astype(str)
    if "_" in pattern:
        rx = re.compile(
            "^" + re.escape(pattern).replace("%", ".*").replace("_", ".")
            + "$", re.DOTALL)
        return np.array([bool(rx.match(v)) for v in vals])
    start_anchor, end_anchor, segs = _like_to_segments(pattern)
    u = np.asarray(vals, dtype=np.str_)
    ok = np.ones(len(u), dtype=bool)
    pos = np.zeros(len(u), dtype=np.int64)
    for i, seg in enumerate(segs):
        if i == 0 and start_anchor:
            found = np.char.startswith(u, seg)
            ok &= found
            pos = np.where(found, len(seg), pos)
        else:
            idx = np.char.find(u, seg)
            # search from current position
            idx2 = np.array([v.find(seg, p) for v, p in zip(vals, pos)])
            found = idx2 >= 0
            ok &= found
            pos = np.where(found, idx2 + len(seg), pos)
    if segs and end_anchor:
        last = segs[-1]
        if len(segs) == 1 and start_anchor:
            ok &= np.char.str_len(u) == len(last)  # exact match
        else:
            ok &= np.char.endswith(u, last)
            # ensure the end match doesn't precede previous segments
    return ok


class CpuExecutor:
    def __init__(self, tables: dict[str, HostTable]):
        self.tables = tables
        self._node_cache: dict[int, Context] = {}
        self.scalars: dict[int, tuple] = {}  # id -> (value, dtype)

    # ----------------------------------------------------------------- API

    def execute(self, planned: P.PlannedQuery):
        from nds_tpu.resilience import faults, watchdog
        # parameterized plans (sql/params.py) substitute their literals
        # back: the oracle evaluates constants, and stays byte-exact
        # with the pre-parameterization plan by construction
        from nds_tpu.sql import params as sqlparams
        planned = sqlparams.inline(planned)
        # chaos site shared with the device executors: CPU-backend runs
        # exercise the retry/fallback machinery without a chip
        faults.fault_point("device.execute", executor="CpuExecutor")
        watchdog.beat("engine", phase="device.execute",
                      executor="CpuExecutor")
        # memory HWM (obs/memwatch): the oracle has no allocator to
        # sample — account the scanned tables' host bytes instead so
        # CPU runs still report a per-query working-set gauge
        from nds_tpu.obs import memwatch
        scanned = {node.table
                   for root in [planned.root, *planned.scalar_subplans]
                   for node in P.walk_plan(root)
                   if isinstance(node, P.Scan)}
        scan_bytes = sum(memwatch.table_bytes(self.tables[t])
                         for t in scanned if t in self.tables)
        memwatch.add_live(scan_bytes)
        try:
            return self._execute_inner(planned)
        finally:
            memwatch.sub_live(scan_bytes)

    def _execute_inner(self, planned: P.PlannedQuery):
        self._node_cache.clear()
        self.scalars.clear()
        for i, sub in enumerate(planned.scalar_subplans):
            ctx = self.run(sub)
            name, dt = sub.output[0]
            arr = ctx.cols[(sub.binding, name)]
            if len(arr) != 1:
                raise ExecError(
                    f"scalar subquery returned {len(arr)} rows")
            valid = ctx.valid[(sub.binding, name)]
            v = None if (valid is not None and not valid[0]) else arr[0]
            self.scalars[i] = (v, dt)
        ctx = self.run(planned.root)
        return self._result(ctx, planned.root, planned.column_names)

    def _result(self, ctx: Context, root: P.Node, names: list[str]):
        b = root.binding
        cols, dtypes = [], []
        for name, dt in root.output:
            arr = ctx.cols[(b, name)]
            cols.append(arr)
            dtypes.append(dt)
        return ResultTable(names, cols, dtypes,
                           [ctx.valid[(b, n)] for n, _ in root.output])

    # --------------------------------------------------------------- nodes

    def run(self, node: P.Node) -> Context:
        nid = id(node)
        if nid in self._node_cache:
            return self._node_cache[nid]
        method = getattr(self, "_run_" + type(node).__name__.lower())
        ctx = method(node)
        # ndslint: waive[NDS101] -- cleared at execute() entry; the running plan pins nodes
        self._node_cache[nid] = ctx
        return ctx

    def _run_scan(self, node: P.Scan) -> Context:
        t = self.tables[node.table]
        ctx = Context(t.nrows)
        for name, _dt in node.output:
            col = t.columns[name]
            arr = col.decode() if col.is_string else col.values
            ctx.put((node.binding, name), np.asarray(arr), col.null_mask)
        from nds_tpu.columnar import delta
        live = delta.live_mask(t)
        if live is not None:
            # delta deleted-row bitmask: DF_*-deleted rows drop out of
            # every scan before any predicate sees them
            ctx = ctx.mask(live)
        for pred in node.filters:
            m, mv = self.eval(pred, ctx)
            m = m.astype(bool)
            if mv is not None:
                m &= mv
            ctx = ctx.mask(m)
        return ctx

    def _run_derivedscan(self, node: P.DerivedScan) -> Context:
        child_ctx = self.run(node.child)
        cb = node.child.binding
        out = Context(child_ctx.nrows)
        for name, _dt in node.child.output:
            out.put((node.binding, name), child_ctx.cols[(cb, name)],
                    child_ctx.valid[(cb, name)])
        return out

    def _run_filter(self, node: P.Filter) -> Context:
        ctx = self.run(node.child)
        m, mv = self.eval(node.predicate, ctx)
        m = m.astype(bool)
        if mv is not None:
            m = m & mv
        return ctx.mask(m)

    def _run_project(self, node: P.Project) -> Context:
        ctx = self.run(node.child)
        out = Context(ctx.nrows)
        for name, e in node.exprs:
            arr, valid = self.eval(e, ctx)
            if np.isscalar(arr) or arr.ndim == 0:
                arr = np.full(ctx.nrows, arr)
            out.put((node.binding, name), arr, valid)
        return out

    def _key_frame(self, ctx: Context, keys: list[ir.IR],
                   side: str = "") -> pd.DataFrame:
        """Join-key frame. NULL keys must never match anything (SQL
        equality semantics; pandas merge would happily pair NaN with
        NaN), so invalid rows get a per-side, per-row unique sentinel."""
        data = {}
        # never-matching sentinel blocks per side, far below any real
        # key domain (keys are sks/dates/codes, all > -2^40)
        base = (np.iinfo(np.int64).min // 4) * (2 if side == "L" else 3)
        for i, k in enumerate(keys):
            arr, valid = self.eval(k, ctx)
            is_obj = (isinstance(arr.dtype, object.__class__)
                      or arr.dtype == object)
            if is_obj:
                arr = arr.astype(str).astype(object)
            if valid is not None and not valid.all():
                bad = np.nonzero(~valid)[0]
                if not is_obj and np.issubdtype(arr.dtype, np.integer):
                    arr = arr.astype(np.int64)
                    arr[bad] = base + bad
                else:
                    arr = arr.astype(object)
                    arr[bad] = [f"__null{side}{j}" for j in bad]
            data[f"k{i}"] = arr
        return pd.DataFrame(data)

    def _run_join(self, node: P.Join) -> Context:
        lctx, rctx = self.run(node.left), self.run(node.right)
        if not node.left_keys:  # cross join
            li = np.repeat(np.arange(lctx.nrows), rctx.nrows)
            ri = np.tile(np.arange(rctx.nrows), lctx.nrows)
            out = lctx.take(li).merge(rctx.take(ri))
            return out
        lk = self._key_frame(lctx, node.left_keys, "L")
        rk = self._key_frame(rctx, node.right_keys, "R")
        lk["_li"] = np.arange(lctx.nrows)
        rk["_ri"] = np.arange(rctx.nrows)
        how = {"left": "left", "full": "outer"}.get(node.kind, "inner")
        m = lk.merge(rk, on=[f"k{i}" for i in range(len(node.left_keys))],
                     how=how)
        if node.kind == "full":
            if node.residual is not None:
                raise ExecError("FULL OUTER residual unsupported")
            lmatched = m["_li"].notna().to_numpy()
            rmatched = m["_ri"].notna().to_numpy()
            li = np.where(lmatched, m["_li"].fillna(0).to_numpy(),
                          0).astype(np.int64)
            ri = np.where(rmatched, m["_ri"].fillna(0).to_numpy(),
                          0).astype(np.int64)
            return lctx.take(li, matched=lmatched).merge(
                rctx.take(ri, matched=rmatched))
        li = m["_li"].to_numpy()
        if node.kind == "left":
            matched = m["_ri"].notna().to_numpy()
            ri = np.where(matched, m["_ri"].fillna(0).to_numpy(), 0).astype(
                np.int64)
            out = lctx.take(li).merge(rctx.take(ri, matched=matched))
            if node.residual is not None:
                rm, rmv = self.eval(node.residual, out)
                rm = rm.astype(bool)
                if rmv is not None:
                    rm &= rmv
                keep_match = matched & rm
                # left join: keep every left row; null out right side where
                # the residual fails, then dedupe to one row per unmatched li
                unmatched_li = np.setdiff1d(li, li[keep_match])
                lidx = np.concatenate([li[keep_match], unmatched_li])
                ridx = np.concatenate(
                    [ri[keep_match], np.zeros(len(unmatched_li), np.int64)])
                mflag = np.concatenate(
                    [np.ones(keep_match.sum(), bool),
                     np.zeros(len(unmatched_li), bool)])
                out = lctx.take(lidx).merge(rctx.take(ridx, matched=mflag))
            return out
        ri = m["_ri"].to_numpy().astype(np.int64)
        out = lctx.take(li).merge(rctx.take(ri))
        if node.residual is not None:
            rm, rmv = self.eval(node.residual, out)
            rm = rm.astype(bool)
            if rmv is not None:
                rm &= rmv
            out = out.mask(rm)
        return out

    def _run_semijoin(self, node: P.SemiJoin) -> Context:
        lctx, rctx = self.run(node.left), self.run(node.right)
        if node.left_keys:
            lk = self._key_frame(lctx, node.left_keys, "L")
            rk = self._key_frame(rctx, node.right_keys, "R")
            lk["_li"] = np.arange(lctx.nrows)
            rk["_ri"] = np.arange(rctx.nrows)
            m = lk.merge(rk, on=[f"k{i}" for i in range(len(node.left_keys))],
                         how="inner")
            li = m["_li"].to_numpy()
            ri = m["_ri"].to_numpy()
        else:
            li = np.repeat(np.arange(lctx.nrows), rctx.nrows)
            ri = np.tile(np.arange(rctx.nrows), lctx.nrows)
        if node.residual is not None:
            combined = lctx.take(li).merge(rctx.take(ri))
            rm, rmv = self.eval(node.residual, combined)
            rm = rm.astype(bool)
            if rmv is not None:
                rm &= rmv
            li = li[rm]
        exists = np.zeros(lctx.nrows, dtype=bool)
        exists[li] = True
        return lctx.mask(~exists if node.anti else exists)

    def _run_aggregate(self, node: P.Aggregate) -> Context:
        ctx = self.run(node.child)
        b = node.binding
        n_keys = len(node.group_keys)
        if n_keys == 0:
            out = Context(1)
            for name, spec in node.aggs:
                v = self._agg_all(spec, ctx)
                if v is None:  # SQL: aggregate over empty input is NULL
                    out.put((b, name), np.zeros(1, dtype=np.int64),
                            np.array([False]))
                else:
                    out.put((b, name), np.array([v]))
            return out
        # SQL GROUP BY: NULL keys form one group and the output key is
        # NULL — grouping must factor in validity, never the raw fill
        # value (ADVICE r1: NULL group corruption)
        keyframes = {}
        keyvals = []
        for i, (kname, kexpr) in enumerate(node.group_keys):
            arr, v = self.eval(kexpr, ctx)
            keyvals.append((arr, v))
            col = arr if arr.dtype != object else arr.astype(str)
            if v is not None:
                fill = col[0] if len(col) else 0
                col = np.where(v, col, fill)
                keyframes[f"k{i}n"] = ~v
            keyframes[f"k{i}"] = col
        df = pd.DataFrame(keyframes)
        if len(df) == 0:
            # this pandas raises on MultiIndex.from_frame of an empty
            # frame; an empty input groups to zero groups either way
            codes, ngroups = np.zeros(0, dtype=np.int64), 0
        else:
            codes, uniques = pd.factorize(
                pd.MultiIndex.from_frame(df) if len(df.columns) > 1
                else df.iloc[:, 0], sort=False)
            ngroups = len(uniques)
        out = Context(ngroups)
        # representative (first-occurrence) row per group for key values
        rev = np.arange(len(codes))[::-1]
        first = np.full(ngroups, -1, dtype=np.int64)
        first[codes[rev]] = rev
        for (kname, _kexpr), (arr, v) in zip(node.group_keys, keyvals):
            out.put((b, kname), arr[first],
                    None if v is None else v[first])
        for name, spec in node.aggs:
            vals, gvalid = self._agg_grouped(spec, ctx, codes, ngroups)
            out.put((b, name), vals, gvalid)
        return out

    def _agg_input(self, spec: P.AggSpec, ctx: Context):
        if spec.arg is None:
            return None, None
        return self.eval(spec.arg, ctx)

    def _agg_all(self, spec: P.AggSpec, ctx: Context):
        arr, valid = self._agg_input(spec, ctx)
        if spec.func == "count":
            if arr is None:
                return ctx.nrows
            n = ctx.nrows if valid is None else int(valid.sum())
            if spec.distinct:
                a = arr if valid is None else arr[valid]
                return len(pd.unique(a))
            return n
        if valid is not None:
            arr = arr[valid]
        if len(arr) == 0:
            return None  # SQL NULL
        if spec.func == "sum":
            return arr.sum()
        if spec.func == "min":
            return arr.min()
        if spec.func == "max":
            return arr.max()
        if spec.func == "avg":
            return _to_float(arr, spec.arg.dtype).mean()
        if spec.func in ("stddev_samp", "stddev"):
            f = _to_float(arr, spec.arg.dtype)
            return np.nan if len(f) < 2 else float(np.std(f, ddof=1))
        raise ExecError(spec.func)

    def _agg_grouped(self, spec: P.AggSpec, ctx: Context,
                     codes: np.ndarray, ngroups: int):
        """-> (values, validity-or-None). A group whose every input is
        NULL aggregates to NULL for sum/min/max/avg (and stddev needs
        two valid rows) — only count stays 0-valued (SQL semantics the
        device engine already implements)."""
        arr, valid = self._agg_input(spec, ctx)
        if spec.func == "count":
            if spec.distinct:
                df = pd.DataFrame({"g": codes, "v": arr.astype(str)
                                   if arr.dtype == object else arr})
                if valid is not None:
                    df = df[valid]
                s = df.groupby("g")["v"].nunique()
                out = np.zeros(ngroups, dtype=np.int64)
                out[s.index.to_numpy()] = s.to_numpy()
                return out, None
            if arr is None:
                return (np.bincount(codes, minlength=ngroups)
                        .astype(np.int64), None)
            m = valid if valid is not None else np.ones(len(arr), bool)
            return (np.bincount(codes[m], minlength=ngroups)
                    .astype(np.int64), None)
        m = valid if valid is not None else None
        vals = arr if m is None else arr[m]
        gcodes = codes if m is None else codes[m]
        nvalid = np.bincount(gcodes, minlength=ngroups)
        gvalid = (None if ngroups and nvalid.min() > 0
                  else nvalid > 0)
        if spec.func == "sum":
            if isinstance(spec.dtype, FloatType):
                return (np.bincount(gcodes,
                                    weights=vals.astype(np.float64),
                                    minlength=ngroups), gvalid)
            # integer/decimal sums accumulate in int64 — exact (the decimal
            # policy this oracle exists to enforce; bincount would round
            # through float64 past 2^53)
            out = np.zeros(ngroups, dtype=np.int64)
            np.add.at(out, gcodes, vals.astype(np.int64))
            return out, gvalid
        if spec.func == "avg":
            f = _to_float(vals, spec.arg.dtype)
            s = np.bincount(gcodes, weights=f, minlength=ngroups)
            c = np.bincount(gcodes, minlength=ngroups)
            with np.errstate(invalid="ignore"):
                return s / np.maximum(c, 1), gvalid
        if spec.func in ("min", "max"):
            df = pd.DataFrame({"g": gcodes, "v": vals})
            s = df.groupby("g")["v"].min() if spec.func == "min" \
                else df.groupby("g")["v"].max()
            out = np.zeros(ngroups, dtype=vals.dtype)
            out[s.index.to_numpy()] = s.to_numpy()
            return out, gvalid
        if spec.func in ("stddev_samp", "stddev"):
            f = _to_float(vals, spec.arg.dtype)
            s = pd.DataFrame({"g": gcodes, "v": f}).groupby("g")["v"].std(
                ddof=1)
            out = np.full(ngroups, np.nan)
            out[s.index.to_numpy()] = s.to_numpy()
            # stddev_samp needs >= 2 valid rows
            two = np.bincount(gcodes, minlength=ngroups) >= 2
            return np.nan_to_num(out), two if not two.all() else None
        raise ExecError(spec.func)

    def _run_window(self, node: P.Window) -> Context:
        """Namespace-extending window evaluation (pandas per spec)."""
        ctx = self.run(node.child)
        out = Context(ctx.nrows)
        out.cols.update(ctx.cols)
        out.valid.update(ctx.valid)
        for name, spec in node.specs:
            arr, valid = self._window_col(spec, ctx)
            out.put((node.binding, name), arr, valid)
        return out

    def _window_col(self, spec: P.WindowSpec, ctx: Context):
        n = ctx.nrows
        # partition codes (validity-aware, like GROUP BY)
        if spec.partition:
            frames = {}
            for i, p in enumerate(spec.partition):
                a, v = self.eval(p, ctx)
                col = a.astype(str) if a.dtype == object else a
                if v is not None:
                    frames[f"p{i}n"] = ~v
                    col = np.where(v, col, col[0] if len(col) else 0)
                frames[f"p{i}"] = col
            pdf = pd.DataFrame(frames)
            if len(pdf) == 0:
                # MultiIndex.from_frame raises on empty frames here
                codes = np.zeros(0, dtype=np.int64)
            else:
                codes, _ = pd.factorize(
                    pd.MultiIndex.from_frame(pdf) if len(pdf.columns) > 1
                    else pdf.iloc[:, 0], sort=False)
        else:
            codes = np.zeros(n, dtype=np.int64)
        # sorted space: partition-major, order-minor (stable); NULL order
        # keys sort per nulls_first (default last), matching the device
        idx = np.arange(n)
        for e, asc, nf in reversed(spec.order):
            a, v = self.eval(e, ctx)
            a2 = a[idx]
            if a2.dtype == object:
                a2 = a2.astype(str)
            key = a2 if asc else _rank_desc(a2)
            idx = idx[np.argsort(key, kind="stable")]
            if v is not None:
                v2 = v[idx]
                rank = np.where(v2, 1, 0) if nf else np.where(v2, 0, 1)
                idx = idx[np.argsort(rank, kind="stable")]
        idx = idx[np.argsort(codes[idx], kind="stable")]
        pc = codes[idx]
        part_start = np.concatenate([[True], pc[1:] != pc[:-1]])
        pos = np.arange(n)
        start_pos = np.maximum.accumulate(np.where(part_start, pos, 0))

        def scatter(res_sorted, valid_sorted=None):
            o = np.empty(n, dtype=np.asarray(res_sorted).dtype)
            o[idx] = res_sorted
            vo = None
            if valid_sorted is not None and not valid_sorted.all():
                vo = np.empty(n, dtype=bool)
                vo[idx] = valid_sorted
            return o, vo

        def order_change(base):
            """OR in order-key (value AND validity) change flags."""
            change = base.copy()
            for e, _asc, _nf in spec.order:
                a, v = self.eval(e, ctx)
                a2 = a[idx]
                if a2.dtype == object:
                    a2 = a2.astype(str)
                if v is not None:
                    v2 = v[idx]
                    a2 = np.where(v2, a2, a2[0] if len(a2) else 0)
                    change |= np.concatenate([[True], v2[1:] != v2[:-1]])
                change |= np.concatenate([[True], a2[1:] != a2[:-1]])
            return change

        if spec.func in ("rank", "dense_rank", "row_number"):
            if spec.func == "row_number":
                return scatter(pos - start_pos + 1)
            change = order_change(part_start)
            if spec.func == "dense_rank":
                c = np.cumsum(change)
                cstart = np.maximum.accumulate(np.where(part_start, c, 0))
                return scatter(c - cstart + 1)
            lastchg = np.maximum.accumulate(np.where(change, pos, 0))
            return scatter(lastchg - start_pos + 1)

        # aggregate windows
        if spec.arg is not None:
            a, v = self.eval(spec.arg, ctx)
            w = np.ones(n, bool) if v is None else v
            vals = a[idx]
            w = w[idx]
        else:  # count(*)
            vals = np.ones(n, dtype=np.int64)
            w = np.ones(n, bool)
        running = bool(spec.order)
        df = pd.DataFrame({"g": pc})
        if spec.func == "count":
            cnt_src = w.astype(np.int64)
            res = (df.assign(v=cnt_src).groupby("g")["v"].cumsum()
                   if running else
                   df.assign(v=cnt_src).groupby("g")["v"].transform("sum"))
            res = res.to_numpy()
            out_valid = None
            cnt = None
        else:
            is_f = vals.dtype.kind == "f"
            fvals = vals.astype(np.float64) if is_f else vals
            if spec.func == "avg":
                fvals = _to_float(vals, spec.arg.dtype)
                is_f = True
            g = df.assign(
                v=np.where(w, fvals, 0 if spec.func in ("sum", "avg")
                           else fvals),
                c=w.astype(np.int64)).groupby("g")
            if running:
                cnt = g["c"].cumsum().to_numpy()
            else:
                cnt = g["c"].transform("sum").to_numpy()
            if spec.func in ("sum", "avg"):
                res = (g["v"].cumsum() if running
                       else g["v"].transform("sum")).to_numpy()
                if spec.func == "avg":
                    with np.errstate(invalid="ignore"):
                        res = res / np.maximum(cnt, 1)
            elif spec.func in ("min", "max"):
                masked = pd.Series(
                    fvals.astype(np.float64)).where(w)
                g2 = pd.DataFrame({"g": pc, "v": masked}).groupby("g")
                if running:
                    res = (g2["v"].cummin() if spec.func == "min"
                           else g2["v"].cummax())
                    # pandas cum* leaves NaN AT null positions instead
                    # of carrying the running extremum forward — ffill
                    # within the partition (SQL: max over rows so far)
                    res = res.groupby(pc).ffill().to_numpy()
                else:
                    res = g2["v"].transform(spec.func).to_numpy()
                res = np.nan_to_num(res)
                if not is_f:
                    res = np.round(res).astype(np.int64)
            else:
                raise ExecError(f"window func {spec.func}")
            out_valid = cnt > 0
        if spec.func == "sum" and not is_f:
            res = np.round(res).astype(np.int64)
        if running and spec.frame is None:
            # SQL default frame with ORDER BY: RANGE ... CURRENT ROW —
            # tie rows (order-key peers) share the value at the peer
            # group's last row
            change = order_change(part_start)
            pg = np.cumsum(change)
            res = pd.DataFrame({"g": pg, "v": res}).groupby(
                "g")["v"].transform("last").to_numpy()
            if out_valid is not None:
                out_valid = pd.DataFrame(
                    {"g": pg, "v": out_valid}).groupby("g")["v"].transform(
                    "last").to_numpy().astype(bool)
        return scatter(res, out_valid)

    def _run_sort(self, node: P.Sort) -> Context:
        ctx = self.run(node.child)
        idx = np.arange(ctx.nrows)
        # stable sort from last key to first; NULL keys per nulls_first
        # (default last), matching the device engine
        for e, asc, nf in reversed(node.keys):
            arr, v = self.eval(e, ctx)
            arr = arr[idx]
            if arr.dtype == object:
                arr = arr.astype(str)
            key = arr if asc else _rank_desc(arr)
            idx = idx[np.argsort(key, kind="stable")]
            if v is not None:
                v2 = v[idx]
                rank = np.where(v2, 1, 0) if nf else np.where(v2, 0, 1)
                idx = idx[np.argsort(rank, kind="stable")]
        return ctx.take(idx)

    def _run_limit(self, node: P.Limit) -> Context:
        ctx = self.run(node.child)
        return ctx.take(np.arange(min(node.count, ctx.nrows)))

    def _run_distinct(self, node: P.Distinct) -> Context:
        ctx = self.run(node.child)
        b = node.binding
        data = {}
        for n, _ in node.output:
            arr = ctx.cols[(b, n)]
            data[n] = arr.astype(str) if arr.dtype == object else arr
            v = ctx.valid[(b, n)]
            if v is not None:  # NULLs compare equal under DISTINCT
                data[n + "#n"] = ~v
                data[n] = np.where(v, data[n], data[n][0] if len(arr) else 0)
        df = pd.DataFrame(data)
        keep = ~df.duplicated().to_numpy()
        return ctx.mask(keep)

    def _setop_frame(self, ctx: Context, node: P.Node) -> pd.DataFrame:
        b = node.binding
        data = {}
        for i, (name, _) in enumerate(node.output):
            arr = ctx.cols[(b, name)]
            v = ctx.valid[(b, name)]
            col = pd.Series(arr.astype(str) if arr.dtype == object else arr)
            if v is not None:
                col = col.mask(~v)
            data[f"c{i}"] = col
        return pd.DataFrame(data)

    def _run_setop(self, node: P.SetOp) -> Context:
        lctx, rctx = self.run(node.left), self.run(node.right)
        lb = node.left.binding
        if node.kind.startswith("union"):
            out = Context(lctx.nrows + rctx.nrows)
            rb = node.right.binding
            for (lname, _), (rname, _) in zip(node.left.output,
                                              node.right.output):
                a = np.concatenate([lctx.cols[(lb, lname)],
                                    rctx.cols[(rb, rname)]])
                lv = lctx.valid[(lb, lname)]
                rv = rctx.valid[(rb, rname)]
                if lv is not None or rv is not None:
                    lv = lv if lv is not None else np.ones(lctx.nrows, bool)
                    rv = rv if rv is not None else np.ones(rctx.nrows, bool)
                    out.put((lb, lname), a, np.concatenate([lv, rv]))
                else:
                    out.put((lb, lname), a)
            return out
        # intersect / except: row-set membership against the right side
        ldf = self._setop_frame(lctx, node.left)
        rdf = self._setop_frame(rctx, node.right)
        rkeys = set(map(tuple, rdf.itertuples(index=False, name=None)))
        in_right = np.array(
            [tuple(row) in rkeys
             for row in ldf.itertuples(index=False, name=None)])
        keep = in_right if node.kind == "intersect" else ~in_right
        return lctx.mask(keep)

    # ---------------------------------------------------------- expressions

    def eval(self, e: ir.IR, ctx: Context):
        """-> (ndarray, valid_mask|None)"""
        if isinstance(e, ir.ColRef):
            return ctx.cols[(e.binding, e.name)], ctx.valid.get(
                (e.binding, e.name))
        if isinstance(e, ir.Lit):
            if e.value is None:  # typed NULL literal: fill value, no valid
                if isinstance(e.dtype, StringType):
                    z = np.full(ctx.nrows, "", dtype=object)
                elif isinstance(e.dtype, FloatType):
                    z = np.zeros(ctx.nrows, dtype=np.float64)
                else:
                    z = np.zeros(ctx.nrows, dtype=np.int64)
                return z, np.zeros(ctx.nrows, dtype=bool)
            return np.full(ctx.nrows, e.value), None
        if isinstance(e, ir.ScalarRef):
            v, _ = self.scalars[e.plan_id]
            if v is None:  # NULL scalar: every comparison fails
                return (np.zeros(ctx.nrows, dtype=np.int64),
                        np.zeros(ctx.nrows, dtype=bool))
            return np.full(ctx.nrows, v), None
        if isinstance(e, ir.Arith):
            return self._eval_arith(e, ctx)
        if isinstance(e, ir.Cmp):
            return self._eval_cmp(e, ctx)
        if isinstance(e, ir.BoolOp):
            arrs = [self.eval(a, ctx) for a in e.args]
            out = arrs[0][0].astype(bool)
            valid = arrs[0][1]
            for a, v in arrs[1:]:
                if e.op == "and":
                    out = out & a.astype(bool)
                else:
                    out = out | a.astype(bool)
                valid = _and_valid(valid, v)
            return out, valid
        if isinstance(e, ir.Not):
            a, v = self.eval(e.operand, ctx)
            return ~a.astype(bool), v
        if isinstance(e, ir.Neg):
            a, v = self.eval(e.operand, ctx)
            return -a, v
        if isinstance(e, ir.CaseIR):
            conds, vals, bvalids = [], [], []
            for c, v in e.whens:
                ca, cv = self.eval(c, ctx)
                va, vv = self.eval(v, ctx)
                conds.append(ca.astype(bool) if cv is None
                             else (ca.astype(bool) & cv))
                vals.append(self._coerce(va, v.dtype, e.dtype))
                bvalids.append(vv)
            if e.else_ is not None:
                ea, ev = self.eval(e.else_, ctx)
                default = self._coerce(ea, e.else_.dtype, e.dtype)
                default_valid = ev
            else:
                # CASE with no ELSE: rows matching no branch are NULL
                if isinstance(e.dtype, FloatType):
                    default = np.zeros(ctx.nrows, dtype=np.float64)
                elif isinstance(e.dtype, StringType):
                    default = np.full(ctx.nrows, "", dtype=object)
                else:
                    default = np.zeros(ctx.nrows, dtype=np.int64)
                default_valid = np.zeros(ctx.nrows, dtype=bool)
            # result validity follows the SELECTED branch's validity
            if default_valid is None and all(v is None for v in bvalids):
                valid = None
            else:
                ones = np.ones(ctx.nrows, dtype=bool)
                valid = ones if default_valid is None else default_valid
                for c, bv in zip(reversed(conds), reversed(bvalids)):
                    valid = np.where(c, ones if bv is None else bv, valid)
            return np.select(conds, vals, default=default), valid
        if isinstance(e, ir.LikeIR):
            a, v = self.eval(e.operand, ctx)
            m = like_mask(a, e.pattern)
            return (~m if e.negated else m), v
        if isinstance(e, ir.InListIR):
            a, v = self.eval(e.operand, ctx)
            vals = e.values
            if isinstance(e.operand.dtype, DecimalType):
                s = e.operand.dtype.scale
                vals = [int(round(float(x) * 10**s)) for x in vals]
            if a.dtype == object:
                a = a.astype(str)
                vals = [str(x) for x in vals]
            m = np.isin(a, np.array(vals))
            return (~m if e.negated else m), v
        if isinstance(e, ir.IsNullIR):
            a, v = self.eval(e.operand, ctx)
            isnull = (np.zeros(len(a), bool) if v is None else ~v)
            return (~isnull if e.negated else isnull), None
        if isinstance(e, ir.ExtractIR):
            a, v = self.eval(e.operand, ctx)
            d = (np.datetime64("1970-01-01", "D")
                 + a.astype(np.int64)).astype("datetime64[D]")
            if e.part == "year":
                out = d.astype("datetime64[Y]").astype(np.int64) + 1970
            elif e.part == "month":
                out = (d.astype("datetime64[M]").astype(np.int64) % 12) + 1
            elif e.part == "day":
                out = (d - d.astype("datetime64[M]")).astype(np.int64) + 1
            else:
                raise ExecError(f"extract {e.part}")
            return out.astype(np.int32), v
        if isinstance(e, ir.StrMapIR):
            a, v = self.eval(e.operand, ctx)
            sa = a.astype(str)
            out = (np.char.upper(sa) if e.op == "upper"
                   else np.char.lower(sa))
            return out.astype(object), v
        if isinstance(e, ir.ConcatIR):
            a, v = self.eval(e.operand, ctx)
            return (np.array([e.prefix + s + e.suffix
                              for s in a.astype(str)], dtype=object), v)
        if isinstance(e, ir.SubstrIR):
            a, v = self.eval(e.operand, ctx)
            sa = a.astype(str)
            if e.start == 1 and e.length is not None:
                return sa.astype(f"<U{e.length}").astype(object), v
            lo = e.start - 1
            hi = None if e.length is None else lo + e.length
            return np.array([s[lo:hi] for s in sa], dtype=object), v
        if isinstance(e, ir.CastIR):
            a, v = self.eval(e.operand, ctx)
            src = e.operand.dtype
            if isinstance(e.dtype, FloatType):
                return _to_float(a, src), v
            if isinstance(e.dtype, IntType):
                if isinstance(src, DecimalType):
                    return (a // 10**src.scale).astype(np.int64), v
                return a.astype(np.int64), v
            if isinstance(e.dtype, StringType):
                return a.astype(str).astype(object), v
            if isinstance(e.dtype, DecimalType):
                s = e.dtype.scale
                if isinstance(src, DecimalType):
                    return _rescale(a, src.scale, s), v
                if isinstance(src, IntType):
                    return a.astype(np.int64) * 10**s, v
                return np.round(a * 10**s).astype(np.int64), v
            raise ExecError(f"cast to {e.dtype}")
        raise ExecError(f"cannot eval {e!r}")

    def _coerce(self, arr, src: DType, dst: DType):
        if repr(src) == repr(dst):
            return arr
        if isinstance(dst, FloatType):
            return _to_float(arr, src)
        if isinstance(dst, DecimalType):
            ss = _scale_of(src)
            return _rescale(np.asarray(arr, dtype=np.int64), ss, dst.scale)
        return arr

    def _eval_arith(self, e: ir.Arith, ctx: Context):
        l, lv = self.eval(e.left, ctx)
        r, rv = self.eval(e.right, ctx)
        valid = _and_valid(lv, rv)
        lt, rt = e.left.dtype, e.right.dtype
        if isinstance(e.dtype, DateType):
            return l + r, valid
        if e.op == "/":
            return _to_float(l, lt) / _to_float(r, rt), valid
        if isinstance(e.dtype, FloatType):
            return _apply(e.op, _to_float(l, lt), _to_float(r, rt)), valid
        if isinstance(e.dtype, DecimalType):
            if e.op == "*":
                return l.astype(np.int64) * r.astype(np.int64), valid
            s = e.dtype.scale
            return _apply(e.op, _rescale(l, _scale_of(lt), s),
                          _rescale(r, _scale_of(rt), s)), valid
        return _apply(e.op, l, r), valid

    def _eval_cmp(self, e: ir.Cmp, ctx: Context):
        l, lv = self.eval(e.left, ctx)
        r, rv = self.eval(e.right, ctx)
        valid = _and_valid(lv, rv)
        lt, rt = e.left.dtype, e.right.dtype
        if isinstance(lt, StringType) or isinstance(rt, StringType):
            l = l.astype(str)
            r = r.astype(str)
        elif isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            s = max(_scale_of(lt), _scale_of(rt))
            if isinstance(lt, FloatType) or isinstance(rt, FloatType):
                l, r = _to_float(l, lt), _to_float(r, rt)
            else:
                l = _rescale(np.asarray(l, np.int64), _scale_of(lt), s)
                r = _rescale(np.asarray(r, np.int64), _scale_of(rt), s)
        op = {"=": np.equal, "<>": np.not_equal, "<": np.less,
              "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal}
        return op[e.op](l, r), valid


def _apply(op, l, r):
    if op == "+":
        return l + r
    if op == "-":
        return l - r
    if op == "*":
        return l * r
    if op == "%":
        return l % r
    raise ExecError(op)


def _rescale(arr: np.ndarray, from_s: int, to_s: int) -> np.ndarray:
    if from_s == to_s:
        return arr
    if to_s > from_s:
        return arr * 10**(to_s - from_s)
    return arr // 10**(from_s - to_s)


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _rank_desc(arr: np.ndarray) -> np.ndarray:
    """Key transform for stable descending sort."""
    if arr.dtype.kind in "iuf":
        return -arr
    # strings: rank by sorted-unique position, negated
    uniq, inv = np.unique(arr, return_inverse=True)
    return -inv


class ResultTable:
    """Final query output: named columns with dtypes; decimals stay scaled
    until formatted."""

    def __init__(self, names, cols, dtypes, valids=None):
        self.names = names
        self.cols = cols
        self.dtypes = dtypes
        self.valids = valids or [None] * len(cols)

    @property
    def nrows(self):
        return len(self.cols[0]) if self.cols else 0

    def to_pandas(self) -> pd.DataFrame:
        # positional build: duplicate output names are legal SQL
        # (q64 selects cs1.syear and cs2.syear)
        series = []
        for name, arr, dt, valid in zip(self.names, self.cols, self.dtypes,
                                        self.valids):
            if isinstance(dt, DecimalType):
                a = arr.astype(np.float64) / 10**dt.scale
            elif isinstance(dt, DateType):
                a = (np.datetime64("1970-01-01", "D")
                     + arr.astype(np.int64)).astype("datetime64[D]")
            else:
                a = arr
            if valid is not None:
                a = pd.array(a)
                a[~valid] = None
            series.append(pd.Series(a))
        df = pd.concat(series, axis=1, ignore_index=True)
        df.columns = self.names
        return df
