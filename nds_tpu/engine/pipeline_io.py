"""Double-buffered async host<->device pipeline: prefetch + overlap.

Out-of-core execution used to be a strict serial loop — slice chunk,
columnar-encode, upload, compute, repeat — so the device idled for the
entire host-side staging time of every chunk (ROADMAP item 2: "scan
never blocks compute"; the GPU columnar engines this repo reproduces
treat transfer/compute overlap as table stakes, "Accelerating Presto
with GPUs" / TQP, PAPERS.md). This module is the overlap machinery:

- ``ChunkPrefetcher`` — a bounded double-buffered prefetcher: a worker
  thread runs host-side chunk slicing + columnar encoding (pure numpy,
  releases the GIL) and issues the ``jax.device_put`` for chunk N+1
  while the compiled chunk program runs on chunk N (XLA compute
  releases the GIL, so the overlap is real even on one interpreter).
  Both phase-A loops of ``engine/chunked_exec.py`` ride it. Depth 0 is
  the byte-identical serial path: staging runs inline on the caller's
  thread, no worker, no locks, no new spans.
- stall attribution — time the CONSUMER spent blocked on the worker is
  a ``prefetch.wait`` span (category ``prefetch_wait``: the device
  waited on the host) and counts on ``pipeline_stall_seconds_total``;
  worker staging time that ran under compute is ``prefetch_hidden_s``
  (host time the overlap made free). Wait + hidden == total staging
  time, so the tracer's categories+residual==wall-clock invariant is
  preserved (wait is wall-clock, hidden by definition is not).
- admission — staged-but-unconsumed chunks are accounted live bytes
  (``obs/memwatch``), so the MemoryGovernor's projections see in-flight
  prefetch memory; ``chunk_working_set`` + ``MemoryGovernor.
  admit_prefetch`` let the scheduler demote DEPTH before demoting the
  placement when the budget admits the serial loop but not depth x
  chunk of staged buffers on top of it.

The worker rides the existing machinery, not around it: the ``io.read``
fault site fires per staged chunk inside the worker with the caller's
thread-local fault context republished (classification and retry
semantics identical to the serial path — an injected fault surfaces at
the consumer in chunk order and walks the same pipeline retry/ladder),
the watchdog heartbeat beats per staged chunk, and the queue locks come
from the locksan factories so the new concurrency is sanitizer-visible.
``close()`` cancels the worker at a chunk boundary (drain/SIGTERM: the
in-flight query either finishes under ``engine.drain_s`` or the drain
deadline's force-exit path never waits on this daemon thread), and
releases every staged-but-unconsumed chunk's accounted bytes.

Config (``utils/config.py``): ``engine.prefetch.enabled`` (on by
default) / ``engine.prefetch.depth`` (default 2) /
``NDS_TPU_PREFETCH=<depth|off>``; ``engine.prefetch.boundary`` (+
``NDS_TPU_PREFETCH_BOUNDARY``, default off) additionally pipelines
QUERY boundaries — the power loop and the serve engine thread dispatch
query N+1 while query N's compactor output is still in flight D2H,
with the existing async-handle ``result()`` as the sync point (README
"Pipelined execution").
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from nds_tpu.analysis import locksan
from nds_tpu.obs import memwatch
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs.trace import get_tracer
from nds_tpu.resilience import faults, watchdog

PREFETCH_ENV = "NDS_TPU_PREFETCH"
BOUNDARY_ENV = "NDS_TPU_PREFETCH_BOUNDARY"
DEFAULT_DEPTH = 2

_OFF = ("off", "0", "false", "no")
_ON = ("on", "1", "true", "yes")


def resolve_depth(config=None) -> int:
    """Effective prefetch depth: 0 = serial (byte-identical pre-pipeline
    behavior). Explicit config keys win over the env var; the default
    is depth 2 (double-buffered: stage N+1 and N+2 while N computes)."""
    if config is not None:
        enabled = config.get("engine.prefetch.enabled")
        if enabled is not None and str(enabled).strip().lower() in _OFF:
            return 0
        depth = config.get("engine.prefetch.depth")
        if depth is not None:
            try:
                return max(0, int(str(depth).strip()))
            except ValueError:
                raise ValueError(
                    f"bad engine.prefetch.depth {depth!r}") from None
        if enabled is not None:
            return DEFAULT_DEPTH
    env = os.environ.get(PREFETCH_ENV)
    if env is not None:
        e = env.strip().lower()
        if e in _OFF:
            return 0
        try:
            return max(0, int(e))
        except ValueError:
            return DEFAULT_DEPTH
    return DEFAULT_DEPTH


def boundary_enabled(config=None) -> bool:
    """Query-boundary pipelining switch (power loop + serve engine
    thread). Off by default: overlapping query brackets changes how
    per-query metric deltas attribute work at the boundary (totals stay
    exact — see README "Pipelined execution"), so the operator opts in.
    Depth 0 (prefetch off) forces it off too — one master off switch
    restores the fully serial engine."""
    if resolve_depth(config) <= 0:
        return False
    if config is not None \
            and config.get("engine.prefetch.boundary") is not None:
        return config.get_bool("engine.prefetch.boundary")
    return os.environ.get(BOUNDARY_ENV, "").strip().lower() in _ON


def chunk_working_set(est, chunk_rows: int) -> int:
    """Bytes one staged chunk of the estimate's widest-scan table holds
    (the unit the governor multiplies by depth for in-flight prefetch
    admission). Scales the per-table scan-byte estimate by the chunk
    fraction; tables smaller than a chunk cost their whole size."""
    best = 0
    for rows, nbytes in (getattr(est, "tables", None) or {}).values():
        if rows <= 0 or nbytes <= 0:
            continue
        frac = min(1.0, float(chunk_rows) / float(rows))
        best = max(best, int(nbytes * frac))
    return best


class StagedChunk:
    """One staged chunk: the original work item, the staged payload
    (device buffers), and a pop-once release of its accounted bytes."""

    __slots__ = ("item", "payload", "nbytes", "_live")

    def __init__(self, item, payload, nbytes: int):
        self.item = item
        self.payload = payload
        self.nbytes = int(nbytes)
        self._live = True

    def release(self) -> None:
        """Release the accounted live bytes exactly once (the consumer
        calls this after the chunk's compute; close() sweeps whatever
        was never consumed)."""
        if self._live:
            self._live = False
            memwatch.sub_live(self.nbytes)


class ChunkPrefetcher:
    """Bounded in-order prefetcher over a chunk work list.

    ``stage(item) -> (payload, nbytes)`` runs the host-side staging
    (slice + encode + ``jax.device_put``); with ``depth > 0`` it runs
    on a daemon worker thread that keeps at most ``depth`` staged
    chunks ahead of the consumer, with ``depth <= 0`` it runs inline at
    ``__next__`` (the serial path, byte-identical to the pre-pipeline
    loops). Iteration yields ``StagedChunk``s in submission order;
    a staging exception is delivered at the corresponding ``__next__``
    so the consumer's classification/retry path sees exactly what the
    serial loop would have raised."""

    # worker join bound at close(): the thread is a daemon, so a wedged
    # device_put can never block process exit — the join is courtesy
    JOIN_S = 30.0

    def __init__(self, items, stage, depth: int,
                 unit: str = "engine", **site_info):
        self.items = list(items)
        self._stage = stage
        self.depth = max(0, int(depth))
        self.unit = unit
        self.site_info = dict(site_info)
        self.stats = {"depth": self.depth, "staged": 0,
                      "stage_s": 0.0, "wait_s": 0.0, "hidden_s": 0.0}
        # worker-side counters live in their own dict (merged into
        # ``stats`` at close(), after the join orders the last worker
        # write): no attribute is ever mutated from both threads
        self._wstats = {"staged": 0, "stage_s": 0.0}
        self._next_i = 0
        self._closed = False
        self._thread = None
        if self.depth > 0 and self.items:
            self._cv = locksan.condition(
                "engine.pipeline_io.ChunkPrefetcher._cv")
            self._buf: deque = deque()
            self._cancel = False
            self._done = False
            # the worker republishes the SUBMITTING thread's fault
            # context (query/stream names are thread-local): a schedule
            # scoped to the current query must keep matching when the
            # staging moved off-thread
            self._ctx = faults.current_context()
            obs_metrics.gauge("prefetch_depth").set(self.depth)
            self._thread = threading.Thread(
                target=self._worker, name="nds-tpu-prefetch",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ stage

    def _stage_one(self, item) -> StagedChunk:
        """One chunk's host staging, identical on both paths: the
        ``io.read`` fault site fires first (same classification/retry
        semantics as every other warehouse read), then the caller's
        stage function runs and its bytes go live in the memwatch
        accounting (so governor projections see in-flight prefetch)."""
        faults.fault_point("io.read", **self.site_info)
        # ndslint: waive[NDS102,NDS103] -- staging wall-clock feeds the prefetch_hidden_s attribution (device_put is async; nothing here closes a device bracket)
        t0 = time.perf_counter()
        payload, nbytes = self._stage(item)
        # ndslint: waive[NDS102] -- closes the staging bracket opened above; feeds prefetch_hidden_s
        dt = time.perf_counter() - t0
        # ndsraces: waive[NDSR204] -- exclusive by mode, never concurrent: depth>0 stages ONLY on the worker thread, depth 0 ONLY inline on the consumer (no worker exists); close() merges only after a COMPLETED join (timed-out joins skip the merge)
        self._wstats["staged"] += 1
        self._wstats["stage_s"] += dt
        memwatch.add_live(nbytes)
        return StagedChunk(item, payload, nbytes)

    # ----------------------------------------------------------- worker

    def _worker(self) -> None:
        try:
            with faults.context(**self._ctx):
                for item in self.items:
                    with self._cv:
                        while (len(self._buf) >= self.depth
                               and not self._cancel):
                            self._cv.wait(timeout=0.1)
                        if self._cancel:
                            # chunk-boundary cancellation: nothing half
                            # staged, nothing leaked
                            break
                    try:
                        staged = self._stage_one(item)
                    except BaseException as exc:  # noqa: BLE001
                        # delivered to the consumer at this chunk's
                        # __next__, in order — the serial path's raise
                        # point
                        with self._cv:
                            self._buf.append(("err", exc))
                            self._done = True
                            self._cv.notify_all()
                        return
                    watchdog.beat(self.unit, phase="prefetch.stage",
                                  **self.site_info)
                    with self._cv:
                        if self._cancel:
                            # close() may have swept the buffer while a
                            # slow device_put held this chunk mid-stage
                            # (past close's bounded join): the release
                            # must happen HERE or its accounted bytes
                            # would inflate the governor's live-memory
                            # view for the process lifetime
                            dropped = staged
                        else:
                            dropped = None
                            self._buf.append(("ok", staged))
                        self._cv.notify_all()
                    if dropped is not None:
                        dropped.release()
                        break
        finally:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    # --------------------------------------------------------- consumer

    def __iter__(self):
        return self

    def __next__(self) -> StagedChunk:
        if self._next_i >= len(self.items):
            raise StopIteration
        self._next_i += 1
        if self.depth <= 0:
            return self._stage_one(self.items[self._next_i - 1])
        # stats mutations stay OUTSIDE the condition: the worker's own
        # stats writes are ordered by the buffer hand-off + close()'s
        # join, so the dict needs no lock — and must then never LOOK
        # lock-guarded (ndsraces NDSR201 guard inference)
        wait_s = 0.0
        with self._cv:
            if not self._buf and not self._done:
                # the device is about to wait on the host: the stall
                # the whole module exists to hide, measured and billed
                # to its own category
                # ndslint: waive[NDS102] -- the wait bracket IS the prefetch_wait span; no device work is being timed
                t0 = time.perf_counter()
                with get_tracer().span("prefetch.wait",
                                       **self.site_info):
                    while not self._buf and not self._done:
                        self._cv.wait(timeout=0.1)
                # ndslint: waive[NDS102] -- closes the wait bracket; the prefetch.wait span records the same window
                wait_s = time.perf_counter() - t0
            if self._buf:
                kind, value = self._buf.popleft()
            else:
                # worker exited without staging this chunk (cancelled
                # close): the consumer is already unwinding
                kind = None
            self._cv.notify_all()
        if wait_s:
            self.stats["wait_s"] += wait_s
        if kind is None:
            raise StopIteration
        if kind == "err":
            raise value
        return value

    # ------------------------------------------------------------ close

    def close(self) -> dict:
        """Cancel at the next chunk boundary, join the worker, release
        unconsumed staged bytes, finalize + publish the stall/overlap
        attribution. Idempotent; never raises. Returns the stats dict
        ({"depth", "staged", "stage_s", "wait_s", "hidden_s"})."""
        if self._closed:
            return self.stats
        self._closed = True
        joined = True
        if self._thread is not None:
            with self._cv:
                self._cancel = True
                self._cv.notify_all()
            self._thread.join(timeout=self.JOIN_S)
            joined = not self._thread.is_alive()
            with self._cv:
                leftovers = [v for k, v in self._buf if k == "ok"]
                self._buf.clear()
            for staged in leftovers:
                staged.release()
        if joined:
            # merge the worker-side counters — ONLY after a completed
            # join (a timed-out join means the worker is still wedged
            # inside a device_put and may be mid-write: publishing torn
            # numbers is worse than publishing none; the wedged chunk
            # releases itself at the worker's cancel check); in serial
            # mode they were written on this thread all along
            self.stats["staged"] = self._wstats["staged"]
            self.stats["stage_s"] = self._wstats["stage_s"]
        if self._thread is not None and joined:
            # host staging the consumer never waited for ran entirely
            # under compute: the hidden (overlapped) time
            self.stats["hidden_s"] = max(
                0.0, self.stats["stage_s"] - self.stats["wait_s"])
            if self.stats["wait_s"]:
                obs_metrics.counter(
                    "pipeline_stall_seconds_total").inc(
                    self.stats["wait_s"])
            if self.stats["hidden_s"]:
                obs_metrics.counter(
                    "prefetch_hidden_seconds_total").inc(
                    self.stats["hidden_s"])
        return self.stats
