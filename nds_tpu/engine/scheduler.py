"""Unified execution pipeline: placement as a scheduling decision.

ROADMAP item 5. The four execution strategies — single-device
(engine/device_exec.py), sharded mesh (parallel/dist_exec.py),
out-of-core chunked (engine/chunked_exec.py), and the host/CPU oracle
(engine/cpu_exec.py) — used to be four separate Session executor
factories, each carrying its own copy of the retry/heartbeat/memwatch
wiring, and recovery was a one-shot stream-wide ``engine.fallback=cpu``
demotion that multi-process SPMD had to disable outright (rank-local
demotion deadlocks collectives). This module replaces all of that with
ONE pipeline that treats the strategies as *placements*:

- **Cost model** — per query, an initial placement is chosen from the
  plan verifier's size estimates (analysis/plan_verify.estimate_plan)
  plus this process's per-query device-memory HWM history
  (obs/memwatch): plans whose working set exceeds the device budget
  start out-of-core, everything else starts on the fastest placement
  the backend offers. Pure Python — tools/ndsverify.py assigns
  placements for all 125 statements with no accelerator.

- **Degradation ladder** — a classified transient failure reschedules
  THAT QUERY one rung down instead of demoting the stream:
  device OOM -> chunked (chunk_rows halved) -> cpu; sharded exchange
  overflow -> re-plan with grown slack -> chunked -> cpu. Deterministic
  failures (planner bugs) never walk the ladder. Generic transients
  retry at the same rung under the config retry policy
  (``engine.retry.*`` / ``engine.query_deadline_s``) before stepping.

- **Promotion** — repeated ladder walks sticky-demote the *starting*
  rung (Execution-Templates-style caching of the control-plane
  decision); ``engine.placement.promote_after`` clean queries at the
  demoted rung promote the stream back to the cost model's choice.

- **Consensus** — on multi-process SPMD every placement switch is a
  collective decision: all ranks vote (an allgather over the existing
  multihost layer), the deepest demotion proposed by any rank wins, and
  either every rank switches or none does. A rank that cannot reach
  consensus keeps its placement and fails the query instead of
  deadlocking the others inside the next collective. Single-process
  runs use the degenerate one-voter channel, so the code path is
  identical everywhere.

This is also the single home of the engine-layer retry wiring: the
pipeline owns the per-query RetryPolicy, and the executors' internal
adaptive loops (exchange slack doubling, partial-agg overflow, chunk
halving) borrow their no-sleep policies from :func:`adaptive_policy`
here instead of instantiating their own (ndslint NDS110 keeps direct
executor construction from reappearing outside this module).

Config keys (README "Placement & degradation"):
``engine.placement.force`` pins the initial placement;
``engine.placement.ladder`` (default on) / ``engine.placement.floor``
(default cpu); ``engine.placement.demote_after`` /
``engine.placement.promote_after`` shape the sticky demotion;
``engine.placement.device_budget_bytes`` is the cost-model budget.
``engine.fallback=cpu`` survives as an alias forcing floor=cpu.
Metrics: ``query_reschedules_total``, ``placement_consensus_total``,
``placement_demotions_total``, ``placement_promotions_total``.
"""

from __future__ import annotations

import os

from nds_tpu.obs import memwatch
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.resilience import faults, watchdog
from nds_tpu.resilience.retry import (
    DETERMINISTIC, QueryDeadlineExceeded, RetryPolicy, RetryStats,
    classify, deadline_scope, is_oom,
)

# placement names, fastest-first per backend universe
DEVICE = "device"
SHARDED = "sharded"
CHUNKED = "chunked"
CPU = "cpu"

# sharded pseudo-rung: same placement, slack doubled + plan recompiled
SHARDED_REPLAN = "sharded+slack"

UNIVERSES = {
    "tpu": (DEVICE, CHUNKED, CPU),
    "distributed": (SHARDED, CHUNKED, CPU),
    "cpu": (CPU,),
}

# default device working-set budget for the cost model: conservative
# half of a 16G-HBM chip, leaving room for join expansion and results
DEFAULT_DEVICE_BUDGET = 8 << 30
# estimated bytes inflate by this factor before comparing to the budget
# (intermediates, padding, exchange buffers)
EXPANSION = 2.0

# consecutive ladder-walked queries before the STARTING rung demotes
DEFAULT_DEMOTE_AFTER = 2
# consecutive clean queries at a demoted start before promotion back
DEFAULT_PROMOTE_AFTER = 3


def adaptive_policy(max_attempts: int) -> RetryPolicy:
    """No-sleep retry policy for executor-internal adaptive loops (the
    exchange slack-doubling / partial-agg overflow / chunk-halving
    shapes): each retry already pays a recompile or re-scan, so backoff
    would only add latency. Centralized here so the pipeline module is
    the one place engine-layer retry wiring is instantiated."""
    return RetryPolicy(max_attempts=max_attempts, base_delay_s=0.0)


def load_policy(policy: RetryPolicy) -> RetryPolicy:
    """The warehouse-load variant of a query policy: same
    attempts/backoff shape, NO per-query deadline (a 25-table load is
    not a query)."""
    return RetryPolicy(
        max_attempts=policy.max_attempts,
        base_delay_s=policy.base_delay_s,
        max_delay_s=policy.max_delay_s, jitter=policy.jitter,
        deadline_s=None, seed=policy.seed)


def is_exchange_overflow(exc: BaseException) -> bool:
    return "exchange overflow" in str(exc)


# ------------------------------------------------------------ consensus

class NullChannel:
    """Single-process world: one voter, trivially unanimous."""

    world = 1

    def gather(self, vote: int) -> "list[int] | None":
        return [vote]


class MultihostChannel:
    """Vote transport over the multi-controller SPMD runtime
    (parallel/multihost.gather_votes — an allgather across processes
    over DCN). On a multi-rank world the pipeline enters exactly ONE
    vote per query, at the query boundary, success or failure
    (ExecutionPipeline._boundary_vote) — so the allgathers pair
    deterministically across ranks even when the triggering failure
    was rank-local, and no rank waits on a collective another rank
    skipped."""

    def __init__(self):
        import jax
        self.world = jax.process_count()

    def gather(self, vote: int) -> "list[int] | None":
        from nds_tpu.parallel import multihost
        votes = multihost.gather_votes(vote)
        if votes is None:
            from nds_tpu.utils.report import TaskFailureCollector
            TaskFailureCollector.notify(
                "placement consensus allgather failed; "
                "keeping placement")
        return votes


class Consensus:
    """All-or-none placement agreement. Votes are rung indices into the
    shared ladder (higher = more demoted); after the gather every rank
    applies the same deterministic rule — the DEEPEST demotion any rank
    proposed wins — so all ranks switch together or, when the gather
    fails (a lagging/dead rank), nobody switches."""

    def __init__(self, channel=None):
        self.channel = channel or NullChannel()

    def decide(self, vote: int) -> "int | None":
        obs_metrics.counter("placement_consensus_total").inc()
        votes = self.channel.gather(vote)
        if votes is None or len(votes) < getattr(self.channel, "world", 1):
            obs_metrics.counter("placement_consensus_failed_total").inc()
            return None
        return max(votes)


# ------------------------------------------------------------ cost model

class CostModel:
    """Initial-placement chooser. Inputs: the plan verifier's static
    size estimates and the per-query device-memory HWM history this
    process has observed (a query that blew past the budget last time
    starts out-of-core this time — Execution Templates' re-validated
    cached decision, PAPERS.md)."""

    def __init__(self, device_budget: int = DEFAULT_DEVICE_BUDGET,
                 stream_bytes: int = 0,
                 expansion: float = EXPANSION):
        self.device_budget = device_budget
        self.stream_bytes = stream_bytes
        self.expansion = expansion
        # query name -> observed device HWM bytes (max over runs)
        self.hwm_history: dict[str, int] = {}

    def observe(self, qname: str | None, hwm_bytes: int) -> None:
        if qname and hwm_bytes:
            self.hwm_history[qname] = max(
                self.hwm_history.get(qname, 0), int(hwm_bytes))

    def choose(self, planned, universe: tuple,
               tables: "dict | None" = None, catalog=None,
               qname: "str | None" = None, est=None) -> tuple:
        """-> (placement, reason). Deterministic over identical inputs,
        which multi-process SPMD relies on: every rank computes the
        same initial placement without a consensus round. ``est``
        accepts a precomputed plan estimate (the pipeline shares one
        estimate between this choice and the memory governor)."""
        from nds_tpu.analysis import plan_verify
        if est is None:
            est = plan_verify.estimate_plan(planned, tables=tables,
                                            catalog=catalog)
        fast = universe[0]
        if CHUNKED in universe and fast != CHUNKED:
            hwm = self.hwm_history.get(qname or "")
            if hwm and hwm > self.device_budget:
                return CHUNKED, f"hwm-history:{hwm}>{self.device_budget}"
            if (self.stream_bytes
                    and est.widest_table_bytes > self.stream_bytes):
                return CHUNKED, (f"table-exceeds-stream-bytes:"
                                 f"{est.widest_table_bytes}")
            # join/sort/window/agg intermediates inflate the working
            # set beyond the raw scans: pad the expansion per operator
            ops = est.joins + est.aggregates + est.sorts + est.windows
            factor = self.expansion * (1.0 + 0.1 * ops)
            if est.bytes * factor > self.device_budget:
                return CHUNKED, (f"working-set:{est.bytes}b"
                                 f"x{factor:.1f}")
        return fast, f"fits:{est.bytes}b"


# ------------------------------------------------------ memory governor

# once governing, projections must fall below this fraction of the
# budget before the governor stands down (hysteresis: borderline
# queries must not flap between device and chunked every other query)
GOVERNOR_LOW_FRAC = 0.8


class MemoryGovernor:
    """Proactive memory-pressure pre-admission check.

    Today OOM is handled REACTIVELY: the query dies on device, the
    ladder walks it to chunked at halved chunk_rows, and the whole
    program re-executes. On a multi-hour run every one of those walks
    is minutes of wasted re-execution. The governor moves the decision
    BEFORE dispatch: project the post-admission high-water mark as

        live bytes now (obs/memwatch.live_bytes — allocator stats when
        a backend is live, accounted buffers otherwise)
      + the plan verifier's size estimate x the expansion factor

    and when the projection exceeds
    ``engine.placement.device_budget_bytes``, demote the query's
    placement (device -> chunked) or — when it is already bound for
    the chunked placement — pre-shrink its ``chunk_rows``, before
    anything is dispatched. Hysteresis keeps the decision sticky: once
    governing, projections must fall below ``GOVERNOR_LOW_FRAC`` x
    budget to stand down. Every preemptive demotion counts on
    ``governor_preemptive_demotions_total``; on the summary side the
    query carries ``governed: true`` (BenchReport.attach_schedule).

    Rank-local by construction (live memory diverges across ranks), so
    the pipeline only consults it on single-process worlds — the same
    rule the HWM history follows."""

    def __init__(self, budget: int = DEFAULT_DEVICE_BUDGET,
                 expansion: float = EXPANSION,
                 low_frac: float = GOVERNOR_LOW_FRAC):
        self.budget = int(budget)
        self.expansion = expansion
        self.low_frac = low_frac
        self.governing = False

    def project(self, est) -> int:
        est_bytes = int(getattr(est, "bytes", 0) or 0)
        if est_bytes <= 0:
            return 0
        return memwatch.live_bytes() + int(est_bytes * self.expansion)

    def decide(self, est) -> "str | None":
        """Non-None reason string when the query must be demoted /
        pre-shrunk before dispatch."""
        if self.budget <= 0:
            return None
        projected = self.project(est)
        if projected <= 0:
            return None
        limit = (int(self.budget * self.low_frac) if self.governing
                 else self.budget)
        if projected > limit:
            self.governing = True
            obs_metrics.counter(
                "governor_preemptive_demotions_total").inc()
            return (f"governor:projected:{projected}"
                    f">budget:{self.budget}")
        self.governing = False
        return None

    def admit_prefetch(self, est, chunk_bytes: int, depth: int) -> int:
        """Deepest prefetch depth whose in-flight staged bytes still
        fit the budget ON TOP of the base projection — overlap must not
        reintroduce the OOMs the governor prevents, so depth demotes
        BEFORE placement does: a budget that admits the serial chunked
        loop but not depth x chunk of staged buffers runs the same
        placement shallower, not a deeper ladder rung. Returns
        ``depth`` unchanged when nothing constrains it (no budget, no
        estimate, chunk size unknown)."""
        if (self.budget <= 0 or depth <= 0 or chunk_bytes <= 0):
            return depth
        base = self.project(est)
        if base <= 0:
            return depth
        d = depth
        while d > 0 and base + d * chunk_bytes > self.budget:
            d -= 1
        return d


# ------------------------------------------------------------- pipeline

class _CompletedHandle:
    """Already-finished async handle. Carries the query's own
    stats/schedule — and the timings/span the sync execution left on
    the pipeline — so interleaved dispatches (the in-process throughput
    fleet keeps ``engine.concurrent_tasks`` queries in flight; the
    power loop's boundary pipelining dispatches query N+1 before
    resolving N) cannot clobber each other's accounting: ``result()``
    re-points the pipeline's per-query obs surface at THIS query's."""

    __slots__ = ("_value", "pipe", "stats", "sched", "timings", "span")

    def __init__(self, value, pipe=None, stats=None, sched=None):
        self._value = value
        self.pipe = pipe
        self.stats = stats
        self.sched = sched
        self.timings = getattr(pipe, "last_timings", {}) if pipe else {}
        self.span = (getattr(pipe, "last_query_span", None)
                     if pipe else None)

    def result(self):
        if self.pipe is not None:
            self.pipe.last_stats = self.stats
            self.pipe.last_schedule = self.sched
            self.pipe.last_timings = self.timings
            self.pipe.last_query_span = self.span
        return self._value


class _PipelineHandle:
    """Async handle preserving the device engine's dispatch/materialize
    overlap: the inner placement handle fails only at ``result()``, so
    the ladder rerun happens there, synchronously, on the blocked
    caller's thread — with this query's own stats/schedule objects."""

    __slots__ = ("pipe", "planned", "key", "inner", "placement",
                 "stats", "sched")

    def __init__(self, pipe, planned, key, inner, placement, stats,
                 sched):
        self.pipe = pipe
        self.planned = planned
        self.key = key
        self.inner = inner
        self.placement = placement
        self.stats = stats
        self.sched = sched

    def result(self):
        pipe = self.pipe
        pipe.last_stats = self.stats
        pipe.last_schedule = self.sched
        try:
            out = self.inner.result()
        except Exception as exc:  # noqa: BLE001 - classified in rerun
            self.stats.attempts += 1
            self.stats.errors.append(f"{type(exc).__name__}: {exc}")
            if classify(exc) != "transient":
                self.stats.gave_up_reason = DETERMINISTIC
                raise
            return pipe._run_ladder(
                self.planned, key=self.key, placement=self.placement,
                stats=self.stats, sched=self.sched, pending=exc)
        self.stats.attempts += 1
        pipe._adopt_executor_state(self.placement)
        self.sched["placement"] = self.placement
        pipe._note_success(rescheduled=False)
        return out


class ExecutionPipeline:
    """The Session executor factory for every backend: owns the
    placement executors, the cost model, the ladder, and the query-level
    retry wiring that used to live in utils/power_core.py and (as
    near-copies) in the throughput stream loops."""

    def __init__(self, backend: str = "cpu", config=None,
                 mesh=None, precision: str = "f64",
                 stream_bytes: int = 0, chunk_rows: int | None = None,
                 consensus: "Consensus | None" = None,
                 cost_model: "CostModel | None" = None,
                 prefetch_depth: "int | None" = None):
        from nds_tpu.engine import pipeline_io
        from nds_tpu.engine.chunked_exec import DEFAULT_CHUNK_ROWS
        self.backend = backend
        self.config = config
        self.mesh = mesh
        if precision not in ("f64", "f32", "bf16"):
            # device_exec.PRECISIONS, validated HERE so a config typo
            # fails at session creation, not as a KeyError mid-stream
            # after the warehouse loaded (device_exec itself imports
            # lazily — it pulls in jax)
            raise ValueError(f"unknown engine.precision {precision!r}")
        self.precision = precision
        self.stream_bytes = stream_bytes
        self.chunk_rows = chunk_rows or DEFAULT_CHUNK_ROWS
        # double-buffered phase-A prefetch depth for the chunked
        # placement (engine/pipeline_io.py; engine.prefetch.* /
        # NDS_TPU_PREFETCH; 0 = serial). The governor may demote it
        # per query (_apply_prefetch) before demoting the placement
        self.prefetch_depth = (pipeline_io.resolve_depth(config)
                               if prefetch_depth is None
                               else max(0, int(prefetch_depth)))
        self._gov_depth: "int | None" = None
        self.universe = UNIVERSES.get(backend, (CPU,))
        self.policy = (RetryPolicy.from_config(config) if config
                       else RetryPolicy())
        self.consensus = consensus or Consensus(
            self._default_channel(backend))
        self.cost_model = cost_model or CostModel(
            device_budget=self._cfg_int(
                "engine.placement.device_budget_bytes",
                DEFAULT_DEVICE_BUDGET),
            stream_bytes=stream_bytes)
        # proactive memory-pressure governor (engine.placement.governor,
        # default on): pre-admission demotion/pre-shrink against the
        # same budget the cost model plans with
        self.governor = None
        if str(self._cfg("engine.placement.governor", "on")) not in (
                "off", "0", "false"):
            self.governor = MemoryGovernor(
                budget=self.cost_model.device_budget)
        self._gov_shrink = False
        self.ladder_on = self._cfg("engine.placement.ladder",
                                   "on") not in ("off", "0", "false")
        floor = self._cfg("engine.placement.floor", CPU)
        if self._cfg("engine.fallback") == CPU:
            # legacy alias: the one-shot stream demotion becomes
            # "the ladder bottoms out on the CPU oracle"
            floor = CPU
        self.floor = floor if floor in self.universe else self.universe[-1]
        force = self._cfg("engine.placement.force")
        if force and force not in self.universe:
            # a silently-dropped pin would hand the user unpinned
            # numbers while they believe placement is fixed
            raise ValueError(
                f"engine.placement.force={force!r} is not in the "
                f"{backend!r} backend's placement universe "
                f"{self.universe}")
        self.forced = force or None
        self.demote_after = self._cfg_int("engine.placement.demote_after",
                                          DEFAULT_DEMOTE_AFTER)
        self.promote_after = self._cfg_int(
            "engine.placement.promote_after", DEFAULT_PROMOTE_AFTER)
        # placement name -> live executor (built lazily; device buffers
        # and compile caches persist across queries per placement)
        self._executors: dict = {}
        self._tables: "dict | None" = None
        # sticky stream-level demotion state
        self._demoted_to: "str | None" = None
        self._reschedule_streak = 0
        self._clean_streak = 0
        self._just_promoted = False
        # executor-compatible surface (power loop resets these; the obs
        # layer scrapes them)
        self.last_timings: dict = {}
        self.last_query_span = None
        self.last_stats = RetryStats()
        self.last_schedule: dict = {}

    # -------------------------------------------------------- plumbing

    @property
    def _multi(self) -> bool:
        """Multi-rank world? The placement protocol then switches to
        exactly ONE consensus round per query (_boundary_vote):
        rank-local mid-query ladder walking cannot pair its
        collectives when only the failing rank enters them."""
        return getattr(self.consensus.channel, "world", 1) > 1

    def _cfg(self, key: str, default=None):
        return self.config.get(key, default) if self.config else default

    def _cfg_int(self, key: str, default: int) -> int:
        return (self.config.get_int(key, default) if self.config
                else default)

    @staticmethod
    def _default_channel(backend: str):
        # probe jax ONLY for the distributed backend: process_count()
        # initializes the platform, and a pure-CPU phase must never
        # touch (or block on) a remote accelerator plugin
        if backend != "distributed":
            return NullChannel()
        try:
            import jax
            if jax.process_count() > 1:
                return MultihostChannel()
        except Exception:  # noqa: BLE001 - no jax: single-process world
            pass
        return NullChannel()

    def __call__(self, tables: dict) -> "ExecutionPipeline":
        """Session executor-factory protocol: bind the registry. A NEW
        registry object (DML rebuilt the dict) invalidates the built
        executors the same way the per-backend factories did."""
        if self._tables is not tables:
            self._tables = tables
            self._executors.clear()
        return self

    def invalidate(self) -> None:
        """Session.invalidate hook (DML): drop every placement executor
        (device buffers + compiled programs key on table contents). The
        HWM history and demotion state survive — they describe the
        workload, not the table version."""
        self._executors.clear()

    def reset_query(self) -> None:
        """Pre-query reset (the power loop's stale-state contract): a
        query failing before dispatch must not inherit the previous
        query's span/timings/stats/schedule."""
        self.last_timings = {}
        self.last_query_span = None
        self.last_stats = RetryStats()
        self.last_schedule = {}

    # ------------------------------------------------------- executors

    def _executor(self, placement: str):
        ex = self._executors.get(placement)
        if ex is not None:
            return ex
        tables = self._tables or {}
        if placement == CPU:
            from nds_tpu.engine.cpu_exec import CpuExecutor
            ex = CpuExecutor(tables)
        elif placement == CHUNKED:
            from nds_tpu.engine.chunked_exec import ChunkedExecutor
            from nds_tpu.engine.chunked_exec import DEFAULT_STREAM_BYTES
            ex = ChunkedExecutor(
                tables, self.stream_bytes or DEFAULT_STREAM_BYTES,
                self.chunk_rows, self._float_dtype(),
                prefetch_depth=self.prefetch_depth)
        elif placement == DEVICE:
            from nds_tpu.engine.device_exec import DeviceExecutor
            ex = DeviceExecutor(tables, self._float_dtype())
        elif placement == SHARDED:
            from nds_tpu.parallel.dist_exec import DistributedExecutor
            ex = DistributedExecutor(tables, mesh=self.mesh)
        else:
            raise ValueError(f"unknown placement {placement!r}")
        self._executors[placement] = ex
        return ex

    def _float_dtype(self):
        from nds_tpu.engine.device_exec import PRECISIONS
        name = PRECISIONS[self.precision]
        if name is None:
            return None
        import jax.numpy as jnp
        return getattr(jnp, name)

    def _adopt_executor_state(self, placement: str) -> None:
        """Forward the serving executor's per-query obs surface so
        ``obs.query_timings(pipeline)`` and the power loop see the
        query exactly as before the unification."""
        ex = self._executors.get(placement)
        if ex is None:
            return
        self.last_timings = getattr(ex, "last_timings", {}) or {}
        self.last_query_span = getattr(ex, "last_query_span", None)

    # ------------------------------------------------------ the ladder

    def rungs_for(self, initial: str) -> list:
        """Orderered rung list for a query starting at ``initial``,
        truncated at the configured floor. The sharded re-plan rung is
        inserted conditionally at failure time (only an exchange
        overflow enters it — growing slack cannot fix an OOM). On a
        multi-rank world the list is a single rung: placement moves
        only between queries, through the per-query boundary vote
        every rank enters (_boundary_vote) — a rank-local mid-query
        walk would leave this rank off the collectives its peers are
        still inside."""
        if not self.ladder_on or self._multi:
            return [initial]
        order = list(self.universe)
        try:
            start = order.index(initial)
        except ValueError:
            return [initial]
        rungs = order[start:]
        if self.floor in rungs:
            rungs = rungs[:rungs.index(self.floor) + 1]
        return rungs

    def _encoded_estimates(self) -> bool:
        """Whether size estimates may use the columnar ENCODED widths:
        only when the universe's fast placement actually consumes
        encoded buffers. The sharded SPMD path uploads raw
        (DistributedExecutor.COLUMNAR_UPLOAD = False), so costing it
        at encoded widths would under-count residency by the
        compression ratio and admit queries that then OOM on device —
        the reactive failure the cost model exists to prevent."""
        return self.universe[0] != SHARDED

    def _initial_placement(self, planned, qname) -> tuple:
        self._gov_shrink = False
        self._gov_depth = None
        catalog = None
        from nds_tpu.analysis import plan_verify
        if self.forced or self._demoted_to:
            # pinned/sticky placements skip the cost model but NOT the
            # prefetch depth admission below (a forced chunked run
            # still must not stage depth x chunk past the budget)
            placement, why = ((self.forced, "forced") if self.forced
                              else (self._demoted_to,
                                    "sticky-demotion"))
            return self._admit_depth(planned, placement, catalog), why
        est = plan_verify.estimate_plan(planned, tables=self._tables,
                                        catalog=catalog,
                                        encoded=self._encoded_estimates())
        placement, why = self.cost_model.choose(
            planned, self.universe, tables=self._tables,
            catalog=catalog, qname=qname, est=est)
        # pre-admission governor: projected HWM (live bytes + estimate
        # x expansion) over budget demotes BEFORE dispatch — every
        # avoided OOM is an avoided ladder walk and re-execute.
        # Single-process worlds only: live memory is rank-local, and a
        # divergent projection would start peers at different
        # placements (the consensus-avoidance rule the HWM history
        # follows)
        # only consult the governor when it could actually act: a
        # placement with no relief rung (the CPU oracle, a universe
        # without chunked) must not count phantom demotions or latch
        # the hysteresis
        if (self.governor is not None and not self._multi
                and CHUNKED in self.universe
                and placement in (DEVICE, SHARDED, CHUNKED)):
            reason = self.governor.decide(est)
            if reason and placement in (DEVICE, SHARDED):
                placement, why = CHUNKED, reason
            elif reason and placement == CHUNKED:
                self._gov_shrink = True
                placement, why = CHUNKED, reason
        return self._admit_depth(planned, placement, catalog,
                                 est=est), why

    def _admit_depth(self, planned, placement: str, catalog,
                     est=None) -> str:
        """Prefetch depth admission (engine/pipeline_io.py): a
        chunked-bound query whose base projection fits the budget but
        whose depth x chunk of in-flight staged buffers does not runs
        SHALLOWER, not deeper down the ladder — depth demotes before
        placement (applied per query via _apply_prefetch, restored by
        _run_ladder's finally). Returns the placement unchanged."""
        if (placement != CHUNKED or self.governor is None
                or self._multi or self.prefetch_depth <= 0):
            return placement
        if est is None:
            from nds_tpu.analysis import plan_verify
            est = plan_verify.estimate_plan(
                planned, tables=self._tables, catalog=catalog,
                encoded=self._encoded_estimates())
        from nds_tpu.engine import pipeline_io
        chunk_bytes = pipeline_io.chunk_working_set(
            est, self.chunk_rows)
        allowed = self.governor.admit_prefetch(
            est, chunk_bytes, self.prefetch_depth)
        if allowed < self.prefetch_depth:
            self._gov_depth = allowed
        return placement

    def _apply_governor(self, sched: dict, placement: str) -> None:
        """Post-schedule governor bookkeeping: stamp ``governed`` on
        the summary and pre-shrink chunk_rows for THIS query (restored
        by _run_ladder's finally) when the governed placement is
        already the chunked one."""
        if not str(sched.get("reason", "")).startswith("governor:"):
            return
        sched["governed"] = True
        if self._gov_shrink and placement == CHUNKED:
            from nds_tpu.engine.chunked_exec import ChunkedExecutor
            ex = self._executor(CHUNKED)
            sched.setdefault("_restore", []).append(
                (ex, "chunk_rows", ex.chunk_rows))
            ex.chunk_rows = max(ex.chunk_rows // 2,
                                ChunkedExecutor.MIN_CHUNK_ROWS)
        self._gov_shrink = False

    def _apply_prefetch(self, sched: dict, placement: str) -> None:
        """Apply the depth admission verdict for THIS query (restored
        by _run_ladder's finally, like every per-query executor tweak):
        the chunked executor runs at the admitted depth, the summary
        records ``prefetch_depth``, and the demotion counts."""
        d, self._gov_depth = self._gov_depth, None
        if d is None or placement != CHUNKED:
            return
        ex = self._executor(CHUNKED)
        if not hasattr(ex, "prefetch_depth"):
            return
        sched.setdefault("_restore", []).append(
            (ex, "prefetch_depth", ex.prefetch_depth))
        ex.prefetch_depth = d
        sched["prefetch_depth"] = d
        obs_metrics.counter("prefetch_depth_demotions_total").inc()

    def admission_projection(self, planned) -> tuple:
        """(projected_bytes, budget_bytes) from the MemoryGovernor's
        pre-dispatch model — what the serving layer's admission control
        reads (nds_tpu/serve/server.py): live bytes now + the plan
        verifier's size estimate x expansion, against
        ``engine.placement.device_budget_bytes``. (0, 0) when no
        governor is armed (CPU universe, multi-rank worlds,
        ``engine.placement.governor=off``)."""
        if self.governor is None or self._multi:
            return 0, 0
        from nds_tpu.analysis import plan_verify
        est = plan_verify.estimate_plan(
            planned, tables=self._tables,
            encoded=self._encoded_estimates())
        return self.governor.project(est), self.governor.budget

    def choose_placement(self, planned, qname: "str | None" = None,
                         catalog=None) -> tuple:
        """Cost-model choice WITHOUT executing (tools/ndsverify.py and
        the bench planners): -> (placement, reason)."""
        if self.forced:
            return self.forced, "forced"
        from nds_tpu.analysis import plan_verify
        est = plan_verify.estimate_plan(
            planned, tables=self._tables, catalog=catalog,
            encoded=self._encoded_estimates())
        return self.cost_model.choose(planned, self.universe,
                                      tables=self._tables,
                                      catalog=catalog, qname=qname,
                                      est=est)

    def execute(self, planned, key: object = None):
        qname = self._current_query()
        placement, why = self._initial_placement(planned, qname)
        stats, sched = self._new_schedule(placement, why)
        self._apply_governor(sched, placement)
        self._apply_prefetch(sched, placement)
        self.last_stats, self.last_schedule = stats, sched
        return self._run_ladder(planned, key=key, placement=placement,
                                stats=stats, sched=sched)

    def execute_async(self, planned, key: object = None):
        """Async dispatch with the ladder armed at result() time: the
        fast path delegates to the placement executor's own
        execute_async (device pipelining preserved); any transient
        failure surfaces at result() and reruns down the ladder. Every
        handle carries its own stats/schedule, so interleaved dispatch
        (engine.concurrent_tasks) keeps per-query accounting intact."""
        qname = self._current_query()
        placement, why = self._initial_placement(planned, qname)
        stats, sched = self._new_schedule(placement, why)
        self._apply_governor(sched, placement)
        self._apply_prefetch(sched, placement)
        self.last_stats, self.last_schedule = stats, sched
        ex = self._executor(placement)
        dispatch = getattr(ex, "execute_async", None)
        # multi-rank worlds run synchronously: the per-query boundary
        # vote must fire in dispatch order on every rank, and the
        # compiled collective programs serialize execution anyway.
        # The sharded placement is sync even single-process — the
        # DistributedExecutor overrides execute() only, and the base
        # executor's inherited execute_async would route it through
        # the wrong compile path. Governed and depth-demoted queries
        # run synchronously too — the per-query chunk-shrink /
        # prefetch-depth restores ride _run_ladder's finally
        if dispatch is None or placement in (CPU, SHARDED) \
                or self._multi or sched.get("governed") \
                or "prefetch_depth" in sched:
            out = self._run_ladder(planned, key=key, placement=placement,
                                   stats=stats, sched=sched)
            return _CompletedHandle(out, self, stats, sched)
        try:
            self._predispatch(placement, qname, stats)
            inner = (dispatch(planned, key) if key is not None
                     else dispatch(planned))
        except Exception as exc:  # noqa: BLE001 - classified in rerun
            stats.attempts += 1
            stats.errors.append(f"{type(exc).__name__}: {exc}")
            if classify(exc) != "transient":
                stats.gave_up_reason = DETERMINISTIC
                raise
            out = self._run_ladder(planned, key=key, placement=placement,
                                   stats=stats, sched=sched, pending=exc)
            return _CompletedHandle(out, self, stats, sched)
        return _PipelineHandle(self, planned, key, inner, placement,
                               stats, sched)

    # ---------------------------------------------------- ladder walk

    def _current_query(self) -> "str | None":
        return faults.current_context().get("query")

    def _new_schedule(self, placement: str, why: str) -> tuple:
        stats = RetryStats()
        sched = {
            "initial": placement, "placement": placement,
            "reason": why, "reschedules": 0, "ladder": [placement],
        }
        if self._just_promoted:
            sched["promoted_back"] = True
            self._just_promoted = False
        return stats, sched

    def _predispatch(self, placement: str, qname=None,
                     stats: "RetryStats | None" = None) -> None:
        """The shared per-dispatch wiring every executor used to carry
        a copy of: liveness heartbeat + the per-attempt stream.query
        chaos site (previously fired by the power loop's retry body and
        the throughput loop's dispatch — now exactly once, here)."""
        unit = os.environ.get(watchdog.STREAM_ENV) or "engine"
        watchdog.beat(unit, query=qname, phase="pipeline.dispatch",
                      placement=placement,
                      attempt=stats.attempts if stats else 0)
        faults.fault_point("stream.query")

    def _run_ladder(self, planned, key: object = None,
                    placement: str = CPU,
                    stats: "RetryStats | None" = None,
                    sched: "dict | None" = None,
                    pending: "Exception | None" = None):
        """Walk the ladder for one query. Same-rung generic transients
        retry under the config policy's backoff/attempt budget;
        OOM/exchange-overflow step down immediately (re-running the
        identical program at the identical placement cannot help);
        deterministic failures raise. Every placement switch is a
        consensus decision (degenerate single-voter channel in
        single-process runs). ``pending`` carries an async dispatch's
        already-raised failure so its spent attempt counts against the
        same budget."""
        qname = self._current_query()
        stats = stats if stats is not None else self.last_stats
        sched = sched if sched is not None else self.last_schedule
        rungs = self.rungs_for(placement)
        start = self._clock()
        deadline_s = self.policy.deadline_s
        unit = os.environ.get(watchdog.STREAM_ENV) or "engine"

        def overrun() -> bool:
            return (deadline_s is not None
                    and self._clock() - start > deadline_s)

        def flag_deadline() -> None:
            if not stats.deadline_exceeded:
                stats.deadline_exceeded = True
                obs_metrics.counter(
                    "query_deadline_exceeded_total").inc()

        try:
            return self._walk(planned, key, rungs, stats, sched,
                              pending, qname, unit, deadline_s, start,
                              overrun, flag_deadline)
        finally:
            # per-query executor tweaks (the ladder's chunk halving /
            # stream-threshold lowering / prefetch-depth admission)
            # roll back whether the walk succeeded or raised — in
            # REVERSE order: two entries for the same attribute (depth
            # admitted pre-dispatch, then zeroed by the relief entry)
            # must unwind to the ORIGINAL value, not the intermediate
            for obj, attr, val in reversed(sched.pop("_restore", [])):
                setattr(obj, attr, val)
            sched.pop("_stream_lowered", None)
            ok = sched.pop("_succeeded", False)
            if self._multi:
                # multi-rank placement protocol: EVERY rank votes
                # exactly once per query, success or failure — the
                # only collective the scheduler runs, so vote rounds
                # pair deterministically across ranks even when a
                # failure (OOM, deadline) was rank-local
                self._boundary_vote(failed=not ok)

    def _walk(self, planned, key, rungs, stats, sched, pending, qname,
              unit, deadline_s, start, overrun, flag_deadline):
        with deadline_scope(deadline_s, self._clock, start=start):
            i = 0
            while i < len(rungs):
                rung = rungs[i]
                last_rung = i == len(rungs) - 1
                if pending is not None:
                    exc, pending = pending, None
                else:
                    if rung == CHUNKED and (
                            sched["reschedules"] > 0
                            or str(sched.get("reason", "")
                                   ).startswith("working-set")):
                        # out-of-core as a RELIEF placement must
                        # actually stream something
                        self._ensure_chunked_streams(planned, sched)
                    try:
                        self._predispatch(rung, qname, stats)
                        out = (self._executor(rung).execute(planned)
                               if key is None else
                               self._executor(rung).execute(planned,
                                                            key))
                    except QueryDeadlineExceeded as exc2:
                        stats.errors.append(
                            f"{type(exc2).__name__}: {exc2}")
                        stats.gave_up_reason = "deadline"
                        flag_deadline()
                        raise
                    except Exception as exc2:  # noqa: BLE001
                        stats.attempts += 1
                        stats.errors.append(
                            f"{type(exc2).__name__}: {exc2}")
                        exc = exc2
                    else:
                        stats.attempts += 1
                        if overrun():
                            flag_deadline()
                        self._adopt_executor_state(rung)
                        sched["placement"] = rung
                        sched["_succeeded"] = True
                        self._note_success(
                            rescheduled=sched["reschedules"] > 0,
                            qname=qname)
                        return out
                # ---- failure handling at this rung
                if classify(exc) != "transient":
                    stats.gave_up_reason = DETERMINISTIC
                    if overrun():
                        flag_deadline()
                    raise exc
                stepping = (not last_rung
                            and (is_oom(exc)
                                 or is_exchange_overflow(exc)))
                if stepping:
                    # propose first, AGREE, then act: the slack
                    # re-plan mutates executor state every rank must
                    # share, so no side effect may precede the vote
                    proposal, replan = self._propose(rungs, i, exc,
                                                     sched)
                    agreed = self.consensus.decide(proposal)
                    if agreed is None or agreed >= len(rungs):
                        # no agreement: keep placement, fail the query
                        # rather than diverge from the other ranks
                        stats.gave_up_reason = "consensus"
                        self._note_failure()
                        raise exc
                    if agreed == i and replan:
                        self._apply_replan(sched)
                    elif agreed > i:
                        i = agreed
                        self._reschedule(rungs[i], sched, qname)
                    continue
                # generic transient (or OOM at the floor): same-rung
                # retry under the policy budget, then step down if a
                # rung remains, else give up
                if stats.attempts >= self.policy.max_attempts:
                    if not last_rung:
                        proposal, _replan = self._propose(
                            rungs, i, exc, sched, force_step=True)
                        agreed = self.consensus.decide(proposal)
                        if agreed is not None and agreed < len(rungs) \
                                and agreed > i:
                            i = agreed
                            self._reschedule(rungs[i], sched, qname)
                            stats.attempts = 0
                            continue
                    stats.gave_up_reason = (
                        f"attempts_exhausted({stats.attempts})")
                    if overrun():
                        flag_deadline()
                    self._note_failure()
                    raise exc
                d = self.policy.delay_for(stats.retries)
                if (deadline_s is not None
                        and self._clock() - start + d > deadline_s):
                    stats.gave_up_reason = "deadline"
                    flag_deadline()
                    self._note_failure()
                    raise exc
                stats.retries += 1
                stats.backoff_s += d
                obs_metrics.counter("query_retries_total").inc()
                watchdog.beat(unit, query=qname, phase="retry",
                              attempt=stats.retries)
                if d > 0:
                    self.policy._sleep(d)
        raise RuntimeError("unreachable: ladder exhausted without raise")

    def _clock(self):
        return self.policy._clock()

    def _ensure_chunked_streams(self, planned, sched: dict) -> None:
        """The chunked placement only relieves memory when something
        actually streams: with ``engine.stream_bytes`` unset, no
        sub-threshold table chunks, and a ladder entry (or cost-model
        working-set choice) would re-execute the identical full-upload
        program. Lower the executor's stream threshold FOR THIS QUERY
        (restored after the walk) so the largest scanned table
        streams."""
        if sched.get("_stream_lowered") or not self._tables:
            return
        ex = self._executor(CHUNKED)
        from nds_tpu.sql import plan as P
        biggest = 0
        roots = [planned.root, *planned.scalar_subplans] \
            if isinstance(planned, P.PlannedQuery) else []
        for root in roots:
            for node in P.walk_plan(root):
                if (isinstance(node, P.Scan)
                        and node.table in self._tables):
                    biggest = max(biggest, memwatch.table_bytes(
                        self._tables[node.table]))
        if biggest and ex.stream_bytes >= biggest:
            sched["_stream_lowered"] = True
            sched.setdefault("_restore", []).append(
                (ex, "stream_bytes", ex.stream_bytes))
            ex.stream_bytes = max(biggest - 1, 1)

    def _propose(self, rungs: list, i: int, exc: Exception,
                 sched: dict, force_step: bool = False
                 ) -> "tuple[int, bool]":
        """This rank's vote: (rung index, is_slack_replan). Pure — NO
        side effect happens until the consensus round agrees; the
        sharded re-plan (slack growth) is only proposed once per
        query, and only for exchange overflow (growing slack cannot
        fix an OOM)."""
        if (not force_step and rungs[i] == SHARDED
                and is_exchange_overflow(exc)
                and not sched.get("slack_grown")):
            ex = self._executors.get(SHARDED)
            if ex is not None and hasattr(ex, "grow_slack"):
                return i, True  # re-vote the SAME rung, re-planned
        return i + 1, False

    def _apply_replan(self, sched: dict) -> None:
        """Consensus-agreed sharded re-plan: double the base slack and
        invalidate compiled programs — on every rank, together (the
        vote already passed when this runs)."""
        self._executors[SHARDED].grow_slack()
        sched["slack_grown"] = True
        sched.setdefault("ladder", []).append(SHARDED_REPLAN)
        obs_metrics.counter("query_reschedules_total").inc()
        sched["reschedules"] += 1

    def _reschedule(self, rung: str, sched: dict, qname) -> None:
        if sched.get("ladder", [None])[-1] == rung:
            return  # slack re-plan already recorded this step
        sched["reschedules"] += 1
        sched["ladder"].append(rung)
        # reflect the rung being attempted even if it too fails — a
        # failed query's summary names the DEEPEST placement tried
        sched["placement"] = rung
        obs_metrics.counter("query_reschedules_total").inc()
        if rung == CHUNKED:
            # the ladder's chunked entry runs THIS query at half the
            # current chunk size (the device just proved the full
            # working set does not fit); per-query — _run_ladder
            # restores it afterwards, so repeated walks do not grind
            # every later chunked query down to the floor (the
            # executor's own OOM shrink loop stays the persistent
            # adaptation)
            ex = self._executor(CHUNKED)
            from nds_tpu.engine.chunked_exec import ChunkedExecutor
            sched.setdefault("_restore", []).append(
                (ex, "chunk_rows", ex.chunk_rows))
            ex.chunk_rows = max(ex.chunk_rows // 2,
                                ChunkedExecutor.MIN_CHUNK_ROWS)
            # the relief entry also runs serial: the OOM just proved
            # memory is the constraint, and depth x chunk of staged
            # prefetch buffers works against exactly that relief.
            # Registered in the same _restore list, so depth and
            # chunk_rows roll back TOGETHER after the walk (hasattr:
            # test stubs model only the fields they exercise)
            if hasattr(ex, "prefetch_depth"):
                sched["_restore"].append(
                    (ex, "prefetch_depth", ex.prefetch_depth))
                ex.prefetch_depth = 0
        # deliberately NOT a TaskFailureCollector notification: a
        # reschedule is a scheduling decision, not a recovered task
        # failure — the summary's placement/reschedules/ladder fields
        # and query_reschedules_total carry the signal without turning
        # every walked query into CompletedWithTaskFailures
        print(f"RESCHEDULED {qname or 'query'} -> {rung} "
              f"(ladder {'->'.join(sched['ladder'])})")

    # ------------------------------------------- demotion / promotion

    def _note_success(self, rescheduled: bool,
                      qname: "str | None" = None) -> None:
        hwm = memwatch.high_water()
        if hwm and not self._multi:
            # the HWM history is RANK-LOCAL: feeding it to the cost
            # model on a multi-process world would let one rank's
            # observed peak start a query at a different placement
            # than its peers compute — the silent-divergence deadlock
            # the consensus step exists to prevent. Single-process
            # pipelines (where the initial choice needs no agreement)
            # use it freely.
            self.cost_model.observe(qname or self._current_query(),
                                    hwm.get("device_hwm_bytes", 0))
        if self._multi:
            return  # demotion/promotion run in the boundary vote
        if rescheduled:
            self._clean_streak = 0
            self._reschedule_streak += 1
            if (self._demoted_to is None
                    and self._reschedule_streak >= self.demote_after
                    and self.ladder_on):
                self._switch_start(self.last_schedule.get("placement"))
        else:
            self._reschedule_streak = 0
            if self._demoted_to is not None:
                self._clean_streak += 1
                if self._clean_streak >= self.promote_after:
                    self._promote()

    def _note_failure(self) -> None:
        """A query that exhausted the whole ladder counts toward the
        sticky demotion too — the old FALLBACK_AFTER contract, now
        reversible."""
        if self._multi:
            return  # demotion/promotion run in the boundary vote
        self._clean_streak = 0
        self._reschedule_streak += 1
        if (self._demoted_to is None
                and self._reschedule_streak >= self.demote_after
                and self.ladder_on and len(self.universe) > 1):
            self._switch_start(self.floor)

    def _boundary_vote(self, failed: bool) -> None:
        """Multi-rank placement protocol: one consensus round per
        query, entered by EVERY rank regardless of its local outcome,
        so the allgathers pair deterministically. Each rank votes the
        start-rung it wants next (its local streaks shape the vote;
        the SHARED outcome shapes the state), the deepest demotion
        wins, and either every rank switches or — on a failed/partial
        gather — none does."""
        order = list(self.universe)
        cur = order.index(self._demoted_to) if self._demoted_to else 0
        if failed:
            self._clean_streak = 0
            self._reschedule_streak += 1
            want = cur
            if (self.ladder_on and len(order) > 1
                    and self._reschedule_streak >= self.demote_after):
                floor_i = (order.index(self.floor)
                           if self.floor in order else len(order) - 1)
                want = min(cur + 1, floor_i)
        else:
            self._reschedule_streak = 0
            want = cur
            if cur:
                self._clean_streak += 1
                if self._clean_streak >= self.promote_after:
                    want = 0
        agreed = self.consensus.decide(want)
        if agreed is None:
            return  # no agreement: nobody moves
        agreed = min(agreed, len(order) - 1)
        new = None if agreed == 0 else order[agreed]
        if new == self._demoted_to:
            return
        if new is None:
            self._demoted_to = None
            self._reschedule_streak = 0
            self._clean_streak = 0
            self._just_promoted = True
            obs_metrics.counter("placement_promotions_total").inc()
            print("PLACEMENT PROMOTION: stream restored to the cost "
                  "model's placement after clean queries")
        else:
            self._demoted_to = new
            self._clean_streak = 0
            obs_metrics.counter("placement_demotions_total").inc()
            print(f"PLACEMENT DEMOTION: stream now starts at "
                  f"{new!r} (consensus)")

    def _switch_start(self, target: "str | None") -> None:
        if not target or target == self.universe[0]:
            return
        vote = list(self.universe).index(target) \
            if target in self.universe else len(self.universe) - 1
        agreed = self.consensus.decide(vote)
        if agreed is None:
            return
        agreed = min(agreed, len(self.universe) - 1)
        self._demoted_to = self.universe[agreed]
        self._clean_streak = 0
        obs_metrics.counter("placement_demotions_total").inc()
        print(f"PLACEMENT DEMOTION: stream now starts at "
              f"{self._demoted_to!r} after {self._reschedule_streak} "
              f"consecutive rescheduled queries")

    def _promote(self) -> None:
        agreed = self.consensus.decide(0)
        if agreed is None or agreed != 0:
            # some rank still wants the demotion: stay put, retry the
            # promotion after the next clean streak
            self._clean_streak = 0
            return
        self._demoted_to = None
        self._reschedule_streak = 0
        self._clean_streak = 0
        self._just_promoted = True
        obs_metrics.counter("placement_promotions_total").inc()
        print("PLACEMENT PROMOTION: stream restored to the cost "
              "model's placement after clean queries")


def make_pipeline(config, backend: "str | None" = None
                  ) -> ExecutionPipeline:
    """Build the pipeline a Session uses as its executor factory, from
    an EngineConfig — the single construction point make_session
    (utils/power_core.py) routes every backend through."""
    backend = backend or config.get("engine.backend", "cpu")
    mesh = None
    stream_bytes = config.get_int("engine.stream_bytes", 0)
    chunk_rows = config.get_int("engine.chunk_rows", 0) or None
    precision = "f64"
    if backend == "tpu" and config.get_bool("engine.floats"):
        precision = config.get("engine.precision", "f64")
    if backend == "distributed":
        from nds_tpu.parallel import multihost
        multihost.maybe_initialize()
        shards = config.get_int("engine.mesh.shards", 0)
        mesh = multihost.global_mesh(shards if shards > 1 else None)
    return ExecutionPipeline(
        backend=backend, config=config, mesh=mesh, precision=precision,
        stream_bytes=stream_bytes, chunk_rows=chunk_rows)
