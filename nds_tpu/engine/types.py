"""Logical column types for the TPU columnar engine.

The reference expresses schemas as PySpark ``StructType``s
(`nds/nds_schema.py:49-568`, `nds-h/nds_h_schema.py:36-148`) and toggles
DecimalType vs DoubleType via ``use_decimal`` (`nds/nds_schema.py:43-47`).
Here the logical types are engine-owned and chosen for how they lay out on
TPU:

- integers      -> int32 where the domain fits (TPU-native), int64 otherwise
- DECIMAL(p,s)  -> scaled int64 (exact; reference's use_decimal=True), or
                   float when the config enables floats mode (reference's
                   --floats / use_decimal=False epsilon path)
- DATE          -> int32 days since 1970-01-01 (epoch days); civil-date
                   fields are computed with integer ops on device
- CHAR/VARCHAR  -> dictionary-encoded: int32 codes on device, the code
                   order equals lexicographic value order so comparisons
                   and ORDER BY work directly on codes; the value
                   dictionary stays on host
- IDENTIFIER    -> join keys; int64 by default (sr_ticket_number-style
                   overflow rationale, `nds/nds_schema.py:328-331`)

Nothing here depends on jax; this module is shared by the CPU oracle and
the device engine.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class DType:
    """Base logical type. Instances are immutable and hashable."""

    name: str = "dtype"

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other) -> bool:
        return repr(self) == repr(other)

    def __hash__(self) -> int:
        return hash(repr(self))


class IntType(DType):
    def __init__(self, bits: int = 32) -> None:
        assert bits in (8, 16, 32, 64)
        self.bits = bits
        self.name = f"int{bits}"


class FloatType(DType):
    def __init__(self, bits: int = 64) -> None:
        assert bits in (32, 64)
        self.bits = bits
        self.name = f"float{bits}"


class DecimalType(DType):
    """Exact decimal; physically a scaled int64 unless floats mode."""

    def __init__(self, precision: int, scale: int) -> None:
        self.precision = precision
        self.scale = scale
        self.name = f"decimal({precision},{scale})"


class DateType(DType):
    name = "date"


class StringType(DType):
    """Dictionary-encoded string; length arg kept for schema fidelity."""

    def __init__(self, length: int | None = None, fixed: bool = False) -> None:
        self.length = length
        self.fixed = fixed
        kind = "char" if fixed else "varchar"
        self.name = f"{kind}({length})" if length is not None else "string"


class BoolType(DType):
    name = "bool"


INT32 = IntType(32)
INT64 = IntType(64)
FLOAT32 = FloatType(32)
FLOAT64 = FloatType(64)
DATE = DateType()
STRING = StringType()
BOOL = BoolType()


def char(n: int) -> StringType:
    return StringType(n, fixed=True)


def varchar(n: int) -> StringType:
    return StringType(n, fixed=False)


def decimal(p: int, s: int) -> DecimalType:
    return DecimalType(p, s)


def is_numeric(t: DType) -> bool:
    return isinstance(t, (IntType, FloatType, DecimalType))


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True


@dataclass
class Schema:
    fields: list[Field] = field(default_factory=list)

    @classmethod
    def of(cls, *cols: tuple) -> "Schema":
        fs = []
        for c in cols:
            name, dtype = c[0], c[1]
            nullable = c[2] if len(c) > 2 else True
            fs.append(Field(name, dtype, nullable))
        return cls(fs)

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __len__(self) -> int:
        return len(self.fields)
