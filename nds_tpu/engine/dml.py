"""Host-side DML: INSERT INTO ... SELECT and DELETE FROM ... WHERE.

The reference runs data maintenance through Spark DML against an
Iceberg/Delta warehouse (`nds/nds_maintenance.py:191-268`). The
TPU-native split puts table *mutation* on the host — the authoritative
warehouse is host columnar memory (HostTable) persisted as parquet, and
the device engines consume uploaded snapshots — so DML is:

- INSERT: execute the planned SELECT on the session's engine (the
  LF_* refresh views run as ordinary queries, device or CPU), then
  append the result to the target HostTable;
- DELETE: evaluate the predicate per row host-side with SQL 3-valued
  logic (a row is deleted only where the predicate is TRUE; NULL keeps
  the row), executing any subqueries through the engine first.

Mutations land as DELTAS (`columnar/delta.py`), not rewrites: inserts
append a segment (numeric concat + dictionary-size string merge, specs
re-derived from exact merged stats), deletes flip bits in a deleted-row
bitmask the scan keep-masks consult. Base column arrays — and the
device buffers and AOT programs keyed on their content — are never
touched for tables the DML doesn't name, and the session scopes
invalidation to plans that scan the mutated table (segment-granular
content digests make everything else hit). ``faults.py`` exposes a
``dml.apply`` site here so chaos runs can land a crash between the
journal START-mark and the snapshot commit.
"""

from __future__ import annotations

import numpy as np

from nds_tpu.columnar import delta
from nds_tpu.engine.types import (
    DateType, DecimalType, FloatType, IntType, StringType,
)
from nds_tpu.io.host_table import HostTable, from_arrays
from nds_tpu.resilience import faults
from nds_tpu.sql import ast


class DmlError(ValueError):
    pass


# ------------------------------------------------------------------ insert

def result_to_arrays(result, schema) -> dict:
    """ResultTable -> from_arrays()-shaped dict positionally cast to the
    target schema (INSERT resolves columns by position, like the
    reference's ``insert into T (select * from view)``)."""
    arrays: dict[str, np.ndarray] = {}
    for f, col, dt, valid in zip(schema.fields, result.cols,
                                 result.dtypes, result.valids):
        a = np.asarray(col)
        if isinstance(f.dtype, StringType):
            out = a.astype(object)
        elif isinstance(f.dtype, DecimalType):
            if isinstance(dt, DecimalType):
                # rescale between source/target decimal scales
                shift = f.dtype.scale - dt.scale
                ints = np.asarray(a, dtype=np.int64)
                out = (ints * 10**shift if shift >= 0
                       else ints // 10**(-shift))
            elif isinstance(dt, (IntType, DateType)):
                out = (np.asarray(a, dtype=np.int64)
                       * 10**f.dtype.scale)
            else:
                out = np.round(np.asarray(a, dtype=np.float64)
                               * 10**f.dtype.scale).astype(np.int64)
        elif isinstance(f.dtype, FloatType):
            if isinstance(dt, DecimalType):
                out = np.asarray(a, dtype=np.float64) / 10**dt.scale
            else:
                out = np.asarray(a, dtype=np.float64)
        elif isinstance(f.dtype, (IntType, DateType)):
            if isinstance(dt, DecimalType):
                out = (np.asarray(a, dtype=np.int64)
                       // 10**dt.scale)
            else:
                out = np.asarray(np.nan_to_num(
                    a.astype(np.float64)) if a.dtype.kind == "f" else a,
                    dtype=np.int64)
        else:
            out = a
        arrays[f.name] = out
        if valid is not None:
            arrays[f.name + "#null"] = np.asarray(valid, dtype=bool)
    return arrays


def segment_from_result(table: HostTable, result) -> HostTable:
    """Build the O(result)-sized segment table an INSERT appends —
    encoding only the NEW rows (the base table is never decoded)."""
    return from_arrays(table.name, table.schema,
                       result_to_arrays(result, table.schema))


def append_rows(table: HostTable, result,
                seg_id: str = "") -> HostTable:
    """New effective HostTable with the result's rows appended as a
    delta segment (`columnar/delta.py`): base arrays concatenate
    in-place-free, string dictionaries merge at dictionary size, and
    encoding specs re-derive from exact merged statistics — no
    full-table re-encode."""
    faults.fault_point("dml.apply", table=table.name, action="insert",
                       rows=result.nrows)
    return delta.append_segment(table, segment_from_result(table, result),
                                seg_id=seg_id)


# ------------------------------------------------------------------ delete

def apply_delete(table: HostTable, keep: np.ndarray) -> HostTable:
    """New effective HostTable with non-kept rows marked deleted in the
    delta bitmask — column arrays (and their memoized encoding specs)
    are shared untouched; scans consult the mask."""
    faults.fault_point("dml.apply", table=table.name, action="delete",
                       rows=int((~np.asarray(keep, bool)).sum()))
    return delta.apply_delete(table, keep)


def filter_rows(table: HostTable, keep: np.ndarray) -> HostTable:
    """PHYSICAL row filter (gather): compaction's building block; DML
    itself uses ``apply_delete``'s logical mask."""
    cols = {}
    for f in table.schema:
        col = table.columns[f.name]
        mask = (col.null_mask[keep] if col.null_mask is not None
                else None)
        cols[f.name] = type(col)(col.dtype, col.values[keep],
                                 col.dictionary, mask)
    return HostTable(table.name, table.schema, cols)


def _coerce_pair(lv, lt, rv, rt):
    """Align two comparison operands the way SQL implicitly casts:
    scaled decimals against plain numerics (rescale the plain side),
    DATE against ISO string literals (parse to epoch days). Each side
    is (values, dtype) with dtype None for bare literals."""
    from nds_tpu.sql.planner import _date_to_days

    def to_days(v):
        if isinstance(v, np.ndarray) and v.dtype == object:
            return np.array([_date_to_days(x) for x in v],
                            dtype=np.int64)
        return _date_to_days(v)

    if isinstance(lt, DecimalType) and not isinstance(rt, DecimalType):
        rv = (np.asarray(rv, dtype=np.float64)
              * 10**lt.scale).astype(np.int64)
    elif isinstance(rt, DecimalType) and not isinstance(lt, DecimalType):
        lv = (np.asarray(lv, dtype=np.float64)
              * 10**rt.scale).astype(np.int64)
    elif isinstance(lt, DecimalType) and isinstance(rt, DecimalType):
        if lt.scale != rt.scale:
            s = max(lt.scale, rt.scale)
            lv = np.asarray(lv, np.int64) * 10**(s - lt.scale)
            rv = np.asarray(rv, np.int64) * 10**(s - rt.scale)
    elif isinstance(lt, DateType) and rt is None:
        rv = to_days(rv)
    elif isinstance(rt, DateType) and lt is None:
        lv = to_days(lv)
    return lv, rv


class _PredEval:
    """SQL 3-valued predicate evaluator over a HostTable's columns.
    ``eval`` returns (values, valid, dtype) triples — dtype carries
    decimal scales and DATE-ness into comparisons so literals coerce
    like the planner's `_coerce_date_cmp`/decimal rescaling do.
    Subqueries run through the session's engine first (`DF_SS.sql`
    shapes: IN-subquery and scalar min/max subqueries)."""

    def __init__(self, session, table: HostTable):
        self.session = session
        self.table = table
        self.n = table.nrows

    def _col(self, name: str):
        try:
            col = self.table.columns[name]
        except KeyError:
            raise DmlError(
                f"DELETE predicate references unknown column {name!r}")
        vals = col.decode() if col.is_string else col.values
        valid = (col.null_mask if col.null_mask is not None
                 else np.ones(self.n, dtype=bool))
        return vals, valid, col.dtype

    def _subquery_result(self, sel: ast.Select):
        planned = self.session.plan_ast(sel)
        executor = self.session._executor_factory(self.session.tables)
        return executor.execute(planned)

    def eval(self, e: ast.Expr):
        ones = lambda: np.ones(self.n, dtype=bool)
        if isinstance(e, ast.Column):
            return self._col(e.name)
        if isinstance(e, ast.Literal):
            v = e.value
            if isinstance(v, str):
                arr = np.full(self.n, v, dtype=object)
            else:
                arr = np.full(self.n, v)
            return arr, ones(), None
        if isinstance(e, ast.IsNull):
            _v, valid, _t = self.eval(e.expr)
            out = (valid if e.negated else ~valid)
            return out, ones(), None
        if isinstance(e, ast.UnaryOp) and e.op == "not":
            v, valid, _t = self.eval(e.expr)
            return ~v.astype(bool), valid, None
        if isinstance(e, ast.BinOp):
            return self._binop(e)
        if isinstance(e, ast.Between):
            lo = ast.BinOp(">=", e.expr, e.low)
            hi = ast.BinOp("<=", e.expr, e.high)
            v, valid, _t = self._binop(ast.BinOp("and", lo, hi))
            if e.negated:
                v = ~v
            return v, valid, None
        if isinstance(e, ast.InList):
            v, valid, t = self.eval(e.expr)
            vals = np.asarray([lit.value for lit in e.items])
            vals, v = _coerce_pair(vals, None, v, t)
            out = np.isin(v, vals)
            if e.negated:
                out = ~out
            return out, valid, None
        if isinstance(e, ast.InSubquery):
            v, valid, t = self.eval(e.expr)
            sub = self._subquery_result(e.query)
            if len(sub.cols) != 1:
                raise DmlError("IN subquery must produce one column")
            sv = np.asarray(sub.cols[0])
            svalid = sub.valids[0]
            if svalid is not None:
                sv = sv[svalid]
            v, sv = _coerce_pair(v, t, sv, sub.dtypes[0])
            out = np.isin(v, sv)
            if e.negated:
                # NOT IN with any NULL in the subquery -> never TRUE
                if svalid is not None and not svalid.all():
                    return np.zeros(self.n, dtype=bool), valid, None
                out = ~out
            return out, valid, None
        if isinstance(e, ast.ScalarSubquery):
            sub = self._subquery_result(e.query)
            if sub.nrows != 1 or len(sub.cols) != 1:
                raise DmlError(
                    f"scalar subquery returned {sub.nrows} rows")
            val = np.asarray(sub.cols[0])[0]
            ok = sub.valids[0] is None or bool(sub.valids[0][0])
            return (np.full(self.n, val),
                    np.full(self.n, ok, dtype=bool), sub.dtypes[0])
        raise DmlError(
            f"unsupported DELETE predicate node {type(e).__name__}")

    def _binop(self, e: ast.BinOp):
        op = e.op.lower()
        lv, lval, lt = self.eval(e.left)
        rv, rval, rt = self.eval(e.right)
        if op in ("and", "or"):
            lb, rb = lv.astype(bool), rv.astype(bool)
            if op == "and":
                v = lb & rb
                # NULL AND FALSE = FALSE (valid); NULL AND TRUE = NULL
                valid = (lval & rval) | (lval & ~lb) | (rval & ~rb)
            else:
                v = lb | rb
                valid = (lval & rval) | (lval & lb) | (rval & rb)
            return v, valid, None
        lv, rv = _coerce_pair(lv, lt, rv, rt)
        valid = lval & rval
        cmp = {"=": np.equal, "<>": np.not_equal, "!=": np.not_equal,
               "<": np.less, "<=": np.less_equal, ">": np.greater,
               ">=": np.greater_equal}.get(op)
        if cmp is None:
            raise DmlError(f"unsupported DELETE operator {op!r}")
        return cmp(lv, rv), valid, None


def delete_mask(session, table: HostTable,
                where: ast.Expr | None) -> np.ndarray:
    """True where the row survives the DELETE."""
    if where is None:
        return np.zeros(table.nrows, dtype=bool)
    v, valid, _t = _PredEval(session, table).eval(where)
    # delete iff predicate is TRUE (valid & value); NULL/FALSE keep
    return ~(v.astype(bool) & valid)
