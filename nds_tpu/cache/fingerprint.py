"""Plan fingerprints: the cache key for persisted AOT executables.

A compiled XLA program is reusable across processes only when EVERYTHING
that shaped it matches: the logical plan, the input tables (the trace
bakes host-derived constants — string-dictionary predicate tables,
col_bounds key-packing clips, sorted-build verdicts, reduced-scan
survivor capacities — so table CONTENT matters, not just schema), the
compute precision, the capacity slack, the jax/jaxlib versions, the
backend platform, the mesh topology, and the engine's own trace code.
``fingerprint()`` folds all of it into one sha256 hex string; any drift
in any component lands on a different key, so the cache can never serve
a stale program — version skew is a MISS by construction, never an
error case.

Components:

- ``canonical(obj)`` — deterministic text form of a plan tree
  (dataclass walk over plan.Node / ir.IR / AggSpec / WindowSpec /
  DType; numpy scalars normalized through ``.item()`` so numpy-2 repr
  drift cannot rename keys).
- ``table_stamp(table)`` — name, row count, schema, and a full-content
  sha256 (values + null masks + dictionaries). The digest is computed
  once per HostTable object and memoized ON the object (tables are
  immutable once registered; DML builds new objects), so a 99-query
  power run hashes each table once, not once per query.
- ``code_epoch()`` — sha256 over the engine modules whose source
  shapes the traced program. A PR that changes the trace logic
  silently invalidates every cached executable instead of serving
  programs the new code would no longer build.

Nothing here imports jax: fingerprinting (and the ndscache CLI's
ls/verify/prune verbs) must run on any host.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

import numpy as np

from nds_tpu.engine.types import DType

# bump to invalidate every existing cache entry on a format change
FP_VERSION = 1

# engine modules whose source text shapes the compiled programs (the
# trace interpreters and everything they bake constants from)
_EPOCH_MODULES = (
    "nds_tpu/engine/device_exec.py",
    "nds_tpu/engine/chunked_exec.py",
    "nds_tpu/engine/kernels.py",
    "nds_tpu/engine/staging.py",
    "nds_tpu/parallel/dist_exec.py",
    "nds_tpu/parallel/exchange.py",
    "nds_tpu/parallel/mesh.py",
    "nds_tpu/sql/plan.py",
    "nds_tpu/sql/ir.py",
)

_epoch_cache: str | None = None


def code_epoch() -> str:
    """sha256 (hex) over the engine sources that shape traced programs;
    computed once per process."""
    global _epoch_cache
    if _epoch_cache is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        h = hashlib.sha256()
        for rel in _EPOCH_MODULES:
            path = os.path.join(root, rel)
            h.update(rel.encode())
            try:
                with open(path, "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(b"<missing>")
        _epoch_cache = h.hexdigest()
    return _epoch_cache


# ------------------------------------------------------- canonical plan

def canonical(obj) -> str:
    """Deterministic text form of a plan/IR tree. Object identity and
    field ORDER are preserved (a shared CTE body serializes at each
    reference — the trace caches by identity, but identical text means
    identical traced program, which is all the key needs)."""
    if obj is None:
        return "~"
    if isinstance(obj, DType):
        return repr(obj)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        parts = [type(obj).__name__]
        for f in dataclasses.fields(obj):
            parts.append(f"{f.name}={canonical(getattr(obj, f.name))}")
        return "(" + " ".join(parts) + ")"
    if isinstance(obj, (list, tuple)):
        return "[" + ",".join(canonical(x) for x in obj) + "]"
    if isinstance(obj, np.generic):
        # numpy>=2 reprs carry an "np.int64(...)" wrapper; .item()
        # normalizes both numpy generations onto the python repr
        return repr(obj.item())
    if isinstance(obj, (str, bytes, bool, int, float)):
        return repr(obj)
    return f"<{type(obj).__name__}:{obj!r}>"


def plan_digest(planned) -> str:
    """Short stable digest of one plan tree (stage temp naming and
    cache-entry labels)."""
    return hashlib.sha256(canonical(planned).encode()).hexdigest()[:12]


# --------------------------------------------------------- table stamps

_DIGEST_ATTR = "_nds_content_sha256"


def table_digest(table) -> str:
    """Full-content sha256 of a HostTable, memoized on the object (one
    hash per table per process; DML replaces table objects, so a stale
    memo cannot survive a mutation)."""
    memo = getattr(table, _DIGEST_ATTR, None)
    if memo is not None:
        return memo
    delta = getattr(table, "_nds_delta", None)
    if delta is not None:
        # mutated table: segment-granular composition (base digest +
        # ordered segment digests + deleted-bitmask digest). Only the
        # touched table's stamp moves — every other table keeps its
        # memo, so a delta invalidates nothing it doesn't scan.
        digest = delta.content_digest()
        try:
            setattr(table, _DIGEST_ATTR, digest)
        except Exception:  # noqa: BLE001 - slotted table
            pass
        return digest
    h = hashlib.sha256()
    for name in sorted(table.columns):
        col = table.columns[name]
        h.update(name.encode())
        h.update(repr(col.dtype).encode())
        vals = np.ascontiguousarray(col.values)
        h.update(str(vals.dtype).encode())
        h.update(str(vals.shape).encode())
        h.update(vals)
        if col.null_mask is not None:
            h.update(b"#null")
            h.update(np.ascontiguousarray(col.null_mask))
        if col.dictionary is not None:
            h.update(b"#dict")
            # object arrays have no stable buffer: hash the decoded
            # text form (dictionaries are sorted-unique, so this is
            # deterministic for identical content)
            h.update("\x00".join(
                str(v) for v in col.dictionary).encode())
    digest = h.hexdigest()
    try:
        setattr(table, _DIGEST_ATTR, digest)
    except Exception:  # noqa: BLE001 - slotted table: recompute next time
        pass
    return digest


def table_stamp(table) -> str:
    """One table's contribution to a fingerprint: identity + shape +
    content."""
    return (f"{table.name}|rows={table.nrows}"
            f"|sha256={table_digest(table)}")


def scan_tables(planned) -> list:
    """Sorted unique table names scanned anywhere in a plan (root +
    scalar subplans + any extra roots an executor substitutes in)."""
    from nds_tpu.sql import plan as P
    roots = []
    if isinstance(planned, P.PlannedQuery):
        roots = [planned.root, *planned.scalar_subplans]
    elif planned is not None:
        roots = [planned]
    names = set()
    for root in roots:
        for node in P.walk_plan(root):
            if isinstance(node, P.Scan):
                names.add(node.table)
    return sorted(names)


# ----------------------------------------------------------- fingerprint

def fingerprint(planned, tables: dict, *, kind: str,
                parts: dict | None = None,
                extra_roots: list | None = None) -> str:
    """sha256 hex key for one compilable unit.

    ``kind`` names the program family (executor class / "compact" /
    "chunkscan"); ``parts`` carries every scalar that shapes the
    program (slack, precision, platform, jax versions, mesh shape...);
    ``extra_roots`` adds plan trees outside the PlannedQuery proper
    (the partial-agg merge plan). Tables are stamped by CONTENT, so a
    same-shape warehouse with different rows misses instead of serving
    stale baked constants."""
    h = hashlib.sha256()
    h.update(f"fp_v{FP_VERSION}".encode())
    h.update(code_epoch().encode())
    # columnar encoding mode (nds_tpu/columnar/): encoded buffer sets
    # change every program's input signature and fused decode; specs
    # derive deterministically from table CONTENT (stamped below), so
    # version+mode is the whole remaining degree of freedom
    from nds_tpu import columnar
    h.update(f"columnar={columnar.fingerprint_token()}".encode())
    h.update(kind.encode())
    h.update(canonical(planned).encode())
    for root in (extra_roots or []):
        h.update(canonical(root).encode())
    names = scan_tables(planned)
    for root in (extra_roots or []):
        names = sorted(set(names) | set(scan_tables(root)))
    for name in names:
        t = tables.get(name)
        if t is None:
            h.update(f"{name}|<unregistered>".encode())
        else:
            h.update(table_stamp(t).encode())
    for k in sorted(parts or {}):
        h.update(f"{k}={parts[k]!r}".encode())
    return h.hexdigest()
