"""Persistent AOT plan-cache store: one directory, one entry per
fingerprint, every payload sha256-stamped.

Layout (``<root>/<fp[:2]>/<fp>/``):

- ``payload-<sha16>.bin`` — pickle of the executor-specific payload
  dict (the serialized XLA executable plus its pytree defs and
  host-side trace byproducts such as output string dictionaries),
  named by a prefix of its own sha256 so the file is immutable once
  referenced;
- ``manifest.json`` — metadata + the payload's file name and full
  sha256 (the same digest-manifest idea io/integrity.py uses for
  warehouse artifacts, specialized to the single-entry shape so
  ``ndscache verify`` and the load path share one verdict).

Failure policy (the ISSUE's hard rule): a cache problem is NEVER a
query failure. Any read-side anomaly — torn payload, digest mismatch,
version skew, an unpicklable blob from a different jax — warns once on
stderr, bumps ``compile_cache_errors_total``, quarantines the entry
(best effort, skipped in readonly mode), and returns a miss so the
caller falls through to a fresh compile. Writes are atomic and
manifest-last: the content-named payload lands first (pid-suffixed
tmp + ``os.replace``), then the manifest that references it — so a
reader holding ANY complete manifest always finds the complete
payload it names, even while another process re-persists the same
fingerprint. A superseded payload file (same fingerprint, different
bytes) lingers until ``prune`` removes the entry; deleting it inline
could yank the file out from under a reader that already loaded the
older manifest.

Metrics: ``compile_cache_hits_total`` / ``compile_cache_misses_total``
/ ``compile_cache_errors_total`` and the byte counters
``compile_cache_bytes_read_total`` / ``compile_cache_bytes_written_total``
(per-query deltas surface as the BenchReport ``cache`` block).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time

from nds_tpu.io.integrity import write_json_atomic
from nds_tpu.obs import metrics as obs_metrics

PAYLOAD_PREFIX = "payload-"
MANIFEST_NAME = "manifest.json"


def _payload_name(sha: str) -> str:
    return f"{PAYLOAD_PREFIX}{sha[:16]}.bin"

# manifest format version; payload compatibility itself is governed by
# the fingerprint (FP_VERSION + code epoch + jax versions)
STORE_VERSION = 1


def _warn(msg: str) -> None:
    obs_metrics.counter("compile_cache_errors_total").inc()
    print(f"PLAN-CACHE WARNING: {msg}")


class PlanCache:
    """Disk-backed compile-once store shared by every placement
    executor and every process pointed at the same directory."""

    def __init__(self, root: str, readonly: bool = False):
        self.root = os.path.abspath(root)
        self.readonly = readonly
        if not readonly:
            os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------ paths

    def entry_dir(self, fp: str) -> str:
        return os.path.join(self.root, fp[:2], fp)

    def _manifest_path(self, fp: str) -> str:
        return os.path.join(self.entry_dir(fp), MANIFEST_NAME)

    def _payload_path(self, fp: str, manifest: dict) -> str | None:
        """Path of the payload file the manifest references, or None
        when the reference is absent/unsafe (treated as corrupt)."""
        name = manifest.get("payload")
        if (not isinstance(name, str) or os.path.basename(name) != name
                or not name.startswith(PAYLOAD_PREFIX)):
            return None
        return os.path.join(self.entry_dir(fp), name)

    def payload_path(self, fp: str) -> str | None:
        """Resolve the live payload file for ``fp`` via its manifest
        (admin/test helper; the read path resolves inline)."""
        try:
            with open(self._manifest_path(fp)) as f:
                return self._payload_path(fp, json.load(f))
        except (OSError, ValueError):
            return None

    # ------------------------------------------------------------- read

    def get(self, fp: str, expect_kind: str | None = None):
        """Payload dict for ``fp``, or None (miss). Every anomaly
        degrades to a miss with a warning + metric; the entry is
        quarantined so the next process does not re-pay the failed
        read."""
        manifest_path = self._manifest_path(fp)
        if not os.path.exists(manifest_path):
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as exc:
            _warn(f"unreadable manifest for {fp[:12]}… "
                  f"({type(exc).__name__}: {exc}); recompiling fresh")
            self._quarantine(fp)
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        if manifest.get("store_version") != STORE_VERSION:
            _warn(f"store version skew for {fp[:12]}… "
                  f"({manifest.get('store_version')!r} != "
                  f"{STORE_VERSION}); recompiling fresh")
            self._quarantine(fp)
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        payload_path = self._payload_path(fp, manifest)
        if payload_path is None:
            _warn(f"manifest for {fp[:12]}… names no payload; "
                  f"recompiling fresh")
            self._quarantine(fp)
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        try:
            with open(payload_path, "rb") as f:
                blob = f.read()
        except OSError as exc:
            _warn(f"unreadable payload for {fp[:12]}… ({exc}); "
                  f"recompiling fresh")
            self._quarantine(fp)
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        actual = hashlib.sha256(blob).hexdigest()
        if actual != manifest.get("sha256"):
            _warn(f"corrupt entry {fp[:12]}…: sha256 expected "
                  f"{manifest.get('sha256')}, got {actual}; "
                  f"recompiling fresh")
            self._quarantine(fp)
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        if expect_kind and manifest.get("kind") != expect_kind:
            _warn(f"kind mismatch for {fp[:12]}…: entry is "
                  f"{manifest.get('kind')!r}, wanted {expect_kind!r}; "
                  f"recompiling fresh")
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        try:
            payload = pickle.loads(blob)
        except Exception as exc:  # noqa: BLE001 - any unpickle failure
            _warn(f"unpicklable payload for {fp[:12]}… "
                  f"({type(exc).__name__}: {exc}); recompiling fresh")
            self._quarantine(fp)
            obs_metrics.counter("compile_cache_misses_total").inc()
            return None
        # NOT a hit yet: aot.load_cached counts the hit only after the
        # blob deserializes against the live backend and matches the
        # query's buffer signature — a degraded load must read as a
        # miss, or the BenchReport cache block would call a query "hit"
        # that actually compiled fresh
        obs_metrics.counter("compile_cache_bytes_read_total").inc(
            float(len(blob)))
        return payload

    # ------------------------------------------------------------ write

    def put(self, fp: str, payload: dict, meta: dict | None = None
            ) -> bool:
        """Persist an entry atomically. Returns False (without raising)
        in readonly mode or on any write failure — caching is an
        optimization, never a query hazard."""
        if self.readonly:
            return False
        try:
            blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            sha = hashlib.sha256(blob).hexdigest()
            # payload first (content-named, so the file is immutable
            # once it exists), manifest last: any complete manifest a
            # reader picks up references a complete payload
            payload_path = os.path.join(self.entry_dir(fp),
                                        _payload_name(sha))
            os.makedirs(os.path.dirname(payload_path), exist_ok=True)
            tmp = f"{payload_path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, payload_path)
            manifest = {
                "store_version": STORE_VERSION,
                "fingerprint": fp,
                "payload": os.path.basename(payload_path),
                "sha256": sha,
                "size_bytes": len(blob),
                "created_unix": int(time.time()),
                **(meta or {}),
            }
            write_json_atomic(self._manifest_path(fp), manifest)
        except Exception as exc:  # noqa: BLE001 - cache write best-effort
            _warn(f"failed to persist {fp[:12]}… "
                  f"({type(exc).__name__}: {exc})")
            return False
        obs_metrics.counter("compile_cache_bytes_written_total").inc(
            float(len(blob)))
        return True

    # ------------------------------------------------- admin (ndscache)

    def _quarantine(self, fp: str) -> None:
        """Move a bad entry out of the lookup path so every later
        process misses cleanly instead of re-diagnosing it. Best
        effort; readonly caches leave the entry in place."""
        if self.readonly:
            return
        d = self.entry_dir(fp)
        try:
            os.rename(d, f"{d}.corrupt-{os.getpid()}")
        except OSError:
            pass

    def entries(self) -> list:
        """Every readable manifest, sorted by fingerprint."""
        out = []
        if not os.path.isdir(self.root):
            return out
        for shard in sorted(os.listdir(self.root)):
            sdir = os.path.join(self.root, shard)
            if not os.path.isdir(sdir):
                continue
            for fp in sorted(os.listdir(sdir)):
                if ".corrupt-" in fp:
                    # quarantined by a failed read: out of the lookup
                    # path, not part of the live inventory (prune
                    # --corrupt deletes the husks)
                    continue
                mpath = os.path.join(sdir, fp, MANIFEST_NAME)
                if not os.path.exists(mpath):
                    continue
                try:
                    with open(mpath) as f:
                        out.append(json.load(f))
                except (OSError, ValueError):
                    out.append({"fingerprint": fp, "unreadable": True})
        return out

    def verify(self) -> list:
        """Re-hash every payload against its manifest; returns the
        offending fingerprints (missing payload, digest mismatch,
        unreadable manifest)."""
        bad = []
        for m in self.entries():
            fp = m.get("fingerprint", "?")
            if m.get("unreadable"):
                bad.append(fp)
                continue
            payload_path = self._payload_path(fp, m)
            if payload_path is None:
                bad.append(fp)
                continue
            try:
                with open(payload_path, "rb") as f:
                    blob = f.read()
            except OSError:
                bad.append(fp)
                continue
            if hashlib.sha256(blob).hexdigest() != m.get("sha256"):
                bad.append(fp)
        return bad

    def prune(self, keep_days: float | None = None,
              jax_version: str | None = None,
              corrupt: bool = False) -> list:
        """Delete entries older than ``keep_days``, built by a jax
        other than ``jax_version``, or failing verification
        (``corrupt=True``). Returns the removed fingerprints."""
        import shutil
        removed = []
        bad = set(self.verify()) if corrupt else set()
        now = time.time()
        if corrupt and os.path.isdir(self.root):
            # quarantined husks left by failed reads
            for shard in sorted(os.listdir(self.root)):
                sdir = os.path.join(self.root, shard)
                if not os.path.isdir(sdir):
                    continue
                for fp in sorted(os.listdir(sdir)):
                    if ".corrupt-" in fp:
                        shutil.rmtree(os.path.join(sdir, fp),
                                      ignore_errors=True)
                        removed.append(fp)
        for m in self.entries():
            fp = m.get("fingerprint", "?")
            drop = m.get("unreadable", False) or fp in bad
            if (keep_days is not None and not drop
                    and now - m.get("created_unix", 0)
                    > keep_days * 86400):
                drop = True
            if (jax_version is not None and not drop
                    and m.get("jax") != jax_version):
                drop = True
            if drop:
                shutil.rmtree(self.entry_dir(fp), ignore_errors=True)
                removed.append(fp)
        return removed
