"""Persistent AOT plan cache: compile once, serve every statement warm.

ROADMAP item 1. The engine compiles one XLA program per (plan, table
content, precision, mesh) — expensive on TPU (tens of seconds for the
wide NDS templates) and, before this package, paid again by EVERY
process. This package persists the compiled executables themselves
(jax AOT serialization), keyed by a full plan fingerprint
(cache/fingerprint.py), in a sha256-stamped on-disk store
(cache/store.py): a warm process answers any statement the cache has
seen with ZERO compiles (``compile_ms: 0`` + ``cache_load_ms`` in the
per-query timings, ``compile_cache_hits_total`` in the metrics).
Fingerprint mismatch, version skew, or a corrupt entry always degrades
to a fresh compile with a warning — never a query failure.

Activation (off by default — no cache dir, no cache):

- ``NDS_TPU_PLAN_CACHE=/path`` (+ ``NDS_TPU_PLAN_CACHE_READONLY=1``)
  — environment, inherited by bench phase subprocesses;
- ``cache.dir`` / ``cache.readonly`` EngineConfig keys (the power
  drivers' ``--cache_dir`` flag and the bench YAML ``cache:`` block
  set these) — applied by the execution pipeline at session creation
  via :func:`configure`.

``tools/ndscache.py`` is the admin CLI (ls/verify/prune/warm).
"""

from __future__ import annotations

import os

from nds_tpu.cache.store import PlanCache

ENV_DIR = "NDS_TPU_PLAN_CACHE"
ENV_READONLY = "NDS_TPU_PLAN_CACHE_READONLY"

# (dir, readonly) -> PlanCache the env resolution is memoized under, so
# monkeypatched env vars in tests re-resolve without a reset
_resolved_key: "tuple | None" = None
_resolved: "PlanCache | None" = None
# explicit configure() overrides the environment until reset
_override: "PlanCache | None" = None
_override_set = False


_codegen_checked = False


def _jaxlib_knows_flag(flag: str) -> bool:
    """Whether this jaxlib's XLA understands ``flag`` (grep over the
    installed package, cached on disk per jaxlib+flag): an UNKNOWN
    XLA_FLAGS entry aborts the process at first device use on jaxlib
    >= 0.4.36, so never set one blind (same probe contract as
    tests/conftest.py)."""
    try:
        import hashlib
        import pathlib
        import shlex
        import subprocess
        import tempfile

        import jaxlib  # no backend init: metadata import only
        root = os.path.dirname(os.path.abspath(jaxlib.__file__))
        tag = hashlib.sha256(
            f"{jaxlib.__version__}|{root}|{flag}".encode()
        ).hexdigest()[:12]
        cache = pathlib.Path(tempfile.gettempdir()) / (
            f"nds_tpu_xlaflag_probe_{tag}")
        if cache.exists():
            return cache.read_text() == "1"
        ok = subprocess.run(
            ["sh", "-c", f"grep -rqs {shlex.quote(flag)} "
                         f"{shlex.quote(root)}"],
            timeout=120).returncode == 0
        cache.write_text("1" if ok else "0")
        return ok
    except Exception:  # noqa: BLE001 - no grep/jaxlib layout surprises
        return True


def ensure_reloadable_codegen() -> None:
    """Pin ``--xla_cpu_parallel_codegen_split_count=1`` before the
    backend initializes (idempotent, once per process).

    XLA:CPU splits large modules across parallel codegen units and the
    serialized executable only carries the primary unit's symbols —
    reloading a big program (sort comparators, reduce-window regions)
    then fails with "Symbols not found". One codegen unit makes every
    persisted executable reloadable; measured compile-time cost on the
    NDS q93/96/7 set is ~2%. If jax already initialized its backends
    the flag cannot take effect — persisted large CPU programs then
    degrade to warned fresh compiles on reload, queries never fail."""
    global _codegen_checked
    if _codegen_checked:
        return
    _codegen_checked = True
    flag = "xla_cpu_parallel_codegen_split_count"
    flags = os.environ.get("XLA_FLAGS", "")
    if flag in flags:
        return
    import sys
    if "jax" in sys.modules:
        try:
            from jax._src import xla_bridge as _xb
            if getattr(_xb, "_backends", None):
                # flags parse at first client creation; too late now
                print("PLAN-CACHE NOTE: jax backend already "
                      "initialized — cannot pin "
                      f"--{flag}=1; large CPU executables may not "
                      "reload from the cache (degrades to fresh "
                      "compiles)")
                return
        except Exception:  # noqa: BLE001 - private-symbol drift
            pass
    if not _jaxlib_knows_flag(flag):
        return
    os.environ["XLA_FLAGS"] = f"{flags} --{flag}=1".strip()


def configure(cache_dir: "str | None",
              readonly: bool = False) -> "PlanCache | None":
    """Programmatic activation (EngineConfig ``cache.dir`` path).
    ``cache_dir=None`` explicitly disables the cache regardless of the
    environment. Returns the active cache."""
    global _override, _override_set
    _override = PlanCache(cache_dir, readonly) if cache_dir else None
    _override_set = True
    if _override is not None:
        ensure_reloadable_codegen()
    return _override


def reset() -> None:
    """Drop every resolution (tests)."""
    global _override, _override_set, _resolved, _resolved_key
    _override = None
    _override_set = False
    _resolved = None
    _resolved_key = None


def active() -> "PlanCache | None":
    """The process's plan cache, or None when caching is off. Explicit
    :func:`configure` wins; otherwise the ``NDS_TPU_PLAN_CACHE``
    environment decides (re-resolved whenever the variable changes)."""
    global _resolved, _resolved_key
    if _override_set:
        return _override
    d = os.environ.get(ENV_DIR) or None
    ro = os.environ.get(ENV_READONLY, "0") == "1"
    key = (d, ro)
    if key != _resolved_key:
        _resolved_key = key
        _resolved = PlanCache(d, ro) if d else None
        if _resolved is not None:
            ensure_reloadable_codegen()
    return _resolved


def export_env(cache_cfg) -> None:
    """Bench-orchestrator activation (YAML ``cache: {dir, readonly}``):
    exports ``NDS_TPU_PLAN_CACHE``(+``_READONLY``) into THIS process's
    environment so every engine phase — subprocess or in-process —
    inherits one shared cache directory. A YAML without the block is a
    no-op (the operator's own environment stays in charge)."""
    cache_cfg = cache_cfg or {}
    d = cache_cfg.get("dir")
    if not d:
        return
    os.makedirs(d, exist_ok=True)
    os.environ[ENV_DIR] = d
    ensure_reloadable_codegen()
    # only an EXPLICIT yaml readonly key overrides the operator's
    # environment: a `cache: {dir}` block without it must not silently
    # clear a fleet-wide NDS_TPU_PLAN_CACHE_READONLY=1 pin and start
    # writing into a cache the operator declared read-only
    if "readonly" in cache_cfg:
        if cache_cfg.get("readonly"):
            os.environ[ENV_READONLY] = "1"
        else:
            os.environ.pop(ENV_READONLY, None)


def configure_from(config) -> "PlanCache | None":
    """Apply an EngineConfig's ``cache.*`` keys when present; configs
    without them leave the environment-driven resolution untouched (a
    session created with no cache keys must not clear another's
    explicit configure)."""
    if config is None or not config.get("cache.dir"):
        return active()
    return configure(config.get("cache.dir"),
                     config.get_bool("cache.readonly"))
