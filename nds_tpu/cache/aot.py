"""jax-facing half of the plan cache: AOT lower/compile + executable
(de)serialization.

This module is the ONE place engine code lowers and compiles XLA
programs (ndslint NDS111 keeps ``.lower().compile()`` chains from
reappearing inside ``engine/``/``parallel/``): executors build their
traced callables with ``jax.jit`` and hand them here, so the cache
consult wraps every compile the same way —

    compiled, extra, hit = cached_compile(fp, kind, build, args, ...)

On a HIT the serialized executable deserializes against the live
backend and the query pays ZERO compiles (``compile_ms`` stays 0; the
deserialize cost is reported separately as ``cache_load_ms``). On a
MISS the program compiles exactly as before and — when the cache is
writable — persists for every later process. Programs jax cannot
serialize (no unloaded executable on this backend) compile normally
and simply skip the persist, once-warned.

Payload shape (pickled by store.PlanCache):
``{"exec": bytes, "in_tree": PyTreeDef, "out_tree": PyTreeDef,
"extra": {...}}`` — ``extra`` carries the host-side trace byproducts a
hit must restore without re-tracing (output string dictionaries; the
distributed executor's sharded/replicated key split).
"""

from __future__ import annotations

import time

from nds_tpu.cache import fingerprint as fpmod

_unserializable_warned: set = set()


def platform_parts() -> dict:
    """The backend facts every fingerprint must include: a CPU-compiled
    executable must never key-collide with a TPU one, nor jax 0.4.36
    with 0.4.37, nor x64 with x32."""
    import jax
    import jaxlib
    parts = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "x64": bool(jax.config.jax_enable_x64),
    }
    try:
        dev = jax.devices()[0]
        parts["platform"] = dev.platform
        parts["device_kind"] = dev.device_kind
    except Exception:  # noqa: BLE001 - no live backend: still keyable
        parts["platform"] = "unknown"
    return parts


def try_fingerprint(kind: str, parts: dict, planned=None, tables=None,
                    extra_roots=None):
    """The consult preamble every executor compile site shares:
    ``(cache, fingerprint)`` — ``(None, None)`` when no cache is
    active, ``(cache, None)`` when fingerprinting fails (warned +
    error-counted; the caller compiles uncached — a fingerprint
    problem is never a query failure). ``platform_parts()`` is merged
    into ``parts`` automatically."""
    from nds_tpu import cache as plan_cache
    pc = plan_cache.active()
    if pc is None:
        return None, None
    from nds_tpu.cache.store import _warn
    try:
        fp = fpmod.fingerprint(planned, tables or {}, kind=kind,
                               parts={**platform_parts(), **parts},
                               extra_roots=list(extra_roots or []))
    except Exception as exc:  # noqa: BLE001 - cache is best-effort
        _warn(f"fingerprint failed for {kind} "
              f"({type(exc).__name__}: {exc}); compiling uncached")
        return pc, None
    return pc, fp


def serialize_compiled(compiled) -> "tuple | None":
    """(payload_bytes, in_tree, out_tree) for a jax.stages.Compiled, or
    None when this backend/program does not support serialization
    (warned once per program kind, never raised)."""
    from jax.experimental import serialize_executable as se
    try:
        return se.serialize(compiled)
    except Exception as exc:  # noqa: BLE001 - capability probe
        key = type(exc).__name__
        if key not in _unserializable_warned:
            _unserializable_warned.add(key)
            print(f"PLAN-CACHE NOTE: executable not serializable on "
                  f"this backend ({key}: {exc}); compiles will not "
                  f"persist")
        return None


def deserialize_compiled(payload: dict):
    """payload dict -> live jax.stages.Compiled (raises on failure; the
    caller treats any raise as a miss)."""
    from jax.experimental import serialize_executable as se
    return se.deserialize_and_load(payload["exec"], payload["in_tree"],
                                   payload["out_tree"])


def lower_and_compile(jitted, *args, fresh: bool = False,
                      kind: str = "program"):
    """The engine's single ``.lower().compile()`` site.

    ``fresh=True`` — used for every compile destined for the plan
    cache — bypasses jax's persistent compilation cache for THIS
    compile only: an executable jax's cache serves back re-serializes
    into a blob that cannot reload, so a blob we intend to persist
    must come from a real compile regardless of the ambient
    process-wide cache state (tests and mixed sessions flip it).

    Being the single funnel is what makes the jitsan recompile claim
    airtight: EVERY engine compile — counted or not — announces here
    (``analysis/jitsan.on_compile``), so a compile inside an armed
    post-warmup window is caught even when its call site forgot the
    compiles_total/recompiles_total increment."""
    from nds_tpu.analysis import jitsan
    jitsan.on_compile(kind)
    import jax
    if not fresh or not jax.config.jax_enable_compilation_cache:
        return jitted.lower(*args).compile()
    from nds_tpu.utils import xla_cache
    jax.config.update("jax_enable_compilation_cache", False)
    xla_cache._drop_memoized_verdict()
    try:
        return jitted.lower(*args).compile()
    finally:
        jax.config.update("jax_enable_compilation_cache", True)
        xla_cache._drop_memoized_verdict()


def fresh_for(cache, fp: "str | None") -> bool:
    """Whether a compile at this consult site must bypass jax's own
    compilation cache (``lower_and_compile(fresh=True)``): only when
    the result will actually PERSIST — a writable cache and a real
    fingerprint. A readonly cache never persists, so its misses may
    (and should) amortize through jax's cache like any uncached
    compile."""
    return bool(cache is not None and fp and not cache.readonly)


def call_compatible(compiled, *args) -> bool:
    """Whether a deserialized executable can be invoked with ``args``
    (pytree structure + per-leaf shape/dtype against the executable's
    recorded args_info). A False here means the fingerprint failed to
    capture something — treat as a miss, never as a crash at call
    time."""
    import jax.tree_util as tu
    try:
        info_flat, info_tree = tu.tree_flatten(compiled.args_info)
        arg_flat, arg_tree = tu.tree_flatten((tuple(args), {}))
        if info_tree != arg_tree or len(info_flat) != len(arg_flat):
            return False
        for info, arg in zip(info_flat, arg_flat):
            aval = getattr(info, "_aval", None)
            if aval is None:
                continue
            if (tuple(aval.shape) != tuple(arg.shape)
                    or str(aval.dtype) != str(arg.dtype)):
                return False
        return True
    except Exception:  # noqa: BLE001 - unknown stages API drift: miss
        return False


def load_cached(cache, fp: str, kind: str,
                timings: "dict | None" = None,
                args: "tuple | None" = None, count: bool = True):
    """Cache consult: -> (compiled, extra) on a verified hit, else
    None. Deserialize failures and signature-incompatible executables
    degrade to a miss (warned + counted); ``timings`` gains
    ``cache_load_ms`` on the hit path. ``count=False`` skips the hit
    increment for callers that still have their own verification to
    run (the sharded path's key-split compat check) and count the
    final verdict themselves."""
    from nds_tpu.cache.store import _warn, obs_metrics
    t0 = time.perf_counter()
    payload = cache.get(fp, expect_kind=kind)
    if payload is None:
        return None
    try:
        compiled = deserialize_compiled(payload)
    except Exception as exc:  # noqa: BLE001 - degrade to fresh compile
        _warn(f"deserialize failed for {fp[:12]}… "
              f"({type(exc).__name__}: {exc}); recompiling fresh")
        cache._quarantine(fp)
        obs_metrics.counter("compile_cache_misses_total").inc()
        return None
    if args is not None and not call_compatible(compiled, *args):
        _warn(f"entry {fp[:12]}… is signature-incompatible with this "
              f"query's buffers; recompiling fresh")
        obs_metrics.counter("compile_cache_misses_total").inc()
        return None
    # the hit counts HERE, after the executable proved loadable and
    # signature-compatible — store.get alone is not a served program
    if count:
        obs_metrics.counter("compile_cache_hits_total").inc()
    if timings is not None:
        timings["cache_load_ms"] = (
            timings.get("cache_load_ms", 0.0)
            + (time.perf_counter() - t0) * 1000)
    # warm hits still bill compiler-truth costs: the persisted cost
    # dict rides the payload, pinned here so dispatch-time extraction
    # (obs/costs.record_program) is a dict read, not a re-analysis
    from nds_tpu.obs import costs as obs_costs
    obs_costs.attach(compiled, payload.get("cost"))
    return compiled, payload.get("extra", {})


def persist(cache, fp: str, kind: str, compiled,
            extra: "dict | None" = None,
            meta: "dict | None" = None) -> bool:
    """Serialize + store a freshly compiled program (no-op on readonly
    caches and unserializable backends).

    On CPU the blob is test-deserialized BEFORE it is written: an
    executable that came out of jax's own compile cache (or any future
    backend quirk) can serialize into a blob that cannot reload —
    persisting it would turn every later process's hit into a warned
    recompile. Skipping the persist keeps the store hit-or-miss clean.
    (TPU skips the check: a trial load would claim device memory.)"""
    if cache.readonly:
        return False
    ser = serialize_compiled(compiled)
    if ser is None:
        return False
    blob, in_tree, out_tree = ser
    if platform_parts().get("platform") == "cpu":
        try:
            deserialize_compiled({"exec": blob, "in_tree": in_tree,
                                  "out_tree": out_tree})
        except Exception as exc:  # noqa: BLE001 - capability probe
            key = f"roundtrip:{type(exc).__name__}"
            if key not in _unserializable_warned:
                _unserializable_warned.add(key)
                print(f"PLAN-CACHE NOTE: executable does not survive a "
                      f"serialize round-trip ({type(exc).__name__}); "
                      f"not persisting {kind} {fp[:12]}…")
            return False
    # compiler cost/memory analyses persist alongside the executable
    # (payload for the hit path, manifest meta for offline tooling) so
    # warm runs carry program costs without a live re-analysis
    from nds_tpu.obs import costs as obs_costs
    cost = obs_costs.extract(compiled)
    payload = {"exec": blob, "in_tree": in_tree, "out_tree": out_tree,
               "extra": dict(extra or {})}
    meta_out = {"kind": kind, "fp_version": fpmod.FP_VERSION,
                **platform_parts(), **(meta or {})}
    if cost is not None:
        payload["cost"] = dict(cost)
        meta_out["cost"] = dict(cost)
    return cache.put(fp, payload, meta=meta_out)


def cached_compile(cache, fp: "str | None", kind: str, build, args,
                   extra_fn=None, meta: "dict | None" = None,
                   timings: "dict | None" = None):
    """Compile-or-load one program (the one-shot form the compactor
    and chunk-scan programs use).

    ``build()`` -> jitted is only invoked on a miss; ``args`` are the
    lowering avatars/buffers; ``extra_fn()`` runs AFTER the compile
    (tracing fills the executors' side dicts at lower time) and
    returns the host-side byproducts a future hit must restore.
    Returns ``(compiled, extra, hit)``. With no active cache or no
    fingerprint the compile happens inline, unchanged. ``timings``
    (the executor's per-query bill) gains ``cache_load_ms`` on a hit —
    ``compile_ms`` stays untouched, which is the whole point."""
    if cache is not None and fp:
        hit = load_cached(cache, fp, kind, timings)
        if hit is not None:
            return hit[0], hit[1], True
    compiled = lower_and_compile(build(), *args,
                                 fresh=fresh_for(cache, fp), kind=kind)
    extra = extra_fn() if extra_fn is not None else {}
    if cache is not None and fp:
        persist(cache, fp, kind, compiled, extra, meta)
    return compiled, extra, False
