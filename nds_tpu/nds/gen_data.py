"""NDS (TPC-DS) data generation driver.

Behavioral port of `nds/nds_gen_data.py:183-290`: emit the 25 source
tables as '|'-delimited chunk files under per-table directories with
dsdgen's chunking contract (`-parallel N -child S`,
`nds/nds_gen_data.py:211-222`), single-chunk handling for the fixed
dimensions, and ``--range`` incremental regeneration
(`nds/nds_gen_data.py:155-174`).

Two generation paths (same split as `nds_tpu/nds_h/gen_data.py`):
- builtin (default): the hermetic numpy generator
  (`nds_tpu.datagen.tpcds`) fanned out over a process pool — the
  Hadoop-MR GenTable replacement (`tpcds-gen/.../GenTable.java:188-279`);
- external dsdgen via ``--dsdgen_path`` (the TPC-licensed tool stays
  external, SURVEY.md §2.4; see also `nds_tpu.datagen.toolwrap`).

``--update N`` generates the Nth refresh dataset (the 12 s_* maintenance
tables plus the delete-date tables, `nds/nds_gen_data.py:119-127,259-266`)
used by the data-maintenance phase.
"""

from __future__ import annotations

import argparse
import os
from concurrent.futures import ProcessPoolExecutor

from nds_tpu.datagen import tpcds
from nds_tpu.io.csv_io import write_tbl
from nds_tpu.nds.schema import get_maintenance_schemas, get_schemas

# the reference's source_table_names includes the dsdgen metadata table
# dbgen_version (`nds/nds_gen_data.py:51`) which has no query schema —
# generated for layout parity, skipped by transcode/power like the
# reference does (absent from `nds/nds_schema.py:49-568`)
SOURCE_TABLES = sorted(get_schemas()) + ["dbgen_version"]
# fixed-cardinality dimensions generated as a single chunk
# (reference dsdgen emits these without a _N_M suffix)
SINGLE_CHUNK_TABLES = {
    "date_dim", "time_dim", "reason", "income_band", "ship_mode",
    "call_center", "warehouse", "web_site", "web_page", "store",
    "household_demographics", "customer_demographics", "promotion",
    "dbgen_version",
}


def _gen_chunk(table: str, sf: float, parallel: int, step: int,
               out_dir: str, use_decimal: bool = True) -> str:
    if table == "dbgen_version":
        path = os.path.join(out_dir, table, f"{table}.dat")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        import time
        with open(path, "w") as f:
            f.write(f"nds_tpu-builtin-1.0|"
                    f"{time.strftime('%Y-%m-%d')}|"
                    f"{time.strftime('%H:%M:%S')}|"
                    f"-scale {sf:g} -parallel {parallel}|\n")
        return path
    arrays = tpcds.gen_table(table, sf, parallel, step)
    schemas = get_schemas(use_decimal)
    if table in SINGLE_CHUNK_TABLES or parallel == 1:
        path = os.path.join(out_dir, table, f"{table}.dat")
    else:
        path = os.path.join(out_dir, table,
                            f"{table}_{step}_{parallel}.dat")
    write_tbl(arrays, schemas[table], path)
    return path


def _gen_chunk_star(args):
    return _gen_chunk(*args)


def generate_data_local(scale: float, parallel: int, data_dir: str,
                        overwrite: bool = False, table: str | None = None,
                        chunk_range: tuple[int, int] | None = None,
                        workers: int | None = None,
                        use_decimal: bool = True) -> list[str]:
    if os.path.isdir(data_dir) and os.listdir(data_dir) and not overwrite:
        raise SystemExit(
            f"data dir {data_dir!r} is not empty (pass --overwrite_output)")
    os.makedirs(data_dir, exist_ok=True)
    tables = [table] if table else SOURCE_TABLES
    lo, hi = chunk_range or (1, parallel)
    tasks = []
    for t in tables:
        if t in SINGLE_CHUNK_TABLES:
            if lo == 1:  # fixed tables generated once, by chunk 1's owner
                tasks.append((t, scale, 1, 1, data_dir, use_decimal))
            continue
        for step in range(lo, hi + 1):
            tasks.append((t, scale, parallel, step, data_dir, use_decimal))
    paths = []
    with ProcessPoolExecutor(max_workers=workers) as pool:
        for p in pool.map(_gen_chunk_star, tasks):
            paths.append(p)
    return paths


def generate_refresh_data(scale: float, update: int, data_dir: str,
                          overwrite: bool = False,
                          use_decimal: bool = True) -> list[str]:
    """The ``--update N`` path: refresh (s_*) staging tables + the
    delete/inventory_delete date-range tables, written under
    ``data_dir`` exactly like dsdgen update sets
    (`nds/nds_gen_data.py:119-127,183-244` with ``--update``)."""
    if os.path.isdir(data_dir) and os.listdir(data_dir) and not overwrite:
        raise SystemExit(
            f"data dir {data_dir!r} is not empty (pass --overwrite_output)")
    os.makedirs(data_dir, exist_ok=True)
    from nds_tpu.datagen import tpcds_refresh
    schemas = get_maintenance_schemas(use_decimal)
    paths = []
    for t, schema in schemas.items():
        arrays = tpcds_refresh.gen_refresh_table(t, scale, update)
        path = os.path.join(data_dir, t, f"{t}.dat")
        write_tbl(arrays, schema, path)
        paths.append(path)
    return paths


def generate_data_dsdgen(scale: int, parallel: int, data_dir: str,
                         dsdgen_path: str,
                         update: int | None = None) -> None:
    """External-tool path: one dsdgen process per chunk (the reference's
    per-mapper command, `GenTable.java:233-279`, without Hadoop)."""
    from nds_tpu.datagen.toolwrap import run_dsdgen
    run_dsdgen(dsdgen_path, scale, parallel, data_dir, update=update)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="generate NDS raw data")
    p.add_argument("scale", type=float, help="scale factor")
    p.add_argument("parallel", type=int, help="number of chunks")
    p.add_argument("data_dir", help="output directory")
    p.add_argument("--table", choices=SOURCE_TABLES)
    p.add_argument("--range", dest="chunk_range",
                   help="'first,last' 1-based chunk subrange to (re)generate")
    p.add_argument("--update", type=int,
                   help="generate the Nth refresh dataset instead of the "
                        "base tables")
    p.add_argument("--overwrite_output", action="store_true")
    p.add_argument("--floats", action="store_true",
                   help="double columns instead of decimals")
    p.add_argument("--dsdgen_path",
                   help="use the external TPC dsdgen binary instead of "
                        "the builtin generator")
    p.add_argument("--workers", type=int,
                   help="process-pool size (default: cpu count)")
    args = p.parse_args(argv)
    use_decimal = not args.floats
    if args.dsdgen_path:
        generate_data_dsdgen(int(args.scale), args.parallel, args.data_dir,
                             args.dsdgen_path, args.update)
        return
    if args.update is not None:
        generate_refresh_data(args.scale, args.update, args.data_dir,
                              args.overwrite_output, use_decimal)
        return
    rng = None
    if args.chunk_range:
        lo, hi = (int(x) for x in args.chunk_range.split(","))
        if not (1 <= lo <= hi <= args.parallel):
            raise SystemExit(f"invalid --range {args.chunk_range!r}")
        rng = (lo, hi)
    generate_data_local(args.scale, args.parallel, args.data_dir,
                        args.overwrite_output, args.table, rng,
                        args.workers, use_decimal)


if __name__ == "__main__":
    main()
