"""NDS Data Maintenance driver.

Behavioral port of `nds/nds_maintenance.py`: register the refresh
staging tables (`:270-274`), run the 7 LF_* insert functions and 4 DF_*
delete functions (`INSERT_FUNCS/DELETE_FUNCS:45-58`) with DATE1/DATE2
substituted from the generated delete/inventory_delete tables
(`get_delete_date:60-73`, `replace_date:75-96`), record per-function
times in JSON summaries + the CSV time log, and exit non-zero on
failures.

TPU-native: DML mutates the host warehouse through the engine
(`nds_tpu/engine/dml.py`) as DELTAS (`columnar/delta.py`) — segments
and deleted-row bitmasks over the immutable encoded base, never a
rewrite — and every refresh function commits its deltas as one
snapshot version (`nds_tpu/io/snapshots.py`), the Iceberg-snapshot
analog that `nds_tpu.nds.rollback` undoes by manifest truncation.

Crash safety is the power loop's contract applied to writes: a
write-ahead commit journal START-marks each LF_*/DF_* function before
its DML dispatches and records completion only AFTER its snapshot
commit lands, so ``--resume`` replays completed functions from the
journal, recognizes the crash-after-commit window by the committed
version's note, and NEVER double-applies a mutation; SIGTERM drains
the in-flight function and exits 75 so `bench.py` retries with
``--resume``. Chaos coverage injects at ``dml.apply`` (between
START-mark and commit) and ``store.commit`` (the torn-commit window).

Compaction — folding deltas + bitmasks back into full base files — is
a first-class governed operator: `compact_warehouse` asks the
`MemoryGovernor` for admission (materializing live rows is the one
O(table) step in the write path) and commits a full-file version that
rollback undoes like any other.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from nds_tpu.engine.session import Session
from nds_tpu.utils import power_core
from nds_tpu.utils.report import BenchReport
from nds_tpu.utils.timelog import TimeLog

DM_DIR = os.path.join(os.path.dirname(__file__), "data_maintenance")

INSERT_FUNCS = ["LF_CR", "LF_CS", "LF_I", "LF_SR", "LF_SS", "LF_WR",
                "LF_WS"]
DELETE_FUNCS = ["DF_CS", "DF_SS", "DF_WS"]
INVENTORY_DELETE_FUNCS = ["DF_I"]

# fact tables a full maintenance run can mutate -> committed as one
# snapshot version (the rollback set, `nds/nds_rollback.py:37-43`)
MUTABLE_TABLES = ["store_sales", "store_returns", "catalog_sales",
                  "catalog_returns", "web_sales", "web_returns",
                  "inventory"]


def get_maintenance_queries(funcs: list[str]) -> dict[str, str]:
    """{function: sql text} from the shipped data_maintenance assets
    (`nds/nds_maintenance.py:121-147`)."""
    out = {}
    for f in funcs:
        with open(os.path.join(DM_DIR, f + ".sql")) as fh:
            out[f] = fh.read()
    return out


def get_delete_date(session: Session) -> tuple[str, str, str, str]:
    """(date1, date2, inv_date1, inv_date2) ISO strings read from the
    registered delete/inventory_delete tables
    (`nds/nds_maintenance.py:60-73`)."""
    import numpy as np

    def iso(table, col):
        c = session.tables[table].column(col)
        return str((np.datetime64("1970-01-01", "D")
                    + int(c.values[0])))

    return (iso("delete", "date1"), iso("delete", "date2"),
            iso("inventory_delete", "date1"),
            iso("inventory_delete", "date2"))


def replace_date(sql: str, date1: str, date2: str) -> str:
    """DATE1/DATE2 placeholder substitution
    (`nds/nds_maintenance.py:75-96`)."""
    return sql.replace("DATE1", date1).replace("DATE2", date2)


def statements(sql: str) -> list[str]:
    # strip comment lines BEFORE splitting: headers may contain ';'
    body = "\n".join(ln for ln in sql.splitlines()
                     if not ln.lstrip().startswith("--"))
    return [s.strip() for s in body.split(";") if s.strip()]


def run_dm_query(session: Session, sql: str) -> None:
    for stmt in statements(sql):
        session.sql(stmt)


JOURNAL_NAME = "_maintenance_journal.json"


def journal_path(data_dir: str, refresh_dir: str) -> str:
    """Journal keyed by refresh set: a full bench runs maintenance
    twice (refresh1, refresh2) against ONE warehouse — round 2 resumed
    must not replay round 1's records."""
    tag = os.path.basename(os.path.normpath(refresh_dir)) or "refresh"
    return os.path.join(data_dir, JOURNAL_NAME.replace(
        ".json", f".{tag}.json"))


def _commit_function_deltas(data_dir: str, log, session: Session,
                            note: str) -> "int | None":
    """Persist every pending delta artifact (segments, deleted-row
    bitmask) the just-finished refresh function produced and append ONE
    snapshot version referencing them (the atomic commit point). A
    crash before the manifest append leaves unreferenced files the
    reader never visits — the next incarnation re-runs the function and
    overwrites them. Returns the committed version, or None when the
    function mutated nothing."""
    from nds_tpu.columnar import delta
    version = (log.entries[-1]["version"] + 1) if log.entries else 1
    new_rel: dict[str, list] = {}
    for t in MUTABLE_TABLES:
        table = session.tables.get(t)
        if table is None or delta.state_of(table) is None:
            continue
        files = delta.persist_pending(table, log.version_dir(t, version),
                                      note=note)
        if files:
            new_rel[t] = [os.path.relpath(p, data_dir) for p in files]
    if not new_rel:
        return None
    prev = (dict(log.entries[-1]["tables"]) if log.entries else {})
    merged = {}
    for t, rel in new_rel.items():
        base = prev.get(t)
        if base is None:
            base = log.baseline([t]).get(t, [])
        merged[t] = list(base) + rel
    return log.commit(merged, note=note)


def run_maintenance(data_dir: str, refresh_dir: str, time_log_path: str,
                    config=None,
                    json_summary_folder: str | None = None,
                    refresh_format: str = "raw",
                    commit: bool = True,
                    resume: bool = False) -> int:
    """Run all 11 maintenance functions under the write-ahead commit
    journal; returns the failure count. ``resume=True`` replays
    journaled-complete functions (and functions whose snapshot commit
    landed but whose journal record didn't — the crash-after-commit
    window, recognized by the committed version's note) and re-runs
    only genuinely unfinished ones — zero double-applied DML by
    construction."""
    from nds_tpu.io.snapshots import SnapshotLog
    from nds_tpu.nds.schema import get_maintenance_schemas
    from nds_tpu.resilience import drain
    from nds_tpu.resilience.journal import QueryJournal, config_digest
    config = config or power_core.config_from_args(
        argparse.Namespace(), default_backend="cpu")
    suite = _maintenance_suite(config)
    session = power_core.make_session(suite, config)
    # nonce keeps run ids (and therefore snapshot commit notes) unique
    # even when two rounds start within the same second
    import uuid
    app_id = (f"nds-tpu-maintenance-{int(time.time())}-"
              f"{uuid.uuid4().hex[:8]}")
    tlog = TimeLog(app_id)
    run_dir = (json_summary_folder
               or os.path.dirname(time_log_path) or ".")

    journal = QueryJournal(
        journal_path(data_dir, refresh_dir), phase=app_id,
        digest=config_digest({"data_dir": data_dir,
                              "refresh_dir": refresh_dir,
                              "commit": commit}))
    if resume and journal.load():
        inc = journal.begin_incarnation()
        # the run id binds this journal's records to their snapshot
        # notes; a resumed incarnation inherits the original's
        run_id = journal.state.get("phase") or app_id
        print(f"== resuming maintenance (incarnation {inc}): "
              f"{len(journal.completed())} function(s) journaled ==")
    else:
        journal.reset()
        run_id = app_id

    # graceful preemption: SIGTERM/SIGINT drains the in-flight refresh
    # function and exits 75 (resumable) — installed only when no outer
    # driver (bench.py) already owns the signal chain
    own_drain = drain.manager() is None
    if own_drain:
        drain.install(drain.drain_seconds(config), run_dir)

    # base warehouse (versioned: committed deltas from a crashed run
    # replay through columnar.delta) + refresh staging tables
    setup = power_core.load_warehouse(
        suite, session, data_dir,
        schemas=power_core.suite_schemas(suite, config))
    use_decimal = not config.get_bool("engine.floats")
    maint_schemas = get_maintenance_schemas(use_decimal)
    setup.update(power_core.load_warehouse(
        suite, session, refresh_dir, refresh_format,
        schemas=maint_schemas))
    for tname, secs in setup.items():
        tlog.add(f"CreateTempView {tname}", int(secs * 1000))

    log = SnapshotLog(data_dir) if commit else None
    date1, date2, inv_date1, inv_date2 = get_delete_date(session)
    queries = get_maintenance_queries(
        INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNCS)
    if json_summary_folder:
        os.makedirs(json_summary_folder, exist_ok=True)
    failures = 0
    dm_ms = 0
    try:
        for fname, sql in queries.items():
            # function-boundary drain point: a requested drain exits 75
            # here, with every finished function journaled + committed
            drain.check_boundary()
            note = f"maint:{run_id}:{fname}"
            if journal.done(fname):
                entry = journal.entry(fname)
                elapsed_ms = int(entry.get("wall_ms", 0))
                tlog.add(fname, elapsed_ms)
                dm_ms += elapsed_ms
                if not str(entry.get("status", "")).startswith(
                        "Completed"):
                    failures += 1
                print(f"====== {fname} replayed from journal ======")
                continue
            if log is not None and log.has_note(note):
                # crash landed between this function's snapshot commit
                # and its journal record: the mutation is durable (and
                # already loaded from the committed version) — record
                # retroactively, NEVER re-apply
                journal.record(fname, 0.0,
                               "Completed(replayed-from-snapshot)")
                print(f"====== {fname} already committed "
                      f"(v-note {note}) ======")
                continue
            if fname in INVENTORY_DELETE_FUNCS:
                fsql = replace_date(sql, inv_date1, inv_date2)
            elif fname in DELETE_FUNCS:
                fsql = replace_date(sql, date1, date2)
            else:
                fsql = sql
            # START-mark before dispatch: a kill one instruction later
            # still leaves the attempt on disk (at-most-one in flight)
            journal.start(fname)
            report = BenchReport(fname, config.as_dict())
            summary = report.report_on(run_dm_query, session, fsql)
            elapsed_ms = summary["queryTimes"][-1]
            tlog.add(fname, elapsed_ms)
            dm_ms += elapsed_ms
            print(f"====== Run {fname} ======")
            print(f"Time taken: {elapsed_ms} millis for {fname}")
            ok = report.is_success()
            if not ok:
                failures += 1
            if ok and log is not None:
                v = _commit_function_deltas(data_dir, log, session, note)
                if v is not None:
                    print(f"committed {fname} deltas as snapshot v{v}")
            # journal record AFTER the commit: completion implies the
            # mutation is durable, so resume can safely skip it
            journal.record(fname, elapsed_ms,
                           "Completed" if ok else "Failed")
            if json_summary_folder:
                report.write_summary(prefix=f"maintenance-{app_id}",
                                     out_dir=json_summary_folder)
    finally:
        if own_drain:
            drain.uninstall()
    tlog.add("Data Maintenance Time", dm_ms)
    tlog.write(time_log_path)
    print(f"Data Maintenance Time: {dm_ms} millis")
    return failures


def _maintenance_suite(config) -> power_core.Suite:
    from nds_tpu.nds.schema import get_schemas
    return power_core.Suite(
        name="nds",
        get_schemas=get_schemas,
        parse_query_stream=None,
        session_for=lambda factory, **kw: Session.for_nds(
            factory, include_maintenance=True, **kw),
        raw_ext=".dat",
        floats_toggle=True,
    )


def compact_warehouse(data_dir: str, session: Session,
                      governor=None, note: str = "compact",
                      tables: "list[str] | None" = None) -> "int | None":
    """Fold each mutated table's delta segments + deleted-row bitmask
    back into full base files and commit them as one snapshot version
    (which rollback undoes like any other — manifest truncation).

    Materializing live rows is the one O(table) host-memory step in the
    write path, so compaction is a governed operator: when a
    `MemoryGovernor` refuses admission the fold is deferred (counted as
    ``compaction_deferred_total``) and the delta representation — still
    correct, just less compact — keeps serving queries.

    Returns the committed version, or None when nothing was compacted.
    """
    from nds_tpu.columnar import delta
    from nds_tpu.io import csv_io
    from nds_tpu.io.snapshots import SnapshotLog
    from nds_tpu.obs import metrics as obs_metrics

    targets = []
    for t in (tables or MUTABLE_TABLES):
        table = session.tables.get(t)
        if table is not None and delta.state_of(table) is not None:
            targets.append((t, table))
    if not targets:
        return None

    if governor is not None:
        class _Est:
            bytes = sum(tb.nbytes for _, tb in targets)
            rows = sum(tb.nrows for _, tb in targets)
        reason = governor.decide(_Est())
        if reason is not None:
            obs_metrics.counter("compaction_deferred_total").inc()
            print(f"compaction deferred ({reason}) — delta "
                  f"representation stays in service")
            return None

    log = SnapshotLog(data_dir)
    version = (log.entries[-1]["version"] + 1) if log.entries else 1
    new_files = {}
    for t, table in targets:
        pt = delta.physical(table)
        vdir = log.version_dir(t, version)
        path = os.path.join(vdir, "part-0.parquet")
        csv_io.write_parquet(pt, path)
        new_files[t] = [os.path.relpath(path, data_dir)]
        # the in-session table becomes the compacted physical form;
        # register_table drops the delta attr and re-derives stats
        session.register_table(pt)
    v = log.commit(new_files, note=note)
    session.invalidate(tables=[t for t, _ in targets])
    return v


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="NDS data maintenance (LF_*/DF_* refresh functions)")
    p.add_argument("data_dir", help="warehouse directory (versioned)")
    p.add_argument("refresh_dir",
                   help="refresh dataset directory (gen_data --update)")
    p.add_argument("time_log", help="output CSV time log path")
    p.add_argument("--backend", choices=["tpu", "cpu", "distributed"],
                   default=None)
    p.add_argument("--refresh_format", choices=["raw", "parquet"],
                   default="raw")
    p.add_argument("--json_summary_folder")
    p.add_argument("--no_commit", action="store_true",
                   help="leave the on-disk warehouse untouched")
    p.add_argument("--allow_failure", action="store_true",
                   help="exit 0 even when functions failed")
    p.add_argument("--resume", action="store_true",
                   help="replay the commit journal: skip functions "
                        "whose mutations are already durable")
    p.add_argument("--compact", action="store_true",
                   help="after the refresh functions, fold deltas back "
                        "into full base files (governed)")
    power_core.add_config_args(p)
    args = p.parse_args(argv)
    config = power_core.config_from_args(args, default_backend="cpu")
    failures = run_maintenance(
        args.data_dir, args.refresh_dir, args.time_log, config=config,
        json_summary_folder=args.json_summary_folder,
        refresh_format=args.refresh_format, commit=not args.no_commit,
        resume=args.resume)
    if args.compact and not failures and not args.no_commit:
        from nds_tpu.engine.scheduler import MemoryGovernor
        suite = _maintenance_suite(config)
        session = power_core.make_session(suite, config)
        power_core.load_warehouse(
            suite, session, args.data_dir,
            schemas=power_core.suite_schemas(suite, config))
        budget = config.get("engine.placement.device_budget_bytes")
        gov = (MemoryGovernor(budget=int(budget))
               if budget is not None else MemoryGovernor())
        v = compact_warehouse(args.data_dir, session, governor=gov)
        if v is not None:
            print(f"compacted warehouse as snapshot v{v}")
    sys.exit(0 if (args.allow_failure or not failures) else 1)


if __name__ == "__main__":
    main()
