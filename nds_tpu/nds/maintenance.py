"""NDS Data Maintenance driver.

Behavioral port of `nds/nds_maintenance.py`: register the refresh
staging tables (`:270-274`), run the 7 LF_* insert functions and 4 DF_*
delete functions (`INSERT_FUNCS/DELETE_FUNCS:45-58`) with DATE1/DATE2
substituted from the generated delete/inventory_delete tables
(`get_delete_date:60-73`, `replace_date:75-96`), record per-function
times in JSON summaries + the CSV time log, and exit non-zero on
failures.

TPU-native: DML mutates the host warehouse through the engine
(`nds_tpu/engine/dml.py`); after all functions run, the mutated fact
tables are committed as a new snapshot version
(`nds_tpu/io/snapshots.py`) — the Iceberg-snapshot analog that
`nds_tpu.nds.rollback` undoes.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from nds_tpu.engine.session import Session
from nds_tpu.utils import power_core
from nds_tpu.utils.report import BenchReport
from nds_tpu.utils.timelog import TimeLog

DM_DIR = os.path.join(os.path.dirname(__file__), "data_maintenance")

INSERT_FUNCS = ["LF_CR", "LF_CS", "LF_I", "LF_SR", "LF_SS", "LF_WR",
                "LF_WS"]
DELETE_FUNCS = ["DF_CS", "DF_SS", "DF_WS"]
INVENTORY_DELETE_FUNCS = ["DF_I"]

# fact tables a full maintenance run can mutate -> committed as one
# snapshot version (the rollback set, `nds/nds_rollback.py:37-43`)
MUTABLE_TABLES = ["store_sales", "store_returns", "catalog_sales",
                  "catalog_returns", "web_sales", "web_returns",
                  "inventory"]


def get_maintenance_queries(funcs: list[str]) -> dict[str, str]:
    """{function: sql text} from the shipped data_maintenance assets
    (`nds/nds_maintenance.py:121-147`)."""
    out = {}
    for f in funcs:
        with open(os.path.join(DM_DIR, f + ".sql")) as fh:
            out[f] = fh.read()
    return out


def get_delete_date(session: Session) -> tuple[str, str, str, str]:
    """(date1, date2, inv_date1, inv_date2) ISO strings read from the
    registered delete/inventory_delete tables
    (`nds/nds_maintenance.py:60-73`)."""
    import numpy as np

    def iso(table, col):
        c = session.tables[table].column(col)
        return str((np.datetime64("1970-01-01", "D")
                    + int(c.values[0])))

    return (iso("delete", "date1"), iso("delete", "date2"),
            iso("inventory_delete", "date1"),
            iso("inventory_delete", "date2"))


def replace_date(sql: str, date1: str, date2: str) -> str:
    """DATE1/DATE2 placeholder substitution
    (`nds/nds_maintenance.py:75-96`)."""
    return sql.replace("DATE1", date1).replace("DATE2", date2)


def statements(sql: str) -> list[str]:
    # strip comment lines BEFORE splitting: headers may contain ';'
    body = "\n".join(ln for ln in sql.splitlines()
                     if not ln.lstrip().startswith("--"))
    return [s.strip() for s in body.split(";") if s.strip()]


def run_dm_query(session: Session, sql: str) -> None:
    for stmt in statements(sql):
        session.sql(stmt)


def run_maintenance(data_dir: str, refresh_dir: str, time_log_path: str,
                    config=None,
                    json_summary_folder: str | None = None,
                    refresh_format: str = "raw",
                    commit: bool = True) -> int:
    """Run all 11 maintenance functions; returns the failure count."""
    from nds_tpu.nds.schema import get_maintenance_schemas
    config = config or power_core.config_from_args(
        argparse.Namespace(), default_backend="cpu")
    suite = _maintenance_suite(config)
    session = power_core.make_session(suite, config)
    app_id = f"nds-tpu-maintenance-{int(time.time())}"
    tlog = TimeLog(app_id)

    # base warehouse + refresh staging tables
    setup = power_core.load_warehouse(
        suite, session, data_dir,
        schemas=power_core.suite_schemas(suite, config))
    use_decimal = not config.get_bool("engine.floats")
    maint_schemas = get_maintenance_schemas(use_decimal)
    setup.update(power_core.load_warehouse(
        suite, session, refresh_dir, refresh_format,
        schemas=maint_schemas))
    for tname, secs in setup.items():
        tlog.add(f"CreateTempView {tname}", int(secs * 1000))

    date1, date2, inv_date1, inv_date2 = get_delete_date(session)
    queries = get_maintenance_queries(
        INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNCS)
    if json_summary_folder:
        os.makedirs(json_summary_folder, exist_ok=True)
    failures = 0
    dm_start = time.perf_counter()
    for fname, sql in queries.items():
        if fname in INVENTORY_DELETE_FUNCS:
            sql = replace_date(sql, inv_date1, inv_date2)
        elif fname in DELETE_FUNCS:
            sql = replace_date(sql, date1, date2)
        report = BenchReport(fname, config.as_dict())
        summary = report.report_on(run_dm_query, session, sql)
        elapsed_ms = summary["queryTimes"][-1]
        tlog.add(fname, elapsed_ms)
        print(f"====== Run {fname} ======")
        print(f"Time taken: {elapsed_ms} millis for {fname}")
        if not report.is_success():
            failures += 1
        if json_summary_folder:
            report.write_summary(prefix=f"maintenance-{app_id}",
                                 out_dir=json_summary_folder)
    dm_ms = int((time.perf_counter() - dm_start) * 1000)
    tlog.add("Data Maintenance Time", dm_ms)
    tlog.write(time_log_path)
    print(f"Data Maintenance Time: {dm_ms} millis")

    if commit and not failures:
        version = commit_snapshot(data_dir, session)
        print(f"committed warehouse snapshot v{version}")
    return failures


def _maintenance_suite(config) -> power_core.Suite:
    from nds_tpu.nds.schema import get_schemas
    return power_core.Suite(
        name="nds",
        get_schemas=get_schemas,
        parse_query_stream=None,
        session_for=lambda factory, **kw: Session.for_nds(
            factory, include_maintenance=True, **kw),
        raw_ext=".dat",
        floats_toggle=True,
    )


def commit_snapshot(data_dir: str, session: Session) -> int:
    """Persist the mutated fact tables as a new warehouse version."""
    from nds_tpu.io import csv_io
    from nds_tpu.io.snapshots import SnapshotLog
    log = SnapshotLog(data_dir)
    version = (log.entries[-1]["version"] + 1) if log.entries else 1
    new_files = {}
    for t in MUTABLE_TABLES:
        vdir = log.version_dir(t, version)
        path = os.path.join(vdir, "part-0.parquet")
        csv_io.write_parquet(session.tables[t], path)
        new_files[t] = [os.path.relpath(path, data_dir)]
    return log.commit(new_files, note="data maintenance")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="NDS data maintenance (LF_*/DF_* refresh functions)")
    p.add_argument("data_dir", help="warehouse directory (versioned)")
    p.add_argument("refresh_dir",
                   help="refresh dataset directory (gen_data --update)")
    p.add_argument("time_log", help="output CSV time log path")
    p.add_argument("--backend", choices=["tpu", "cpu", "distributed"],
                   default=None)
    p.add_argument("--refresh_format", choices=["raw", "parquet"],
                   default="raw")
    p.add_argument("--json_summary_folder")
    p.add_argument("--no_commit", action="store_true",
                   help="leave the on-disk warehouse untouched")
    p.add_argument("--allow_failure", action="store_true",
                   help="exit 0 even when functions failed")
    power_core.add_config_args(p)
    args = p.parse_args(argv)
    config = power_core.config_from_args(args, default_backend="cpu")
    failures = run_maintenance(
        args.data_dir, args.refresh_dir, args.time_log, config=config,
        json_summary_folder=args.json_summary_folder,
        refresh_format=args.refresh_format, commit=not args.no_commit)
    sys.exit(0 if (args.allow_failure or not failures) else 1)


if __name__ == "__main__":
    main()
