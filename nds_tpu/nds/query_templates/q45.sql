-- dialect note: the spec's "substr(zip) in (...) OR i_item_id IN
-- (subquery)" disjunct is expressed as a LEFT JOIN against the
-- (tiny, uncorrelated) item-id set + IS NOT NULL, which is the same
-- predicate — the engine plans IN-subqueries only as WHERE conjuncts
select ca_zip, ca_city, sum(ws_sales_price) total_sales
from web_sales, customer, customer_address, date_dim, item
     left outer join
     (select distinct i_item_id hot_item_id from item
      where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)) hot
     on (item.i_item_id = hot.hot_item_id)
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and ws_sold_date_sk = d_date_sk
  and d_qoy = {qoy} and d_year = {year}
  and (substring(ca_zip, 1, 3) in ('100', '102', '103', '105', '108',
                                   '110', '113', '115', '118')
       or hot.hot_item_id is not null)
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
