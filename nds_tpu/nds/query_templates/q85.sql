select substring(r_reason_desc, 1, 20) reason, avg(ws_quantity) q,
       avg(wr_refunded_cash) refunded, avg(wr_fee) fee
from web_sales, web_returns, web_page, customer_demographics cd1,
     customer_demographics cd2, customer_address, date_dim, reason
where ws_web_page_sk = wp_web_page_sk
  and ws_item_sk = wr_item_sk
  and ws_order_number = wr_order_number
  and ws_sold_date_sk = d_date_sk
  and d_year = {year}
  and cd1.cd_demo_sk = wr_refunded_cdemo_sk
  and cd2.cd_demo_sk = wr_returning_cdemo_sk
  and ca_address_sk = wr_refunded_addr_sk
  and r_reason_sk = wr_reason_sk
  and ((cd1.cd_marital_status = 'M'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = 'Advanced Degree'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 20.00 and 60.00)
    or (cd1.cd_marital_status = 'S'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = 'College'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 60.00 and 90.00)
    or (cd1.cd_marital_status = 'W'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = '2 yr Degree'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 0.99 and 20.00))
  and ((ca_country = 'United States'
        and ca_state in ('IN', 'OH', 'KY')
        and ws_net_profit between 100 and 20000)
    or (ca_country = 'United States'
        and ca_state in ('WI', 'CA', 'TX')
        and ws_net_profit between 150 and 30000)
    or (ca_country = 'United States'
        and ca_state in ('LA', 'GA', 'MO')
        and ws_net_profit between 50 and 25000))
group by r_reason_desc
order by reason, q, refunded, fee
limit 100
