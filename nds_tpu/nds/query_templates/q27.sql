select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = '{gender}'
  and cd_marital_status = '{marital}'
  and cd_education_status = '{education}'
  and d_year = {year}
  and s_state in ('{s1}', '{s2}', '{s3}', '{s4}', '{s5}', '{s6}')
group by rollup(i_item_id, s_state)
order by i_item_id, s_state
limit 100
