select s_store_name, sum(ss_net_profit)
from store_sales, date_dim, store,
     (select ca_zip
      from ((select substring(ca_zip, 1, 5) ca_zip
             from customer_address
             where substring(ca_zip, 1, 5) in
                   ('10043', '10079', '10109', '10125', '10129',
                    '10483', '11262', '13063', '13297', '14539',
                    '17227', '18621', '22529', '23255', '25586',
                    '28367', '30009', '33021', '36420', '39986'))
            intersect
            (select ca_zip
             from (select substring(ca_zip, 1, 5) ca_zip, count(*) cnt
                   from customer_address, customer
                   where ca_address_sk = c_current_addr_sk
                     and c_preferred_cust_flag = 'Y'
                   group by ca_zip
                   having count(*) > 1) a1)) a2) v1
where ss_store_sk = s_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_qoy = {qoy} and d_year = {year}
  and (substring(s_zip, 1, 2) = substring(v1.ca_zip, 1, 2))
group by s_store_name
order by s_store_name
limit 100
