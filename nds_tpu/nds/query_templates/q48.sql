select sum(ss_quantity) total_qty
from store_sales, store, customer_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = {year}
  and ((cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '{ms1}'
        and cd_education_status = '{es1}'
        and ss_sales_price between 100.00 and 150.00)
    or (cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '{ms2}'
        and cd_education_status = '{es2}'
        and ss_sales_price between 50.00 and 100.00)
    or (cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '{ms3}'
        and cd_education_status = '{es3}'
        and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('{s1}', '{s2}', '{s3}')
        and ss_net_profit between 0 and 2000)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('{s4}', '{s5}', '{s6}')
        and ss_net_profit between 150 and 3000)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('{s7}', '{s8}', '{s9}')
        and ss_net_profit between 50 and 25000))
