select c_customer_id customer_id, c_last_name customer_name
from customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
where ca_city = '{city}'
  and c_current_addr_sk = ca_address_sk
  and ib_lower_bound >= {income}
  and ib_upper_bound <= {income} + 50000
  and ib_income_band_sk = hd_income_band_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and sr_cdemo_sk = cd_demo_sk
order by c_customer_id
limit 100
