select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = {manufact}
  and i_item_sk = ws_item_sk
  and d_date between date '{date}' and date '{date}' + interval 90 days
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt > (select 1.3 * avg(ws_ext_discount_amt)
                             from web_sales ws2, date_dim d2
                             where ws2.ws_item_sk = i_item_sk
                               and d2.d_date between date '{date}' and
                                   date '{date}' + interval 90 days
                               and d2.d_date_sk = ws2.ws_sold_date_sk)
order by sum(ws_ext_discount_amt)
limit 100
