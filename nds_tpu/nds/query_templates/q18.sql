select i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity as decimal)) agg1,
       avg(cast(cs_list_price as decimal)) agg2,
       avg(cast(cs_coupon_amt as decimal)) agg3,
       avg(cast(cs_sales_price as decimal)) agg4,
       avg(cast(cs_net_profit as decimal)) agg5,
       avg(cast(c_birth_year as decimal)) agg6,
       avg(cast(cd1.cd_dep_count as decimal)) agg7
from catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1.cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd1.cd_gender = '{gender}'
  and cd1.cd_education_status = '{education}'
  and c_current_cdemo_sk = cd2.cd_demo_sk
  and c_current_addr_sk = ca_address_sk
  and d_year = {year}
  and c_birth_month in ({m1}, {m2}, {m3}, {m4}, {m5}, {m6})
  and ca_state in ('{s1}', '{s2}', '{s3}', '{s4}', '{s5}', '{s6}', '{s7}')
group by rollup(i_item_id, ca_country, ca_state, ca_county)
order by ca_country, ca_state, ca_county, i_item_id
limit 100
