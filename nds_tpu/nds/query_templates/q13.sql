select avg(ss_quantity) avg_qty,
       avg(ss_ext_sales_price) avg_esp,
       avg(ss_ext_wholesale_cost) avg_ewc,
       sum(ss_ext_wholesale_cost) sum_ewc
from store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = {year}
  and ((ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '{ms1}'
        and cd_education_status = '{es1}'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '{ms2}'
        and cd_education_status = '{es2}'
        and ss_sales_price between 50.00 and 100.00
        and hd_dep_count = 1)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '{ms3}'
        and cd_education_status = '{es3}'
        and ss_sales_price between 150.00 and 200.00
        and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('{s1}', '{s2}', '{s3}')
        and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('{s4}', '{s5}', '{s6}')
        and ss_net_profit between 150 and 300)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('{s7}', '{s8}', '{s9}')
        and ss_net_profit between 50 and 250))
