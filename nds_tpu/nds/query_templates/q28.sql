select *
from (select avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(distinct ss_list_price) b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between {lp1} and {lp1} + 10
          or ss_coupon_amt between {ca1} and {ca1} + 1000
          or ss_wholesale_cost between {wc1} and {wc1} + 20)) b1,
     (select avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(distinct ss_list_price) b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between {lp2} and {lp2} + 10
          or ss_coupon_amt between {ca2} and {ca2} + 1000
          or ss_wholesale_cost between {wc2} and {wc2} + 20)) b2,
     (select avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(distinct ss_list_price) b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between {lp3} and {lp3} + 10
          or ss_coupon_amt between {ca3} and {ca3} + 1000
          or ss_wholesale_cost between {wc3} and {wc3} + 20)) b3,
     (select avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(distinct ss_list_price) b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between {lp4} and {lp4} + 10
          or ss_coupon_amt between {ca4} and {ca4} + 1000
          or ss_wholesale_cost between {wc4} and {wc4} + 20)) b4,
     (select avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
             count(distinct ss_list_price) b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between {lp5} and {lp5} + 10
          or ss_coupon_amt between {ca5} and {ca5} + 1000
          or ss_wholesale_cost between {wc5} and {wc5} + 20)) b5,
     (select avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
             count(distinct ss_list_price) b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between {lp6} and {lp6} + 10
          or ss_coupon_amt between {ca6} and {ca6} + 1000
          or ss_wholesale_cost between {wc6} and {wc6} + 20)) b6
limit 100
