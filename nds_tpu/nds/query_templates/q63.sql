select *
from (select i_manager_id, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price))
               over (partition by i_manager_id) avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in ({dms}, {dms} + 1, {dms} + 2, {dms} + 3,
                            {dms} + 4, {dms} + 5, {dms} + 6, {dms} + 7,
                            {dms} + 8, {dms} + 9, {dms} + 10, {dms} + 11)
        and ((i_category in ('Books', 'Children', 'Electronics')
              and i_class in ('booksclass1', 'childrenclass2',
                              'electronicsclass3', 'booksclass4')
              and i_brand in ('amalg #1', 'edu pack #2', 'exporti #3',
                              'amalg #4'))
          or (i_category in ('Women', 'Music', 'Men')
              and i_class in ('womenclass1', 'musicclass2', 'menclass3',
                              'womenclass4')
              and i_brand in ('brand #1', 'corp #2', 'maxi #3',
                              'brand #4')))
      group by i_manager_id, d_moy) tmp1
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
