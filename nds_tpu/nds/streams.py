"""NDS (TPC-DS) query + stream generation and stream parsing.

Counterpart of the reference's dsqgen wrapper
(`nds/nds_gen_query_stream.py:42-103`): renders query templates with
substitution parameters and emits permuted streams, each query framed by
the dsqgen-style marker the power driver parses
(`-- start query N in stream S using template queryNN.tpl`, parsed by
`nds/nds_power.py:50-77`). Two-statement templates (q14/23/24/39 in the
full set) split into _part1/_part2 the same way
(`nds/nds_gen_query_stream.py:91-103`).

Template coverage grows with the engine; TEMPLATES lists what is
implemented so stream generation and the orchestrator agree on the set.
"""

from __future__ import annotations

import os
import random
import re
from collections import OrderedDict

TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "query_templates")


def available_templates() -> list[int]:
    out = []
    for f in os.listdir(TEMPLATE_DIR):
        m = re.match(r"q(\d+)\.sql$", f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


# qualification substitution parameters (spec-shaped defaults bound to
# the builtin generator's value domains)
QUALIFICATION: dict[int, dict] = {
    1: {"year": 2000, "state": "TX"},
    3: {"manufact": 128, "month": 11},
    6: {"year": 2001, "month": 1},
    10: {"county1": "Williamson County", "county2": "Walker County",
         "county3": "Ziebach County", "county4": "Franklin County",
         "county5": "Bronx County", "year": 2002, "month": 1},
    12: {"cat1": "Sports", "cat2": "Books", "cat3": "Home",
         "date": "1999-02-22"},
    16: {"date": "2002-02-01", "state": "GA",
         "county": "Williamson County"},
    17: {"year": 2001},
    20: {"cat1": "Sports", "cat2": "Books", "cat3": "Home",
         "date": "1999-02-22"},
    25: {"year": 2001},
    28: {"lp1": 90, "ca1": 459, "wc1": 31,
         "lp2": 142, "ca2": 1000, "wc2": 50,
         "lp3": 66, "ca3": 1500, "wc3": 20,
         "lp4": 135, "ca4": 200, "wc4": 60,
         "lp5": 28, "ca5": 800, "wc5": 40,
         "lp6": 120, "ca6": 600, "wc6": 70},
    29: {"year": 2000},
    32: {"manufact": 320, "date": "1998-03-18"},
    37: {"price": 62, "date": "2000-02-01", "m1": 129, "m2": 270,
         "m3": 821, "m4": 423},
    82: {"price": 62, "date": "2000-05-25", "m1": 129, "m2": 270,
         "m3": 821, "m4": 423},
    92: {"manufact": 350, "date": "2000-01-27"},
    94: {"date": "1999-02-01", "state": "IL", "company": "pri"},
    98: {"cat1": "Sports", "cat2": "Books", "cat3": "Home",
         "date": "1999-02-22"},
    7: {"gender": "M", "marital": "S", "education": "College",
        "year": 2000},
    18: {"gender": "F", "education": "Unknown", "year": 1998,
         "m1": 1, "m2": 6, "m3": 8, "m4": 9, "m5": 12, "m6": 2,
         "s1": "MS", "s2": "IN", "s3": "ND", "s4": "OK", "s5": "NM",
         "s6": "VA", "s7": "MS"},
    21: {"date": "1998-02-01"},
    22: {"dms": 1176},
    27: {"gender": "M", "marital": "S", "education": "College",
         "year": 2000, "s1": "FL", "s2": "IL", "s3": "KY", "s4": "LA",
         "s5": "PA", "s6": "SD"},
    30: {"year": 2002, "state": "GA"},
    33: {"category": "Electronics", "year": 1998, "month": 5,
         "gmt": -5},
    35: {"year": 2002},
    38: {"dms": 1212},
    40: {"date": "2000-03-11"},
    41: {"manufact": 738},
    50: {"year": 2001, "month": 8},
    76: {},
    85: {"year": 2000},
    87: {"dms": 1212},
    4: {"year": 1999},
    8: {"qoy": 2, "year": 1998},
    14: {"year": 1999},
    23: {"year": 1999, "month": 5},
    24: {"market": 5, "c1": "beige", "c2": "azure"},
    39: {"year": 1998, "month": 1},
    64: {"year": 1999, "price": 15,
         "c1": "azure", "c2": "beige", "c3": "black", "c4": "blue",
         "c5": "brown", "c6": "coral"},
    66: {"year": 1999, "time": 30000, "smc1": "UPS", "smc2": "FEDEX"},
    67: {"dms": 1200},
    72: {"bp": ">10000", "ms": "M", "year": 1999},
    75: {"category": "Home", "year": 2000},
    78: {"year": 1999},
    51: {"dms": 1200},
    97: {"dms": 1200},
    34: {"year": 1999, "bp1": ">10000", "bp2": "Unknown",
         "county1": "Barrow County", "county2": "Bronx County",
         "county3": "Maverick County", "county4": "Mobile County",
         "county5": "Orange County", "county6": "Barrow County",
         "county7": "Bronx County", "county8": "Orange County"},
    45: {"qoy": 1, "year": 2000},
    46: {"dep": 5, "veh": 3, "year": 1999, "city1": "Midway",
         "city2": "Bethel"},
    49: {"ramt": 10, "year": 2000, "month": 12},
    54: {"category": "Music", "class": "musicclass5", "month": 4,
         "year": 1999},
    56: {"c1": "azure", "c2": "beige", "c3": "black", "year": 2000,
         "month": 2, "gmt": -5},
    58: {"date": "2000-03-24"},
    60: {"category": "Children", "year": 1999, "month": 9, "gmt": -5},
    81: {"year": 1999, "state": "TX"},
    83: {"date1": "1998-03-20", "date2": "1999-06-14",
         "date3": "2000-11-17"},
    95: {"date": "1999-02-01", "state": "TX", "company": "able"},
    2: {"year": 1998},
    5: {"date": "2000-08-19"},
    11: {"year": 1999},
    31: {"year": 2000},
    59: {"dms": 1200},
    71: {"manager": 1, "month": 12, "year": 1999},
    74: {"year": 1999},
    77: {"date": "2000-08-19"},
    80: {"date": "2000-08-19"},
    36: {"year": 2000, "s1": "FL", "s2": "IL", "s3": "KY", "s4": "LA",
         "s5": "PA", "s6": "SD"},
    44: {"store": 4},
    47: {"year": 2000},
    53: {"dms": 1190},
    57: {"year": 2000},
    63: {"dms": 1190},
    70: {"dms": 1212},
    86: {"dms": 1212},
    89: {"year": 1999},
    9: {"t1": 3000, "t2": 3000, "t3": 3000, "t4": 3000, "t5": 3000},
    13: {"year": 2001, "ms1": "M", "es1": "Advanced Degree",
         "ms2": "S", "es2": "College", "ms3": "W", "es3": "2 yr Degree",
         "s1": "TX", "s2": "OH", "s3": "TX", "s4": "OR", "s5": "NM",
         "s6": "KY", "s7": "VA", "s8": "TX", "s9": "MS"},
    15: {"qoy": 2, "year": 2001},
    19: {"manager": 8, "month": 11, "year": 1998},
    26: {"gender": "M", "marital": "S", "education": "College",
         "year": 2000},
    42: {"month": 11, "year": 2000},
    43: {"gmt": -5, "year": 2000},
    48: {"year": 2000, "ms1": "M", "es1": "4 yr Degree", "ms2": "D",
         "es2": "2 yr Degree", "ms3": "S", "es3": "College",
         "s1": "TX", "s2": "OH", "s3": "TX", "s4": "OR", "s5": "NM",
         "s6": "KY", "s7": "VA", "s8": "TX", "s9": "MS"},
    52: {"month": 11, "year": 2000},
    55: {"manager": 28, "month": 11, "year": 1999},
    61: {"gmt": -5, "category": "Jewelry", "year": 1998},
    62: {"dms": 1200},
    65: {"dms": 1176},
    68: {"dep": 4, "veh": 3, "year": 1999, "city1": "Midway",
         "city2": "Fairview"},
    69: {"s1": "KY", "s2": "GA", "s3": "TX", "year": 2001, "month": 4},
    73: {"year": 1999, "bp1": ">10000", "bp2": "Unknown",
         "county1": "Williamson County", "county2": "Walker County",
         "county3": "Franklin County", "county4": "Ziebach County"},
    79: {"dep": 6, "veh": 2, "year": 1999},
    84: {"city": "Fairview", "income": 38128},
    88: {"d1": 4, "d2": 2, "d3": 0},
    90: {"hour_am": 8, "hour_pm": 19, "dep": 6},
    91: {"year": 1998, "month": 11},
    93: {"reason": "Did not fit"},
    96: {"hour": 20, "dep": 7},
    99: {"dms": 1200},
}


# value domains shared with the builtin generator (datagen/tpcds.py §3
# lists); drawing from these keeps every rebinding inside the data's
# actual domain the way dsqgen's distributions do
_STATES = ["AL", "CA", "CO", "FL", "GA", "IL", "IN", "KS", "KY", "LA",
           "MI", "MN", "MO", "MS", "NC", "NE", "NY", "OH", "OK", "PA",
           "SD", "TN", "TX", "VA", "WA", "WI"]
_COUNTIES = [f"{w} County" for w in
             ["Williamson", "Walker", "Ziebach", "Franklin", "Bronx",
              "Orange", "Fairfield", "Jackson", "Barrow", "Daviess",
              "Luce", "Richland", "Furnas", "Maverick", "Huron",
              "Kittitas", "Mobile", "Coal", "Lunenburg", "Ferry"]]
_CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Oakland",
           "Riverside", "Salem", "Georgetown", "Greenfield", "Liberty",
           "Bethel", "Pleasant Hill", "Lebanon", "Springdale", "Shiloh",
           "Mount Olive", "Glendale", "Marion", "Greenville", "Union"]
_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]
_MARITAL = ["S", "M", "D", "W", "U"]
_BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000",
                  ">10000", "Unknown"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "black", "blue", "blush", "brown", "burlywood", "chartreuse",
           "chiffon", "chocolate", "coral", "cornflower", "cream",
           "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
           "floral", "forest", "frosted", "gainsboro", "ghost",
           "goldenrod", "green", "grey", "honeydew", "hot", "indian",
           "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
           "light", "lime", "linen", "magenta", "maroon", "medium",
           "metallic", "midnight", "mint", "misty", "moccasin", "navajo",
           "navy", "olive", "orange", "orchid", "pale", "papaya",
           "peach", "peru", "pink", "plum", "powder", "puff", "purple",
           "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy",
           "seashell", "sienna", "sky", "slate", "smoke", "snow",
           "spring", "steel", "tan", "thistle", "tomato", "turquoise",
           "violet", "wheat", "white", "yellow"]
_SM_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS",
                "ZHOU", "ZOUROS", "MSC", "LATVIAN", "DIAMOND",
                "ALLIANCE", "ORIENTAL", "BARIAN", "BOXBUNDLES",
                "HARMSTORF", "PRIVATECARRIER", "GERMA", "RUPEKSA",
                "GREAT EASTERN"]
_REASONS = ["Package was damaged", "Stopped working",
            "Did not get it on time", "Not the product that was ordred",
            "Parts missing", "Does not work with a product that I have",
            "Gift exchange", "Did not like the color",
            "Did not like the model", "Did not like the make",
            "Did not fit"]
_WEB_COMPANIES = ["pri", "able", "ought", "ation", "bar", "ese"]
_GMT = [-5, -6, -7, -8]
# sales rows land in 1998-2002 (datagen SALES_DATE_LO/HI); d_month_seq
# = (year-1900)*12 + moy - 1, so the 1998-2002 window is seq 1176-1235
_YEARS = (1998, 2002)
_DMS = (1176, 1224)  # leaves +11 months of headroom for dms..dms+11


def _distinct(rng, pool, k):
    return rng.sample(list(pool), k)


def _date(rng, y_lo=1998, y_hi=2002, m_lo=1, m_hi=12, day=None):
    y = rng.randint(y_lo, y_hi)
    m = rng.randint(m_lo, m_hi)
    d = day if day is not None else rng.randint(1, 28)
    return f"{y:04d}-{m:02d}-{d:02d}"


def random_params(template_number: int, rng, stream: int) -> dict:
    """Per-stream substitution parameters (reference: dsqgen -rngseed
    redraws bindings per stream, `nds/nds_gen_query_stream.py:42-89`, so
    concurrent throughput streams are DISTINCT workloads, not N copies).
    Distributions follow the spec's parameter domains restricted to the
    builtin generator's §3 value lists; templates keep the same keys as
    QUALIFICATION, so a draw is a drop-in replacement."""
    q = template_number
    year = lambda lo=1998, hi=2002: rng.randint(lo, hi)
    dms = lambda: rng.randint(*_DMS)
    manufact = lambda: rng.randint(1, 1000)
    gmt = lambda: rng.choice(_GMT)
    if q == 1:
        return {"year": year(1998, 2000), "state": rng.choice(_STATES)}
    if q == 3:
        return {"manufact": manufact(), "month": rng.randint(11, 12)}
    if q == 6:
        return {"year": year(), "month": rng.randint(1, 8)}
    if q == 10:
        c = _distinct(rng, _COUNTIES, 5)
        return {**{f"county{i}": c[i - 1] for i in range(1, 6)},
                "year": year(1999, 2002), "month": rng.randint(1, 4)}
    if q in (12, 20, 98):
        c = _distinct(rng, _CATEGORIES, 3)
        return {"cat1": c[0], "cat2": c[1], "cat3": c[2],
                "date": _date(rng, 1998, 2002, 1, 7)}
    if q == 16:
        return {"date": _date(rng, 1999, 2002, 1, 12, day=1),
                "state": rng.choice(_STATES),
                "county": rng.choice(_COUNTIES)}
    if q in (17, 25):
        return {"year": year(1998, 2001)}
    if q == 28:
        out = {}
        for i in range(1, 7):
            out[f"lp{i}"] = rng.randint(0, 190)
            out[f"ca{i}"] = rng.randint(0, 2000)
            out[f"wc{i}"] = rng.randint(0, 80)
        return out
    if q == 29:
        return {"year": year(1998, 2000)}
    if q in (32, 92):
        return {"manufact": manufact(), "date": _date(rng)}
    if q in (37, 82):
        ms = _distinct(rng, range(1, 1001), 4)
        return {"price": rng.randint(10, 90), "date": _date(rng),
                **{f"m{i}": ms[i - 1] for i in range(1, 5)}}
    if q in (94, 95):
        return {"date": _date(rng, 1999, 2002, 1, 10, day=1),
                "state": rng.choice(_STATES),
                "company": rng.choice(_WEB_COMPANIES)}
    if q in (7, 26):
        return {"gender": rng.choice("MF"),
                "marital": rng.choice(_MARITAL),
                "education": rng.choice(_EDUCATION), "year": year()}
    if q == 18:
        ms = _distinct(rng, range(1, 13), 6)
        ss = [rng.choice(_STATES) for _ in range(7)]
        return {"gender": rng.choice("MF"),
                "education": rng.choice(_EDUCATION), "year": year(),
                **{f"m{i}": ms[i - 1] for i in range(1, 7)},
                **{f"s{i}": ss[i - 1] for i in range(1, 8)}}
    if q == 21:
        return {"date": _date(rng, 1998, 2002, 1, 12, day=1)}
    if q in (22, 38, 51, 53, 59, 62, 63, 65, 67, 70, 86, 87, 97, 99):
        return {"dms": dms()}
    if q == 24:
        c = _distinct(rng, _COLORS, 2)
        return {"market": rng.randint(1, 10), "c1": c[0], "c2": c[1]}
    if q == 27:
        ss = _distinct(rng, _STATES, 6)
        return {"gender": rng.choice("MF"),
                "marital": rng.choice(_MARITAL),
                "education": rng.choice(_EDUCATION), "year": year(),
                **{f"s{i}": ss[i - 1] for i in range(1, 7)}}
    if q in (30, 81):
        return {"year": year(1999, 2002), "state": rng.choice(_STATES)}
    if q in (33, 56, 60):
        out = {"year": year(), "month": rng.randint(1, 12),
               "gmt": gmt()}
        if q == 56:
            c = _distinct(rng, _COLORS, 3)
            out.update({"c1": c[0], "c2": c[1], "c3": c[2]})
        else:
            out["category"] = rng.choice(_CATEGORIES)
        return out
    if q in (35, 69):
        out = {"year": year(1999, 2002), "month": rng.randint(1, 4)}
        if q == 69:
            ss = _distinct(rng, _STATES, 3)
            out.update({f"s{i}": ss[i - 1] for i in range(1, 4)})
        return out
    if q == 40:
        return {"date": _date(rng)}
    if q == 41:
        return {"manufact": manufact()}
    if q == 50:
        return {"year": year(1999, 2002), "month": rng.randint(8, 10)}
    if q == 85:
        return {"year": year()}
    if q in (4, 11, 74):
        return {"year": year(1998, 2001)}
    if q == 8:
        return {"qoy": rng.randint(1, 2), "year": year()}
    if q == 14:
        return {"year": year(1998, 2000)}
    if q == 23:
        return {"year": year(1998, 2000), "month": rng.randint(1, 7)}
    if q == 39:
        return {"year": year(), "month": rng.randint(1, 11)}
    if q == 64:
        c = _distinct(rng, _COLORS, 6)
        return {"year": year(1998, 2001), "price": rng.randint(0, 85),
                **{f"c{i}": c[i - 1] for i in range(1, 7)}}
    if q == 66:
        sm = _distinct(rng, _SM_CARRIERS, 2)
        return {"year": year(), "time": rng.randint(1, 57600),
                "smc1": sm[0], "smc2": sm[1]}
    if q == 72:
        return {"bp": rng.choice(_BUY_POTENTIAL),
                "ms": rng.choice(_MARITAL), "year": year()}
    if q == 75:
        return {"category": rng.choice(_CATEGORIES),
                "year": year(1998, 2001)}
    if q == 78:
        return {"year": year()}
    if q == 34:
        bp = _distinct(rng, _BUY_POTENTIAL, 2)
        return {"year": year(1998, 2000), "bp1": bp[0], "bp2": bp[1],
                **{f"county{i}": rng.choice(_COUNTIES)
                   for i in range(1, 9)}}
    if q == 45:
        return {"qoy": rng.randint(1, 4), "year": year()}
    if q in (46, 68):
        cities = _distinct(rng, _CITIES, 2)
        return {"dep": rng.randint(0, 9), "veh": rng.randint(-1, 4),
                "year": year(1998, 2000), "city1": cities[0],
                "city2": cities[1]}
    if q == 49:
        return {"ramt": rng.randint(5, 15), "year": year(),
                "month": rng.randint(11, 12)}
    if q == 54:
        cat = rng.choice(_CATEGORIES)
        return {"category": cat,
                "class": f"{cat.lower()}class{rng.randint(1, 16)}",
                "month": rng.randint(1, 7), "year": year()}
    if q == 58:
        return {"date": _date(rng)}
    if q == 83:
        return {"date1": _date(rng), "date2": _date(rng),
                "date3": _date(rng)}
    if q in (2, 31):
        return {"year": year(1998, 2001)}
    if q in (5, 77, 80):
        return {"date": _date(rng)}
    if q == 71:
        return {"manager": rng.randint(1, 100),
                "month": rng.randint(11, 12), "year": year()}
    if q == 36:
        ss = _distinct(rng, _STATES, 6)
        return {"year": year(),
                **{f"s{i}": ss[i - 1] for i in range(1, 7)}}
    if q == 44:
        return {"store": rng.randint(1, 6)}
    if q in (47, 57):
        return {"year": year(1999, 2001)}
    if q == 89:
        return {"year": year()}
    if q == 9:
        return {f"t{i}": rng.randint(1000, 5000) for i in range(1, 6)}
    if q in (13, 48):
        ms = _distinct(rng, _MARITAL, 3)
        es = _distinct(rng, _EDUCATION, 3)
        out = {"year": year(),
               **{f"ms{i}": ms[i - 1] for i in range(1, 4)},
               **{f"es{i}": es[i - 1] for i in range(1, 4)},
               **{f"s{i}": rng.choice(_STATES) for i in range(1, 10)}}
        return out
    if q == 15:
        return {"qoy": rng.randint(1, 4), "year": year()}
    if q in (19, 55):
        return {"manager": rng.randint(1, 100),
                "month": rng.randint(11, 12), "year": year()}
    if q in (42, 52):
        return {"month": rng.randint(11, 12), "year": year()}
    if q == 43:
        return {"gmt": gmt(), "year": year()}
    if q == 61:
        return {"gmt": gmt(), "category": rng.choice(_CATEGORIES),
                "year": year()}
    if q == 73:
        bp = _distinct(rng, _BUY_POTENTIAL, 2)
        c = _distinct(rng, _COUNTIES, 4)
        return {"year": year(1998, 2000), "bp1": bp[0], "bp2": bp[1],
                **{f"county{i}": c[i - 1] for i in range(1, 5)}}
    if q == 79:
        return {"dep": rng.randint(0, 9), "veh": rng.randint(-1, 4),
                "year": year(1998, 2000)}
    if q == 84:
        return {"city": rng.choice(_CITIES),
                "income": rng.randint(0, 70000)}
    if q == 88:
        d = _distinct(rng, range(0, 10), 3)
        return {"d1": d[0], "d2": d[1], "d3": d[2]}
    if q == 90:
        return {"hour_am": rng.randint(6, 12),
                "hour_pm": rng.randint(13, 20), "dep": rng.randint(0, 9)}
    if q == 91:
        return {"year": year(), "month": rng.randint(11, 12)}
    if q == 93:
        return {"reason": rng.choice(_REASONS)}
    if q == 96:
        return {"hour": rng.randint(8, 20), "dep": rng.randint(0, 9)}
    if q == 76:
        return {}
    # any template without an explicit distribution falls back to its
    # qualification bindings (still a valid, spec-shaped draw)
    return dict(QUALIFICATION.get(q, {}))


def render_query(template_number: int, params: dict | None = None) -> str:
    with open(os.path.join(TEMPLATE_DIR, f"q{template_number}.sql")) as f:
        tpl = f.read()
    if params is None:
        params = QUALIFICATION.get(template_number, {})
    return tpl.format(**params)


def stream_order(stream: int, rng_seed: int | None = None,
                 templates: list[int] | None = None) -> list[int]:
    order = list(templates if templates is not None
                 else available_templates())
    if stream == 0:
        return order
    rng = random.Random((rng_seed or 0) * 1000 + stream)
    rng.shuffle(order)
    return order


def generate_query_streams(output_dir: str, streams: int,
                           rng_seed: int | None = None,
                           templates: list[int] | None = None,
                           qualification: bool = True) -> list[str]:
    """Write query_{i}.sql stream files (reference layout:
    `nds/nds_gen_query_stream.py:42-89` emits query_0.sql .. query_N.sql).

    qualification=False redraws every template's substitution parameters
    per stream from a (rng_seed, stream)-seeded generator — the dsqgen
    `-rngseed` behavior — so throughput streams differ in bindings as
    well as order (and the engine cannot reuse one compiled program
    across what the benchmark defines as distinct workloads)."""
    os.makedirs(output_dir, exist_ok=True)
    paths = []
    for i in range(streams):
        rng = random.Random((rng_seed or 0) * 7919 + i)
        parts = []
        for qn in stream_order(i, rng_seed, templates):
            params = None if qualification else random_params(qn, rng, i)
            sql = render_query(qn, params)
            parts.append(
                f"-- start query {qn} in stream {i} using template "
                f"query{qn}.tpl\n{sql}\n-- end query {qn} in stream {i} "
                f"using template query{qn}.tpl\n")
        path = os.path.join(output_dir, f"query_{i}.sql")
        with open(path, "w") as f:
            f.write("\n".join(parts))
        paths.append(path)
    return paths


_MARKER_RE = re.compile(
    r"-- start query (\d+) in stream \d+ using template "
    r"query(\d+)\.tpl\n(.*?)-- end query \1 in stream",
    re.DOTALL)


def parse_query_stream(path: str) -> "OrderedDict[str, str]":
    """Stream file -> {query_name: sql}, splitting multi-statement
    templates into _part1/_part2 (reference: `nds/nds_power.py:50-77` +
    `nds_gen_query_stream.split_special_query:91-103`)."""
    with open(path) as f:
        stream = f.read()
    queries: "OrderedDict[str, str]" = OrderedDict()
    for _num, tpl, body in _MARKER_RE.findall(stream):
        stmts = [s.strip() for s in body.split(";") if s.strip()]
        if len(stmts) == 1:
            queries[f"query{tpl}"] = stmts[0]
        else:
            for i, s in enumerate(stmts, 1):
                queries[f"query{tpl}_part{i}"] = s
    return queries
