"""NDS (TPC-DS) query + stream generation and stream parsing.

Counterpart of the reference's dsqgen wrapper
(`nds/nds_gen_query_stream.py:42-103`): renders query templates with
substitution parameters and emits permuted streams, each query framed by
the dsqgen-style marker the power driver parses
(`-- start query N in stream S using template queryNN.tpl`, parsed by
`nds/nds_power.py:50-77`). Two-statement templates (q14/23/24/39 in the
full set) split into _part1/_part2 the same way
(`nds/nds_gen_query_stream.py:91-103`).

Template coverage grows with the engine; TEMPLATES lists what is
implemented so stream generation and the orchestrator agree on the set.
"""

from __future__ import annotations

import os
import random
import re
from collections import OrderedDict

TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "query_templates")


def available_templates() -> list[int]:
    out = []
    for f in os.listdir(TEMPLATE_DIR):
        m = re.match(r"q(\d+)\.sql$", f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


# qualification substitution parameters (spec-shaped defaults bound to
# the builtin generator's value domains)
QUALIFICATION: dict[int, dict] = {
    1: {"year": 2000, "state": "TX"},
    3: {"manufact": 128, "month": 11},
    6: {"year": 2001, "month": 1},
    10: {"county1": "Williamson County", "county2": "Walker County",
         "county3": "Ziebach County", "county4": "Franklin County",
         "county5": "Bronx County", "year": 2002, "month": 1},
    12: {"cat1": "Sports", "cat2": "Books", "cat3": "Home",
         "date": "1999-02-22"},
    16: {"date": "2002-02-01", "state": "GA",
         "county": "Williamson County"},
    17: {"year": 2001},
    20: {"cat1": "Sports", "cat2": "Books", "cat3": "Home",
         "date": "1999-02-22"},
    25: {"year": 2001},
    28: {"lp1": 90, "ca1": 459, "wc1": 31,
         "lp2": 142, "ca2": 1000, "wc2": 50,
         "lp3": 66, "ca3": 1500, "wc3": 20,
         "lp4": 135, "ca4": 200, "wc4": 60,
         "lp5": 28, "ca5": 800, "wc5": 40,
         "lp6": 120, "ca6": 600, "wc6": 70},
    29: {"year": 2000},
    32: {"manufact": 320, "date": "1998-03-18"},
    37: {"price": 62, "date": "2000-02-01", "m1": 129, "m2": 270,
         "m3": 821, "m4": 423},
    82: {"price": 62, "date": "2000-05-25", "m1": 129, "m2": 270,
         "m3": 821, "m4": 423},
    92: {"manufact": 350, "date": "2000-01-27"},
    94: {"date": "1999-02-01", "state": "IL", "company": "pri"},
    98: {"cat1": "Sports", "cat2": "Books", "cat3": "Home",
         "date": "1999-02-22"},
    7: {"gender": "M", "marital": "S", "education": "College",
        "year": 2000},
    18: {"gender": "F", "education": "Unknown", "year": 1998,
         "m1": 1, "m2": 6, "m3": 8, "m4": 9, "m5": 12, "m6": 2,
         "s1": "MS", "s2": "IN", "s3": "ND", "s4": "OK", "s5": "NM",
         "s6": "VA", "s7": "MS"},
    21: {"date": "1998-02-01"},
    22: {"dms": 1176},
    27: {"gender": "M", "marital": "S", "education": "College",
         "year": 2000, "s1": "FL", "s2": "IL", "s3": "KY", "s4": "LA",
         "s5": "PA", "s6": "SD"},
    30: {"year": 2002, "state": "GA"},
    33: {"category": "Electronics", "year": 1998, "month": 5,
         "gmt": -5},
    35: {"year": 2002},
    38: {"dms": 1212},
    40: {"date": "2000-03-11"},
    41: {"manufact": 738},
    50: {"year": 2001, "month": 8},
    76: {},
    85: {"year": 2000},
    87: {"dms": 1212},
    4: {"year": 1999},
    8: {"qoy": 2, "year": 1998},
    14: {"year": 1999},
    23: {"year": 1999, "month": 5},
    24: {"market": 5, "c1": "beige", "c2": "azure"},
    39: {"year": 1998, "month": 1},
    64: {"year": 1999, "price": 15,
         "c1": "azure", "c2": "beige", "c3": "black", "c4": "blue",
         "c5": "brown", "c6": "coral"},
    66: {"year": 1999, "time": 30000, "smc1": "UPS", "smc2": "FEDEX"},
    67: {"dms": 1200},
    72: {"bp": ">10000", "ms": "M", "year": 1999},
    75: {"category": "Home", "year": 2000},
    78: {"year": 1999},
    51: {"dms": 1200},
    97: {"dms": 1200},
    34: {"year": 1999, "bp1": ">10000", "bp2": "Unknown",
         "county1": "Barrow County", "county2": "Bronx County",
         "county3": "Maverick County", "county4": "Mobile County",
         "county5": "Orange County", "county6": "Barrow County",
         "county7": "Bronx County", "county8": "Orange County"},
    45: {"qoy": 1, "year": 2000},
    46: {"dep": 5, "veh": 3, "year": 1999, "city1": "Midway",
         "city2": "Bethel"},
    49: {"ramt": 10, "year": 2000, "month": 12},
    54: {"category": "Music", "class": "musicclass5", "month": 4,
         "year": 1999},
    56: {"c1": "azure", "c2": "beige", "c3": "black", "year": 2000,
         "month": 2, "gmt": -5},
    58: {"date": "2000-03-24"},
    60: {"category": "Children", "year": 1999, "month": 9, "gmt": -5},
    81: {"year": 1999, "state": "TX"},
    83: {"date1": "1998-03-20", "date2": "1999-06-14",
         "date3": "2000-11-17"},
    95: {"date": "1999-02-01", "state": "TX", "company": "able"},
    2: {"year": 1998},
    5: {"date": "2000-08-19"},
    11: {"year": 1999},
    31: {"year": 2000},
    59: {"dms": 1200},
    71: {"manager": 1, "month": 12, "year": 1999},
    74: {"year": 1999},
    77: {"date": "2000-08-19"},
    80: {"date": "2000-08-19"},
    36: {"year": 2000, "s1": "FL", "s2": "IL", "s3": "KY", "s4": "LA",
         "s5": "PA", "s6": "SD"},
    44: {"store": 4},
    47: {"year": 2000},
    53: {"dms": 1190},
    57: {"year": 2000},
    63: {"dms": 1190},
    70: {"dms": 1212},
    86: {"dms": 1212},
    89: {"year": 1999},
    9: {"t1": 3000, "t2": 3000, "t3": 3000, "t4": 3000, "t5": 3000},
    13: {"year": 2001, "ms1": "M", "es1": "Advanced Degree",
         "ms2": "S", "es2": "College", "ms3": "W", "es3": "2 yr Degree",
         "s1": "TX", "s2": "OH", "s3": "TX", "s4": "OR", "s5": "NM",
         "s6": "KY", "s7": "VA", "s8": "TX", "s9": "MS"},
    15: {"qoy": 2, "year": 2001},
    19: {"manager": 8, "month": 11, "year": 1998},
    26: {"gender": "M", "marital": "S", "education": "College",
         "year": 2000},
    42: {"month": 11, "year": 2000},
    43: {"gmt": -5, "year": 2000},
    48: {"year": 2000, "ms1": "M", "es1": "4 yr Degree", "ms2": "D",
         "es2": "2 yr Degree", "ms3": "S", "es3": "College",
         "s1": "TX", "s2": "OH", "s3": "TX", "s4": "OR", "s5": "NM",
         "s6": "KY", "s7": "VA", "s8": "TX", "s9": "MS"},
    52: {"month": 11, "year": 2000},
    55: {"manager": 28, "month": 11, "year": 1999},
    61: {"gmt": -5, "category": "Jewelry", "year": 1998},
    62: {"dms": 1200},
    65: {"dms": 1176},
    68: {"dep": 4, "veh": 3, "year": 1999, "city1": "Midway",
         "city2": "Fairview"},
    69: {"s1": "KY", "s2": "GA", "s3": "TX", "year": 2001, "month": 4},
    73: {"year": 1999, "bp1": ">10000", "bp2": "Unknown",
         "county1": "Williamson County", "county2": "Walker County",
         "county3": "Franklin County", "county4": "Ziebach County"},
    79: {"dep": 6, "veh": 2, "year": 1999},
    84: {"city": "Fairview", "income": 38128},
    88: {"d1": 4, "d2": 2, "d3": 0},
    90: {"hour_am": 8, "hour_pm": 19, "dep": 6},
    91: {"year": 1998, "month": 11},
    93: {"reason": "Did not fit"},
    96: {"hour": 20, "dep": 7},
    99: {"dms": 1200},
}


def render_query(template_number: int, params: dict | None = None) -> str:
    with open(os.path.join(TEMPLATE_DIR, f"q{template_number}.sql")) as f:
        tpl = f.read()
    if params is None:
        params = QUALIFICATION.get(template_number, {})
    return tpl.format(**params)


def stream_order(stream: int, rng_seed: int | None = None,
                 templates: list[int] | None = None) -> list[int]:
    order = list(templates if templates is not None
                 else available_templates())
    if stream == 0:
        return order
    rng = random.Random((rng_seed or 0) * 1000 + stream)
    rng.shuffle(order)
    return order


def generate_query_streams(output_dir: str, streams: int,
                           rng_seed: int | None = None,
                           templates: list[int] | None = None) -> list[str]:
    """Write query_{i}.sql stream files (reference layout:
    `nds/nds_gen_query_stream.py:42-89` emits query_0.sql .. query_N.sql)."""
    os.makedirs(output_dir, exist_ok=True)
    paths = []
    for i in range(streams):
        parts = []
        for qn in stream_order(i, rng_seed, templates):
            sql = render_query(qn)
            parts.append(
                f"-- start query {qn} in stream {i} using template "
                f"query{qn}.tpl\n{sql}\n-- end query {qn} in stream {i} "
                f"using template query{qn}.tpl\n")
        path = os.path.join(output_dir, f"query_{i}.sql")
        with open(path, "w") as f:
            f.write("\n".join(parts))
        paths.append(path)
    return paths


_MARKER_RE = re.compile(
    r"-- start query (\d+) in stream \d+ using template "
    r"query(\d+)\.tpl\n(.*?)-- end query \1 in stream",
    re.DOTALL)


def parse_query_stream(path: str) -> "OrderedDict[str, str]":
    """Stream file -> {query_name: sql}, splitting multi-statement
    templates into _part1/_part2 (reference: `nds/nds_power.py:50-77` +
    `nds_gen_query_stream.split_special_query:91-103`)."""
    with open(path) as f:
        stream = f.read()
    queries: "OrderedDict[str, str]" = OrderedDict()
    for _num, tpl, body in _MARKER_RE.findall(stream):
        stmts = [s.strip() for s in body.split(";") if s.strip()]
        if len(stmts) == 1:
            queries[f"query{tpl}"] = stmts[0]
        else:
            for i, s in enumerate(stmts, 1):
                queries[f"query{tpl}_part{i}"] = s
    return queries
