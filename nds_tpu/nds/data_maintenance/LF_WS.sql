-- LF_WS: web_sales refresh insert (role of the reference's
-- nds/data_maintenance/LF_WS.sql; spec refresh function LF_WS). Same
-- dialect notes as LF_SS.sql. The inc_ship / inc_ship_tax formulas
-- follow the spec intent (net paid + shipping [+ tax]).
DROP VIEW IF EXISTS wsv;
CREATE TEMP VIEW wsv AS
WITH cur_item AS (SELECT * FROM item WHERE i_rec_end_date IS NULL),
     cur_web AS (SELECT * FROM web_site WHERE web_rec_end_date IS NULL),
     cur_wp AS (SELECT * FROM web_page WHERE wp_rec_end_date IS NULL)
SELECT d1.d_date_sk ws_sold_date_sk,
 t_time_sk ws_sold_time_sk,
 d2.d_date_sk ws_ship_date_sk,
 i_item_sk ws_item_sk,
 c1.c_customer_sk ws_bill_customer_sk,
 c1.c_current_cdemo_sk ws_bill_cdemo_sk,
 c1.c_current_hdemo_sk ws_bill_hdemo_sk,
 c1.c_current_addr_sk ws_bill_addr_sk,
 c2.c_customer_sk ws_ship_customer_sk,
 c2.c_current_cdemo_sk ws_ship_cdemo_sk,
 c2.c_current_hdemo_sk ws_ship_hdemo_sk,
 c2.c_current_addr_sk ws_ship_addr_sk,
 wp_web_page_sk ws_web_page_sk,
 web_site_sk ws_web_site_sk,
 sm_ship_mode_sk ws_ship_mode_sk,
 w_warehouse_sk ws_warehouse_sk,
 p_promo_sk ws_promo_sk,
 word_order_id ws_order_number,
 wlin_quantity ws_quantity,
 i_wholesale_cost ws_wholesale_cost,
 i_current_price ws_list_price,
 wlin_sales_price ws_sales_price,
 (i_current_price - wlin_sales_price) * wlin_quantity ws_ext_discount_amt,
 wlin_sales_price * wlin_quantity ws_ext_sales_price,
 i_wholesale_cost * wlin_quantity ws_ext_wholesale_cost,
 i_current_price * wlin_quantity ws_ext_list_price,
 i_current_price * web_tax_percentage ws_ext_tax,
 wlin_coupon_amt ws_coupon_amt,
 wlin_ship_cost * wlin_quantity ws_ext_ship_cost,
 (wlin_sales_price * wlin_quantity) - wlin_coupon_amt ws_net_paid,
 ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt) * (1 + web_tax_percentage) ws_net_paid_inc_tax,
 (wlin_sales_price * wlin_quantity) - wlin_coupon_amt + (wlin_ship_cost * wlin_quantity) ws_net_paid_inc_ship,
 (wlin_sales_price * wlin_quantity) - wlin_coupon_amt + (wlin_ship_cost * wlin_quantity)
  + i_current_price * web_tax_percentage ws_net_paid_inc_ship_tax,
 ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt) - (wlin_quantity * i_wholesale_cost) ws_net_profit
FROM s_web_order
JOIN s_web_order_lineitem ON (word_order_id = wlin_order_id)
LEFT OUTER JOIN date_dim d1 ON (word_order_date = d1.d_date)
LEFT OUTER JOIN time_dim ON (word_order_time = t_time)
LEFT OUTER JOIN customer c1 ON (word_bill_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2 ON (word_ship_customer_id = c2.c_customer_id)
LEFT OUTER JOIN cur_web ON (word_web_site_id = web_site_id)
LEFT OUTER JOIN ship_mode ON (word_ship_mode_id = sm_ship_mode_id)
LEFT OUTER JOIN date_dim d2 ON (wlin_ship_date = d2.d_date)
LEFT OUTER JOIN cur_item ON (wlin_item_id = i_item_id)
LEFT OUTER JOIN cur_wp ON (wlin_web_page_id = wp_web_page_id)
LEFT OUTER JOIN warehouse ON (wlin_warehouse_id = w_warehouse_id)
LEFT OUTER JOIN promotion ON (wlin_promotion_id = p_promo_id);
INSERT INTO web_sales (SELECT * FROM wsv ORDER BY ws_sold_date_sk);
DROP VIEW wsv;
