-- DF_I: inventory delete (role of the reference's
-- nds/data_maintenance/DF_I.sql; spec refresh function DF_I). DATE1 and
-- DATE2 come from the inventory_delete table, which carries a widened
-- window so the weekly snapshots are hit.
DELETE FROM inventory
 WHERE inv_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                       WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
   AND inv_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                       WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
