-- LF_I: inventory refresh insert (role of the reference's
-- nds/data_maintenance/LF_I.sql; spec refresh function LF_I). Same
-- dialect notes as LF_SS.sql.
DROP VIEW IF EXISTS iv;
CREATE TEMP VIEW iv AS
WITH cur_item AS (SELECT * FROM item WHERE i_rec_end_date IS NULL)
SELECT d_date_sk inv_date_sk,
 i_item_sk inv_item_sk,
 w_warehouse_sk inv_warehouse_sk,
 invn_qty_on_hand inv_quantity_on_hand
FROM s_inventory
LEFT OUTER JOIN warehouse ON (invn_warehouse_id = w_warehouse_id)
LEFT OUTER JOIN cur_item ON (invn_item_id = i_item_id)
LEFT OUTER JOIN date_dim ON (d_date = invn_date);
INSERT INTO inventory (SELECT * FROM iv ORDER BY inv_date_sk);
DROP VIEW iv;
