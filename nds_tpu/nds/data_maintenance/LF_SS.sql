-- LF_SS: store_sales refresh insert (role of the reference's
-- nds/data_maintenance/LF_SS.sql; TPC-DS spec refresh function LF_SS).
-- Dialect notes vs the reference: staging dates/times are engine-typed
-- (DATE epoch days / integer seconds), so the cast()/substr() hops are
-- unnecessary, and the *_rec_end_date IS NULL current-record filters
-- are expressed as CTEs over the SCD dimensions.
DROP VIEW IF EXISTS ssv;
CREATE TEMP VIEW ssv AS
WITH cur_item AS (SELECT * FROM item WHERE i_rec_end_date IS NULL),
     cur_store AS (SELECT * FROM store WHERE s_rec_end_date IS NULL)
SELECT d_date_sk ss_sold_date_sk,
 t_time_sk ss_sold_time_sk,
 i_item_sk ss_item_sk,
 c_customer_sk ss_customer_sk,
 c_current_cdemo_sk ss_cdemo_sk,
 c_current_hdemo_sk ss_hdemo_sk,
 c_current_addr_sk ss_addr_sk,
 s_store_sk ss_store_sk,
 p_promo_sk ss_promo_sk,
 purc_purchase_id ss_ticket_number,
 plin_quantity ss_quantity,
 i_wholesale_cost ss_wholesale_cost,
 i_current_price ss_list_price,
 plin_sale_price ss_sales_price,
 (i_current_price - plin_sale_price) * plin_quantity ss_ext_discount_amt,
 plin_sale_price * plin_quantity ss_ext_sales_price,
 i_wholesale_cost * plin_quantity ss_ext_wholesale_cost,
 i_current_price * plin_quantity ss_ext_list_price,
 i_current_price * s_tax_precentage ss_ext_tax,
 plin_coupon_amt ss_coupon_amt,
 (plin_sale_price * plin_quantity) - plin_coupon_amt ss_net_paid,
 ((plin_sale_price * plin_quantity) - plin_coupon_amt) * (1 + s_tax_precentage) ss_net_paid_inc_tax,
 ((plin_sale_price * plin_quantity) - plin_coupon_amt) - (plin_quantity * i_wholesale_cost) ss_net_profit
FROM s_purchase
JOIN s_purchase_lineitem ON (purc_purchase_id = plin_purchase_id)
LEFT OUTER JOIN customer ON (purc_customer_id = c_customer_id)
LEFT OUTER JOIN cur_store ON (purc_store_id = s_store_id)
LEFT OUTER JOIN date_dim ON (purc_purchase_date = d_date)
LEFT OUTER JOIN time_dim ON (purc_purchase_time = t_time)
LEFT OUTER JOIN promotion ON (plin_promotion_id = p_promo_id)
LEFT OUTER JOIN cur_item ON (plin_item_id = i_item_id);
INSERT INTO store_sales (SELECT * FROM ssv ORDER BY ss_sold_date_sk);
DROP VIEW ssv;
