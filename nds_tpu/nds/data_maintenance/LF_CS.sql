-- LF_CS: catalog_sales refresh insert (role of the reference's
-- nds/data_maintenance/LF_CS.sql; spec refresh function LF_CS). Same
-- dialect notes as LF_SS.sql.
DROP VIEW IF EXISTS csv;
CREATE TEMP VIEW csv AS
WITH cur_item AS (SELECT * FROM item WHERE i_rec_end_date IS NULL),
     cur_cc AS (SELECT * FROM call_center WHERE cc_rec_end_date IS NULL)
SELECT d1.d_date_sk cs_sold_date_sk,
 t_time_sk cs_sold_time_sk,
 d2.d_date_sk cs_ship_date_sk,
 c1.c_customer_sk cs_bill_customer_sk,
 c1.c_current_cdemo_sk cs_bill_cdemo_sk,
 c1.c_current_hdemo_sk cs_bill_hdemo_sk,
 c1.c_current_addr_sk cs_bill_addr_sk,
 c2.c_customer_sk cs_ship_customer_sk,
 c2.c_current_cdemo_sk cs_ship_cdemo_sk,
 c2.c_current_hdemo_sk cs_ship_hdemo_sk,
 c2.c_current_addr_sk cs_ship_addr_sk,
 cc_call_center_sk cs_call_center_sk,
 cp_catalog_page_sk cs_catalog_page_sk,
 sm_ship_mode_sk cs_ship_mode_sk,
 w_warehouse_sk cs_warehouse_sk,
 i_item_sk cs_item_sk,
 p_promo_sk cs_promo_sk,
 cord_order_id cs_order_number,
 clin_quantity cs_quantity,
 i_wholesale_cost cs_wholesale_cost,
 i_current_price cs_list_price,
 clin_sales_price cs_sales_price,
 (i_current_price - clin_sales_price) * clin_quantity cs_ext_discount_amt,
 clin_sales_price * clin_quantity cs_ext_sales_price,
 i_wholesale_cost * clin_quantity cs_ext_wholesale_cost,
 i_current_price * clin_quantity cs_ext_list_price,
 i_current_price * cc_tax_percentage cs_ext_tax,
 clin_coupon_amt cs_coupon_amt,
 clin_ship_cost * clin_quantity cs_ext_ship_cost,
 (clin_sales_price * clin_quantity) - clin_coupon_amt cs_net_paid,
 ((clin_sales_price * clin_quantity) - clin_coupon_amt) * (1 + cc_tax_percentage) cs_net_paid_inc_tax,
 (clin_sales_price * clin_quantity) - clin_coupon_amt + (clin_ship_cost * clin_quantity) cs_net_paid_inc_ship,
 (clin_sales_price * clin_quantity) - clin_coupon_amt + (clin_ship_cost * clin_quantity)
  + i_current_price * cc_tax_percentage cs_net_paid_inc_ship_tax,
 ((clin_sales_price * clin_quantity) - clin_coupon_amt) - (clin_quantity * i_wholesale_cost) cs_net_profit
FROM s_catalog_order
JOIN s_catalog_order_lineitem ON (cord_order_id = clin_order_id)
LEFT OUTER JOIN date_dim d1 ON (cord_order_date = d1.d_date)
LEFT OUTER JOIN time_dim ON (cord_order_time = t_time)
LEFT OUTER JOIN customer c1 ON (cord_bill_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2 ON (cord_ship_customer_id = c2.c_customer_id)
LEFT OUTER JOIN cur_cc ON (cord_call_center_id = cc_call_center_id)
LEFT OUTER JOIN ship_mode ON (cord_ship_mode_id = sm_ship_mode_id)
LEFT OUTER JOIN date_dim d2 ON (clin_ship_date = d2.d_date)
LEFT OUTER JOIN catalog_page ON (clin_catalog_page_number = cp_catalog_page_number
  AND clin_catalog_number = cp_catalog_number)
LEFT OUTER JOIN warehouse ON (clin_warehouse_id = w_warehouse_id)
LEFT OUTER JOIN cur_item ON (clin_item_id = i_item_id)
LEFT OUTER JOIN promotion ON (clin_promotion_id = p_promo_id);
INSERT INTO catalog_sales (SELECT * FROM csv ORDER BY cs_sold_date_sk);
DROP VIEW csv;
