-- LF_WR: web_returns refresh insert (role of the reference's
-- nds/data_maintenance/LF_WR.sql; spec refresh function LF_WR). Same
-- dialect notes as LF_SS.sql.
DROP VIEW IF EXISTS wrv;
CREATE TEMP VIEW wrv AS
WITH cur_item AS (SELECT * FROM item WHERE i_rec_end_date IS NULL),
     cur_wp AS (SELECT * FROM web_page WHERE wp_rec_end_date IS NULL)
SELECT d_date_sk wr_returned_date_sk,
 t_time_sk wr_returned_time_sk,
 i_item_sk wr_item_sk,
 c1.c_customer_sk wr_refunded_customer_sk,
 c1.c_current_cdemo_sk wr_refunded_cdemo_sk,
 c1.c_current_hdemo_sk wr_refunded_hdemo_sk,
 c1.c_current_addr_sk wr_refunded_addr_sk,
 c2.c_customer_sk wr_returning_customer_sk,
 c2.c_current_cdemo_sk wr_returning_cdemo_sk,
 c2.c_current_hdemo_sk wr_returning_hdemo_sk,
 c2.c_current_addr_sk wr_returning_addr_sk,
 wp_web_page_sk wr_web_page_sk,
 r_reason_sk wr_reason_sk,
 wret_order_id wr_order_number,
 wret_return_qty wr_return_quantity,
 wret_return_amt wr_return_amt,
 wret_return_tax wr_return_tax,
 wret_return_amt + wret_return_tax wr_return_amt_inc_tax,
 wret_return_fee wr_fee,
 wret_return_ship_cost wr_return_ship_cost,
 wret_refunded_cash wr_refunded_cash,
 wret_reversed_charge wr_reversed_charge,
 wret_account_credit wr_account_credit,
 wret_return_amt + wret_return_tax + wret_return_fee
  - wret_refunded_cash - wret_reversed_charge - wret_account_credit wr_net_loss
FROM s_web_returns
LEFT OUTER JOIN date_dim ON (wret_return_date = d_date)
LEFT OUTER JOIN time_dim ON (wret_return_time = t_time)
LEFT OUTER JOIN cur_item ON (wret_item_id = i_item_id)
LEFT OUTER JOIN customer c1 ON (wret_refund_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2 ON (wret_return_customer_id = c2.c_customer_id)
LEFT OUTER JOIN reason ON (wret_reason_id = r_reason_id)
LEFT OUTER JOIN cur_wp ON (wret_web_page_id = wp_web_page_id);
INSERT INTO web_returns (SELECT * FROM wrv ORDER BY wr_returned_date_sk);
DROP VIEW wrv;
