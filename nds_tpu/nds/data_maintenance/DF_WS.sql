-- DF_WS: web channel delete (role of the reference's
-- nds/data_maintenance/DF_WS.sql; spec refresh function DF_WS).
DELETE FROM web_returns WHERE wr_order_number IN
  (SELECT DISTINCT ws_order_number FROM web_sales, date_dim
   WHERE ws_sold_date_sk = d_date_sk AND d_date BETWEEN 'DATE1' AND 'DATE2');
DELETE FROM web_sales
 WHERE ws_sold_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                           WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
   AND ws_sold_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                           WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
