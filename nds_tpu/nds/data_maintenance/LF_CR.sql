-- LF_CR: catalog_returns refresh insert (role of the reference's
-- nds/data_maintenance/LF_CR.sql; spec refresh function LF_CR). Same
-- dialect notes as LF_SS.sql.
DROP VIEW IF EXISTS crv;
CREATE TEMP VIEW crv AS
WITH cur_item AS (SELECT * FROM item WHERE i_rec_end_date IS NULL),
     cur_cc AS (SELECT * FROM call_center WHERE cc_rec_end_date IS NULL)
SELECT d_date_sk cr_returned_date_sk,
 t_time_sk cr_returned_time_sk,
 i_item_sk cr_item_sk,
 c1.c_customer_sk cr_refunded_customer_sk,
 c1.c_current_cdemo_sk cr_refunded_cdemo_sk,
 c1.c_current_hdemo_sk cr_refunded_hdemo_sk,
 c1.c_current_addr_sk cr_refunded_addr_sk,
 c2.c_customer_sk cr_returning_customer_sk,
 c2.c_current_cdemo_sk cr_returning_cdemo_sk,
 c2.c_current_hdemo_sk cr_returning_hdemo_sk,
 c2.c_current_addr_sk cr_returning_addr_sk,
 cc_call_center_sk cr_call_center_sk,
 cp_catalog_page_sk cr_catalog_page_sk,
 sm_ship_mode_sk cr_ship_mode_sk,
 w_warehouse_sk cr_warehouse_sk,
 r_reason_sk cr_reason_sk,
 cret_order_id cr_order_number,
 cret_return_qty cr_return_quantity,
 cret_return_amt cr_return_amount,
 cret_return_tax cr_return_tax,
 cret_return_amt + cret_return_tax cr_return_amt_inc_tax,
 cret_return_fee cr_fee,
 cret_return_ship_cost cr_return_ship_cost,
 cret_refunded_cash cr_refunded_cash,
 cret_reversed_charge cr_reversed_charge,
 cret_merchant_credit cr_store_credit,
 cret_return_amt + cret_return_tax + cret_return_fee
  - cret_refunded_cash - cret_reversed_charge - cret_merchant_credit cr_net_loss
FROM s_catalog_returns
LEFT OUTER JOIN date_dim ON (cret_return_date = d_date)
LEFT OUTER JOIN time_dim ON (cret_return_time = t_time)
LEFT OUTER JOIN cur_item ON (cret_item_id = i_item_id)
LEFT OUTER JOIN customer c1 ON (cret_refund_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2 ON (cret_return_customer_id = c2.c_customer_id)
LEFT OUTER JOIN reason ON (cret_reason_id = r_reason_id)
LEFT OUTER JOIN cur_cc ON (cret_call_center_id = cc_call_center_id)
LEFT OUTER JOIN catalog_page ON (cret_catalog_page_id = cp_catalog_page_id)
LEFT OUTER JOIN ship_mode ON (cret_shipmode_id = sm_ship_mode_id)
LEFT OUTER JOIN warehouse ON (cret_warehouse_id = w_warehouse_id);
INSERT INTO catalog_returns (SELECT * FROM crv ORDER BY cr_returned_date_sk);
DROP VIEW crv;
