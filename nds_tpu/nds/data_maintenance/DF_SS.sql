-- DF_SS: store channel delete (role of the reference's
-- nds/data_maintenance/DF_SS.sql; spec refresh function DF_SS). DATE1
-- and DATE2 are substituted from the generated delete table
-- (`nds/nds_maintenance.py:75-96`).
DELETE FROM store_returns WHERE sr_ticket_number IN
  (SELECT DISTINCT ss_ticket_number FROM store_sales, date_dim
   WHERE ss_sold_date_sk = d_date_sk AND d_date BETWEEN 'DATE1' AND 'DATE2');
DELETE FROM store_sales
 WHERE ss_sold_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                           WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
   AND ss_sold_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                           WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
