-- DF_CS: catalog channel delete (role of the reference's
-- nds/data_maintenance/DF_CS.sql; spec refresh function DF_CS).
DELETE FROM catalog_returns WHERE cr_order_number IN
  (SELECT DISTINCT cs_order_number FROM catalog_sales, date_dim
   WHERE cs_sold_date_sk = d_date_sk AND d_date BETWEEN 'DATE1' AND 'DATE2');
DELETE FROM catalog_sales
 WHERE cs_sold_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                           WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
   AND cs_sold_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                           WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
