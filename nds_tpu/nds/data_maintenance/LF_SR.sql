-- LF_SR: store_returns refresh insert (role of the reference's
-- nds/data_maintenance/LF_SR.sql; spec refresh function LF_SR). Same
-- dialect notes as LF_SS.sql.
DROP VIEW IF EXISTS srv;
CREATE TEMP VIEW srv AS
WITH cur_item AS (SELECT * FROM item WHERE i_rec_end_date IS NULL),
     cur_store AS (SELECT * FROM store WHERE s_rec_end_date IS NULL)
SELECT d_date_sk sr_returned_date_sk,
 t_time_sk sr_return_time_sk,
 i_item_sk sr_item_sk,
 c_customer_sk sr_customer_sk,
 c_current_cdemo_sk sr_cdemo_sk,
 c_current_hdemo_sk sr_hdemo_sk,
 c_current_addr_sk sr_addr_sk,
 s_store_sk sr_store_sk,
 r_reason_sk sr_reason_sk,
 sret_ticket_number sr_ticket_number,
 sret_return_qty sr_return_quantity,
 sret_return_amt sr_return_amt,
 sret_return_tax sr_return_tax,
 sret_return_amt + sret_return_tax sr_return_amt_inc_tax,
 sret_return_fee sr_fee,
 sret_return_ship_cost sr_return_ship_cost,
 sret_refunded_cash sr_refunded_cash,
 sret_reversed_charge sr_reversed_charge,
 sret_store_credit sr_store_credit,
 sret_return_amt + sret_return_tax + sret_return_fee
  - sret_refunded_cash - sret_reversed_charge - sret_store_credit sr_net_loss
FROM s_store_returns
LEFT OUTER JOIN date_dim ON (sret_return_date = d_date)
LEFT OUTER JOIN time_dim ON (sret_return_time = t_time)
LEFT OUTER JOIN cur_item ON (sret_item_id = i_item_id)
LEFT OUTER JOIN customer ON (sret_customer_id = c_customer_id)
LEFT OUTER JOIN cur_store ON (sret_store_id = s_store_id)
LEFT OUTER JOIN reason ON (sret_reason_id = r_reason_id);
INSERT INTO store_returns (SELECT * FROM srv ORDER BY sr_returned_date_sk);
DROP VIEW srv;
