"""NDS Throughput Run: N concurrent 99-query streams.

The reference does this with xargs -P spawning one spark-submit per
stream (`nds/nds-throughput:23`). Here each stream is one subprocess
running the NDS power driver (process isolation keeps per-stream XLA
compile caches and HBM pools independent — the analog of per-stream
Spark apps); throughput elapse is max(end) - min(start) rounded up to
0.1 s (`nds/nds_bench.py:138-157,207-208`).

Subprocess streams run SUPERVISED (resilience/supervise.py): each
child publishes heartbeats through its per-stream metrics-snapshot
file, a hung stream is killed (child watchdog self-exit, parent
SIGTERM→SIGKILL backstop) once ``--stall_s`` is set, a dead stream
restarts at most once from its last completed query, and exit codes /
signals / stalls / restarts land in ``throughput_summary.json``
instead of a bare failure count.
"""

from __future__ import annotations

import argparse
import math
import os
import sys
import time


def _stream_specs(data_dir: str, stream_paths: list[str], out_dir: str,
                  backend: str, input_format: str,
                  allow_failure: bool, module: str, parse_stream):
    """Supervised-stream specs for a power-driver fleet (shared with
    NDS-H, which passes its own module + stream parser)."""
    from nds_tpu.obs.snapshot import SNAP_ENV, parse_spec
    from nds_tpu.obs.trace import TRACE_ENV
    from nds_tpu.resilience.supervise import StreamSpec
    from nds_tpu.utils.power_core import subprocess_env
    specs = []
    for sp in stream_paths:
        name = os.path.splitext(os.path.basename(sp))[0]
        env = subprocess_env(backend)
        hb = os.path.join(out_dir, f"{name}_hb.json")
        if env.get(TRACE_ENV):
            # one trace shard PER STREAM: N children appending to one
            # JSONL interleave partial lines under buffered writes.
            # Each child also pins its export pid to the stream index
            # (obs/fleet.py reads NDS_TPU_STREAM), so the merged
            # timeline's lanes are deterministic across runs
            troot, text = os.path.splitext(env[TRACE_ENV])
            env[TRACE_ENV] = f"{troot}_{name}{text or '.jsonl'}"
        if env.get(SNAP_ENV):
            # one snapshot file PER STREAM: N subprocesses inheriting
            # the same path would race on it (and on its .tmp),
            # exactly what the atomic-write contract forbids. The
            # re-pointed file doubles as the supervisor's heartbeat
            # source
            path, interval = parse_spec(env[SNAP_ENV])
            root, ext = os.path.splitext(path)
            hb = f"{root}_{name}{ext or '.json'}"
            env[SNAP_ENV] = f"{hb}:{interval}"

        def make_cmd(incarnation, remaining, _sp=sp, _name=name):
            suffix = "" if incarnation == 0 else f"_r{incarnation}"
            tlog = os.path.join(out_dir, f"{_name}{suffix}_time.csv")
            cmd = [sys.executable, "-m", module,
                   data_dir, _sp, tlog, "--backend", backend,
                   "--input_format", input_format]
            if allow_failure:
                cmd.append("--allow_failure")
            if remaining:
                cmd += ["--query_subset", *remaining]
            return cmd

        specs.append(StreamSpec(
            name=name, make_cmd=make_cmd, hb_path=hb,
            queries=list(parse_stream(sp)), env=env))
    return specs


def run_streams(data_dir: str, stream_paths: list[str], out_dir: str,
                backend: str = "tpu",
                input_format: str = "parquet",
                allow_failure: bool = False,
                stall_s: float | None = None,
                max_restarts: int | None = None
                ) -> tuple[float, list[int]]:
    """Launch one supervised power-run subprocess per stream; returns
    (throughput_elapse_seconds, per-stream final exit codes). With
    ``stall_s`` set, hung streams are killed and restarted (up to
    ``max_restarts`` times, default once) from their last completed
    query; ``throughput_summary.json`` in ``out_dir`` records the
    supervision verdicts either way — including the exact queries a
    degraded stream skipped."""
    from nds_tpu.nds.streams import parse_query_stream
    from nds_tpu.resilience.supervise import (
        StreamSupervisor, describe_summary,
    )
    os.makedirs(out_dir, exist_ok=True)
    specs = _stream_specs(data_dir, stream_paths, out_dir, backend,
                          input_format, allow_failure,
                          "nds_tpu.nds.power", parse_query_stream)
    # restarts need the heartbeat plumbing stall_s arms: without it a
    # completed-with-failures stream (exit 1, no snapshot) would be
    # indistinguishable from a crash and get re-run
    if max_restarts is None:
        max_restarts = 1 if stall_s else 0
    sup = StreamSupervisor(specs, out_dir, stall_s=stall_s,
                           max_restarts=max_restarts)
    elapse, codes, summary = sup.run()
    print(describe_summary(summary))
    # round up to 0.1 s, the reference's Ttt granularity
    elapse = math.ceil(elapse * 10) / 10.0
    return elapse, codes


def run_streams_inprocess(data_dir: str, stream_paths: list[str],
                          out_dir: str, backend: str = "tpu",
                          input_format: str = "parquet",
                          ) -> tuple[float, list[int]]:
    """Single-process multi-stream throughput for ONE-chip runs.

    The reference splits cluster executors between concurrent streams
    (`nds/README.md:530-535`); N subprocesses each opening the same
    single TPU chip would instead contend for (or fail to share) HBM.
    This mode time-shares the chip: the warehouse loads ONCE, one
    Session serves every stream (shared device buffers + compile cache
    — streams differ in parameter bindings, so each still compiles its
    own programs), and queries interleave round-robin so all streams
    progress together the way the xargs -P fan-out does. Per-stream time
    logs keep the reference format. Returns (elapse_s, failure counts).

    ``NDS_TPU_METRICS_SNAP`` is honored here too: this mode never
    enters ``run_query_stream`` (it drives ``session.sql_async``
    directly), so it owns its own snapshot emitter."""
    from nds_tpu.obs.snapshot import MetricsSnapshotter
    progress = {"mode": "throughput-inprocess",
                "streams": len(stream_paths),
                "queries_completed": 0, "current_query": None}
    snap = MetricsSnapshotter.from_env(progress)
    if snap:
        snap.start()
    try:
        return _run_streams_inprocess(data_dir, stream_paths, out_dir,
                                      backend, input_format, progress)
    finally:
        if snap:
            progress["current_query"] = None
            snap.stop()


def _run_streams_inprocess(data_dir, stream_paths, out_dir, backend,
                           input_format, progress
                           ) -> tuple[float, list[int]]:
    from nds_tpu.nds.power import SUITE
    from nds_tpu.resilience import faults
    from nds_tpu.resilience.journal import QueryJournal, config_digest
    from nds_tpu.resilience.retry import (
        TRANSIENT, RetryPolicy, RetryStats, classify,
    )
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    from nds_tpu.utils.report import BenchReport
    from nds_tpu.utils.timelog import TimeLog

    os.makedirs(out_dir, exist_ok=True)
    # clock starts BEFORE the warehouse load: subprocess mode's window
    # (max(end) - min(start)) includes each stream's load, and the Ttt
    # terms must be measured under the same rule in both modes
    start = time.time()
    config = EngineConfig(overrides={"engine.backend": backend})
    policy = RetryPolicy.from_config(config)
    session = power_core.make_session(SUITE, config)
    pipeline = session._executor_factory(session.tables)
    power_core.load_warehouse(
        SUITE, session, data_dir, input_format,
        schemas=power_core.suite_schemas(SUITE, config))
    streams = []
    for sp in stream_paths:
        name = os.path.splitext(os.path.basename(sp))[0]
        # per-stream query journal (resilience/journal.py): every
        # completed statement lands on disk as it finishes, so an
        # interrupted round leaves a per-stream completion record with
        # result digests, not just whatever stdout survived
        qj = QueryJournal(
            os.path.join(out_dir, f"{name}_queries.json"), phase=name,
            digest=config_digest(config.as_dict()))
        qj.reset()
        streams.append({
            "name": name,
            "queries": list(SUITE.parse_query_stream(sp).items()),
            "tlog": TimeLog(f"nds-tpu-throughput-{name}"),
            "failures": 0,
            # per-stream BenchReport material: statuses/exception text
            # per query, so throughput failures are diagnosable from
            # the report JSON (the power path's `exceptions` contract)
            "statuses": [],
            "exceptions": [],
            "qtimes": [],
            "retries": 0,
            "reschedules": 0,
            "journal": qj,
        })
    # flatten round-robin, then run with `engine.concurrent_tasks`
    # queries in flight: dispatch is async on the device engine
    # (Session.sql_async), so device execution of query N+1 overlaps
    # host materialization of query N — the wired-up analog of
    # spark.rapids.sql.concurrentGpuTasks (`nds/power_run_gpu.template:38`)
    interleaved = []
    for k in range(max(len(s["queries"]) for s in streams)):
        for s in streams:
            if k < len(s["queries"]):
                interleaved.append((s, *s["queries"][k]))
    depth = max(config.get_int("engine.concurrent_tasks", 2), 1)
    inflight: list = []

    def _finish_one():
        s, qname, sql, t0, handle, err = inflight.pop(0)
        res = None
        if err is None:
            try:
                # retry + the degradation ladder run INSIDE the
                # pipeline (engine/scheduler.py): a transient failure
                # surfaces here at result() and reruns down the ladder
                # on this blocked call, so the stream keeps its
                # pipelining for the healthy queries and pays the
                # recovery only on the sick one
                with faults.context(query=qname, stream=s["name"]):
                    res = handle.result()
            except Exception as exc:  # noqa: BLE001
                err = exc
        # per-query recovery accounting comes from the pipeline's
        # handle-local stats (re-pointed at result() even under
        # interleaved dispatch); a dispatch-time failure (handle None:
        # parse/plan or a deterministic classify) never dispatched, so
        # it has nothing to read
        if handle is not None:
            st = getattr(pipeline, "last_stats", None)
            sched = getattr(pipeline, "last_schedule", None) or {}
            if st is not None:
                s["retries"] += st.retries
            if sched.get("reschedules"):
                s["reschedules"] += sched["reschedules"]
        if err is not None:
            import traceback
            traceback.print_exception(type(err), err, err.__traceback__)
            s["failures"] += 1
            # exception text into the stream's report summary: a
            # throughput failure used to be a bare count, invisible in
            # the report JSON
            s["exceptions"].append(
                f"{qname}: {type(err).__name__}: {err}")
            s["statuses"].append("Failed")
        else:
            s["statuses"].append("Completed")
        done = time.time()
        progress["queries_completed"] += 1
        # dispatch->result bracket; queue wait from pipelining is
        # inherent to a time-shared chip, exactly as a query inside a
        # reference throughput stream waits on cluster resources
        wall_ms = int((done - t0) * 1000)
        s["tlog"].add(qname, wall_ms)
        s["qtimes"].append(wall_ms)
        s["first_t0"] = min(s.get("first_t0", t0), t0)
        s["last_done"] = done
        # journal the completion (status + wall + result digest): the
        # same per-statement durability contract as the power loop
        from nds_tpu.io.result_io import result_digest
        s["journal"].record(qname, wall_ms, s["statuses"][-1],
                            result_digest=result_digest(res))

    from nds_tpu.resilience import watchdog
    for s, qname, sql in interleaved:
        progress["current_query"] = f"{s['name']}/{qname}"
        # heartbeat per dispatch: the in-process fleet shows liveness
        # to any armed watchdog exactly like a subprocess stream does
        watchdog.beat(s["name"], query=qname, phase="dispatch")
        s["journal"].start(qname)
        t0 = time.time()
        handle, err = None, None
        try:
            # the stream.query chaos site fires inside the pipeline's
            # per-attempt dispatch (engine/scheduler.py), under this
            # query/stream context
            with faults.context(query=qname, stream=s["name"]):
                handle = session.sql_async(sql)
        except Exception as exc:  # noqa: BLE001
            err = exc
            if classify(exc) == TRANSIENT and policy.max_attempts > 1:
                # a dispatch-time transient never reached the pipeline
                # (parse/plan window): re-run synchronously under the
                # remaining budget, same contract as the power path's
                # front-door retry
                st = RetryStats()
                from nds_tpu.obs import metrics as obs_metrics
                obs_metrics.counter("query_retries_total").inc()
                s["retries"] += 1
                rerun = policy.with_attempts(policy.max_attempts - 1)
                try:
                    with faults.context(query=qname, stream=s["name"]):
                        rerun.call(session.sql, sql, stats=st)
                    err = None
                except Exception as exc2:  # noqa: BLE001
                    err = exc2
                s["retries"] += st.retries
                # the rerun went through the pipeline: its internal
                # retries/reschedules belong to this query too (the
                # handle-None guard in _finish_one will skip them)
                st2 = getattr(pipeline, "last_stats", None)
                sched2 = getattr(pipeline, "last_schedule", None) or {}
                if st2 is not None:
                    s["retries"] += st2.retries
                if sched2.get("reschedules"):
                    s["reschedules"] += sched2["reschedules"]
        inflight.append((s, qname, sql, t0, handle, err))
        while len(inflight) >= depth:
            _finish_one()
    while inflight:
        _finish_one()
    for s in streams:
        # per-stream Power Test Time is the stream's WALL window (first
        # dispatch -> last result), not the sum of per-query brackets:
        # pipelined queries overlap, and a sum would double-count
        ptt = int((s.get("last_done", start) -
                   s.get("first_t0", start)) * 1000)
        s["tlog"].add("Power Test Time", ptt)
        s["tlog"].write(os.path.join(out_dir, f"{s['name']}_time.csv"))
        # one BenchReport JSON per stream (reference summary shape, one
        # entry per query): failures carry their exception text, the
        # resilience fields record recovery work
        rep = BenchReport(s["name"], config.as_dict())
        rep.capture_env()
        rep.summary["startTime"] = int(start * 1000)
        rep.summary["queryStatus"] = s["statuses"]
        rep.summary["exceptions"] = s["exceptions"]
        rep.summary["queryTimes"] = s["qtimes"]
        rep.summary["retries"] = s["retries"]
        if s["reschedules"]:
            rep.summary["reschedules"] = s["reschedules"]
        rep.write_summary(prefix="throughput", out_dir=out_dir)
    elapse = math.ceil((time.time() - start) * 10) / 10.0
    return elapse, [s["failures"] for s in streams]


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="NDS throughput run")
    p.add_argument("data_dir")
    p.add_argument("streams", nargs="+", help="query_N.sql stream files")
    p.add_argument("--out_dir", default="throughput_logs")
    p.add_argument("--backend", choices=["tpu", "cpu", "distributed"],
                   default="tpu")
    p.add_argument("--input_format", choices=["parquet", "raw"],
                   default="parquet")
    p.add_argument("--allow_failure", action="store_true")
    p.add_argument("--in_process", action="store_true",
                   help="time-share one device inside a single process "
                        "(required when all streams target one TPU chip)")
    p.add_argument("--stall_s", type=float, default=None,
                   help="supervise subprocess streams: kill a stream "
                        "whose heartbeats stall past this budget and "
                        "restart it from its last completed query "
                        "(README Resilience)")
    p.add_argument("--max_restarts", type=int, default=None,
                   help="restart budget per supervised stream (default "
                        "1 when --stall_s is set; graceful-drain exits "
                        "75 resume without charging it)")
    args = p.parse_args(argv)
    if args.in_process:
        elapse, codes = run_streams_inprocess(
            args.data_dir, args.streams, args.out_dir, args.backend,
            args.input_format)
    else:
        elapse, codes = run_streams(args.data_dir, args.streams,
                                    args.out_dir, args.backend,
                                    args.input_format,
                                    args.allow_failure,
                                    stall_s=args.stall_s,
                                    max_restarts=args.max_restarts)
    print(f"Throughput Time: {elapse} s over {len(args.streams)} streams")
    sys.exit(1 if any(codes) and not args.allow_failure else 0)


if __name__ == "__main__":
    main()
