"""NDS rollback driver.

Behavioral port of `nds/nds_rollback.py:46-51`: undo data-maintenance
mutations by rolling the warehouse's fact tables back to a timestamp —
there via Iceberg ``rollback_to_timestamp``, here by truncating the
snapshot manifest (`nds_tpu/io/snapshots.py`); files written by undone
versions stay on disk but drop out of the live file map.
"""

from __future__ import annotations

import argparse

from nds_tpu.io.snapshots import SnapshotLog
from nds_tpu.nds.maintenance import MUTABLE_TABLES

tables_to_rollback = MUTABLE_TABLES


def rollback(warehouse_dir: str, timestamp: float) -> None:
    log = SnapshotLog(warehouse_dir)
    before = log.entries[-1]["version"] if log.entries else None
    after = log.rollback_to_timestamp(timestamp)
    print(f"rolled back {warehouse_dir}: v{before} -> "
          f"{'baseline' if after is None else f'v{after}'}")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="roll the warehouse back to a timestamp")
    p.add_argument("warehouse_dir")
    p.add_argument("--timestamp", type=float, default=None,
                   help="unix seconds; default: before every commit "
                        "(baseline)")
    args = p.parse_args(argv)
    if args.timestamp is None:
        print("no --timestamp given: rolling back to the baseline")
    rollback(args.warehouse_dir,
             args.timestamp if args.timestamp is not None else 0.0)


if __name__ == "__main__":
    main()
