"""NDS Load Test: raw '|'-delimited text -> columnar Parquet warehouse.

Behavioral port of `nds/nds_transcode.py:154-229`: per-table transcode
timing, the fact-table date partition map (`TABLE_PARTITIONING:45-53`),
``--update`` switching to the refresh/maintenance schemas (`:170-176`),
a plain-text report with per-table seconds + Total time, and the
load-end timestamp the orchestrator reads back as the stream RNGSEED
(`nds/nds_transcode.py:210-216` -> `nds/nds_bench.py:60-74`).

TPU-native: partitioned facts write one parquet file per distinct
partition key value under ``<table>/<part_col>=<val>/`` (hive-style —
the layout multi-host loaders shard by), instead of a Spark
repartition+sortWithinPartitions shuffle; dictionary-encoded strings are
re-sorted on read (`nds_tpu/io/csv_io.py`).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

from nds_tpu.io import csv_io
from nds_tpu.nds.schema import get_maintenance_schemas, get_schemas

# fact date-partition columns (`nds/nds_transcode.py:45-53`)
TABLE_PARTITIONING = {
    "catalog_sales": "cs_sold_date_sk",
    "catalog_returns": "cr_returned_date_sk",
    "inventory": "inv_date_sk",
    "store_sales": "ss_sold_date_sk",
    "store_returns": "sr_returned_date_sk",
    "web_sales": "ws_sold_date_sk",
    "web_returns": "wr_returned_date_sk",
}


def _raw_paths(input_dir: str, name: str) -> list[str]:
    from nds_tpu.io.integrity import MANIFEST_NAME
    tdir = os.path.join(input_dir, name)
    if os.path.isdir(tdir):
        return sorted(os.path.join(tdir, f) for f in os.listdir(tdir)
                      if not f.startswith(".") and f != MANIFEST_NAME)
    return [os.path.join(input_dir, f"{name}.dat")]


def transcode_table(name, schema, input_dir: str, output_dir: str,
                    compression: str = "snappy",
                    partition: bool = True,
                    output_format: str = "parquet") -> float:
    t0 = time.perf_counter()
    table = csv_io.read_tbl(_raw_paths(input_dir, name), name, schema)
    ext = csv_io.FORMAT_EXT[output_format]
    part_col = TABLE_PARTITIONING.get(name) if partition else None
    if part_col and table.nrows:
        col = table.column(part_col)
        vals = col.values
        valid = (col.null_mask if col.null_mask is not None
                 else np.ones(len(vals), dtype=bool))
        arrow = csv_io.to_arrow(table)
        # coarse month buckets: one file per ~30-day band keeps file
        # counts sane while preserving partition-prunable layout
        band = np.where(valid, vals // 30, -1)
        for b in np.unique(band):
            sel = np.nonzero(band == b)[0]
            sub = arrow.take(sel)
            label = "__HIVE_DEFAULT_PARTITION__" if b < 0 else str(
                int(b) * 30)
            out = os.path.join(output_dir, name, f"{part_col}={label}",
                               f"part-0{ext}")
            if output_format == "avro":
                # avro writes from the engine schema (write_table), not
                # from a bare arrow table
                csv_io.write_table(
                    csv_io.from_arrow(name, table.schema, sub), out,
                    "avro", compression)
            else:
                csv_io.write_arrow(sub, out, output_format, compression)
    else:
        out = os.path.join(output_dir, name, f"part-0{ext}")
        csv_io.write_table(table, out, output_format,
                           compression=compression)
    # per-table digest manifest: loads can verify every chunk they read
    # back (io/integrity.py; README "Resilience")
    from nds_tpu.io import integrity
    integrity.write_manifest(os.path.join(output_dir, name))
    return time.perf_counter() - t0


def transcode(input_dir: str, output_dir: str, report_path: str,
              tables: list[str] | None = None,
              compression: str = "snappy", update: bool = False,
              use_decimal: bool = True, partition: bool = True,
              output_format: str = "parquet",
              resume: bool = False) -> dict:
    schemas = (get_maintenance_schemas(use_decimal) if update
               else get_schemas(use_decimal))
    if tables:
        unknown = set(tables) - set(schemas)
        if unknown:
            raise ValueError(f"unknown tables: {sorted(unknown)}")
        schemas = {t: schemas[t] for t in tables}
    os.makedirs(output_dir, exist_ok=True)
    # options stamp: resuming under DIFFERENT transcode options would
    # silently keep tables built with the old schema/format (their
    # manifests still verify — they hash the old bytes) and yield a
    # mixed warehouse; refuse loudly, like the resume journals'
    # config-digest guard
    from nds_tpu.io import integrity
    opts = {"use_decimal": use_decimal, "compression": compression,
            "partition": partition, "output_format": output_format,
            "update": update}
    opts_path = os.path.join(output_dir, "_transcode_options.json")
    if resume and os.path.exists(opts_path):
        try:
            with open(opts_path) as f:
                prior = json.load(f)
        except (OSError, ValueError):
            prior = None
        if prior is not None and prior != opts:
            raise ValueError(
                f"--resume under different transcode options: "
                f"{opts_path} records {prior}, current run wants "
                f"{opts} — delete the warehouse (or drop --resume) "
                f"to rebuild consistently")
    integrity.write_json_atomic(opts_path, opts)
    timings = {}
    for name, schema in schemas.items():
        if resume:
            # preemption-safe resume: a table whose _manifest.json
            # digests all verify was FULLY transcoded by an earlier
            # incarnation (the manifest is written last, after every
            # data file) — re-transcoding it would burn the load-phase
            # budget re-doing finished work. A missing/torn manifest or
            # any mismatch re-transcodes from scratch.
            if integrity.verify_manifest(os.path.join(output_dir,
                                                      name)):
                timings[name] = 0.0
                print(f"Skipped table {name} (manifest verified, "
                      f"already transcoded)")
                continue
        timings[name] = transcode_table(
            name, schema, input_dir, output_dir, compression, partition,
            output_format)
        print(f"Time taken: {timings[name]:.3f} s for table {name}")
    load_end = int(time.time())
    report = ["Total conversion time for %d tables was %.3fs" % (
        len(timings), sum(timings.values()))]
    for name, secs in timings.items():
        report.append("Time to convert '%s' was %.4fs" % (name, secs))
    report.append("")
    # the stream-seed contract: RNGSEED = load end timestamp
    report.append(f"RNGSEED used: {load_end}")
    os.makedirs(os.path.dirname(report_path) or ".", exist_ok=True)
    with open(report_path, "w") as f:
        f.write("\n".join(report) + "\n")
    return timings


# anchored report parsing, shared with NDS-H (`nds/nds_bench.py:60-89`)
from nds_tpu.utils.loadreport import get_load_time, get_rngseed  # noqa: E402,F401


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="NDS load test: raw text -> Parquet warehouse")
    p.add_argument("input_dir", help="raw data directory (datagen output)")
    p.add_argument("output_dir", help="Parquet warehouse directory")
    p.add_argument("report_file", help="load-report text file")
    p.add_argument("--tables", nargs="+", help="subset of tables")
    p.add_argument("--update", action="store_true",
                   help="transcode refresh (maintenance) tables instead")
    p.add_argument("--floats", action="store_true",
                   help="double columns instead of decimals")
    p.add_argument("--no_partition", action="store_true",
                   help="disable fact date partitioning")
    p.add_argument("--compression", default="snappy")
    p.add_argument("--output_format", default="parquet",
                   choices=["parquet", "orc", "json", "avro"],
                   help="warehouse file format "
                        "(`nds/nds_transcode.py:69-152`; avro via the "
                        "built-in container codec, io/avro_io.py)")
    p.add_argument("--resume", action="store_true",
                   help="skip tables whose _manifest.json digests "
                        "already verify (an interrupted load resumes "
                        "table-granular; README 'Preemption & "
                        "resume')")
    args = p.parse_args(argv)
    transcode(args.input_dir, args.output_dir, args.report_file,
              args.tables, args.compression, update=args.update,
              use_decimal=not args.floats,
              partition=not args.no_partition,
              output_format=args.output_format, resume=args.resume)


if __name__ == "__main__":
    main()
