"""NDS (TPC-DS v3.2) table schemas, engine-typed.

Role of the reference's `nds/nds_schema.py:49-568` (25 source tables as
PySpark StructTypes with a use_decimal toggle, `:43-47`) re-expressed in
engine types: DECIMAL -> scaled int64 (or float64 in floats mode), DATE ->
epoch-day int32, CHAR/VARCHAR -> dictionary codes. Column names/types
follow the public TPC-DS specification (including the spec's own
`s_tax_precentage` spelling); surrogate keys are int32 except the two
documented 64-bit identifiers (ticket/order numbers — reference keeps
them LongType for SF3K+ overflow, `nds/nds_schema.py:328-331`).

PRIMARY_KEYS drive the planner's unique-side join orientation; SIZES are
the dsdgen row-count model used for greedy join ordering.
"""

from __future__ import annotations

from nds_tpu.engine.types import (
    DATE, INT32, INT64, Schema, char, decimal, varchar,
)


def _dec_factory(use_decimal: bool):
    """decimal(p,s) when use_decimal, float64 in the reference's
    --floats mode (`nds/nds_schema.py:43-47`) — one switch shared by the
    source and maintenance schemas."""
    if use_decimal:
        return decimal
    from nds_tpu.engine.types import FLOAT64
    return lambda p, s: FLOAT64


def get_schemas(use_decimal: bool = True) -> dict[str, Schema]:
    """25 source tables. use_decimal=False (the reference's --floats mode)
    swaps decimals for float64."""
    dec = _dec_factory(use_decimal)

    def money():
        return dec(7, 2)

    s: dict[str, Schema] = {}
    s["customer_address"] = Schema.of(
        ("ca_address_sk", INT32), ("ca_address_id", char(16)),
        ("ca_street_number", char(10)), ("ca_street_name", varchar(60)),
        ("ca_street_type", char(15)), ("ca_suite_number", char(10)),
        ("ca_city", varchar(60)), ("ca_county", varchar(30)),
        ("ca_state", char(2)), ("ca_zip", char(10)),
        ("ca_country", varchar(20)), ("ca_gmt_offset", dec(5, 2)),
        ("ca_location_type", char(20)))
    s["customer_demographics"] = Schema.of(
        ("cd_demo_sk", INT32), ("cd_gender", char(1)),
        ("cd_marital_status", char(1)),
        ("cd_education_status", char(20)),
        ("cd_purchase_estimate", INT32), ("cd_credit_rating", char(10)),
        ("cd_dep_count", INT32), ("cd_dep_employed_count", INT32),
        ("cd_dep_college_count", INT32))
    s["date_dim"] = Schema.of(
        ("d_date_sk", INT32), ("d_date_id", char(16)), ("d_date", DATE),
        ("d_month_seq", INT32), ("d_week_seq", INT32),
        ("d_quarter_seq", INT32), ("d_year", INT32), ("d_dow", INT32),
        ("d_moy", INT32), ("d_dom", INT32), ("d_qoy", INT32),
        ("d_fy_year", INT32), ("d_fy_quarter_seq", INT32),
        ("d_fy_week_seq", INT32), ("d_day_name", char(9)),
        ("d_quarter_name", char(6)), ("d_holiday", char(1)),
        ("d_weekend", char(1)), ("d_following_holiday", char(1)),
        ("d_first_dom", INT32), ("d_last_dom", INT32),
        ("d_same_day_ly", INT32), ("d_same_day_lq", INT32),
        ("d_current_day", char(1)), ("d_current_week", char(1)),
        ("d_current_month", char(1)), ("d_current_quarter", char(1)),
        ("d_current_year", char(1)))
    s["warehouse"] = Schema.of(
        ("w_warehouse_sk", INT32), ("w_warehouse_id", char(16)),
        ("w_warehouse_name", varchar(20)), ("w_warehouse_sq_ft", INT32),
        ("w_street_number", char(10)), ("w_street_name", varchar(60)),
        ("w_street_type", char(15)), ("w_suite_number", char(10)),
        ("w_city", varchar(60)), ("w_county", varchar(30)),
        ("w_state", char(2)), ("w_zip", char(10)),
        ("w_country", varchar(20)), ("w_gmt_offset", dec(5, 2)))
    s["ship_mode"] = Schema.of(
        ("sm_ship_mode_sk", INT32), ("sm_ship_mode_id", char(16)),
        ("sm_type", char(30)), ("sm_code", char(10)),
        ("sm_carrier", char(20)), ("sm_contract", char(20)))
    s["time_dim"] = Schema.of(
        ("t_time_sk", INT32), ("t_time_id", char(16)), ("t_time", INT32),
        ("t_hour", INT32), ("t_minute", INT32), ("t_second", INT32),
        ("t_am_pm", char(2)), ("t_shift", char(20)),
        ("t_sub_shift", char(20)), ("t_meal_time", char(20)))
    s["reason"] = Schema.of(
        ("r_reason_sk", INT32), ("r_reason_id", char(16)),
        ("r_reason_desc", char(100)))
    s["income_band"] = Schema.of(
        ("ib_income_band_sk", INT32), ("ib_lower_bound", INT32),
        ("ib_upper_bound", INT32))
    s["item"] = Schema.of(
        ("i_item_sk", INT32), ("i_item_id", char(16)),
        ("i_rec_start_date", DATE), ("i_rec_end_date", DATE),
        ("i_item_desc", varchar(200)), ("i_current_price", money()),
        ("i_wholesale_cost", money()), ("i_brand_id", INT32),
        ("i_brand", char(50)), ("i_class_id", INT32),
        ("i_class", char(50)), ("i_category_id", INT32),
        ("i_category", char(50)), ("i_manufact_id", INT32),
        ("i_manufact", char(50)), ("i_size", char(20)),
        ("i_formulation", char(20)), ("i_color", char(20)),
        ("i_units", char(10)), ("i_container", char(10)),
        ("i_manager_id", INT32), ("i_product_name", char(50)))
    s["store"] = Schema.of(
        ("s_store_sk", INT32), ("s_store_id", char(16)),
        ("s_rec_start_date", DATE), ("s_rec_end_date", DATE),
        ("s_closed_date_sk", INT32), ("s_store_name", varchar(50)),
        ("s_number_employees", INT32), ("s_floor_space", INT32),
        ("s_hours", char(20)), ("s_manager", varchar(40)),
        ("s_market_id", INT32), ("s_geography_class", varchar(100)),
        ("s_market_desc", varchar(100)),
        ("s_market_manager", varchar(40)), ("s_division_id", INT32),
        ("s_division_name", varchar(50)), ("s_company_id", INT32),
        ("s_company_name", varchar(50)),
        ("s_street_number", varchar(10)),
        ("s_street_name", varchar(60)), ("s_street_type", char(15)),
        ("s_suite_number", char(10)), ("s_city", varchar(60)),
        ("s_county", varchar(30)), ("s_state", char(2)),
        ("s_zip", char(10)), ("s_country", varchar(20)),
        ("s_gmt_offset", dec(5, 2)),
        ("s_tax_precentage", dec(5, 2)))  # spec's own spelling
    s["call_center"] = Schema.of(
        ("cc_call_center_sk", INT32), ("cc_call_center_id", char(16)),
        ("cc_rec_start_date", DATE), ("cc_rec_end_date", DATE),
        ("cc_closed_date_sk", INT32), ("cc_open_date_sk", INT32),
        ("cc_name", varchar(50)), ("cc_class", varchar(50)),
        ("cc_employees", INT32), ("cc_sq_ft", INT32),
        ("cc_hours", char(20)), ("cc_manager", varchar(40)),
        ("cc_mkt_id", INT32), ("cc_mkt_class", char(50)),
        ("cc_mkt_desc", varchar(100)),
        ("cc_market_manager", varchar(40)), ("cc_division", INT32),
        ("cc_division_name", varchar(50)), ("cc_company", INT32),
        ("cc_company_name", char(50)), ("cc_street_number", char(10)),
        ("cc_street_name", varchar(60)), ("cc_street_type", char(15)),
        ("cc_suite_number", char(10)), ("cc_city", varchar(60)),
        ("cc_county", varchar(30)), ("cc_state", char(2)),
        ("cc_zip", char(10)), ("cc_country", varchar(20)),
        ("cc_gmt_offset", dec(5, 2)), ("cc_tax_percentage", dec(5, 2)))
    s["customer"] = Schema.of(
        ("c_customer_sk", INT32), ("c_customer_id", char(16)),
        ("c_current_cdemo_sk", INT32), ("c_current_hdemo_sk", INT32),
        ("c_current_addr_sk", INT32), ("c_first_shipto_date_sk", INT32),
        ("c_first_sales_date_sk", INT32), ("c_salutation", char(10)),
        ("c_first_name", char(20)), ("c_last_name", char(30)),
        ("c_preferred_cust_flag", char(1)), ("c_birth_day", INT32),
        ("c_birth_month", INT32), ("c_birth_year", INT32),
        ("c_birth_country", varchar(20)), ("c_login", char(13)),
        ("c_email_address", char(50)), ("c_last_review_date_sk", INT32))
    s["web_site"] = Schema.of(
        ("web_site_sk", INT32), ("web_site_id", char(16)),
        ("web_rec_start_date", DATE), ("web_rec_end_date", DATE),
        ("web_name", varchar(50)), ("web_open_date_sk", INT32),
        ("web_close_date_sk", INT32), ("web_class", varchar(50)),
        ("web_manager", varchar(40)), ("web_mkt_id", INT32),
        ("web_mkt_class", varchar(50)), ("web_mkt_desc", varchar(100)),
        ("web_market_manager", varchar(40)), ("web_company_id", INT32),
        ("web_company_name", char(50)), ("web_street_number", char(10)),
        ("web_street_name", varchar(60)), ("web_street_type", char(15)),
        ("web_suite_number", char(10)), ("web_city", varchar(60)),
        ("web_county", varchar(30)), ("web_state", char(2)),
        ("web_zip", char(10)), ("web_country", varchar(20)),
        ("web_gmt_offset", dec(5, 2)),
        ("web_tax_percentage", dec(5, 2)))
    s["store_returns"] = Schema.of(
        ("sr_returned_date_sk", INT32), ("sr_return_time_sk", INT32),
        ("sr_item_sk", INT32), ("sr_customer_sk", INT32),
        ("sr_cdemo_sk", INT32), ("sr_hdemo_sk", INT32),
        ("sr_addr_sk", INT32), ("sr_store_sk", INT32),
        ("sr_reason_sk", INT32),
        ("sr_ticket_number", INT64),  # 64-bit identifier
        ("sr_return_quantity", INT32), ("sr_return_amt", money()),
        ("sr_return_tax", money()), ("sr_return_amt_inc_tax", money()),
        ("sr_fee", money()), ("sr_return_ship_cost", money()),
        ("sr_refunded_cash", money()), ("sr_reversed_charge", money()),
        ("sr_store_credit", money()), ("sr_net_loss", money()))
    s["household_demographics"] = Schema.of(
        ("hd_demo_sk", INT32), ("hd_income_band_sk", INT32),
        ("hd_buy_potential", char(15)), ("hd_dep_count", INT32),
        ("hd_vehicle_count", INT32))
    s["web_page"] = Schema.of(
        ("wp_web_page_sk", INT32), ("wp_web_page_id", char(16)),
        ("wp_rec_start_date", DATE), ("wp_rec_end_date", DATE),
        ("wp_creation_date_sk", INT32), ("wp_access_date_sk", INT32),
        ("wp_autogen_flag", char(1)), ("wp_customer_sk", INT32),
        ("wp_url", varchar(100)), ("wp_type", char(50)),
        ("wp_char_count", INT32), ("wp_link_count", INT32),
        ("wp_image_count", INT32), ("wp_max_ad_count", INT32))
    s["promotion"] = Schema.of(
        ("p_promo_sk", INT32), ("p_promo_id", char(16)),
        ("p_start_date_sk", INT32), ("p_end_date_sk", INT32),
        ("p_item_sk", INT32), ("p_cost", dec(15, 2)),
        ("p_response_target", INT32), ("p_promo_name", char(50)),
        ("p_channel_dmail", char(1)), ("p_channel_email", char(1)),
        ("p_channel_catalog", char(1)), ("p_channel_tv", char(1)),
        ("p_channel_radio", char(1)), ("p_channel_press", char(1)),
        ("p_channel_event", char(1)), ("p_channel_demo", char(1)),
        ("p_channel_details", varchar(100)), ("p_purpose", char(15)),
        ("p_discount_active", char(1)))
    s["catalog_page"] = Schema.of(
        ("cp_catalog_page_sk", INT32), ("cp_catalog_page_id", char(16)),
        ("cp_start_date_sk", INT32), ("cp_end_date_sk", INT32),
        ("cp_department", varchar(50)), ("cp_catalog_number", INT32),
        ("cp_catalog_page_number", INT32),
        ("cp_description", varchar(100)), ("cp_type", varchar(100)))
    s["inventory"] = Schema.of(
        ("inv_date_sk", INT32), ("inv_item_sk", INT32),
        ("inv_warehouse_sk", INT32), ("inv_quantity_on_hand", INT32))
    s["catalog_returns"] = Schema.of(
        ("cr_returned_date_sk", INT32), ("cr_returned_time_sk", INT32),
        ("cr_item_sk", INT32), ("cr_refunded_customer_sk", INT32),
        ("cr_refunded_cdemo_sk", INT32), ("cr_refunded_hdemo_sk", INT32),
        ("cr_refunded_addr_sk", INT32),
        ("cr_returning_customer_sk", INT32),
        ("cr_returning_cdemo_sk", INT32),
        ("cr_returning_hdemo_sk", INT32),
        ("cr_returning_addr_sk", INT32), ("cr_call_center_sk", INT32),
        ("cr_catalog_page_sk", INT32), ("cr_ship_mode_sk", INT32),
        ("cr_warehouse_sk", INT32), ("cr_reason_sk", INT32),
        ("cr_order_number", INT64), ("cr_return_quantity", INT32),
        ("cr_return_amount", money()), ("cr_return_tax", money()),
        ("cr_return_amt_inc_tax", money()), ("cr_fee", money()),
        ("cr_return_ship_cost", money()), ("cr_refunded_cash", money()),
        ("cr_reversed_charge", money()), ("cr_store_credit", money()),
        ("cr_net_loss", money()))
    s["web_returns"] = Schema.of(
        ("wr_returned_date_sk", INT32), ("wr_returned_time_sk", INT32),
        ("wr_item_sk", INT32), ("wr_refunded_customer_sk", INT32),
        ("wr_refunded_cdemo_sk", INT32), ("wr_refunded_hdemo_sk", INT32),
        ("wr_refunded_addr_sk", INT32),
        ("wr_returning_customer_sk", INT32),
        ("wr_returning_cdemo_sk", INT32),
        ("wr_returning_hdemo_sk", INT32),
        ("wr_returning_addr_sk", INT32), ("wr_web_page_sk", INT32),
        ("wr_reason_sk", INT32), ("wr_order_number", INT64),
        ("wr_return_quantity", INT32), ("wr_return_amt", money()),
        ("wr_return_tax", money()), ("wr_return_amt_inc_tax", money()),
        ("wr_fee", money()), ("wr_return_ship_cost", money()),
        ("wr_refunded_cash", money()), ("wr_reversed_charge", money()),
        ("wr_account_credit", money()), ("wr_net_loss", money()))
    s["web_sales"] = Schema.of(
        ("ws_sold_date_sk", INT32), ("ws_sold_time_sk", INT32),
        ("ws_ship_date_sk", INT32), ("ws_item_sk", INT32),
        ("ws_bill_customer_sk", INT32), ("ws_bill_cdemo_sk", INT32),
        ("ws_bill_hdemo_sk", INT32), ("ws_bill_addr_sk", INT32),
        ("ws_ship_customer_sk", INT32), ("ws_ship_cdemo_sk", INT32),
        ("ws_ship_hdemo_sk", INT32), ("ws_ship_addr_sk", INT32),
        ("ws_web_page_sk", INT32), ("ws_web_site_sk", INT32),
        ("ws_ship_mode_sk", INT32), ("ws_warehouse_sk", INT32),
        ("ws_promo_sk", INT32), ("ws_order_number", INT64),
        ("ws_quantity", INT32), ("ws_wholesale_cost", money()),
        ("ws_list_price", money()), ("ws_sales_price", money()),
        ("ws_ext_discount_amt", money()),
        ("ws_ext_sales_price", money()),
        ("ws_ext_wholesale_cost", money()),
        ("ws_ext_list_price", money()), ("ws_ext_tax", money()),
        ("ws_coupon_amt", money()), ("ws_ext_ship_cost", money()),
        ("ws_net_paid", money()), ("ws_net_paid_inc_tax", money()),
        ("ws_net_paid_inc_ship", money()),
        ("ws_net_paid_inc_ship_tax", money()),
        ("ws_net_profit", money()))
    s["catalog_sales"] = Schema.of(
        ("cs_sold_date_sk", INT32), ("cs_sold_time_sk", INT32),
        ("cs_ship_date_sk", INT32), ("cs_bill_customer_sk", INT32),
        ("cs_bill_cdemo_sk", INT32), ("cs_bill_hdemo_sk", INT32),
        ("cs_bill_addr_sk", INT32), ("cs_ship_customer_sk", INT32),
        ("cs_ship_cdemo_sk", INT32), ("cs_ship_hdemo_sk", INT32),
        ("cs_ship_addr_sk", INT32), ("cs_call_center_sk", INT32),
        ("cs_catalog_page_sk", INT32), ("cs_ship_mode_sk", INT32),
        ("cs_warehouse_sk", INT32), ("cs_item_sk", INT32),
        ("cs_promo_sk", INT32), ("cs_order_number", INT64),
        ("cs_quantity", INT32), ("cs_wholesale_cost", money()),
        ("cs_list_price", money()), ("cs_sales_price", money()),
        ("cs_ext_discount_amt", money()),
        ("cs_ext_sales_price", money()),
        ("cs_ext_wholesale_cost", money()),
        ("cs_ext_list_price", money()), ("cs_ext_tax", money()),
        ("cs_coupon_amt", money()), ("cs_ext_ship_cost", money()),
        ("cs_net_paid", money()), ("cs_net_paid_inc_tax", money()),
        ("cs_net_paid_inc_ship", money()),
        ("cs_net_paid_inc_ship_tax", money()),
        ("cs_net_profit", money()))
    s["store_sales"] = Schema.of(
        ("ss_sold_date_sk", INT32), ("ss_sold_time_sk", INT32),
        ("ss_item_sk", INT32), ("ss_customer_sk", INT32),
        ("ss_cdemo_sk", INT32), ("ss_hdemo_sk", INT32),
        ("ss_addr_sk", INT32), ("ss_store_sk", INT32),
        ("ss_promo_sk", INT32), ("ss_ticket_number", INT64),
        ("ss_quantity", INT32), ("ss_wholesale_cost", money()),
        ("ss_list_price", money()), ("ss_sales_price", money()),
        ("ss_ext_discount_amt", money()),
        ("ss_ext_sales_price", money()),
        ("ss_ext_wholesale_cost", money()),
        ("ss_ext_list_price", money()), ("ss_ext_tax", money()),
        ("ss_coupon_amt", money()), ("ss_net_paid", money()),
        ("ss_net_paid_inc_tax", money()), ("ss_net_profit", money()))
    return s


def get_maintenance_schemas(use_decimal: bool = True) -> dict[str, Schema]:
    """The 12 refresh/staging tables feeding data maintenance
    (role of `nds/nds_schema.py:570-716`, columns per the public TPC-DS
    spec's s_* source schemas). Staging rows carry business IDs (char),
    not surrogate keys — the LF_* refresh functions join them back to
    dimensions. Dates that the refresh SQL compares against date_dim are
    engine DATE (epoch days) rather than char(10): the builtin generator
    owns the raw format, so the reference's ``cast(char as date)`` hop
    is unnecessary on TPU."""
    dec = _dec_factory(use_decimal)

    def money():
        return dec(7, 2)

    s: dict[str, Schema] = {}
    s["s_purchase_lineitem"] = Schema.of(
        ("plin_purchase_id", INT32, False),
        ("plin_line_number", INT32, False),
        ("plin_item_id", char(16)), ("plin_promotion_id", char(16)),
        ("plin_quantity", INT32), ("plin_sale_price", money()),
        ("plin_coupon_amt", money()), ("plin_comment", varchar(100)))
    s["s_purchase"] = Schema.of(
        ("purc_purchase_id", INT32, False), ("purc_store_id", char(16)),
        ("purc_customer_id", char(16)), ("purc_purchase_date", DATE),
        ("purc_purchase_time", INT32), ("purc_register_id", INT32),
        ("purc_clerk_id", INT32), ("purc_comment", char(100)))
    s["s_catalog_order"] = Schema.of(
        ("cord_order_id", INT32, False),
        ("cord_bill_customer_id", char(16)),
        ("cord_ship_customer_id", char(16)),
        ("cord_order_date", DATE), ("cord_order_time", INT32),
        ("cord_ship_mode_id", char(16)),
        ("cord_call_center_id", char(16)),
        ("cord_order_comments", varchar(100)))
    s["s_web_order"] = Schema.of(
        ("word_order_id", INT32, False),
        ("word_bill_customer_id", char(16)),
        ("word_ship_customer_id", char(16)),
        ("word_order_date", DATE), ("word_order_time", INT32),
        ("word_ship_mode_id", char(16)), ("word_web_site_id", char(16)),
        ("word_order_comments", char(100)))
    s["s_catalog_order_lineitem"] = Schema.of(
        ("clin_order_id", INT32, False), ("clin_line_number", INT32, False),
        ("clin_item_id", char(16)), ("clin_promotion_id", char(16)),
        ("clin_quantity", INT32), ("clin_sales_price", money()),
        ("clin_coupon_amt", money()), ("clin_warehouse_id", char(16)),
        ("clin_ship_date", DATE), ("clin_catalog_number", INT32),
        ("clin_catalog_page_number", INT32), ("clin_ship_cost", money()))
    s["s_web_order_lineitem"] = Schema.of(
        ("wlin_order_id", INT32, False), ("wlin_line_number", INT32, False),
        ("wlin_item_id", char(16)), ("wlin_promotion_id", char(16)),
        ("wlin_quantity", INT32), ("wlin_sales_price", money()),
        ("wlin_coupon_amt", money()), ("wlin_warehouse_id", char(16)),
        ("wlin_ship_date", DATE), ("wlin_ship_cost", money()),
        ("wlin_web_page_id", char(16)))
    s["s_store_returns"] = Schema.of(
        ("sret_store_id", char(16)), ("sret_purchase_id", char(16), False),
        ("sret_line_number", INT32, False),
        ("sret_item_id", char(16), False),
        ("sret_customer_id", char(16)), ("sret_return_date", DATE),
        ("sret_return_time", INT32), ("sret_ticket_number", INT64),
        ("sret_return_qty", INT32), ("sret_return_amt", money()),
        ("sret_return_tax", money()), ("sret_return_fee", money()),
        ("sret_return_ship_cost", money()), ("sret_refunded_cash", money()),
        ("sret_reversed_charge", money()), ("sret_store_credit", money()),
        ("sret_reason_id", char(16)))
    s["s_catalog_returns"] = Schema.of(
        ("cret_call_center_id", char(16)), ("cret_order_id", INT32, False),
        ("cret_line_number", INT32, False),
        ("cret_item_id", char(16), False),
        ("cret_return_customer_id", char(16)),
        ("cret_refund_customer_id", char(16)),
        ("cret_return_date", DATE), ("cret_return_time", INT32),
        ("cret_return_qty", INT32), ("cret_return_amt", money()),
        ("cret_return_tax", money()), ("cret_return_fee", money()),
        ("cret_return_ship_cost", money()), ("cret_refunded_cash", money()),
        ("cret_reversed_charge", money()),
        ("cret_merchant_credit", money()), ("cret_reason_id", char(16)),
        ("cret_shipmode_id", char(16)), ("cret_catalog_page_id", char(16)),
        ("cret_warehouse_id", char(16)))
    s["s_web_returns"] = Schema.of(
        ("wret_web_page_id", char(16)), ("wret_order_id", INT32, False),
        ("wret_line_number", INT32, False),
        ("wret_item_id", char(16), False),
        ("wret_return_customer_id", char(16)),
        ("wret_refund_customer_id", char(16)),
        ("wret_return_date", DATE), ("wret_return_time", INT32),
        ("wret_return_qty", INT32), ("wret_return_amt", money()),
        ("wret_return_tax", money()), ("wret_return_fee", money()),
        ("wret_return_ship_cost", money()), ("wret_refunded_cash", money()),
        ("wret_reversed_charge", money()), ("wret_account_credit", money()),
        ("wret_reason_id", char(16)))
    s["s_inventory"] = Schema.of(
        ("invn_warehouse_id", char(16), False),
        ("invn_item_id", char(16), False),
        ("invn_date", DATE, False), ("invn_qty_on_hand", INT32))
    s["delete"] = Schema.of(
        ("date1", DATE, False), ("date2", DATE, False))
    s["inventory_delete"] = Schema.of(
        ("date1", DATE, False), ("date2", DATE, False))
    return s


PRIMARY_KEYS: dict[str, tuple] = {
    "customer_address": ("ca_address_sk",),
    "customer_demographics": ("cd_demo_sk",),
    "date_dim": ("d_date_sk",),
    "warehouse": ("w_warehouse_sk",),
    "ship_mode": ("sm_ship_mode_sk",),
    "time_dim": ("t_time_sk",),
    "reason": ("r_reason_sk",),
    "income_band": ("ib_income_band_sk",),
    "item": ("i_item_sk",),
    "store": ("s_store_sk",),
    "call_center": ("cc_call_center_sk",),
    "customer": ("c_customer_sk",),
    "web_site": ("web_site_sk",),
    "store_returns": ("sr_item_sk", "sr_ticket_number"),
    "household_demographics": ("hd_demo_sk",),
    "web_page": ("wp_web_page_sk",),
    "promotion": ("p_promo_sk",),
    "catalog_page": ("cp_catalog_page_sk",),
    "inventory": ("inv_date_sk", "inv_item_sk", "inv_warehouse_sk"),
    "catalog_returns": ("cr_item_sk", "cr_order_number"),
    "web_returns": ("wr_item_sk", "wr_order_number"),
    "web_sales": ("ws_item_sk", "ws_order_number"),
    "catalog_sales": ("cs_item_sk", "cs_order_number"),
    "store_sales": ("ss_item_sk", "ss_ticket_number"),
}


def table_rows(table: str, sf: float) -> int:
    """dsdgen's row-count scaling model (public spec table 3-2 shapes;
    linear for facts, stepped for dimensions — approximated log-linear
    the way dsdgen scales between published SF points)."""
    import math
    sf = max(sf, 0.01)
    lin = {
        "store_sales": 2_880_404, "store_returns": 287_514,
        "catalog_sales": 1_441_548, "catalog_returns": 144_067,
        "web_sales": 719_384, "web_returns": 71_763,
        "inventory": 11_745_000,
    }
    if table in lin:
        return max(int(lin[table] * sf), 100)
    fixed = {
        "date_dim": 73049, "time_dim": 86400, "ship_mode": 20,
        "income_band": 20, "reason": 35 if sf >= 1 else 35,
    }
    if table in fixed:
        return fixed[table]
    # stepped dimensions: value at SF1 scaled ~ sf^0.5 (dsdgen steps are
    # coarser; sqrt keeps FK densities workable at sub-SF1 test scales)
    sf1 = {
        "customer": 100_000, "customer_address": 50_000,
        "customer_demographics": 1_920_800, "household_demographics": 7200,
        "item": 18_000, "store": 12, "call_center": 6, "web_site": 30,
        "web_page": 60, "promotion": 300, "catalog_page": 11_718,
        "warehouse": 5,
    }
    if table in ("customer_demographics", "household_demographics"):
        return sf1[table]  # fixed cross-product tables
    n = sf1[table]
    if sf >= 1:
        return int(n * max(1.0, math.log2(sf) if table != "customer"
                           else sf ** 0.5))
    return max(int(n * sf ** 0.5), 6)


SIZES = {t: table_rows(t, 1) for t in [
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "web_sales", "web_returns", "inventory", "customer",
    "customer_address", "customer_demographics",
    "household_demographics", "item", "store", "call_center", "web_site",
    "web_page", "promotion", "catalog_page", "warehouse", "date_dim",
    "time_dim", "ship_mode", "income_band", "reason"]}
