"""NDS output validation: diff two power runs' saved query outputs.

Behavioral port of `nds/nds_validate.py:194-260` over the shared diff
core: per query, row-count check then epsilon compare, order-insensitive
mode, and the reference's documented carve-outs — q65 skip (ties at the
LIMIT edge, `nds/nds_validate.py:232-234`), q67 skip under floats mode
(`:235-237`), and q78's rounded-ratio column tolerance 0.01001
(`:166-190`). Also patches ``queryValidationStatus`` into the JSON
summaries like `nds/nds_validate.py:262-296`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from nds_tpu.nds import streams
from nds_tpu.utils.validate_core import compare_results

SKIP_QUERIES = {"query65"}
FLOATS_SKIP_QUERIES = {"query67"}
# q78 emits a rounded ratio column (positional 3): both engines round a
# near-tie differently, tolerance widened (`nds/nds_validate.py:166-190`)
COLUMN_REL_TOL = {("query78", 3): 0.01001}


def iterate_queries(dir1: str, dir2: str, stream_path: str,
                    ignore_ordering: bool = True,
                    epsilon: float = 0.00001,
                    floats: bool = False) -> list[str]:
    """Compare every query in the stream; returns names that mismatched."""
    queries = streams.parse_query_stream(stream_path)
    unmatched = []
    for qname in queries:
        base = qname.split("_part")[0]
        if base in SKIP_QUERIES or (floats and base in
                                    FLOATS_SKIP_QUERIES):
            print(f"=== Skipping {qname} ===")
            continue
        here1 = os.path.isdir(os.path.join(dir1, qname))
        here2 = os.path.isdir(os.path.join(dir2, qname))
        if not here1 and not here2:
            # subset runs leave most queries without output; loud so a
            # double-crash (both engines failed the query) is visible
            print(f"=== {qname}: no output on either side — "
                  f"not compared ===")
            continue
        if here1 != here2:
            print(f"=== {qname}: output present on only one side ===")
            unmatched.append(qname)
            continue
        ok = compare_results(dir1, dir2, qname, ignore_ordering, epsilon,
                             column_rel_tol=COLUMN_REL_TOL)
        status = "MATCH" if ok else "MISMATCH"
        print(f"=== Comparing Query: {qname} -> {status} ===")
        if not ok:
            unmatched.append(qname)
    if unmatched:
        print(f"Unmatched queries: {unmatched}")
    return unmatched


def update_summary(summary_folder: str, unmatched: list[str]) -> None:
    """Patch queryValidationStatus into each per-query JSON summary
    (`nds/nds_validate.py:262-296`)."""
    for path in glob.glob(os.path.join(summary_folder, "*.json")):
        with open(path) as f:
            summary = json.load(f)
        qname = summary.get("query")
        if not qname:
            continue
        status = ("NotMatch" if qname in unmatched else "Match")
        summary["queryValidationStatus"] = [status]
        # atomic (NDS109): this REWRITES an existing summary in place —
        # a crash mid-dump must not destroy the original report
        from nds_tpu.io.integrity import write_json_atomic
        write_json_atomic(path, summary)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="diff saved query outputs from two NDS power runs")
    p.add_argument("dir1", help="first output_prefix (e.g. CPU oracle run)")
    p.add_argument("dir2", help="second output_prefix (e.g. TPU run)")
    p.add_argument("query_stream", help="stream file both runs executed")
    p.add_argument("--epsilon", type=float, default=0.00001)
    p.add_argument("--ignore_ordering", action="store_true")
    p.add_argument("--floats", action="store_true",
                   help="floats-mode run: skip q67 like the reference")
    p.add_argument("--json_summary_folder",
                   help="patch queryValidationStatus into these summaries")
    args = p.parse_args(argv)
    unmatched = iterate_queries(args.dir1, args.dir2, args.query_stream,
                                args.ignore_ordering, args.epsilon,
                                args.floats)
    if args.json_summary_folder:
        update_summary(args.json_summary_folder, unmatched)
    sys.exit(1 if unmatched else 0)


if __name__ == "__main__":
    main()
