"""NDS whole-benchmark orchestrator.

Behavioral port of `nds/nds_bench.py:367-498`: run the TPC-DS phases as
subprocesses in spec order — data-gen (base + per-stream refresh sets)
-> load (transcode) -> stream-gen (RNGSEED = load end timestamp,
`nds/nds_bench.py:60-74`) -> power -> throughput 1 -> maintenance 1 ->
throughput 2 -> maintenance 2 -> validate (optional: post-maintenance
engine outputs diffed against a CPU-oracle round, nds/validate.py) —
with crash isolation via report-file state passing (SURVEY.md §3.4),
then compute the 4-term composite metric (`nds/nds_bench.py:334-357`):

    Q   = Sq * 99
    Tpt = Tpower * Sq / 3600 ;  Ttt = (Ttt1 + Ttt2) / 3600
    Tdm = (Tdm1 + Tdm2) / 3600 ; Tld = 0.01 * Sq * Tload / 3600
    metric = int(SF * Q / (Tpt * Ttt * Tdm * Tld) ** (1/4))

Config comes from a YAML like `configs/bench_nds.yml` (the reference's
`nds/bench.yml:18-59`).

Resumability (README "Resilience"): every completed phase journals its
timings to ``bench_state.json`` in the report dir; ``--resume`` replays
completed phases from the journal instead of re-running them, so a
crash in throughput round 2 costs only that round — the journal guards
against config drift via a digest, and the resumed run computes the
SAME composite metric an uninterrupted one would.

Observability (README "Observability"): power and throughput phases
leave ``analysis.json`` + ``report.html`` (per-query time attribution,
nds_tpu/obs/analyze.py) next to their BenchReport JSONs, and a
``metrics_snap: {dir, interval}`` YAML block threads
``NDS_TPU_METRICS_SNAP`` into every engine phase so long runs publish
live metrics snapshots while in flight.
"""

from __future__ import annotations

import argparse
import csv
import os
import subprocess
import sys
import time

import yaml

from nds_tpu.nds.transcode import get_load_time, get_rngseed
from nds_tpu.resilience.journal import PhaseJournal, config_digest
from nds_tpu.utils.timelog import TimeLog


def _run(cmd: list[str], backend: str | None = None,
         extra_env: dict | None = None) -> None:
    from nds_tpu.utils.power_core import subprocess_env
    print("+", " ".join(cmd))
    env = subprocess_env(backend)
    if extra_env:
        env.update(extra_env)
    subprocess.run(cmd, check=True, env=env)


def _run_rc(cmd: list[str], backend: str | None = None,
            extra_env: dict | None = None) -> int:
    """Like _run but returns the exit code instead of raising — the
    caller distinguishes RESUMABLE exits (75, a graceful preemption
    drain; resilience/drain.py) from real failures."""
    from nds_tpu.utils.power_core import subprocess_env
    print("+", " ".join(cmd))
    env = subprocess_env(backend)
    if extra_env:
        env.update(extra_env)
    return subprocess.run(cmd, env=env).returncode


# how many graceful-drain (exit 75) resumes the power phase tolerates
# before the bench gives up — the query journal makes each retry cost
# only the statements not yet journaled
MAX_PHASE_RESUMES = 5


def _analyze_phase(phase_name: str, run_dir: str) -> None:
    """Post-phase run analysis (nds_tpu/obs/analyze.py): write
    ``analysis.json`` + ``report.html`` next to the phase's BenchReport
    JSONs so every bench round leaves a per-query attribution
    breakdown, not just composite-metric inputs. Best-effort — a phase
    that wrote no summaries (skipped via cfg['skip']) is not an
    error."""
    try:
        from nds_tpu.obs import analyze
        paths = analyze.write_outputs(analyze.analyze_run(run_dir),
                                      run_dir)
        print(f"[{phase_name}] analysis: {paths['report']}")
    except Exception as exc:  # noqa: BLE001 - reporting only
        print(f"[{phase_name}] run analysis skipped: "
              f"{type(exc).__name__}: {exc}")


def get_power_time(time_log_path: str) -> float:
    for _app, query, ms in TimeLog.read(time_log_path):
        if query == "Power Test Time":
            return ms / 1000.0
    raise ValueError(f"no Power Test Time row in {time_log_path}")


def get_maintenance_time(time_log_path: str) -> float:
    """Tdm seconds from a maintenance CSV log
    (`nds/nds_bench.py:176-196` reads per-stream refresh times)."""
    for _app, query, ms in TimeLog.read(time_log_path):
        if query == "Data Maintenance Time":
            return ms / 1000.0
    raise ValueError(f"no Data Maintenance Time row in {time_log_path}")


def get_stream_range(num_streams: int, first_or_second: int) -> list[int]:
    """Stream numbers per throughput test (`nds/nds_bench.py:126-135`):
    9 streams -> test 1 runs [1..4], test 2 runs [5..8]."""
    if first_or_second == 1:
        return list(range(1, num_streams // 2 + 1))
    return list(range(num_streams // 2 + 1, num_streams))


def get_perf_metric(scale: float, num_streams: int, tload: float,
                    tpower: float, ttt1: float, ttt2: float,
                    tdm1: float, tdm2: float) -> int:
    """4-term composite (`nds/nds_bench.py:334-357`)."""
    sq = max(num_streams, 1)
    q = sq * 99
    tpt = (tpower * sq) / 3600.0
    ttt = (ttt1 + ttt2) / 3600.0
    tdm = (tdm1 + tdm2) / 3600.0
    tld = (0.01 * sq * tload) / 3600.0
    denom = (tpt * ttt * tdm * tld) ** (1.0 / 4.0)
    return int(scale * q / denom) if denom > 0 else 0


def run_full_bench(cfg: dict, resume: bool = False) -> dict:
    paths = cfg["paths"]
    scale = float(cfg.get("scale_factor", 1))
    parallel = int(cfg.get("parallel", 2))
    # total stream count is Sq*2+1 in the reference's bench.yml
    # convention: stream 0 powers, halves run the two throughput tests
    num_streams = int(cfg.get("num_streams", 2)) * 2 + 1
    backend = cfg.get("backend", "tpu")
    skip = cfg.get("skip", {})
    raw_dir = paths["raw_data"]
    refresh_base = paths.get("refresh_data",
                             os.path.join(raw_dir, "_refresh"))
    wh_dir = paths["warehouse"]
    stream_dir = paths["streams"]
    report_dir = paths.get("reports", "bench_reports")
    os.makedirs(report_dir, exist_ok=True)
    load_report = os.path.join(report_dir, "load_report.txt")
    metrics: dict = {"scale": scale, "streams": num_streams}

    # live metrics snapshots (README "Observability"): YAML
    # ``metrics_snap: {dir: ..., interval: 5}`` threads
    # NDS_TPU_METRICS_SNAP into every engine phase subprocess, one
    # snapshot file per phase
    snap_cfg = cfg.get("metrics_snap") or {}

    # YAML ``cache: {dir, readonly}`` (README "Plan cache"): one
    # persistent AOT plan cache shared by every phase subprocess, so
    # the throughput rounds replay the power round's compiles as hits
    from nds_tpu import cache as plan_cache
    plan_cache.export_env(cfg.get("cache"))

    def _snap_env(phase_name: str) -> dict | None:
        snap_dir = snap_cfg.get("dir")
        if not snap_dir:
            return None
        os.makedirs(snap_dir, exist_ok=True)
        interval = snap_cfg.get("interval", 5)
        return {"NDS_TPU_METRICS_SNAP":
                f"{os.path.join(snap_dir, phase_name)}.json:{interval}"}

    journal = PhaseJournal(os.path.join(report_dir, "bench_state.json"),
                           config_digest(cfg))
    if resume:
        if journal.load():
            done = sorted(journal.state["phases"])
            print(f"== resuming: journal has {', '.join(done)} ==")
    else:
        # a fresh run must not leave a stale journal a later --resume
        # could splice in
        journal.reset()

    def phase(name, body):
        """Run one phase unless the journal already has it; journal its
        result values (the numbers the composite metric needs) on
        completion. Phase bodies honor cfg['skip'] themselves."""
        if resume and journal.done(name):
            print(f"== skipping {name} (journaled) ==")
            return journal.timings(name)
        vals = body()
        journal.complete(name, **vals)
        return vals

    def _data_gen():
        if not skip.get("data_gen", False):
            _run([sys.executable, "-m", "nds_tpu.nds.gen_data",
                  str(scale), str(parallel), raw_dir,
                  "--overwrite_output"], backend="cpu")
            # one refresh set per maintenance run (2 per full bench)
            for update in (1, 2):
                _run([sys.executable, "-m", "nds_tpu.nds.gen_data",
                      str(scale), "1", f"{refresh_base}{update}",
                      "--update", str(update), "--overwrite_output"],
                     backend="cpu")
        return {}

    def _load_test():
        if not skip.get("load_test", False):
            cmd = [sys.executable, "-m", "nds_tpu.nds.transcode",
                   raw_dir, wh_dir, load_report]
            if resume:
                # an interrupted load resumes table-granular: tables
                # whose _manifest.json digests verify are not
                # re-transcoded (nds/transcode.py --resume)
                cmd.append("--resume")
            _run(cmd, backend="cpu")
        return {"load_time_s": get_load_time(load_report),
                "rngseed": get_rngseed(load_report)}

    phase("data_gen", _data_gen)
    load_vals = phase("load_test", _load_test)
    metrics["load_time_s"] = tld = load_vals["load_time_s"]
    rngseed = load_vals["rngseed"]

    def _stream_gen():
        if not skip.get("stream_gen", False):
            from nds_tpu.nds.streams import generate_query_streams
            # rngseed from the load report redraws every stream's
            # parameter bindings (dsqgen -rngseed,
            # `nds/nds_bench.py:415`): throughput streams must be
            # distinct workloads, not N copies
            generate_query_streams(stream_dir, num_streams,
                                   rng_seed=rngseed,
                                   qualification=False)
        return {}

    phase("stream_gen", _stream_gen)

    power_log = os.path.join(report_dir, "power_time.csv")

    def _power_test():
        if not skip.get("power_test", False):
            from nds_tpu.resilience.drain import EXIT_RESUMABLE
            base_cmd = [sys.executable, "-m", "nds_tpu.nds.power",
                        wh_dir,
                        os.path.join(stream_dir, "query_0.sql"),
                        power_log, "--backend", backend,
                        "--json_summary_folder",
                        os.path.join(report_dir, "json")]
            # a bench-level --resume also resumes mid-phase: the query
            # journal in the json dir replays finished statements
            cmd = base_cmd + (["--resume"] if resume else [])
            resumes = 0
            while True:
                rc = _run_rc(cmd, backend=backend,
                             extra_env=_snap_env("power"))
                if rc == 0:
                    break
                if rc == EXIT_RESUMABLE and resumes < MAX_PHASE_RESUMES:
                    # graceful preemption drain: re-run with --resume —
                    # only the statements not yet journaled execute,
                    # and the retry never counts as a failed phase
                    resumes += 1
                    print(f"== power phase drained (exit "
                          f"{EXIT_RESUMABLE}) — resuming "
                          f"({resumes}/{MAX_PHASE_RESUMES}) ==")
                    cmd = base_cmd + ["--resume"]
                    continue
                raise subprocess.CalledProcessError(rc, cmd)
            _analyze_phase("power", os.path.join(report_dir, "json"))
        return {"power_time_s": get_power_time(power_log)}

    metrics["power_time_s"] = tpt = phase(
        "power_test", _power_test)["power_time_s"]

    def _throughput(round_no):
        from nds_tpu.nds.throughput import (
            run_streams, run_streams_inprocess,
        )
        streams_n = get_stream_range(num_streams, round_no)
        tstreams = [os.path.join(stream_dir, f"query_{i}.sql")
                    for i in streams_n]
        tdir = os.path.join(report_dir, f"throughput{round_no}")
        # one TPU chip cannot be opened by N subprocesses; the
        # in-process mode time-shares it (cpu/distributed keep the
        # reference's process fan-out). Overridable via YAML.
        mode = cfg.get("throughput_mode",
                       "inprocess" if backend == "tpu"
                       else "subprocess")
        # in-process mode starts its own emitter in THIS process;
        # subprocess mode inherits the var (run_streams re-points it
        # per stream). Save/restore so a user's own setting survives.
        snap_env = _snap_env(f"throughput{round_no}") or {}
        saved = {k: os.environ.get(k) for k in snap_env}
        os.environ.update(snap_env)
        try:
            if mode == "inprocess":
                ttt, codes = run_streams_inprocess(
                    wh_dir, tstreams, tdir, backend=backend)
            else:
                # YAML ``watchdog: {stall_s, max_restarts}`` arms
                # subprocess stream supervision (kill + bounded
                # restarts; README Resilience)
                wd_cfg = cfg.get("watchdog") or {}
                ttt, codes = run_streams(
                    wh_dir, tstreams, tdir, backend=backend,
                    stall_s=wd_cfg.get("stall_s"),
                    max_restarts=wd_cfg.get("max_restarts"))
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        _analyze_phase(f"throughput{round_no}", tdir)
        if any(codes):
            raise SystemExit(
                f"throughput {round_no} streams failed: {codes}")
        return {"ttt": ttt}

    def _maintenance(round_no):
        from nds_tpu.resilience.drain import EXIT_RESUMABLE
        dm_log = os.path.join(report_dir,
                              f"maintenance{round_no}_time.csv")
        base_cmd = [sys.executable, "-m", "nds_tpu.nds.maintenance",
                    wh_dir, f"{refresh_base}{round_no}", dm_log,
                    "--backend", backend,
                    "--json_summary_folder",
                    os.path.join(report_dir,
                                 f"maintenance{round_no}_json")]
        # bench-level --resume also resumes mid-phase: the maintenance
        # commit journal in the warehouse replays the refresh functions
        # whose snapshot commits already landed (never double-applies)
        cmd = base_cmd + (["--resume"] if resume else [])
        resumes = 0
        while True:
            rc = _run_rc(cmd, backend=backend,
                         extra_env=_snap_env(f"maintenance{round_no}"))
            if rc == 0:
                break
            if rc == EXIT_RESUMABLE and resumes < MAX_PHASE_RESUMES:
                resumes += 1
                print(f"== maintenance {round_no} drained (exit "
                      f"{EXIT_RESUMABLE}) — resuming "
                      f"({resumes}/{MAX_PHASE_RESUMES}) ==")
                cmd = base_cmd + ["--resume"]
                continue
            raise subprocess.CalledProcessError(rc, cmd)
        return {"tdm": get_maintenance_time(dm_log)}

    ttts, tdms = [], []
    for round_no in (1, 2):
        if not skip.get("throughput_test", False):
            ttts.append(phase(f"throughput_{round_no}",
                              lambda r=round_no: _throughput(r))["ttt"])
        if not skip.get("maintenance_test", False):
            tdms.append(phase(f"maintenance_{round_no}",
                              lambda r=round_no: _maintenance(r))["tdm"])
    metrics["throughput_times_s"] = ttts
    metrics["maintenance_times_s"] = tdms

    def _validate():
        """Post-maintenance validation: run the power stream twice on
        the CURRENT (maintained) warehouse — once on the bench backend,
        once on the CPU oracle — and diff the saved outputs
        (nds/validate.py), patching ``queryValidationStatus`` into the
        engine round's JSON summaries."""
        vcfg = cfg.get("validate") or {}
        stream0 = os.path.join(stream_dir, "query_0.sql")
        vdir = os.path.join(report_dir, "validate")
        jdir = os.path.join(vdir, "json")
        subset = [str(q) for q in (vcfg.get("query_subset") or [])]
        out_engine = os.path.join(vdir, "output_engine")
        out_oracle = os.path.join(vdir, "output_oracle")
        for be, outp, tag in ((backend, out_engine, "engine"),
                              ("cpu", out_oracle, "oracle")):
            cmd = [sys.executable, "-m", "nds_tpu.nds.power",
                   wh_dir, stream0,
                   os.path.join(vdir, f"{tag}_time.csv"),
                   "--backend", be, "--output_prefix", outp]
            if tag == "engine":
                cmd += ["--json_summary_folder", jdir]
            if subset:
                cmd += ["--query_subset", *subset]
            _run(cmd, backend=be)
        vcmd = [sys.executable, "-m", "nds_tpu.nds.validate",
                out_engine, out_oracle, stream0, "--ignore_ordering",
                "--json_summary_folder", jdir]
        if vcfg.get("epsilon") is not None:
            vcmd += ["--epsilon", str(vcfg["epsilon"])]
        rc = _run_rc(vcmd, backend="cpu")
        if rc and not vcfg.get("allow_failure"):
            raise SystemExit(
                f"validate: engine outputs diverge from the CPU "
                f"oracle (exit {rc}; mismatches listed above)")
        return {"validation_ok": 0 if rc else 1}

    if cfg.get("validate") and not skip.get("validate", False):
        metrics["validation_ok"] = bool(
            phase("validate", _validate)["validation_ok"])

    # all four terms or no composite (a fabricated term would silently
    # skew the geometric mean)
    if len(ttts) == 2 and len(tdms) == 2:
        metrics["metric"] = get_perf_metric(
            scale, num_streams // 2, tld, tpt, ttts[0], ttts[1],
            tdms[0], tdms[1])
    else:
        metrics["metric"] = None
    out_csv = paths.get("metrics_csv",
                        os.path.join(report_dir, "metrics.csv"))
    with open(out_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["scale", "streams", "load_s", "power_s",
                    "throughput1_s", "throughput2_s", "maintenance1_s",
                    "maintenance2_s", "metric", "timestamp"])
        w.writerow([scale, num_streams, tld, tpt,
                    *(ttts or [None, None]), *(tdms or [None, None]),
                    metrics["metric"], int(time.time())])
    print(f"perf metric: {metrics['metric']} (details in {out_csv})")
    return metrics


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="full NDS benchmark")
    p.add_argument("config", help="bench YAML (like configs/bench_nds.yml)")
    p.add_argument("--resume", action="store_true",
                   help="replay completed phases from the report dir's "
                        "bench_state.json journal instead of re-running "
                        "them (crash recovery; README Resilience)")
    args = p.parse_args(argv)
    with open(args.config) as f:
        cfg = yaml.safe_load(f)
    run_full_bench(cfg, resume=args.resume)


if __name__ == "__main__":
    main()
