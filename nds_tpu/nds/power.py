"""NDS Power Run driver.

Behavioral port of `nds/nds_power.py:184-410` over the shared power core
(`nds_tpu/utils/power_core.py`): parse a 99-query stream by its dsqgen
markers (multi-statement templates q14/23/24/39 split into parts,
`nds/nds_gen_query_stream.py:91-103`), register the 25 tables, run every
query in stream order recording per-query wall-clock ms, emit the CSV
time log + per-query JSON summaries, honor ``--allow_failure``
(`nds/nds_power.py:391-393`) and the template/property-file config
layers (`:324-330`), and exit non-zero if any query failed.
"""

from __future__ import annotations

import argparse
import sys

from nds_tpu.engine.session import Session
from nds_tpu.nds import streams
from nds_tpu.nds.schema import get_schemas
from nds_tpu.utils import power_core

SUITE = power_core.Suite(
    name="nds",
    get_schemas=get_schemas,
    parse_query_stream=streams.parse_query_stream,
    session_for=Session.for_nds,
    raw_ext=".dat",
    floats_toggle=True,
)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="NDS power run on the TPU columnar engine")
    p.add_argument("data_dir", help="warehouse directory (transcode output)")
    p.add_argument("query_stream", help="query_N.sql stream file")
    p.add_argument("time_log", help="output CSV time log path")
    p.add_argument("--backend", choices=["tpu", "cpu", "distributed"],
                   default=None,
                   help="overrides engine.backend from template/property "
                        "files (default tpu)")
    p.add_argument("--placement",
                   choices=["device", "sharded", "chunked", "cpu"],
                   default=None,
                   help="pin the initial placement for every query "
                        "(engine.placement.force); default: the "
                        "scheduler's cost model picks per query "
                        "(README 'Placement & degradation')")
    p.add_argument("--input_format",
                   choices=["parquet", "orc", "json", "avro", "raw"],
                   default="parquet")
    p.add_argument("--extra_time_log",
                   help="write a second copy of the CSV time log here "
                        "(`nds/nds_power.py:305-308`)")
    p.add_argument("--json_summary_folder",
                   help="folder for per-query JSON summaries")
    p.add_argument("--output_prefix",
                   help="save each query's result under this directory")
    p.add_argument("--warmup", type=int, default=0,
                   help="untimed runs per query before the timed one")
    p.add_argument("--profile_dir",
                   help="write jax profiler traces for the stream here")
    p.add_argument("--allow_failure", action="store_true",
                   help="exit 0 even when queries failed "
                        "(`nds/nds_power.py:391-393`)")
    p.add_argument("--query_subset", nargs="+",
                   help="run only these query names (e.g. query96)")
    p.add_argument("--floats", action="store_true",
                   help="schema uses doubles instead of decimals")
    p.add_argument("--resume", action="store_true",
                   help="replay completed statements from the run "
                        "dir's query journal and restart mid-stream "
                        "at the next unfinished one (README "
                        "'Preemption & resume')")
    power_core.add_config_args(p)
    args = p.parse_args(argv)
    config = power_core.config_from_args(args)
    if args.floats:
        config.conf["engine.floats"] = "true"
    if args.placement:
        config.conf["engine.placement.force"] = args.placement
    failures = power_core.run_query_stream(
        SUITE, args.data_dir, args.query_stream, args.time_log,
        config=config, input_format=args.input_format,
        json_summary_folder=args.json_summary_folder,
        output_prefix=args.output_prefix, warmup=args.warmup,
        query_subset=args.query_subset, profile_dir=args.profile_dir,
        extra_time_log=args.extra_time_log, resume=args.resume)
    sys.exit(0 if (args.allow_failure or not failures) else 1)


if __name__ == "__main__":
    main()
