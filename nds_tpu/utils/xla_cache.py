"""Persistent XLA compilation cache.

The engine compiles one XLA program per (query, scale factor). First
compiles are expensive (tens of seconds on TPU); the jax persistent
compilation cache amortizes them across processes and across benchmark
rounds — the engine-side analog of the reference's warmed-JVM steady
state (`nds/nds_power.py:184-322` keeps one Spark session across the
whole stream for the same reason).
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".xla_cache")


def enable(cache_dir: str | None = None) -> str:
    """Turn on jax's persistent compilation cache. Idempotent."""
    import jax

    cache_dir = cache_dir or os.environ.get(
        "NDS_TPU_XLA_CACHE", _DEFAULT_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every program: benchmark queries are all worth persisting
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir
