"""Persistent XLA compilation cache.

The engine compiles one XLA program per (query, scale factor). First
compiles are expensive (tens of seconds on TPU); the jax persistent
compilation cache amortizes them across processes and across benchmark
rounds — the engine-side analog of the reference's warmed-JVM steady
state (`nds/nds_power.py:184-322` keeps one Spark session across the
whole stream for the same reason).
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".xla_cache")


def enable(cache_dir: str | None = None) -> str:
    """Turn on jax's persistent compilation cache. Idempotent.

    The cache dir is suffixed by a digest of the XLA_FLAGS in effect:
    jax's cache key EXCLUDES codegen debug options, so an entry
    compiled under different flags would otherwise be served silently
    — observed as "Symbols not found" when the plan cache
    (nds_tpu/cache/) re-serializes an executable a stale entry built
    with parallel-split codegen (cache.ensure_reloadable_codegen pins
    the split count precisely so executables can reload)."""
    import hashlib

    import jax

    cache_dir = cache_dir or os.environ.get(
        "NDS_TPU_XLA_CACHE", _DEFAULT_DIR)
    flags = os.environ.get("XLA_FLAGS", "")
    if flags:
        cache_dir = os.path.join(
            cache_dir,
            "flags-" + hashlib.sha256(flags.encode()).hexdigest()[:10])
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # a prior disable() must not stick
    jax.config.update("jax_enable_compilation_cache", True)
    # cache every program: benchmark queries are all worth persisting
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _drop_memoized_verdict()
    return cache_dir


def disable() -> None:
    """Turn jax's persistent compilation cache OFF (process-wide
    setting). The plan cache (nds_tpu/cache/) requires this: an
    executable jax's cache serves back re-serializes into a blob that
    cannot reload, so plan-cache sessions must see only REAL
    compiles."""
    import jax
    jax.config.update("jax_enable_compilation_cache", False)
    _drop_memoized_verdict()


def _drop_memoized_verdict() -> None:
    """``compilation_cache.is_cache_used`` memoizes its on/off verdict
    at the FIRST compile and then ignores every later
    ``jax_enable_compilation_cache`` update, so an enable()/disable()
    after any compile would silently not take. ``reset_cache()`` drops
    the memo (and the dir-bound cache singleton) so the next compile
    re-reads the config."""
    try:
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except Exception:  # noqa: BLE001 - private API: a jax that moved it
        # presumably also dropped the memoization; the config update
        # above is then sufficient, and session creation must not die
        pass
