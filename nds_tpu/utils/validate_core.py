"""Suite-independent output-diff core (one copy of the reference's
duplicated validators, `nds/nds_validate.py:48-215` /
`nds-h/nds_h_validate.py`): row-count check then per-column compare with
``math.isclose`` epsilon on float columns, canonical order-insensitive
sort, positional column skips, and per-column overrides for documented
nondeterminism carve-outs (q78's rounded-ratio tolerance,
`nds/nds_validate.py:146-190`)."""

from __future__ import annotations

import math
import os

import numpy as np
import pandas as pd

from nds_tpu.io.result_io import read_result


def canon_sort(df: pd.DataFrame) -> pd.DataFrame:
    """Deterministic whole-row sort (floats rounded so epsilon-equal rows
    sort identically on both sides, `nds/nds_validate.py:130-131`)."""
    if not len(df):
        return df
    keys = {}
    for i, c in enumerate(df.columns):
        col = df.iloc[:, i]
        if col.dtype.kind == "f":
            keys[f"k{i}"] = col.round(4)
        else:
            keys[f"k{i}"] = col.astype(str)
    order = pd.DataFrame(keys).sort_values(list(keys)).index
    return df.loc[order].reset_index(drop=True)


def col_equal(a: pd.Series, b: pd.Series, epsilon: float,
              rel_tol: float | None = None) -> bool:
    na, nb = a.isna().to_numpy(), b.isna().to_numpy()
    if not (na == nb).all():
        return False
    a, b = a[~na], b[~nb]
    if a.dtype.kind == "f" or b.dtype.kind == "f":
        fa = pd.to_numeric(a, errors="coerce").to_numpy(dtype=float)
        fb = pd.to_numeric(b, errors="coerce").to_numpy(dtype=float)
        tol = rel_tol if rel_tol is not None else epsilon
        # abs_tol matters when the true value is exactly 0: backends
        # that reduce in a different order leave ulp-scale residues
        # (e.g. 2^-43 from a cumsum-difference group sum) where the
        # oracle computes a literal 0.0, and rel_tol alone rejects ANY
        # nonzero-vs-zero pair no matter the epsilon
        return all(math.isclose(x, y, rel_tol=tol, abs_tol=tol)
                   for x, y in zip(fa, fb))
    return list(a.astype(str)) == list(b.astype(str))


def compare_results(dir1: str, dir2: str, query_name: str,
                    ignore_ordering: bool = True,
                    epsilon: float = 0.00001,
                    skip_columns: dict | None = None,
                    column_rel_tol: dict | None = None) -> bool:
    """Diff one query's saved outputs. skip_columns maps query name ->
    positional column indexes to drop; column_rel_tol maps (query name,
    column index) -> relaxed tolerance."""
    df1 = read_result(os.path.join(dir1, query_name))
    df2 = read_result(os.path.join(dir2, query_name))
    if len(df1) != len(df2):
        print(f"[{query_name}] row count mismatch: "
              f"{len(df1)} vs {len(df2)}")
        return False
    if df1.shape[1] != df2.shape[1]:
        print(f"[{query_name}] column count mismatch: "
              f"{df1.shape[1]} vs {df2.shape[1]}")
        return False
    drop = (skip_columns or {}).get(query_name, [])
    if drop:
        keep = [i for i in range(df1.shape[1]) if i not in drop]
        df1 = df1.iloc[:, keep]
        df2 = df2.iloc[:, keep]
    if ignore_ordering:
        df1 = canon_sort(df1)
        df2 = canon_sort(df2)
    for i in range(df1.shape[1]):
        a = df1.iloc[:, i]
        b = df2.iloc[:, i]
        rel = (column_rel_tol or {}).get((query_name, i))
        if not col_equal(a, b, epsilon, rel):
            print(f"[{query_name}] column {i} ({df1.columns[i]}) differs")
            return False
    return True
