"""Fail-fast precondition guards for CLI drivers.

Plays the role of the reference's `nds/check.py:38-152` / `utils/check.py`
(version gate, path validation, range validation, parallelism validation,
summary-folder guard, query-subset check) for the TPU harness. One shared
copy — the reference's nds/ vs utils/ duplication is deliberately not
reproduced (SURVEY.md §1).
"""

from __future__ import annotations

import os
import sys


class CheckError(ValueError):
    """Raised when a precondition guard fails."""


def check_version(minimum=(3, 9)) -> None:
    """Gate on interpreter version (reference gates on >=3.6; jax needs 3.9+)."""
    if sys.version_info < minimum:
        req = ".".join(str(p) for p in minimum)
        raise CheckError(f"Python {req}+ required, found {sys.version.split()[0]}")


def get_abs_path(path: str) -> str:
    """Expand and absolutize a user-supplied path, requiring existence."""
    p = os.path.abspath(os.path.expanduser(path))
    if not os.path.exists(p):
        raise CheckError(f"path does not exist: {path}")
    return p


def valid_range(value: str, parallel: int) -> tuple[int, int]:
    """Parse an inclusive 'start,end' chunk range for incremental data gen.

    Mirrors the semantics of the reference's ``--range`` option
    (`nds/nds_gen_data.py` valid_range): both ends in [1, parallel],
    start <= end.
    """
    try:
        start_s, end_s = value.split(",")
        start, end = int(start_s), int(end_s)
    except ValueError as e:
        raise CheckError(f"invalid range {value!r}: expected 'start,end'") from e
    if not (1 <= start <= end <= parallel):
        raise CheckError(
            f"invalid range {value!r}: need 1 <= start <= end <= parallel={parallel}")
    return start, end


def parallel_value_type(value: str) -> int:
    """Parallelism must be an int >= 2 (reference: parallel_value_type)."""
    try:
        v = int(value)
    except ValueError as e:
        raise CheckError(f"parallel must be an integer, got {value!r}") from e
    if v < 2:
        raise CheckError(f"parallel must be >= 2, got {v}")
    return v


def check_json_summary_folder(path: str | None) -> None:
    """Require the summary folder, if given, to be absent or an empty dir.

    Same guard as the reference's check_json_summary_folder: refuses to mix
    new per-query JSON summaries with stale ones.
    """
    if not path:
        return
    if os.path.exists(path):
        if not os.path.isdir(path):
            raise CheckError(f"json summary folder is not a directory: {path}")
        if os.listdir(path):
            raise CheckError(f"json summary folder is not empty: {path}")


def check_query_subset_exists(query_dict, subset) -> None:
    """Every requested query name must exist in the parsed stream."""
    missing = [q for q in subset if q not in query_dict]
    if missing:
        raise CheckError(f"queries not found in stream: {missing}")


def get_dir_size(path: str) -> int:
    """Total bytes under a directory tree (used for raw-data size reporting)."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            fp = os.path.join(root, f)
            if os.path.isfile(fp):
                total += os.path.getsize(fp)
    return total
