"""Load-report parsing shared by both suites' orchestrators.

The report is the inter-phase state file of the reference pipeline
(`nds/nds_transcode.py:205-229` writes it; `nds/nds_bench.py:60-89`
reads the load time and RNGSEED back). Parsing is ANCHORED to the
written format — a drifted report raises instead of returning a
silently-wrong number.
"""

from __future__ import annotations

import re

_TOTAL_RE = re.compile(
    r"^Total conversion time for \d+ tables was (?P<secs>[0-9.]+)s\s*$")
_RNGSEED_RE = re.compile(r"^RNGSEED used:\s*(?P<seed>\d+)\s*$")


def get_load_time(report_path: str) -> float:
    """Total load seconds from the report header line (anchored to the
    exact format ``transcode`` writes)."""
    with open(report_path) as f:
        first = f.readline()
    m = _TOTAL_RE.match(first)
    if not m:
        raise ValueError(
            f"load report {report_path} header not recognised: {first!r}")
    return float(m.group("secs"))


def get_rngseed(report_path: str) -> int:
    """The RNGSEED (load-end timestamp) recorded in the report
    (`nds/nds_bench.py:60-74` contract)."""
    with open(report_path) as f:
        for line in f:
            m = _RNGSEED_RE.match(line)
            if m:
                return int(m.group("seed"))
    raise ValueError(f"no RNGSEED in {report_path}")
