"""Suite-independent power-run core.

The reference duplicates its power loop between the NDS and NDS-H suites
(`nds/nds_power.py:184-322`, `nds-h/nds_h_power.py`); SURVEY.md §1 calls
out that the shared layer should be built once — this module is that
single copy. Each suite's driver supplies a ``Suite`` descriptor (schema
getter, stream parser, raw extension) and gets: warehouse registration
with CreateTempView-analog timings, the timed query loop with per-query
JSON summaries and the CSV time log, the ``--allow_failure`` contract
(`nds/nds_power.py:391-393`), warmup handling, and EngineConfig-driven
session construction (template < property file precedence,
`nds/spark-submit-template:24-33` + `nds_power.py:324-330`).

Observability: each query runs inside a root span (nds_tpu/obs) whose
tree — engine compile/execute/materialize and staged sub-programs
included — is attached to the JSON summary (``spans``) together with
the per-query metrics delta (``metrics``); ``NDS_TPU_TRACE=path``
additionally appends every tree to a Chrome trace-event JSONL.

Resilience: every backend now runs through the unified execution
pipeline (``nds_tpu/engine/scheduler.py``) — per query, a cost model
picks the initial placement (single-device / sharded / out-of-core /
CPU), classified transient failures walk a degradation ladder as a
reschedule of that one query, and the pipeline owns the retry policy
(``engine.retry.*`` / ``engine.query_deadline_s``). The per-query
summary records ``retries`` / ``gave_up_reason`` /
``deadline_exceeded`` plus the scheduling decisions: ``placement``,
``reschedules``, ``promoted_back`` (README "Placement &
degradation"). ``engine.fallback=cpu`` survives as an alias forcing
the ladder floor to the CPU oracle. Fault injection context
(``NDS_TPU_FAULTS``) carries the query name — and the stream name
(``NDS_TPU_STREAM``) when a supervisor launched this process as one
throughput stream.

Preemption safety (README "Preemption & resume"): every completed
statement appends to a per-phase QueryJournal (name, wall, status,
result digest — resilience/journal.py) AFTER its summary lands, a
chaining SIGTERM/SIGINT drain (resilience/drain.py) lets the in-flight
query finish under ``engine.drain_s`` before exiting 75 (resumable),
and ``resume=True`` replays journaled statements and restarts
mid-phase at the next unfinished one, then writes a merged phase
report (``merged-<unit>.json``) billing every incarnation's statements
exactly once.

Hang detection (resilience/watchdog.py): the loop publishes heartbeats
(query, phase, attempt) around every dispatch and retry; with
``engine.watchdog.stall_s`` (or ``NDS_TPU_WATCHDOG=stall_s[:action]``)
a daemon watchdog dumps all-thread stacks + live metrics to
``stall-<query>.json`` in the run dir when the heartbeats go silent,
and ``action=kill`` hard-exits so a stream supervisor can restart the
process. The warehouse load runs under the same retry policy and —
with ``io.verify_digests`` — digest verification: a corrupt artifact
fails the load fast, with a diagnosable BenchReport naming the file.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from nds_tpu import obs
from nds_tpu.engine.session import Session
from nds_tpu.obs import costs as obs_costs
from nds_tpu.obs import fleet as obs_fleet
from nds_tpu.obs import memwatch
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs import profile as obs_profile
from nds_tpu.obs import telemetry as obs_telemetry
from nds_tpu.obs import trace as obs_trace
from nds_tpu.obs.trace import get_tracer
from nds_tpu.resilience import drain, faults, watchdog
from nds_tpu.resilience.journal import QueryJournal, config_digest
from nds_tpu.resilience.retry import (
    DETERMINISTIC, TRANSIENT, RetryPolicy, RetryStats, classify,
)
from nds_tpu.utils.config import EngineConfig
from nds_tpu.utils.report import BenchReport
from nds_tpu.utils.timelog import TimeLog


def _front_door_retry(policy, pipeline, unit, qname, body):
    """Retry TRANSIENT failures that never reached the pipeline
    (parse/plan phase — the executor-phase retry + ladder live inside
    engine/scheduler.py): a plan-site chaos injection or a flaky
    catalog read retries with the same backoff policy, a deterministic
    planner bug fails fast. Accounting merges into the pipeline's
    per-query stats so the summary reports ONE recovery budget."""
    from nds_tpu.obs import metrics as obs_metrics
    attempts = 0
    front_retries = 0
    front_backoff = 0.0
    start = time.monotonic()

    def _merge(st):
        if st is not None:
            st.retries += front_retries
            st.backoff_s += front_backoff

    def _flag_deadline(st):
        if st is not None and not st.deadline_exceeded:
            st.deadline_exceeded = True
            obs_metrics.counter("query_deadline_exceeded_total").inc()

    # ndslint: waive[NDS108] -- capped (attempts >= policy.max_attempts raises) with policy.delay_for backoff; while-True only because the cap check needs the classified exception first
    while True:
        try:
            out = body()
        except Exception as exc:  # noqa: BLE001 - classified below
            st = getattr(pipeline, "last_stats", None)
            pre_dispatch = (st is not None and st.attempts == 0
                            and not st.gave_up_reason)
            if not pre_dispatch:
                # the pipeline saw this query: its classification and
                # ladder already ran — nothing to add but the bill
                _merge(st)
                raise
            attempts += 1
            st.errors.append(f"{type(exc).__name__}: {exc}")
            if classify(exc) != TRANSIENT:
                st.gave_up_reason = DETERMINISTIC
                _merge(st)
                raise
            if attempts >= policy.max_attempts:
                st.gave_up_reason = f"attempts_exhausted({attempts})"
                _merge(st)
                raise
            d = policy.delay_for(front_retries)
            if (policy.deadline_s is not None
                    and time.monotonic() - start + d
                    > policy.deadline_s):
                # same pre-sleep deadline check policy.call enforces:
                # the plan window must not back off past the query's
                # wall-clock budget
                st.gave_up_reason = "deadline"
                _flag_deadline(st)
                _merge(st)
                raise
            front_retries += 1
            front_backoff += d
            obs_metrics.counter("query_retries_total").inc()
            watchdog.beat(unit, query=qname, phase="retry",
                          attempt=front_retries)
            if d > 0:
                time.sleep(d)
            continue
        _merge(getattr(pipeline, "last_stats", None))
        return out


@dataclass
class Suite:
    """What a benchmark suite must provide to the shared drivers."""
    name: str                      # "nds" | "nds_h"
    get_schemas: object            # callable(**kw) -> {table: Schema}
    parse_query_stream: object     # callable(path) -> OrderedDict
    session_for: object            # callable(factory, **kw) -> Session
    raw_ext: str = ".tbl"          # dbgen .tbl / dsdgen .dat
    # query names whose warmup is skipped (stateful parts, e.g. q15 view
    # lifecycle in NDS-H)
    warmup_skip_prefixes: tuple = ()
    schema_kwargs: dict = field(default_factory=dict)
    # suite honors the --floats/engine.floats toggle (NDS decimal vs
    # double schemas, `nds/nds_schema.py:43-47`)
    floats_toggle: bool = False


def schema_kwargs_for(suite: Suite, config: EngineConfig) -> dict:
    kwargs = dict(suite.schema_kwargs)
    if suite.floats_toggle:
        kwargs["use_decimal"] = not config.get_bool("engine.floats")
    return kwargs


def suite_schemas(suite: Suite, config: EngineConfig) -> dict:
    """Config-aware schemas — table LOADING must agree with the session
    catalog on decimal-vs-float, or money columns load as scaled ints
    under a float catalog."""
    return suite.get_schemas(**schema_kwargs_for(suite, config))


def prepare_engine(config: EngineConfig) -> None:
    """Engine-wide activation shared by every session-construction
    path (the power drivers' make_session and the query server's
    QueryServer._build_engine): plan-cache configuration plus the
    plan-cache/XLA-compile-cache interplay the backend requires."""
    backend = config.get("engine.backend", "cpu")
    # columnar.encode/columnar.dict_union_cap activate the compressed
    # device-resident store (nds_tpu/columnar/; README "Compressed
    # columnar store"); configs without the keys defer to
    # NDS_TPU_COLUMNAR, and `off` keeps byte-identical raw behavior
    from nds_tpu import columnar
    columnar.configure_from(config)
    # cache.dir/cache.readonly activate the persistent AOT plan cache
    # for every executor this session schedules (README "Plan cache");
    # configs without the keys leave the NDS_TPU_PLAN_CACHE env
    # resolution in charge
    from nds_tpu import cache as plan_cache
    active_cache = plan_cache.configure_from(config)
    if backend in ("tpu", "distributed"):
        from nds_tpu.utils import xla_cache
        multiproc = False
        if backend == "distributed":
            # idempotent (session construction calls it again); needed
            # NOW because the cache decision below depends on world
            # size, which only exists after the runtime initializes
            from nds_tpu.parallel import multihost
            multiproc = multihost.maybe_initialize()
        if active_cache is None or multiproc or active_cache.readonly:
            # compiles amortize across driver invocations (same cache
            # bench.py uses); harmless for repeated in-process queries.
            # Multi-rank worlds keep this EVEN with a plan cache: the
            # plan cache refuses multi-controller sharded programs
            # (per-rank deserialization against a local client is not
            # a supported jax path), so jax's own cache is the only
            # compile amortization those programs get. READONLY plan
            # caches keep it too: their misses never persist (the
            # reloadability hazard below only bites blobs we write),
            # so without jax's cache every miss would pay a full
            # compile on every process start
            xla_cache.enable()
        else:
            # NOT layered under the plan cache: an executable jax's
            # compile cache serves back re-serializes into a blob that
            # cannot reload ("Symbols not found" on XLA:CPU), so a
            # plan-cache session must see only REAL compiles — and a
            # prior session's enable() is process-sticky, so disable
            # explicitly
            xla_cache.disable()
    elif backend != "cpu":
        raise ValueError(f"unknown engine.backend {backend!r}")


def make_session(suite: Suite, config: EngineConfig) -> Session:
    """Session from an EngineConfig — the template/property-file layer
    actually driving engine choice (closes the reference's
    spark-submit-template contract). EVERY backend routes through the
    unified execution pipeline (engine/scheduler.py): the backend picks
    the placement *universe* (tpu -> device/chunked/cpu, distributed ->
    sharded/chunked/cpu, cpu -> cpu), and the pipeline's cost model +
    degradation ladder schedule each query within it."""
    backend = config.get("engine.backend", "cpu")
    kwargs = schema_kwargs_for(suite, config)
    prepare_engine(config)
    from nds_tpu.engine.scheduler import make_pipeline
    return suite.session_for(make_pipeline(config, backend), **kwargs)


def load_warehouse(suite: Suite, session: Session, data_dir: str,
                   fmt: str = "parquet",
                   tables: list[str] | None = None,
                   schemas: dict | None = None) -> dict:
    """Register every table from a warehouse directory; returns
    {table: seconds} setup timings (the CreateTempView analog,
    `nds/nds_power.py:95-105`)."""
    from nds_tpu.io import csv_io
    from nds_tpu.io.snapshots import MANIFEST, SnapshotLog
    if schemas is None:
        schemas = suite.get_schemas(**suite.schema_kwargs)
    log = (SnapshotLog(data_dir)
           if os.path.exists(os.path.join(data_dir, MANIFEST)) else None)
    timings = {}
    for name, schema in schemas.items():
        if tables is not None and name not in tables:
            continue
        # per-table liveness: a multi-minute warehouse load must not
        # read as a hang to the watchdog (resilience/watchdog.py)
        watchdog.beat("engine", phase="load_warehouse", table=name)
        t0 = time.perf_counter()
        tdir = os.path.join(data_dir, name)
        if fmt in csv_io.FORMAT_EXT:
            ext = csv_io.FORMAT_EXT[fmt]
            if log is not None and os.path.isdir(tdir):
                # versioned warehouse: the snapshot manifest names the
                # live files (maintenance commits new versions, always
                # as parquet — formats may mix, so read per-extension).
                # Delta lineages (files under <table>/_v<N>/) replay
                # through columnar.delta: base files load normally,
                # then each committed version's segments/bitmask apply
                # in order — rebuilding the same content digests and
                # merged-stats encoding specs the writer had
                paths = log.current([name]).get(name, [])
                from nds_tpu.columnar import delta
                if delta.has_delta_paths(paths):
                    table = delta.load_versioned(name, schema, paths,
                                                 fmt)
                else:
                    table = csv_io.read_paths_auto(paths, name, schema,
                                                   fmt)
                session.register_table(table)
                timings[name] = time.perf_counter() - t0
                continue
            elif os.path.isdir(tdir):
                # recursive: partitioned tables nest hive-style dirs
                paths = sorted(
                    os.path.join(root, f)
                    for root, _dirs, files in os.walk(tdir)
                    for f in files if f.endswith(ext))
            else:
                paths = [os.path.join(data_dir, f"{name}{ext}")]
            table = csv_io.read_table_fmt(paths, name, schema, fmt)
        elif fmt == "raw":
            if os.path.isdir(tdir):
                from nds_tpu.io.integrity import MANIFEST_NAME
                paths = sorted(
                    os.path.join(tdir, f) for f in os.listdir(tdir)
                    if not f.startswith(".") and f != MANIFEST_NAME)
            else:
                paths = [os.path.join(data_dir, f"{name}{suite.raw_ext}")]
            table = csv_io.read_tbl(paths, name, schema)
        else:
            raise ValueError(f"unknown input format {fmt!r}")
        session.register_table(table)
        timings[name] = time.perf_counter() - t0
    return timings


def run_one_query(session: Session, sql: str, qname: str = "",
                  output_prefix: str | None = None):
    result = session.sql(sql)
    if result is not None and output_prefix:
        from nds_tpu.io.result_io import write_result
        write_result(result, os.path.join(output_prefix, qname))
    return result


def run_query_stream(suite: Suite, data_dir: str, stream_path: str,
                     time_log_path: str,
                     config: EngineConfig | None = None,
                     input_format: str = "parquet",
                     json_summary_folder: str | None = None,
                     output_prefix: str | None = None,
                     warmup: int = 0,
                     query_subset: list[str] | None = None,
                     profile_dir: str | None = None,
                     extra_time_log: str | None = None,
                     resume: bool = False) -> int:
    """The power loop (`nds/nds_power.py:184-322`): every query runs
    regardless of earlier failures (the reference never aborts
    mid-stream; ``--allow_failure`` only downgrades the exit code,
    `nds/nds_power.py:391-393` — handled by the driver mains). Returns
    the number of failed queries.

    With ``NDS_TPU_METRICS_SNAP=path[:interval]`` set, a snapshot
    emitter (nds_tpu/obs/snapshot.py) publishes the metrics registry +
    run progress + heartbeat ages periodically while the stream runs,
    so long runs are observable in flight, not only post-mortem.

    Preemption safety (README "Preemption & resume"): every completed
    statement appends to a per-phase query journal, a SIGTERM/SIGINT
    drains gracefully (the in-flight query finishes under
    ``engine.drain_s``, then the process exits 75 = resumable), and
    ``resume=True`` replays journaled statements and restarts at the
    next unfinished one — an interruption loses at most the one
    in-flight query."""
    from contextlib import nullcontext

    from nds_tpu.obs.snapshot import MetricsSnapshotter
    config = config or EngineConfig()
    progress = {"suite": suite.name, "stream": stream_path,
                "queries_completed": 0, "current_query": None}
    snap = MetricsSnapshotter.from_env(progress)
    if snap:
        snap.start()
    # live device-memory telemetry (obs/telemetry.py): a no-op sampler
    # on backends without allocator stats; per-query readout happens in
    # the query loop, counter lanes export next to the span trees
    obs_telemetry.start_from_config(config)
    # compiler cost ledger on/off (obs.costs.enabled, default on)
    obs_costs.configure_from(config)
    # hang watchdog: stall reports land next to the run's artifacts
    run_dir = (json_summary_folder
               or os.path.dirname(time_log_path) or ".")
    wd = (watchdog.Watchdog.from_config(config, run_dir)
          or watchdog.Watchdog.from_env(run_dir))
    if wd:
        wd.start()
    # graceful preemption drain (resilience/drain.py): SIGTERM/SIGINT
    # lets the in-flight query finish under engine.drain_s, flushes
    # journal/trace/flight/snapshot state, and exits 75 (resumable)
    dm = drain.install(drain.drain_seconds(config), run_dir)
    if snap:
        # the force-exit path skips every finally: the final snapshot
        # must be flushed explicitly
        dm.add_flush_hook(snap.write_once)
    # supervised throughput streams carry their stream name into the
    # fault-injection context, so seeded chaos schedules can target
    # one stream (and one incarnation) of a fleet
    stream_name = os.environ.get(watchdog.STREAM_ENV)
    ctx = (faults.context(stream=stream_name) if stream_name
           else nullcontext())
    try:
        with ctx:
            return _run_query_stream(
                suite, data_dir, stream_path, time_log_path, config,
                input_format, json_summary_folder, output_prefix,
                warmup, query_subset, profile_dir, extra_time_log,
                progress, resume)
    finally:
        drain.uninstall()
        if wd:
            wd.stop()
        watchdog.clear_unit(stream_name or f"power-{suite.name}")
        # fleet teardown: the next run in this process re-arms its own
        # flight recorder / profiler against its own run dir
        obs_fleet.disarm_flight_recorder()
        obs_profile.teardown()
        obs_telemetry.stop()
        if snap:
            progress["current_query"] = None
            snap.stop()


def _run_query_stream(suite, data_dir, stream_path, time_log_path,
                      config, input_format, json_summary_folder,
                      output_prefix, warmup, query_subset, profile_dir,
                      extra_time_log, progress, resume=False) -> int:
    config = config or EngineConfig()
    if config.get_bool("io.verify_digests"):
        # sticky per process, like the env-var gate it mirrors: every
        # later read in this run verifies too (resume, maintenance)
        from nds_tpu.io import integrity
        integrity.set_verify(True)
    unit = (os.environ.get(watchdog.STREAM_ENV)
            or f"power-{suite.name}")
    run_dir_early = (json_summary_folder
                     or os.path.dirname(time_log_path) or ".")
    # query-granular resume journal (resilience/journal.py): one file
    # per phase, named by the stream unit with any restart-incarnation
    # suffix stripped (every incarnation of one stream shares a
    # journal). Fresh runs reset it; --resume replays it. Created here,
    # activated (reset/load) once the primary rank is known below.
    jname = unit.split("#")[0]
    os.makedirs(run_dir_early, exist_ok=True)
    journal = QueryJournal(
        os.path.join(run_dir_early, f"{jname}_queries.json"),
        phase=jname, digest=config_digest(config.as_dict()))
    session = make_session(suite, config)
    backend = config.get("engine.backend", "cpu")
    # multi-controller SPMD: every process computes every query, rank 0
    # records (reports/time logs/result files would otherwise collide
    # on shared storage)
    primary = True
    if backend == "distributed":
        from nds_tpu.parallel.multihost import is_primary
        primary = is_primary()
    run_dir = (json_summary_folder
               or os.path.dirname(time_log_path) or ".")
    # fleet wiring (obs/fleet.py): on a multi-rank world this runs the
    # clock handshake (every rank enters — the session above already
    # initialized the SPMD runtime), re-points NDS_TPU_TRACE at this
    # rank's trace-r<rank> shard, pins the Chrome export pid to the
    # rank, and drops the fleet-r<rank>.json sidecar ndsreport's merge
    # reads; single-rank worlds only pin the deterministic stream pid
    fleet_meta = obs_fleet.init_fleet(run_dir,
                                      distributed=(backend
                                                   == "distributed"))
    if fleet_meta and fleet_meta.get("rank"):
        # rank-0-writes holds for ANY multi-rank world, not only the
        # distributed backend: a fleet of rank-local sessions (each
        # rank executing on its own devices) still shares the run dir
        primary = False
    # activate the journal now that the primary rank is known:
    # non-primary ranks LOAD it (their replay decisions must match the
    # primary's) but never write the shared file. A supervisor-
    # relaunched incarnation (unit '<name>#rN' — restart OR exit-75
    # resume) implicitly resumes the journal too: its --query_subset
    # already scopes what re-runs, and a reset here would wipe the
    # first incarnation's completion records (digests, start marks —
    # exactly the evidence the journal exists to preserve)
    journal.readonly = not primary
    if resume or "#r" in unit:
        if journal.load():
            inc = journal.begin_incarnation()
            done = sorted(journal.completed())
            print(f"== resuming {jname} (incarnation {inc}): "
                  f"{len(done)} journaled quer"
                  f"{'y' if len(done) == 1 else 'ies'} replayed ==")
    else:
        journal.reset()
    dm = drain.manager()
    if dm is not None:
        # drain-deadline force exit: the abandoned in-flight query is
        # journaled explicitly not-done before the process dies
        dm.add_flush_hook(
            lambda: journal.mark_aborted(progress.get("current_query")))
    flight = obs_fleet.arm_flight_recorder(
        run_dir, rank=(fleet_meta or {}).get("rank", 0))
    # on-demand XLA profiler (obs/profile.py): trigger policy from
    # engine.profile.* / NDS_TPU_PROFILE; also arms the on-stall
    # capture hook the watchdog report points at
    profiler = obs_profile.configure(config)
    app_id = f"{suite.name}-tpu-{backend}-{int(time.time())}"
    tlog = TimeLog(app_id)
    total_start = time.perf_counter()

    # the warehouse load runs under the SAME retry policy shape as
    # queries — transient io hiccups retry, a CorruptArtifact (digest
    # mismatch, io/integrity.py) is deterministic and fails the run
    # FAST with a BenchReport naming the file and both digests,
    # retries=0 — but NOT under the per-QUERY deadline (a 25-table
    # load is not a query). Built by the pipeline module, the single
    # home of the engine retry wiring.
    from nds_tpu.engine.scheduler import load_policy as _mk_load_policy
    front_policy = RetryPolicy.from_config(config)
    load_policy = _mk_load_policy(front_policy)
    watchdog.beat(unit, phase="load_warehouse")
    lstats = RetryStats()
    load_hold: dict = {}

    def _load_bracket():
        def _load():
            return load_warehouse(suite, session, data_dir,
                                  input_format,
                                  schemas=suite_schemas(suite, config))
        try:
            load_hold["setup"] = load_policy.call(_load, stats=lstats)
        except Exception as exc:  # noqa: BLE001 - re-raised below
            load_hold["error"] = exc
            raise

    load_report = BenchReport("load_warehouse", config.as_dict())
    load_report.report_on(_load_bracket)
    load_report.attach_retry(lstats)
    load_report.attach_degradations()
    if "error" in load_hold:
        # post-mortem before the raise: a CorruptArtifact (or any
        # final load failure) dumps the flight ring so the run leaves
        # metrics + heartbeats even though no query ever ran
        if flight:
            err = load_hold["error"]
            fpath = flight.dump(
                f"load-failed:{type(err).__name__}")
            load_report.attach_flight(fpath,
                                      reason=f"{type(err).__name__}",
                                      entries=len(flight.ring))
        if json_summary_folder and primary:
            os.makedirs(json_summary_folder, exist_ok=True)
            load_report.write_summary(prefix=f"power-{app_id}",
                                      out_dir=json_summary_folder)
        raise load_hold["error"]
    setup = load_hold["setup"]
    for tname, secs in setup.items():
        tlog.add(f"CreateTempView {tname}", int(secs * 1000))

    queries = suite.parse_query_stream(stream_path)
    if query_subset:
        queries = type(queries)(
            (q, s) for q, s in queries.items() if q in query_subset)
    progress["app_id"] = app_id
    progress["queries_total"] = len(queries)
    if json_summary_folder:
        os.makedirs(json_summary_folder, exist_ok=True)
    # device-level traces for the whole stream (XLA op timeline per
    # query via named TraceAnnotations) — the jax-profiler analog of
    # the reference's setJobGroup Spark-UI hook; begin/end live in
    # obs/profile.py (NDS113: the engine's one jax.profiler owner),
    # and the outer finally's obs_profile.teardown() closes the trace
    # even when an exception carries past this loop
    from contextlib import nullcontext
    if profile_dir and profiler:
        # single-active-trace invariant: with the whole stream under
        # capture, every per-query/stall trigger would fail to start —
        # and a stall report would publish a capture path that could
        # never be filled. Explicitly one or the other, decided BEFORE
        # the stream trace starts (no junk capture from a start/stop/
        # restart dance).
        print("[obs] --profile_dir stream trace active: per-query/"
              "stall profile triggers disabled for this run")
        obs_profile.teardown()
        profiler = None
    stream_prof = obs_profile.begin_stream_trace(profile_dir)
    failures = 0
    replayed_ms = 0.0
    power_start = time.perf_counter()
    # query-boundary pipelining (engine/pipeline_io.py; README
    # "Pipelined execution"): with ``engine.prefetch.boundary`` on,
    # query N+1 dispatches while query N's compactor output is still
    # in flight D2H — the async handle's result() is the sync point,
    # and each query's bracket is its dispatch-start -> result-done
    # window (the same dispatch->result wall contract the in-process
    # throughput loop already bills pipelined queries under)
    from nds_tpu.engine import pipeline_io
    boundary = pipeline_io.boundary_enabled(config)
    tracer = get_tracer()
    pending: "dict | None" = None
    # per-query metric windows partition at finalize boundaries in
    # pipelined mode (query N's dispatch-side counters bill to N-1's
    # window; the per-run totals stay exact — README "Pipelined
    # execution"); None = fresh snapshot at the next dispatch
    mbase: "dict | None" = None

    def _resolve(p) -> None:
        """Blocking half of one dispatched query: result() is the sync
        point; failures bill to THIS query's bracket exactly as
        report_on's except-clause did."""
        err = p.pop("dispatch_error", None)
        if err is None:
            try:
                with tracer.attach(p["span"]), \
                        faults.context(query=p["qname"]), \
                        p["report"].focus_failures():
                    out = p["handle"].result()
                p["result"] = out
                if out is not None and p["out_pref"]:
                    from nds_tpu.io.result_io import write_result
                    write_result(out, os.path.join(p["out_pref"],
                                                   p["qname"]))
            except Exception as exc:  # noqa: BLE001 - billed below
                err = exc
        span = p["span"]
        if span:
            if err is not None:
                span.set(error=f"{type(err).__name__}: {err}")
            span.end()
        p["summary"] = p["report"].end_async(error=err)

    def _post(p) -> None:
        """Everything that used to follow the report bracket: summary
        attachments, metrics delta, flight/profiler bookkeeping, the
        TimeLog row, the summary write, and the journal append."""
        nonlocal failures, mbase
        qname = p["qname"]
        report, summary = p["report"], p["summary"]
        # engine-side perf accounting: compile vs execute vs
        # device->host materialization, fed by the query span tree
        # (obs.query_timings falls back to legacy last_timings; the
        # CPU oracle has neither). The pipeline's async handles
        # re-point the per-query obs surface at result(), so this
        # reads THIS query's numbers even under boundary overlap
        executor = session._executor_factory(session.tables)
        timings = obs.query_timings(executor)
        if timings:
            # dunder keys are executor-internal accounting state (the
            # memwatch release token), never part of the summary
            summary["engineTimings"] = {k: round(v, 3)
                                        for k, v in timings.items()
                                        if not k.startswith("__")}
        if p["span"]:
            summary["spans"] = p["span"].to_dict()
        # the pipeline owns retry + scheduling accounting; a bare
        # executor factory (tests driving run_query_stream with a
        # custom session) degrades to empty stats
        report.attach_retry(getattr(executor, "last_stats", None)
                            or RetryStats())
        report.attach_schedule(getattr(executor, "last_schedule",
                                       None))
        report.attach_memory(p.get("hwm") if p.get("hwm") is not None
                             else memwatch.high_water())
        # compiler-truth cost ledger + HBM-occupancy series (the
        # overlapped path snapshotted both at the successor's reset;
        # the sync path reads the live windows here), cross-checked
        # against the hand-rolled ops_est roofline input
        cost_block = (p.get("cost") if p.get("cost") is not None
                      else obs_costs.query_block())
        report.attach_cost(obs_costs.cross_check(
            cost_block, (timings or {}).get("ops_est")))
        report.attach_telemetry(
            p.get("telemetry") if p.get("telemetry") is not None
            else obs_telemetry.query_block())
        # resume bookkeeping: which incarnation served this query, the
        # result's content digest (what the soak gate diffs against a
        # clean run), and any torn-state degradations this process saw
        report.attach_incarnation(journal.incarnation)
        from nds_tpu.io.result_io import result_digest
        rdigest = result_digest(p.pop("result", None))
        report.attach_result_digest(rdigest)
        report.attach_degradations()
        elapsed_ms = summary["queryTimes"][-1]
        obs_metrics.counter("queries_total").inc()
        obs_metrics.histogram("query_seconds").observe(
            elapsed_ms / 1000.0)
        if not report.is_success():
            failures += 1
            obs_metrics.counter("query_failures_total").inc()
        before = (p["metrics_before"] if p["metrics_before"] is not None
                  else mbase) or obs_metrics.snapshot()
        mdelta = obs_metrics.delta(before, obs_metrics.snapshot())
        if mdelta:
            summary["metrics"] = mdelta
        # plan-cache activity for THIS query (hits/misses/bytes +
        # deserialize ms), derived from the same metrics delta
        report.attach_cache(mdelta, timings)
        # which relational kernels the compiled program actually used
        # (engine/kernels.py): the block ndsreport diff watches for
        # silent demotions to the slow paths. Read from the executor's
        # own dict — the span-fed timings strip dunder side-channels
        report.attach_kernels(getattr(executor, "last_timings", None)
                              or timings)
        # XLA capture bookkeeping: the profile block when a trigger
        # fired, and the wall-clock observation arming the slow
        # trigger for this query's NEXT run
        if p.get("cap_info"):
            report.attach_profile(p["cap_info"])
        elif p.get("stall_path") and profiler:
            # the drained reservation's capture never started: put it
            # back so a later query can still fill the stall report's
            # forward pointer
            profiler.requeue_pending(p["stall_path"])
        if profiler:
            profiler.observe(qname, elapsed_ms)
        # flight recorder (obs/fleet.py): the ring holds the last N
        # span trees; a FINAL-attempt failure dumps it so the failed
        # query's summary points at a post-mortem
        if flight:
            flight.record(qname, summary["queryStatus"][-1],
                          p.get("span"), wall_ms=elapsed_ms,
                          metrics_delta=mdelta)
            if summary["queryStatus"][-1] == "Failed":
                fpath = flight.dump(f"query-failed:{qname}")
                report.attach_flight(
                    fpath, reason=f"query-failed:{qname}",
                    entries=len(flight.ring))
        tlog.add(qname, elapsed_ms)
        progress["queries_completed"] += 1
        watchdog.beat(unit, query=qname, phase="done")
        print(f"====== Run {qname} ======")
        print(f"Time taken: {elapsed_ms} millis for {qname}")
        if json_summary_folder and primary:
            report.write_summary(prefix=f"power-{app_id}",
                                 out_dir=json_summary_folder)
        # journal AFTER the summary landed: resume must never skip a
        # statement whose summary is missing (the one-query loss window
        # is between this append and the previous instruction)
        journal.record(qname, elapsed_ms, summary["queryStatus"][-1],
                       result_digest=rdigest)
        # exports parked during the bracket flush now; the metric
        # window for the NEXT pipelined query starts here
        tracer.flush_exports()
        # device-memory counter lanes ride the same trace stream as
        # the spans: telemetry samples since the last drain, plus one
        # per-query HWM point — Perfetto renders them as memory tracks
        trace_path = os.environ.get(obs_trace.TRACE_ENV)
        if trace_path:
            events = [obs_trace.counter_event(
                "device_memory_bytes", {"bytes_in_use": b}, t=t)
                for t, b in obs_telemetry.drain_counter_events()]
            hwm_bytes = (summary.get("memory")
                         or {}).get("device_hwm_bytes")
            if hwm_bytes:
                events.append(obs_trace.counter_event(
                    "device_hwm_bytes", {"hwm": hwm_bytes}))
            try:
                obs_trace.export_counters(events, trace_path)
            except OSError:  # tracing must never fail the query
                pass
        mbase = obs_metrics.snapshot()

    def _finalize_pending() -> None:
        nonlocal pending
        if pending is None:
            return
        p, pending = pending, None
        _resolve(p)
        _post(p)

    # exports park while query brackets are open (even a ~ms inline
    # write would skew span totals vs the TimeLog row); _post flushes
    # after each bracket closes
    tracer.defer_exports = True
    try:
        for qname, sql in queries.items():
            watchdog.beat(unit, query=qname, phase="dispatch")
            # preemption drain checkpoint: once a SIGTERM/SIGINT was
            # seen, stop HERE — the finished queries (the overlapped
            # in-flight one resolves first, so the journal stays
            # consistent) are journaled, the process exits 75, and
            # --resume picks up at this statement
            if drain.requested():
                _finalize_pending()
            drain.check_boundary()
            if journal.done(qname):
                # resumed incarnation: replay the journaled outcome
                # (time log row + failure accounting) so the merged
                # phase totals match an uninterrupted run — never
                # re-execute
                e = journal.entry(qname)
                wall = float(e.get("wall_ms") or 0)
                replayed_ms += wall
                tlog.add(qname, int(wall))
                if e.get("status") == "Failed":
                    failures += 1
                progress["queries_completed"] += 1
                print(f"====== Replay {qname} (journaled "
                      f"{e.get('status')}, incarnation "
                      f"{e.get('incarnation', 0)}) ======")
                continue
            if warmup and not qname.startswith(
                    suite.warmup_skip_prefixes):
                # warmup executes synchronously through the session:
                # resolve any overlapped query first. Span recording
                # off during warmup: untimed passes would otherwise
                # append orphan root trees to the Chrome trace,
                # uncorrelated with any CSV row. Fault injection is
                # suppressed too — warmup must not consume the timed
                # query's fault budget
                _finalize_pending()
                wtracer = get_tracer()
                was_enabled = wtracer.enabled
                wtracer.enabled = False
                try:
                    with faults.suppress():
                        for _ in range(warmup):
                            try:
                                run_one_query(session, sql)
                            except Exception:
                                break
                finally:
                    wtracer.enabled = was_enabled
                mbase = None  # warmup counters are nobody's delta
            progress["current_query"] = qname
            # execution-start mark BEFORE dispatch: a kill -9 mid-query
            # leaves a start with no completion — the journal evidence
            # that exactly this one query was lost (under boundary
            # overlap: at most the TWO in-flight queries)
            journal.start(qname)
            # per-query XLA capture triggers force the sync path: a
            # capture bracket cannot span overlapped brackets
            trigger = profiler.trigger_for(qname) if profiler else None
            stall_path = profiler.take_pending() if profiler else None
            run_sync = (not boundary or bool(trigger)
                        or bool(stall_path) or bool(stream_prof))
            if run_sync:
                _finalize_pending()
            # fresh per-query memory/cost/telemetry windows: each is
            # monotone within the query and resets here; an overlapped
            # predecessor's readings snapshot into its record first
            # (the reset precedes this query's dispatch AND the
            # predecessor's _post, so dispatches land in the fresh
            # window and _post reads the snapshot)
            if pending is not None:
                pending["hwm"] = memwatch.high_water()
                pending["cost"] = obs_costs.query_block()
                pending["telemetry"] = obs_telemetry.query_block()
            memwatch.reset_query()
            obs_costs.reset_query()
            obs_telemetry.reset_query()
            report = BenchReport(qname, config.as_dict())
            out_pref = output_prefix if primary else None
            # a query that fails BEFORE reaching the executor
            # (parse/plan errors) must not inherit the previous
            # query's span/timings/stats into its summary — the
            # pipeline's reset covers exactly that window (an
            # overlapped predecessor's handle re-points the surface
            # back at resolve time)
            pre_ex = session._executor_factory(session.tables)
            if hasattr(pre_ex, "reset_query"):
                pre_ex.reset_query()
            else:
                pre_ex.last_query_span = None
                pre_ex.last_timings = {}
            # pipelined queries take their metric window from the
            # previous finalize (partition — no double counting);
            # sync queries snapshot here, exactly as before
            metrics_before = (obs_metrics.snapshot()
                              if run_sync or pending is None else None)
            # per-query root span: brackets EXACTLY what queryTimes/
            # TimeLog brackets (begin_async -> end_async), so span
            # totals and the CSV agree; forced root — under overlap
            # the next dispatch must not nest inside it
            qspan = tracer.begin("query", parent=None, query=qname,
                                 suite=suite.name, backend=backend)
            p = {"qname": qname, "report": report, "span": qspan,
                 "out_pref": out_pref, "metrics_before": metrics_before,
                 "hwm": None, "cost": None, "telemetry": None,
                 "stall_path": stall_path}
            report.begin_async()

            def _dispatch(_p=p, _sql=sql, _ex=pre_ex):
                # retry + the degradation ladder live INSIDE the
                # pipeline and surface at the handle (dispatch-time
                # transients rerun there; result-time transients rerun
                # at result()); _front_door_retry covers only the
                # pre-dispatch (parse/plan) window the pipeline cannot
                # see
                try:
                    with tracer.attach(_p["span"]), \
                            faults.context(query=_p["qname"]), \
                            _p["report"].focus_failures():
                        _p["handle"] = _front_door_retry(
                            front_policy, _ex, unit, _p["qname"],
                            lambda: session.sql_async(_sql))
                except Exception as exc:  # noqa: BLE001 - billed later
                    _p["dispatch_error"] = exc

            if run_sync:
                if trigger or stall_path:
                    # a stall reservation drains into THIS query's
                    # capture — into the reserved path (the stall
                    # report already points there), under the query's
                    # own trigger when it has one
                    cap_cm = profiler.capture(qname, trigger or "stall",
                                              path=stall_path)
                else:
                    cap_cm = nullcontext({})
                with cap_cm as cap_info:
                    if stream_prof:
                        with obs_profile.annotate(qname):
                            _dispatch()
                            _resolve(p)
                    else:
                        _dispatch()
                        _resolve(p)
                p["cap_info"] = cap_info
                _post(p)
            else:
                # the overlap: dispatch THIS query, then resolve the
                # previous one while this one's device work (and D2H)
                # is in flight
                _dispatch()
                _finalize_pending()
                pending = p
        _finalize_pending()
    finally:
        tracer.defer_exports = False
        if pending is not None:
            # exceptional unwind with a query still in flight: resolve
            # best-effort so neither the handle nor the journal strand
            try:
                _finalize_pending()
            except BaseException:  # noqa: BLE001 - already unwinding
                pending = None
        tracer.flush_exports()
    obs_profile.end_stream_trace()
    # resumed incarnations bill the replayed queries' journaled walls
    # into the phase total: the merged Power Test Time approximates the
    # uninterrupted loop (per-query walls, minus inter-query overhead)
    power_ms = int((time.perf_counter() - power_start) * 1000
                   + replayed_ms)
    tlog.add("Power Test Time", power_ms)
    total_ms = int((time.perf_counter() - total_start) * 1000)
    tlog.add("Total Time", total_ms)
    if primary:
        tlog.write(time_log_path)
        if extra_time_log:
            # second copy of the time log, e.g. on shared storage — the
            # reference's --extra_time_log writes the same rows via
            # Spark to a cloud path (`nds/nds_power.py:305-308`)
            tlog.write(extra_time_log)
    if journal.incarnation > 0 and primary and json_summary_folder:
        # one merged phase report over every incarnation's partial
        # BenchReports (utils/report.merge_incarnations): each
        # statement billed once, latest incarnation wins — the doc the
        # soak gate and downstream metric consumers read instead of
        # stitching incarnations themselves
        from nds_tpu.io.integrity import write_json_atomic
        from nds_tpu.obs import analyze as _analyze
        from nds_tpu.utils.report import merge_incarnations
        known = set(queries)
        merged = merge_incarnations(
            [s for s in _analyze.load_summaries(json_summary_folder)
             if s.get("query") in known], phase=jname)
        write_json_atomic(
            os.path.join(json_summary_folder, f"merged-{jname}.json"),
            merged)
    print(f"Power Test Time: {power_ms} millis")
    return failures


def subprocess_env(backend: str | None = None) -> dict:
    """Environment for phase subprocesses: nds_tpu importable regardless
    of the orchestrator's cwd (preserving the ambient PYTHONPATH — the
    TPU plugin's site dir may live there).

    A cpu-backend subprocess additionally pins NDS_TPU_PLATFORM=cpu:
    the deployment sitecustomize re-points JAX at the remote TPU plugin
    at interpreter startup, and initializing that backend can block
    indefinitely when the chip tunnel is down — a pure-CPU phase must
    never touch the accelerator at all."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    if backend == "cpu":
        env["NDS_TPU_PLATFORM"] = "cpu"
    elif backend is not None:
        # the backend argument is authoritative: a stale cpu pin in the
        # launching shell must not silently demote tpu/distributed
        # phases to CPU timings
        env.pop("NDS_TPU_PLATFORM", None)
    return env


def add_config_args(parser) -> None:
    """The --template/--property_file/--trace CLI surface shared by
    every driver (reference: spark-submit-template sources the
    template, `nds_power.py:324-330` merges the property file)."""
    parser.add_argument("--template",
                        help="engine template file (k=v with ${ENV:-default})")
    parser.add_argument("--property_file",
                        help="k=v property file overriding the template")
    parser.add_argument("--trace",
                        help="append per-query Chrome trace-event JSONL "
                             "here (same as NDS_TPU_TRACE=path; see "
                             "README Observability)")
    parser.add_argument("--cache_dir",
                        help="persistent AOT plan-cache directory "
                             "(cache.dir; same as NDS_TPU_PLAN_CACHE — "
                             "README 'Plan cache')")
    parser.add_argument("--cache_readonly", action="store_true",
                        help="consult the plan cache but never write it "
                             "(cache.readonly)")


def config_from_args(args, default_backend: str = "tpu") -> EngineConfig:
    """CLI --backend > property file > template > the driver's default
    (matching spark-submit-template < --property_file precedence with
    spark-submit's own CLI last)."""
    if getattr(args, "trace", None):
        os.environ["NDS_TPU_TRACE"] = args.trace
    cli_backend = getattr(args, "backend", None)
    overrides = {}
    if cli_backend is not None:
        overrides["engine.backend"] = cli_backend
    if getattr(args, "cache_dir", None):
        overrides["cache.dir"] = args.cache_dir
    if getattr(args, "cache_readonly", False):
        overrides["cache.readonly"] = "1"
    cfg = EngineConfig(getattr(args, "template", None),
                       getattr(args, "property_file", None), overrides)
    if "engine.backend" not in cfg.explicit:
        cfg.conf["engine.backend"] = default_backend
    return cfg
