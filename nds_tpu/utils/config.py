"""Layered engine configuration.

The reference configures Spark through bash-sourced template files building
a ``SPARK_CONF`` array (`nds/spark-submit-template:28-40`,
`nds/base.template:26-37`) overlaid by ``--property_file`` k=v files merged
into the session (`nds/nds_power.py:324-330`). There is no shell or JVM in
this stack, so templates become plain ``key=value`` files with ``${ENV:-default}``
substitution; precedence is identical: template < property file < explicit
CLI overrides.

Engine keys (the TPU analog of the spark.* / spark.rapids.* namespace):

  engine.backend            tpu|cpu (which jax backend executes queries)
  engine.mesh.shards        data-parallel shard count (devices in mesh)
  engine.floats             true -> float64/float32 arithmetic (reference
                            --floats mode); false -> scaled-int decimals
  engine.batch.capacity     static row capacity override per table scan
  engine.concurrent_tasks   async dispatch depth (analog of
                            spark.rapids.sql.concurrentGpuTasks,
                            nds/power_run_gpu.template:38)
  engine.precision          f64|f32|bf16 float compute dtype in floats
                            mode (f64 default matches the CPU oracle;
                            f32/bf16 run native-speed on the VPU)
  engine.stream_bytes       tables above this many bytes stream through
                            the device in chunks instead of uploading
                            whole (out-of-core path; 0 = off)
  engine.chunk_rows         rows per streamed chunk
  engine.retry.max_attempts per-query attempt cap for transient
                            failures (resilience layer; default 3)
  engine.retry.base_delay_s / engine.retry.max_delay_s /
  engine.retry.jitter / engine.retry.seed
                            exponential-backoff shape (seeded jitter:
                            chaos runs replay exactly)
  engine.query_deadline_s   per-query wall-clock deadline (0/unset =
                            none); overruns are flagged and counted
  engine.placement.force    pin the initial placement (device/sharded/
                            chunked/cpu); the power drivers'
                            --placement flag sets this
  engine.placement.ladder   on (default) / off: reschedule classified
                            transient failures down the degradation
                            ladder (engine/scheduler.py)
  engine.placement.floor    deepest ladder rung (default cpu)
  engine.placement.demote_after / engine.placement.promote_after
                            sticky stream-demotion shape: consecutive
                            ladder-walked queries before the starting
                            rung demotes / clean queries before it
                            promotes back
  engine.placement.device_budget_bytes
                            cost-model working-set budget for the
                            device placement (default 8 GiB); also the
                            memory governor's pre-admission ceiling
  engine.placement.governor on (default) / off: proactive memory-
                            pressure governor — project live bytes +
                            plan estimate before dispatch and demote /
                            pre-shrink instead of waiting for the OOM
                            (engine/scheduler.MemoryGovernor)
  engine.drain_s            graceful preemption drain deadline
                            (default 30; NDS_TPU_DRAIN_S for fleets):
                            on SIGTERM/SIGINT the in-flight query gets
                            this long to finish before being abandoned
                            and journaled not-done; either way the
                            process exits 75 = resumable (README
                            "Preemption & resume")
  engine.fallback           legacy alias: "cpu" forces
                            engine.placement.floor=cpu (the one-shot
                            stream demotion it used to trigger is now
                            the ladder + sticky demotion)
  engine.prefetch.enabled   on (default) / off: double-buffered
                            phase-A prefetch in the chunked executor
                            (engine/pipeline_io.py, README "Pipelined
                            execution") — a worker thread slices,
                            columnar-encodes, and device_puts chunk
                            N+1 while the compiled program scans chunk
                            N. ``off`` restores the byte-identical
                            serial loops. Env: NDS_TPU_PREFETCH
                            (depth, or "off").
  engine.prefetch.depth     staged-chunks-ahead bound (default 2;
                            0 = serial). MEMORY CONTRACT: the
                            MemoryGovernor's admission projections
                            count depth x one chunk's working set as
                            in-flight prefetch bytes (staged buffers
                            are live accounted bytes from device_put
                            to consumption) and DEMOTE DEPTH before
                            demoting placement — a budget that admits
                            the serial chunked loop but not the
                            staged overlap runs the same placement
                            shallower, recorded as the summary's
                            ``prefetch_depth`` +
                            prefetch_depth_demotions_total.
  engine.prefetch.boundary  on / off (default): additionally pipeline
                            QUERY boundaries — the power loop and the
                            serve engine thread dispatch query N+1
                            while query N's compactor output is still
                            in flight D2H (async-handle result() as
                            the sync point). Per-query walls become
                            dispatch->result brackets (the throughput
                            loop's contract) and boundary metric
                            deltas attribute the next dispatch to the
                            previous window (totals stay exact). Env:
                            NDS_TPU_PREFETCH_BOUNDARY.

Columnar keys (compressed device-resident store, nds_tpu/columnar/ —
README "Compressed columnar store"):

  columnar.encode           off (default) | auto | dict | bitpack |
                            rle. ``auto`` picks per column from
                            load-time stats (dictionary codes and
                            narrow ints bitpack into int32 words,
                            sorted fact columns run-length encode)
                            and the engine scans/joins/aggregates the
                            encoded form directly, decoding once
                            inside the compiled program (late
                            materialization). The forced modes apply
                            ONE encoding family wherever applicable
                            (differential debugging). ``off``
                            preserves byte-identical pre-columnar
                            behavior. Env: NDS_TPU_COLUMNAR.
  columnar.dict_union_cap   bound on the executor's memoized string-
                            dictionary unions (default 256; was a
                            hard cap — serving workloads cycling many
                            table pairs need it raised). Env:
                            NDS_TPU_DICT_UNION_CAP.

Serving keys (the query server, nds_tpu/serve/ — README "Serving"):

  serve.max_queue           admission bound: a submit that would make
                            the request queue deeper than this sheds
                            immediately (status "shed",
                            server_shed_total; default 64). Brownout,
                            not backpressure: past saturation the
                            server degrades its ANSWER RATE, never
                            its liveness
  serve.deadline_ms         queue-age deadline: a request still queued
                            after this many ms sheds at dequeue
                            instead of executing late (0 = off,
                            default)
  serve.max_batch           same-template batching bound: how many
                            queued requests with the SAME
                            parameterized plan digest one dispatch
                            group drains back-to-back against the
                            shared compiled program (default 8)
  serve.shed_factor         memory brownout: shed when the
                            MemoryGovernor's pre-dispatch projection
                            exceeds this multiple of
                            engine.placement.device_budget_bytes
                            (default 1.5; inside the factor the
                            governor demotes placements instead of
                            shedding)
  serve.summary_dir         per-request BenchReport summaries land
                            here (tenant field attached) so
                            ``ndsreport analyze`` reports serving
                            p50/p99 like any run dir (unset = no
                            summaries)
  serve.replica_id          fleet identity stamped on responses,
                            summaries, and tenant metrics (usually
                            injected by the supervisor via
                            NDS_TPU_REPLICA, which wins over this
                            key; unset = single-server mode)

Serve-fleet keys (router + replicas, nds_tpu/serve/fleet.py — README
"Serve fleet"):

  serve.net.read_timeout_s  per-connection read deadline on the TCP
                            front: a peer silent this long is cut
                            (shed notice "conn-read-timeout:<t>s",
                            server_conn_timeouts_total; default 300,
                            0/negative = no deadline)
  serve.net.max_line_bytes  JSON-lines frame bound: a longer line
                            sheds "line-too-long" and closes the
                            connection (server_conn_overruns_total;
                            default 1 MiB, floor 1024)
  serve.fleet.ping_interval_s
                            router health-probe cadence per replica
                            (announce re-read + op:ping; default 0.5)
  serve.fleet.ping_timeout_s
                            deadline on one probe round-trip
                            (default 5)
  serve.fleet.ping_misses   consecutive probe misses before the
                            router ejects a replica from the healthy
                            ring (default 3; supervisor membership
                            "down" events eject immediately)
  serve.fleet.hb_stale_s    optional heartbeat-file staleness bound:
                            effective age = (now - snapshot mtime) +
                            youngest in-file heartbeat age; older
                            than this counts as a probe miss (0 =
                            off, default — the app-level ping is the
                            primary signal)
  serve.fleet.request_timeout_s
                            end-to-end deadline the router puts on
                            one dispatched request (default 600);
                            expiry triggers redelivery, not an error
  serve.fleet.redeliver_max how many times one request may be
                            redelivered after connection loss or a
                            departure notice before the router
                            answers "redeliver-exhausted" (default 4)
  serve.fleet.max_pending   router admission bound: submits beyond
                            this many in-flight requests shed
                            "router-admission" (default 0 = derive
                            healthy-ring-size x serve.max_queue)
  serve.fleet.member_wait_s how long one dispatch attempt waits for
                            ANY healthy replica before falling back /
                            shedding (default 30)

Observability keys (cost ledger + device telemetry, nds_tpu/obs/ —
README "Cost ledger & telemetry"):

  obs.costs.enabled         compiler cost ledger (obs/costs.py): every
                            dispatched compiled program's XLA
                            ``cost_analysis()`` /
                            ``memory_analysis()`` is billed to the
                            running query and lands in the BenchReport
                            ``cost`` block (flops, bytes accessed,
                            transcendentals, temp/argument/output
                            bytes, per-kind program census). On by
                            default — the readings come from the
                            already-compiled executable, so the only
                            cost is a dict copy per dispatch. ``off``
                            drops the block entirely.
  obs.telemetry.enabled     background device-memory sampler
                            (obs/telemetry.py): a daemon thread polls
                            per-device ``memory_stats()`` into a
                            bounded ring; per-query HBM occupancy
                            summaries land in the BenchReport
                            ``telemetry`` block and the samples export
                            as Chrome-trace counter lanes. Graceful
                            no-op on backends without allocator stats
                            (CPU). On by default. Env:
                            NDS_TPU_TELEMETRY=0/1 wins over the
                            config key.
  obs.telemetry.interval_ms sampling period in milliseconds (default
                            250). The ring is bounded, so long runs
                            decimate rather than grow.

Diagnostics env toggles (no config-file analog — they gate process
instrumentation, not workload shape, and must be readable before any
config loads):

  NDS_TPU_LOCKSAN=1         runtime lock-order sanitizer
                            (nds_tpu/analysis/locksan.py): every lock
                            the engine's threaded modules create is
                            wrapped to record per-thread acquisition
                            order; inversions print loudly, count on
                            ``lock_order_inversions_total``, and fail
                            the tier-1 locksan gate. On for tests
                            (tests/conftest.py) and the chaos/soak/
                            serve gates; off (zero overhead) by
                            default.
  NDS_TPU_LOCKSAN_REPORT    directory the sanitizer writes its
                            ``locksan-<pid>.json`` exit report into
                            (atomic, thread-unique tmp); unset =
                            stderr-only on inversions. static_checks
                            points subprocess fleets at a shared dir
                            and sweeps it.
"""

from __future__ import annotations

import os
import re

_ENV_RE = re.compile(r"\$\{(?P<name>[A-Za-z_][A-Za-z0-9_]*)(?::-(?P<default>[^}]*))?\}")


def _substitute_env(value: str, env: dict | None = None) -> str:
    env = env if env is not None else os.environ

    def repl(m):
        name, default = m.group("name"), m.group("default")
        if name in env:
            return env[name]
        if default is not None:
            return default
        raise KeyError(f"undefined environment variable ${{{name}}} in config")

    return _ENV_RE.sub(repl, value)


def load_properties(path: str, env: dict | None = None) -> dict:
    """Parse a k=v property/template file with comments and env substitution."""
    conf: dict[str, str] = {}
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"{path}:{lineno}: expected key=value, got {line!r}")
            key, _, value = line.partition("=")
            conf[key.strip()] = _substitute_env(value.strip(), env)
    return conf


DEFAULTS = {
    "engine.backend": "cpu",
    "engine.mesh.shards": "1",
    "engine.floats": "false",
    "engine.concurrent_tasks": "2",
    # f64 default: floats-mode differential validation matches the CPU
    # oracle out of the box; f32/bf16 are the opt-in fast path
    "engine.precision": "f64",
}


class EngineConfig:
    """Merged view over defaults < template < property file < overrides."""

    def __init__(self, template_path: str | None = None,
                 property_path: str | None = None,
                 overrides: dict | None = None) -> None:
        conf = dict(DEFAULTS)
        self.sources = {"template": template_path, "property_file": property_path}
        # keys set by an explicit layer (vs DEFAULTS) — lets drivers
        # apply their own fallback default without trampling templates
        self.explicit: set[str] = set()
        for layer in (load_properties(template_path) if template_path
                      else {},
                      load_properties(property_path) if property_path
                      else {},
                      {k: str(v) for k, v in (overrides or {}).items()}):
            conf.update(layer)
            self.explicit.update(layer)
        self.conf = conf

    def get(self, key: str, default=None):
        return self.conf.get(key, default)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.conf.get(key)
        if v is None:
            return default
        return str(v).strip().lower() in ("1", "true", "yes", "on")

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.conf.get(key)
        return default if v is None else int(v)

    def as_dict(self) -> dict:
        return dict(self.conf)
