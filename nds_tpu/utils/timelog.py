"""CSV time-log writer.

Header and row format match the reference power-run time log
(`nds/nds_power.py:294-303`): ["application_id", "query",
"time/milliseconds"], with synthetic rows for per-phase brackets
(CreateTempView / WriteTimeLog / Total / benchmark times), so tooling that
parses the reference CSV parses ours.
"""

from __future__ import annotations

import csv

HEADER = ["application_id", "query", "time/milliseconds"]


class TimeLog:
    def __init__(self, app_id: str) -> None:
        self.app_id = app_id
        self.rows: list[list] = []

    def add(self, query_name: str, millis: int) -> None:
        self.rows.append([self.app_id, query_name, int(millis)])

    def write(self, path: str) -> None:
        import os
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(HEADER)
            w.writerows(self.rows)

    @staticmethod
    def read(path: str) -> list[tuple[str, str, int]]:
        out = []
        with open(path, newline="") as f:
            r = csv.reader(f)
            header = next(r)
            if header != HEADER:
                raise ValueError(f"unexpected time log header {header!r} in {path}")
            for app_id, query, ms in r:
                out.append((app_id, query, int(ms)))
        return out
