"""Per-query JSON summary reports.

Format-compatible with the reference's `nds/PysparkBenchReport.py:47-122`
summary dict (env/queryStatus/exceptions/startTime/queryTimes/query +
filename '{prefix}-{query}-{startTime}.json'), so downstream report
consumers keep working. Differences are TPU-native by design:

- env captures jax backend/devices instead of sparkConf/sparkVersion;
- "task failure" detection (reference: Scala SparkListener bridged over
  py4j, `nds/python_listener/PythonListener.py:21-61`) is an in-process
  failure collector — there is no JVM boundary in this stack;
- timing brackets call ``block_until_ready`` upstream so async dispatch
  cannot hide work (SURVEY.md §5 tracing note).

Schema additions over the reference format (README "Observability"):
the power loop attaches ``spans`` (the per-query span tree from
nds_tpu/obs/trace.py) and ``metrics`` (the per-query delta of the
global counter registry) to each summary; both are absent when the
corresponding subsystem recorded nothing. The resilience layer
(README "Resilience") adds ``retries`` plus, when set,
``gave_up_reason`` and ``deadline_exceeded`` via ``attach_retry``;
``attach_memory`` adds the per-query device-memory high-water mark
(``memory``, fed by obs/memwatch.py). ``tools/check_trace_schema.py
--summary`` validates the full shape.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Callable

from nds_tpu.analysis import locksan

_REDACTED_MARKERS = ("TOKEN", "SECRET", "PASSWORD", "KEY", "CREDENTIAL")


def redact_env(env: dict) -> dict:
    """Drop env vars whose *name* suggests a secret.

    Stricter than the reference (exact-name match on TOKEN/SECRET/PASSWORD,
    `PysparkBenchReport.py:72-73`): substring match plus KEY/CREDENTIAL.
    """
    return {
        k: v for k, v in env.items()
        if not any(m in k.upper() for m in _REDACTED_MARKERS)
    }


class TaskFailureCollector:
    """In-process stand-in for the reference's jvm/python listener chain.

    Engine internals append non-fatal anomalies (retries, padded-capacity
    overflows that were recovered by re-execution, host fallbacks). A query
    that completes with collected failures is reported
    'CompletedWithTaskFailures', matching `PysparkBenchReport.py:90-93`.
    """

    _active: list["TaskFailureCollector"] = []
    # concurrent throughput streams notify from their own threads; the
    # class-level listener list and each listener's failure store must
    # not race (lost appends silently under-report anomalies)
    _lock = locksan.lock("utils.TaskFailureCollector._lock")
    # per-thread focus stack: boundary pipelining (README "Pipelined
    # execution") keeps TWO report brackets — and therefore two
    # registered collectors — open at once on one thread; a focused
    # collector receives that thread's notifications EXCLUSIVELY, so
    # query N's recovered anomalies cannot cross-bill into query N+1's
    # summary (and vice versa). Empty stack = the legacy broadcast.
    _tls = threading.local()

    def __init__(self) -> None:
        # ordered UNIQUE reasons; repeats count in _counts so a noisy
        # anomaly (the same overflow retried 50 times) is one summary
        # line with a multiplier, not 50 identical lines
        self.failures: list[str] = []
        self._counts: dict[str, int] = {}

    def register(self) -> None:
        with TaskFailureCollector._lock:
            TaskFailureCollector._active.append(self)

    def unregister(self) -> None:
        with TaskFailureCollector._lock:
            if self in TaskFailureCollector._active:
                TaskFailureCollector._active.remove(self)

    def formatted(self) -> list[str]:
        """Unique reasons in first-seen order, deduplicated repeats
        annotated with their count."""
        with TaskFailureCollector._lock:
            return [r if self._counts[r] == 1 else
                    f"{r} (x{self._counts[r]})" for r in self.failures]

    @classmethod
    @contextmanager
    def focused(cls, collector: "TaskFailureCollector | None"):
        """Route the CALLING thread's notifications exclusively to one
        collector for the block (no-op on None): the dispatch/resolve
        halves of an overlapped query bracket each focus their own
        report's collector."""
        if collector is None:
            yield
            return
        stack = getattr(cls._tls, "stack", None)
        if stack is None:
            stack = cls._tls.stack = []
        stack.append(collector)
        try:
            yield
        finally:
            stack.pop()

    @classmethod
    def notify(cls, reason: str) -> None:
        """Called by engine internals on recoverable task-level
        failures. Every notification also increments the
        ``task_failures_total`` metrics counter, so anomaly volume is
        visible across a whole run even when no collector is
        registered (warmups, direct executor use)."""
        from nds_tpu.obs import metrics as obs_metrics
        obs_metrics.counter("task_failures_total").inc()
        stack = getattr(cls._tls, "stack", None)
        with cls._lock:
            for listener in (stack[-1],) if stack else cls._active:
                if reason in listener._counts:
                    listener._counts[reason] += 1
                else:
                    listener._counts[reason] = 1
                    listener.failures.append(reason)


class BenchReport:
    """Build and persist one per-query JSON summary."""

    def __init__(self, query_name: str, engine_info: dict | None = None) -> None:
        self.summary = {
            "env": {
                "envVars": {},
                "engineConf": {},
                "engineVersion": None,
            },
            "queryStatus": [],
            "exceptions": [],
            "startTime": None,
            "queryTimes": [],
            "query": query_name,
        }
        self._engine_info = engine_info or {}
        self._collector: "TaskFailureCollector | None" = None

    def capture_env(self) -> None:
        self.summary["env"]["envVars"] = redact_env(dict(os.environ))
        conf = dict(self._engine_info)
        try:
            import jax
            self.summary["env"]["engineVersion"] = f"jax-{jax.__version__}"
        except Exception:  # jax optional for harness-only paths
            jax = None
            self.summary["env"]["engineVersion"] = "cpu-harness"
        if jax is not None:
            # NEVER initialize backends from the reporter:
            # jax.default_backend()/devices() force platform discovery,
            # and on a remote-attached chip (axon) that blocks
            # indefinitely when the tunnel is down — which froze even
            # pure-CPU power runs. Only report a backend that is
            # ALREADY live; otherwise record the configured platform.
            # The live-check peeks at a PRIVATE jax symbol, so it gets
            # its own try: if a jax upgrade moves it, we still record
            # the jax version + configured platform (advisor, round 4).
            try:
                from jax._src import xla_bridge as _xb
                if getattr(_xb, "_backends", None):
                    # discovery already completed: the canonical
                    # accessors are cached and non-blocking now, and
                    # report the PRIORITY backend (not registration
                    # order)
                    conf.setdefault("backend", jax.default_backend())
                    conf.setdefault("device_count", jax.device_count())
                    conf.setdefault(
                        "devices", [str(d) for d in jax.devices()][:8])
                else:
                    raise LookupError("backends not initialized")
            except Exception:
                try:
                    platforms = jax.config.jax_platforms
                except Exception:
                    platforms = None
                conf.setdefault("backend",
                                f"configured:{platforms or 'auto'}")
        self.summary["env"]["engineConf"] = {str(k): str(v) for k, v in conf.items()}

    def report_on(self, fn: Callable, *args):
        """Run fn(*args), recording status/exception/elapsed-ms.

        Statuses: Completed | CompletedWithTaskFailures | Failed — the same
        vocabulary the reference emits (`PysparkBenchReport.py:90-103`).
        """
        self.capture_env()
        collector = TaskFailureCollector()
        collector.register()
        start_time = int(time.time() * 1000)
        try:
            fn(*args)
            end_time = int(time.time() * 1000)
            if collector.failures:
                self.summary["queryStatus"].append("CompletedWithTaskFailures")
                self.summary["exceptions"].extend(collector.formatted())
            else:
                self.summary["queryStatus"].append("Completed")
        except Exception as e:
            print("ERROR BEGIN")
            traceback.print_exc()
            print("ERROR END")
            end_time = int(time.time() * 1000)
            self.summary["queryStatus"].append("Failed")
            self.summary["exceptions"].append(str(e))
        finally:
            collector.unregister()
        self.summary["startTime"] = start_time
        self.summary["queryTimes"].append(end_time - start_time)
        return self.summary

    def begin_async(self) -> None:
        """Open the report bracket without a body: the split form of
        ``report_on`` the query-boundary pipelining uses (README
        "Pipelined execution") — the dispatch half runs now, the
        result() half may run after the NEXT query dispatched, and
        ``end_async`` closes the bracket with the same status
        vocabulary. The bracket endpoints are dispatch-start and
        result-done, the same contract the throughput loop's
        dispatch->result walls already use."""
        self.capture_env()
        self._collector = TaskFailureCollector()
        self._collector.register()
        self._t0 = int(time.time() * 1000)

    def focus_failures(self):
        """Context manager for the dispatch/resolve halves of an open
        ``begin_async`` bracket: this thread's TaskFailureCollector
        notifications go to THIS report only (under boundary
        pipelining two brackets' collectors are registered at once —
        broadcast would cross-bill one query's recovered anomalies
        into the other's summary). No-op before begin_async."""
        return TaskFailureCollector.focused(self._collector)

    def end_async(self, error: "BaseException | None" = None):
        """Close a ``begin_async`` bracket: status/exception/elapsed
        recording identical to ``report_on``'s (Completed |
        CompletedWithTaskFailures | Failed)."""
        end_time = int(time.time() * 1000)
        collector = self._collector
        self._collector = None
        collector.unregister()
        if error is not None:
            print("ERROR BEGIN")
            traceback.print_exception(type(error), error,
                                      error.__traceback__)
            print("ERROR END")
            self.summary["queryStatus"].append("Failed")
            self.summary["exceptions"].append(str(error))
        elif collector.failures:
            self.summary["queryStatus"].append(
                "CompletedWithTaskFailures")
            self.summary["exceptions"].extend(collector.formatted())
        else:
            self.summary["queryStatus"].append("Completed")
        self.summary["startTime"] = self._t0
        self.summary["queryTimes"].append(end_time - self._t0)
        return self.summary

    def attach_retry(self, stats) -> None:
        """Record a resilience.retry.RetryStats into the summary:
        ``retries`` always (0 is meaningful — the query needed no
        recovery), ``gave_up_reason`` / ``deadline_exceeded`` only
        when set (README "Resilience" schema)."""
        self.summary["retries"] = stats.retries
        if stats.retries:
            # how much of the query's wall clock was backoff, so a
            # retried query's TimeLog row can be decomposed
            self.summary["retry_backoff_s"] = round(stats.backoff_s, 3)
        if stats.gave_up_reason:
            self.summary["gave_up_reason"] = stats.gave_up_reason
        if stats.deadline_exceeded:
            self.summary["deadline_exceeded"] = True

    def attach_schedule(self, sched: dict | None) -> None:
        """Record the pipeline's scheduling decision
        (engine/scheduler.py): ``placement`` (the placement that served
        the query) and ``reschedules`` always when the pipeline ran;
        ``ladder`` (the rungs walked) only when the query was
        rescheduled; ``promoted_back`` only on the query where a
        stream promotion took effect (README "Placement &
        degradation" schema)."""
        if not sched or "placement" not in sched:
            return
        self.summary["placement"] = sched["placement"]
        self.summary["reschedules"] = int(sched.get("reschedules", 0))
        if sched.get("reschedules"):
            self.summary["ladder"] = list(sched.get("ladder", []))
        if sched.get("promoted_back"):
            self.summary["promoted_back"] = True
        if sched.get("governed"):
            # the memory governor demoted/pre-shrank this query BEFORE
            # dispatch (engine/scheduler.MemoryGovernor)
            self.summary["governed"] = True
        if sched.get("prefetch_depth") is not None:
            # governor depth admission lowered the phase-A prefetch
            # depth for this query (engine/pipeline_io.py; depth
            # demotes before placement)
            self.summary["prefetch_depth"] = int(sched["prefetch_depth"])

    def attach_cache(self, mdelta: dict | None,
                     timings: dict | None = None) -> None:
        """Record the query's persistent plan-cache activity (README
        "Plan cache") as the ``cache`` block, derived from the
        per-query metrics delta: ``{"hits": int, "misses": int}``
        always when the cache was consulted, plus ``errors`` /
        ``bytes_read`` / ``bytes_written`` / ``load_ms`` (deserialize
        wall-clock from engineTimings' ``cache_load_ms``) when
        non-zero. Absent entirely when no plan cache is active — the
        pre-cache summary shape is unchanged."""
        counters = (mdelta or {}).get("counters", {})
        hits = counters.get("compile_cache_hits_total", 0)
        misses = counters.get("compile_cache_misses_total", 0)
        errors = counters.get("compile_cache_errors_total", 0)
        if not (hits or misses or errors):
            return
        block = {"hits": int(hits), "misses": int(misses)}
        if errors:
            block["errors"] = int(errors)
        for key, name in (("bytes_read",
                           "compile_cache_bytes_read_total"),
                          ("bytes_written",
                           "compile_cache_bytes_written_total")):
            if counters.get(name):
                block[key] = int(counters[name])
        load_ms = (timings or {}).get("cache_load_ms")
        if load_ms:
            block["load_ms"] = round(load_ms, 3)
        self.summary["cache"] = block

    def attach_kernels(self, timings: dict | None) -> None:
        """Record which relational kernels the query's compiled
        program actually used (engine/kernels.py trace counts, carried
        in engineTimings' dunder side-channel) as the ``kernels``
        block: ``{"join.direct": 2, "semi.bitmask": 4, ...}``. Absent
        for queries with no kernel-lowered operators (pure scans, the
        CPU oracle). ``ndsreport diff`` watches this block for silent
        demotions — a planner regression that drops q21 back to
        ``join.sortmerge`` fails the gate like a compile-count change
        does."""
        kern = (timings or {}).get("__kernels")
        if kern:
            self.summary["kernels"] = {str(k): int(v)
                                       for k, v in sorted(kern.items())}

    def attach_profile(self, info: dict | None) -> None:
        """Record an on-demand XLA profiler capture (obs/profile.py)
        as the ``profile`` block: ``{"path", "trigger", "bytes"}``.
        Absent when no trigger fired for this query — the common
        summary shape is unchanged."""
        if info and info.get("path"):
            block = {"path": str(info["path"]),
                     "trigger": str(info.get("trigger", "query"))}
            if "bytes" in info:
                block["bytes"] = int(info["bytes"])
            self.summary["profile"] = block

    def attach_flight(self, path: str | None,
                      reason: str | None = None,
                      entries: int | None = None) -> None:
        """Record a flight-recorder dump (obs/fleet.py) triggered by
        this query's final failure as the ``flight`` block:
        ``{"path", "reason", "entries"}`` — the summary points at the
        post-mortem instead of leaving it to a directory listing."""
        if path:
            block: dict = {"path": str(path)}
            if reason:
                block["reason"] = str(reason)
            if entries is not None:
                block["entries"] = int(entries)
            self.summary["flight"] = block

    def attach_tenant(self, tenant: str | None) -> None:
        """Serving-layer attribution (nds_tpu/serve/): which tenant
        submitted the request this summary bills. Absent on benchmark
        summaries; ndsreport analyze groups per-tenant latency
        quantiles over it."""
        if tenant:
            self.summary["tenant"] = str(tenant)

    def attach_replica(self, replica: str | None) -> None:
        """Fleet attribution (nds_tpu/serve/fleet.py): which engine
        replica answered the request this summary bills. Absent on
        single-process serving; ndsreport analyze rolls per-replica
        latency quantiles over it and flags divergent replicas."""
        if replica:
            self.summary["replica"] = str(replica)

    def attach_incarnation(self, incarnation: int | None) -> None:
        """Record which resume incarnation produced this summary
        (resilience/journal.QueryJournal). 0 = the original process;
        a resumed process stamps 1, 2, ... — ``merge_incarnations``
        and ndsreport's merged billing key on it."""
        if incarnation is not None:
            self.summary["incarnation"] = int(incarnation)

    def attach_result_digest(self, digest: str | None) -> None:
        """Record the query result's content fingerprint
        (io/result_io.result_digest) — the value the soak gate compares
        between an interrupted-then-resumed run and a clean one."""
        if digest:
            self.summary["result_digest"] = str(digest)

    def attach_degradations(self) -> None:
        """Surface torn-state degradations in the summary: nonzero
        ``journal_resets_total`` / ``snapshot_resets_total`` mean prior
        on-disk state was thrown away somewhere in this process — a
        silent fresh start must be visible in every summary it could
        have affected, not only in a log line that scrolled away."""
        from nds_tpu.obs import metrics as obs_metrics
        counters = obs_metrics.snapshot().get("counters", {})
        block = {}
        for key, name in (("journal_resets", "journal_resets_total"),
                          ("snapshot_resets", "snapshot_resets_total")):
            if counters.get(name):
                block[key] = int(counters[name])
        if block:
            self.summary["degradations"] = block

    def attach_memory(self, hwm: dict | None) -> None:
        """Record the per-query device-memory high-water mark
        (obs/memwatch.py) as the ``memory`` block:
        ``{"device_hwm_bytes": int, "source": "device"|"accounted"}``.
        Absent when the query touched no tracked memory (README
        "Observability" schema)."""
        if hwm:
            self.summary["memory"] = dict(hwm)

    def attach_cost(self, block: dict | None) -> None:
        """Record the compiler-truth cost ledger (obs/costs.py) as the
        ``cost`` block: summed XLA cost_analysis (flops/bytes/
        transcendentals), maxed memory_analysis sizes, the per-kind
        program census, and the ops_est cross-check. Absent when the
        query dispatched no compiled programs (CPU oracle, harness
        paths) — pre-cost summaries keep their shape."""
        if block:
            self.summary["cost"] = dict(block)

    def attach_telemetry(self, block: dict | None) -> None:
        """Record the per-query HBM-occupancy time series summary
        (obs/telemetry.py) as the ``telemetry`` block. Absent when the
        sampler is off or the backend has no allocator stats — CPU
        summaries stay byte-identical to pre-telemetry runs."""
        if block:
            self.summary["telemetry"] = dict(block)

    def write_summary(self, prefix: str = "",
                      out_dir: str | None = None) -> str:
        """Write '{prefix}-{query}-{startTime}.json' (reference filename
        contract, `PysparkBenchReport.py:117-119`), into ``out_dir``
        when given (the recorded ``filename`` stays bare either way),
        and return the written path."""
        filename = f"{prefix}-{self.summary['query']}-{self.summary['startTime']}.json"
        self.summary["filename"] = filename
        path = (os.path.join(out_dir, filename) if out_dir
                else filename)
        with open(path, "w") as f:
            # ndslint: waive[NDS109] -- filename embeds query+startTime so every write is to a fresh unique path; no reader races a first write
            json.dump(self.summary, f, indent=2)
        return path

    def is_success(self) -> bool:
        return self.summary["queryStatus"] == ["Completed"]


def merge_incarnations(summaries: list, phase: str = "") -> dict:
    """Merge the partial per-query BenchReports of EVERY incarnation of
    a resumed phase into one phase report (README "Preemption &
    resume"): one entry per statement, where a statement reported by
    more than one incarnation (the kill-between-summary-and-journal
    window) is billed ONCE, by its latest (incarnation, startTime)
    report — the same rule ``ndsreport analyze`` applies, so the merged
    report and the analysis agree by construction. The merged wall
    clock is the sum of per-query walls: the only phase total that is
    invariant under where the interruptions fell."""
    best: dict = {}
    for s in summaries:
        if not isinstance(s, dict) or "query" not in s \
                or "queryStatus" not in s:
            continue
        q = str(s["query"])
        key = (int(s.get("incarnation") or 0), s.get("startTime") or 0)
        if q not in best or key > best[q][0]:
            best[q] = (key, s)
    ordered = sorted(best.values(), key=lambda kv: kv[1].get(
        "startTime") or 0)
    merged: dict = {
        "phase": phase,
        "merged": True,
        "incarnations": max((k[0] for k, _s in ordered),
                            default=0) + 1,
        "queries": [s["query"] for _k, s in ordered],
        "queryStatus": [s["queryStatus"][-1] if s.get("queryStatus")
                        else "Failed" for _k, s in ordered],
        "queryTimes": [(s.get("queryTimes") or [0])[-1]
                       for _k, s in ordered],
        "startTime": min((s.get("startTime") or 0
                          for _k, s in ordered), default=0),
    }
    merged["wall_ms_total"] = sum(merged["queryTimes"])
    digests = {s["query"]: s["result_digest"] for _k, s in ordered
               if s.get("result_digest")}
    if digests:
        merged["result_digests"] = digests
    return merged
