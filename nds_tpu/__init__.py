"""nds_tpu — TPU-native decision-support benchmark framework.

Re-implements the capabilities of NVIDIA's spark-rapids-benchmarks (NDS /
NDS-H harness over Spark + spark-rapids GPU plugin; see SURVEY.md) with a
TPU-first architecture: the harness half (data/query generation, schemas,
phase drivers, reporting, validation, orchestration) is pure Python; the
engine half is a columnar SQL execution layer lowering
scan -> join -> aggregate -> sort -> exchange to XLA via JAX
(`jit`/`shard_map`), with shuffle exchange riding ICI/DCN collectives in
place of Spark's block shuffle (reference delegated all execution to Spark:
/root/reference/nds/power_run_gpu.template:35).
"""

__version__ = "0.1.0"
