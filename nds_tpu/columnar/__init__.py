"""Compressed device-resident columnar store (ROADMAP item 4).

The warehouse stays on device in ENCODED form — dictionary codes
bit-packed to the dictionary's width, narrow ints shift/mask-packed
into int32 words, sorted fact columns run-length encoded — and every
operator consumes codes/packed words directly, decoding exactly once
inside the compiled program (late materialization; string bytes still
only exist at the result compactor). ``bytes_scanned`` therefore
measures ENCODED bytes, and the per-query ``compression_ratio`` rides
the engine timings into ndsreport.

Activation (off by default — ``off`` preserves byte-identical
pre-columnar behavior):

  columnar.encode           off | auto | dict | bitpack | rle
                            (EngineConfig key; forced modes apply one
                            encoding family wherever applicable)
  NDS_TPU_COLUMNAR          env equivalent for driverless entry points
  columnar.dict_union_cap   bound on the executor's memoized
                            string-dictionary unions (default 256;
                            NDS_TPU_DICT_UNION_CAP)

Layout: ``encodings.py`` plans + encodes on the host (numpy only, runs
at load/transcode time); ``device.py`` decodes inside the jax trace.
The single ``fingerprint_token()`` folds the mode + encoder version
into every AOT plan-cache fingerprint (cache/fingerprint.py), so an
encoding change is a cache MISS by construction.
"""

from __future__ import annotations

import os

from nds_tpu.columnar.encodings import (  # noqa: F401
    ENC_VERSION, EncSpec, chunk_spec, column_spec, encode_column,
    encode_values, encoded_nbytes, manifest_encodings,
    manifest_set_encodings, plan_padded, plan_values, raw_nbytes,
    scan_nbytes, seed_column_spec, spec_from_json, spec_to_json,
    table_compression, table_specs,
)

MODES = ("off", "auto", "dict", "bitpack", "rle")

ENV_MODE = "NDS_TPU_COLUMNAR"
ENV_UNION_CAP = "NDS_TPU_DICT_UNION_CAP"

DEFAULT_DICT_UNION_CAP = 256

_mode_override: "str | None" = None
_union_cap_override: "int | None" = None


def set_mode(mode: "str | None") -> None:
    """Programmatic mode gate (None = defer to the env var)."""
    global _mode_override
    if mode is not None and mode not in MODES:
        raise ValueError(
            f"unknown columnar.encode {mode!r} (known: {MODES})")
    _mode_override = mode


def mode() -> str:
    if _mode_override is not None:
        return _mode_override
    env = os.environ.get(ENV_MODE, "").strip().lower()
    if env in ("", "0", "false"):
        return "off"
    if env in ("1", "true", "on"):
        return "auto"
    if env not in MODES:
        return "off"  # telemetry-grade tolerance: a typo never crashes
    return env


def enabled() -> bool:
    return mode() != "off"


def set_dict_union_cap(cap: "int | None") -> None:
    global _union_cap_override
    _union_cap_override = cap


def dict_union_cap() -> int:
    """Bound on the executor's memoized string-dictionary unions
    (device_exec._dict_union) — a config key because a serving
    workload cycling many table pairs silently thrashes a hard cap.
    Floored at 1: the eviction loop holds the just-built entry, so a
    zero/negative cap ("disable the memo") would pop from an empty
    dict mid-query — cap=1 IS the no-reuse behavior."""
    if _union_cap_override is not None:
        return max(1, _union_cap_override)
    try:
        return max(1, int(os.environ.get(ENV_UNION_CAP, "")
                          or DEFAULT_DICT_UNION_CAP))
    except ValueError:
        return DEFAULT_DICT_UNION_CAP


def configure_from(config) -> None:
    """Engine-activation hook (power_core.prepare_engine): explicit
    ``columnar.*`` config keys override the environment; absent keys
    RESET the override so one process's sessions don't inherit a
    previous session's choices."""
    set_mode(config.get("columnar.encode") or None)
    cap = config.get("columnar.dict_union_cap")
    set_dict_union_cap(int(cap) if cap is not None else None)


def fingerprint_token() -> str:
    """What the AOT plan-cache fingerprint folds in: encoder version +
    active mode. Specs themselves derive deterministically from table
    content (already content-digested into every fingerprint), so the
    token is sufficient to distinguish any two encoded programs."""
    return f"v{ENC_VERSION}:{mode()}"
