"""Encoded-column planning and host-side encoding (numpy only).

The device engine is bandwidth-bound (the per-query roofline column:
ops/byte vs ``bytes_scanned``), so the cheapest large speedup left is
moving fewer bytes through HBM. This module picks a per-column encoding
from load-time statistics and produces the host buffer set the
executors upload INSTEAD of the raw values; the device side
(``columnar/device.py``) fuses the decode into the consuming XLA
program, so encoded columns never materialize at full width in HBM —
the GPU columnar playbook ("Accelerating Presto with GPUs", Flare)
applied to the TPU.

Encodings:

- **bitpack** — integer columns (dates, surrogate keys, dictionary
  codes, flags) whose host value range fits ``bits`` ∈ {1,2,4,8,16}
  pack ``32//bits`` biased values per int32 word; ``bits=32`` is the
  biased-downcast special case for int64 storage whose range fits
  int32. Decode is a word gather + shift/mask + bias add, fused into
  the consuming kernel by XLA.
- **rle** — run-length encoding for sorted/clustered columns (fact
  date and surrogate-key columns): run values + int32 run starts.
  Decode rebuilds run ids with one scatter + prefix sum, then gathers.
- **raw + packed mask** — a column whose values stay raw can still
  pack its null mask at 1 bit/row (8x on the mask bytes).

Dictionary-encoded strings already live on device as int32 codes
(io/host_table.py); here their codes additionally bitpack to the
dictionary's width, so "dictionary-encoded end-to-end" also means
"narrow on the wire". Selection is deterministic from column content
(+ the mode), so identical warehouses produce identical encodings —
which is what lets encoding choices ride the AOT plan-cache
fingerprint as a single mode token (cache/fingerprint.py).

No jax imports: planning/encoding must run wherever the warehouse
loads (transcode, table_cache, bare-CPU cost estimation).
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, replace

import numpy as np

# bump to invalidate memoized specs, manifest metadata, and (via the
# fingerprint token) every cached executable built over encoded buffers
ENC_VERSION = 1

# columns below this row count stay raw: there is nothing to win and
# the degenerate shapes (0/1 rows) keep their existing special cases
MIN_ROWS = 2

# auto mode requires a real gain: encoded bytes <= 3/4 of raw bytes
# (forced modes only require encoded < raw)
GAIN_NUM, GAIN_DEN = 3, 4

# pack the null mask when it spans at least this many rows (below, the
# mask is already tiny and the extra decode is pure overhead)
MASK_PACK_MIN_ROWS = 64

_PACK_BITS = (1, 2, 4, 8, 16)


@dataclass(frozen=True)
class EncSpec:
    """One column's encoding choice. ``rows`` is the (padded) logical
    row count the decode reproduces; ``dtype`` the numpy dtype name of
    the decoded values (encoded-dtype propagation: the decode must
    hand downstream operators exactly the dtype the raw upload would
    have)."""

    kind: str            # "bitpack" | "rle" | "raw" (mask-only)
    rows: int
    dtype: str
    bits: int = 0        # bitpack: payload bits per value
    lo: int = 0          # bitpack: bias subtracted before packing
    runs: int = 0        # rle: number of runs
    mask_packed: bool = False


def spec_to_json(spec: EncSpec) -> dict:
    return asdict(spec)


def spec_from_json(doc: dict) -> EncSpec | None:
    try:
        spec = EncSpec(**doc)
    except TypeError:
        return None
    if spec.kind not in ("bitpack", "rle", "raw"):
        return None
    return spec


# ----------------------------------------------------------- statistics

def _int_bounds(values: np.ndarray, mask) -> "tuple[int, int] | None":
    vals = values if mask is None else values[mask]
    if len(vals) == 0:
        return (0, 0)
    return (int(vals.min()), int(vals.max()))


def _runs_of(values: np.ndarray) -> int:
    if len(values) < 2:
        return len(values)
    return int(np.count_nonzero(values[1:] != values[:-1])) + 1


def _pack_bits_for(span: int, itemsize: int) -> int:
    """Smallest supported bit width covering ``span`` (= hi - lo), or
    0 when bit packing cannot shrink this column."""
    for bits in _PACK_BITS:
        if span <= (1 << bits) - 1:
            # packing into int32 words only gains when the packed
            # width beats the storage width
            return bits if bits < itemsize * 8 else 0
    if itemsize == 8 and span <= 2**31 - 1:
        return 32  # biased downcast: int64 storage, int32 range
    return 0


# ------------------------------------------------------ size accounting

def _mask_words(rows: int) -> int:
    return (rows + 31) // 32


def encoded_nbytes(spec: EncSpec) -> int:
    """Bytes the device scan reads for a column encoded per ``spec``."""
    item = np.dtype(spec.dtype).itemsize
    if spec.kind == "bitpack":
        if spec.bits >= 32:
            body = spec.rows * 4
        else:
            per = 32 // spec.bits
            body = ((spec.rows + per - 1) // per) * 4
    elif spec.kind == "rle":
        body = spec.runs * (item + 4)
    else:
        body = spec.rows * item
    if spec.mask_packed:
        body += _mask_words(spec.rows) * 4
    return body


def raw_nbytes(values: np.ndarray, mask=None) -> int:
    return int(values.nbytes) + (0 if mask is None else int(mask.nbytes))


# ------------------------------------------------------------- planning

def plan_from_stats(*, rows: int, dtype: str, raw: int,
                    lo: "int | None", hi: "int | None",
                    runs: "int | None", has_mask: bool,
                    is_string: bool = False,
                    mode: str | None = None) -> EncSpec | None:
    """The pure decision procedure behind ``plan_values``, driven by
    exact column statistics instead of the value array. Split out so
    the delta-segment append path (columnar/delta.py) can MERGE base +
    segment stats and plan the widened column without an O(rows)
    re-scan — and, because both paths share this one procedure, a
    merged-stats plan is provably the plan a fresh process would
    derive from the concatenated content (the AOT fingerprint stamps
    content, not specs, so the two must never diverge)."""
    from nds_tpu import columnar
    mode = columnar.mode() if mode is None else mode
    if mode == "off" or rows < MIN_ROWS:
        return None
    np_dtype = np.dtype(dtype)
    if not np.issubdtype(np_dtype, np.number):
        return None
    cands: list[EncSpec] = []
    forced = mode in ("dict", "bitpack", "rle")
    if (np.issubdtype(np_dtype, np.integer)
            and mode in ("auto", "dict", "bitpack")
            and (mode != "dict" or is_string)
            and lo is not None and hi is not None):
        bits = _pack_bits_for(hi - lo, np_dtype.itemsize)
        if bits:
            cands.append(EncSpec("bitpack", rows, dtype, bits=bits,
                                 lo=lo))
    # RLE never applies to floats: run detection (and the run-value
    # representative) compares by VALUE, and -0.0 == +0.0 would
    # splice signed zeros into one run — the decode then flips
    # signbits vs the raw upload, breaking the byte-identical
    # contract (and sign-sensitive math like 1/x)
    if (not has_mask and mode in ("auto", "rle")
            and not np.issubdtype(np_dtype, np.floating)
            and runs is not None):
        cands.append(EncSpec("rle", rows, dtype, runs=runs))
    if (has_mask and rows >= MASK_PACK_MIN_ROWS
            and (mode in ("auto", "bitpack")
                 or (mode == "dict" and is_string))):
        # mask packing rides every candidate, and stands alone when no
        # value encoding applies
        cands = [replace(c, mask_packed=True) for c in cands]
        cands.append(EncSpec("raw", rows, dtype, mask_packed=True))
    if not cands:
        return None
    best = min(cands, key=encoded_nbytes)
    enc = encoded_nbytes(best)
    if forced or best.kind == "raw":
        # forced modes — and mask-only packing, whose decode is a
        # couple of int32 ops — only need to actually shrink; the
        # auto-mode gain margin exists to keep marginal VALUE decodes
        # off the critical path
        return best if enc < raw else None
    return best if enc * GAIN_DEN <= raw * GAIN_NUM else None


def plan_values(values: np.ndarray, mask=None, *,
                mode: str | None = None,
                is_string: bool = False) -> EncSpec | None:
    """Encoding choice for one column's (possibly padded) value array,
    or None for the raw path. Deterministic in (content, mode): the
    same bytes under the same mode always plan the same spec. Forced
    modes apply exactly ONE family — ``dict`` touches only
    dictionary-code (string) columns, so a differential run can
    attribute a reproduction to one encoding."""
    from nds_tpu import columnar
    mode = columnar.mode() if mode is None else mode
    rows = len(values)
    if mode == "off" or rows < MIN_ROWS:
        return None
    lo = hi = runs = None
    if np.issubdtype(values.dtype, np.number):
        if np.issubdtype(values.dtype, np.integer):
            lo, hi = _int_bounds(values, mask)
        if mask is None and not np.issubdtype(values.dtype,
                                              np.floating):
            runs = _runs_of(values)
    return plan_from_stats(
        rows=rows, dtype=values.dtype.name,
        raw=raw_nbytes(values, mask), lo=lo, hi=hi, runs=runs,
        has_mask=mask is not None, is_string=is_string, mode=mode)


def plan_padded(values: np.ndarray, mask, nrows: int, *,
                is_string: bool = False) -> EncSpec | None:
    """Encoding choice for a PADDED buffer (reduced scan views pad
    survivors to a power-of-two capacity): the plan derives from the
    LIVE prefix only — pad zeros are gated by the row mask and must
    not drag the bitpack bounds (or the run count) toward 0 — and the
    spec's ``rows`` covers the full padded capacity. Encode with the
    matching ``nrows`` so the verifier gates the same prefix."""
    if nrows < MIN_ROWS:
        return None
    spec = plan_values(values[:nrows],
                       None if mask is None else mask[:nrows],
                       is_string=is_string)
    return None if spec is None else replace(spec, rows=len(values))


_SPEC_MEMO = "_nds_enc_memo"


def column_spec(col) -> EncSpec | None:
    """Memoized encoding choice for a HostColumn (the load-time stats
    pass). The memo keys on the active fingerprint token so a mode
    change mid-process cannot serve a stale spec; DML builds new
    column objects, so content drift can't either."""
    from nds_tpu import columnar
    token = columnar.fingerprint_token()
    memo = getattr(col, _SPEC_MEMO, None)
    if memo is not None and memo[0] == token:
        return memo[1]
    spec = plan_values(col.values, col.null_mask,
                       is_string=col.is_string)
    try:
        setattr(col, _SPEC_MEMO, (token, spec))
    except Exception:  # noqa: BLE001 - slotted column: recompute next time
        pass
    return spec


def seed_column_spec(col, spec: EncSpec | None) -> None:
    """Pre-seed the memo from persisted metadata (table_cache restore).
    Rejected when the spec no longer fits the column (stale manifest)."""
    if spec is not None and spec.rows != len(col.values):
        return
    from nds_tpu import columnar
    try:
        setattr(col, _SPEC_MEMO, (columnar.fingerprint_token(), spec))
    except Exception:  # noqa: BLE001
        pass


def chunk_spec(col, chunk_rows: int, bounds: tuple) -> EncSpec | None:
    """Encoding for a STREAMED table's per-chunk buffers: bitpack only
    (every chunk must share one static shape — RLE run counts vary per
    chunk) with bounds from the WHOLE table, so one spec serves every
    chunk of the column and the compiled chunk program is reused
    unchanged."""
    from nds_tpu import columnar
    mode = columnar.mode()
    if mode not in ("auto", "dict", "bitpack"):
        return None
    if mode == "dict" and not col.is_string:
        return None
    if chunk_rows < MIN_ROWS or not np.issubdtype(
            col.values.dtype, np.integer):
        return None
    lo, hi = bounds
    if lo is None or hi is None:
        return None
    bits = _pack_bits_for(hi - lo, col.values.dtype.itemsize)
    mask_packed = (col.null_mask is not None
                   and chunk_rows >= MASK_PACK_MIN_ROWS)
    if not bits and not mask_packed:
        return None
    spec = EncSpec("bitpack" if bits else "raw", chunk_rows,
                   col.values.dtype.name, bits=bits, lo=lo,
                   mask_packed=mask_packed)
    raw = col.values.dtype.itemsize * chunk_rows + (
        chunk_rows if col.null_mask is not None else 0)
    return spec if encoded_nbytes(spec) * GAIN_DEN <= raw * GAIN_NUM \
        else None


# ------------------------------------------------------------- encoding

def _pack_words(norm: np.ndarray, bits: int) -> np.ndarray:
    """Pack non-negative int64 values < 2**bits into int32 words,
    ``32//bits`` per word, low field first."""
    per = 32 // bits
    nwords = (len(norm) + per - 1) // per
    lanes = np.zeros(nwords * per, dtype=np.uint64)
    lanes[:len(norm)] = norm.astype(np.uint64)
    lanes = lanes.reshape(nwords, per)
    shifts = (np.arange(per, dtype=np.uint64) * np.uint64(bits))
    words = np.bitwise_or.reduce(lanes << shifts, axis=1)
    return words.astype(np.uint32).view(np.int32)


def encode_values(spec: EncSpec, values: np.ndarray, mask=None,
                  nrows: "int | None" = None) -> dict:
    """Host buffer set for one column under ``spec``: suffix -> numpy
    array. ``""`` is the primary buffer the scan reads, ``"#x"`` the
    RLE run STARTS (the decode rebuilds run ids via scatter+prefix
    sum), ``"#v"`` the (possibly bit-packed) validity mask. ``nrows``
    marks the live prefix (chunk tails and reduced views pad past
    it); RLE runs derive from the live prefix and the decode extends
    the last run over the pad. Null/pad slots clip into the packed
    range — they are gated by the row/validity masks, never read as
    values.

    THREAD CONTRACT (engine/pipeline_io.py stages chunks on a worker
    thread): this function is a pure function of its arguments — no
    module/column memo is read or written here (``column_spec`` /
    ``chunk_spec`` derive specs on the CALLING thread before staging
    begins) — and its numpy kernels release the GIL, which is exactly
    what lets chunk N+1's encode overlap chunk N's XLA compute."""
    from nds_tpu.analysis import plan_verify
    if plan_verify.verify_enabled():
        vs = plan_verify.check_encoding_spec(spec, values, mask,
                                             nrows=nrows)
        if vs:
            raise plan_verify.PlanVerifyError(vs, "columnar encode")
    out: dict[str, np.ndarray] = {}
    if spec.kind == "bitpack":
        norm = values.astype(np.int64) - spec.lo
        if spec.bits >= 32:
            out[""] = np.clip(norm, 0, 2**31 - 1).astype(np.int32)
        else:
            norm = np.clip(norm, 0, (1 << spec.bits) - 1)
            out[""] = _pack_words(norm, spec.bits)
    elif spec.kind == "rle":
        live = values if nrows is None else values[:nrows]
        change = np.nonzero(live[1:] != live[:-1])[0]
        starts = np.concatenate(
            [np.zeros(1, dtype=np.int64), change + 1])
        out[""] = np.ascontiguousarray(live[starts])
        # run STARTS (not cumulative ends): the decode rebuilds run
        # ids with one scatter + cumsum — linear work and a native
        # scan on TPU, where a searchsorted over ends would cost a
        # full sort of the decoded length
        out["#x"] = starts.astype(np.int32)
    else:
        out[""] = values
    if mask is not None:
        out["#v"] = (_pack_words(mask.astype(np.int64), 1)
                     if spec.mask_packed else mask)
    return out


def encode_column(spec: EncSpec, col) -> dict:
    return encode_values(spec, col.values, col.null_mask)


# -------------------------------------------------- per-table reporting

def scan_nbytes(col) -> int:
    """Bytes a device scan of this column reads under the active mode
    (encoded when a spec applies, raw otherwise) — the encoded-width
    input to the scheduler cost model and MemoryGovernor budget."""
    spec = column_spec(col)
    if spec is None:
        return raw_nbytes(col.values, col.null_mask)
    return encoded_nbytes(spec)


def table_specs(table) -> dict:
    """{column: EncSpec|None} under the active mode."""
    return {name: column_spec(col)
            for name, col in table.columns.items()}


def table_compression(table) -> dict:
    """Per-table compression report: raw vs encoded bytes and the
    ratio (1.0 when nothing encodes)."""
    raw = enc = 0
    for col in table.columns.values():
        r = raw_nbytes(col.values, col.null_mask)
        raw += r
        spec = column_spec(col)
        enc += r if spec is None else encoded_nbytes(spec)
    return {"raw_bytes": raw, "encoded_bytes": enc,
            "ratio": round(raw / enc, 4) if enc else 1.0}


# -------------------------------------------- manifest metadata (io/)

def manifest_set_encodings(dirpath: str, table: str,
                           specs: dict) -> None:
    """Record {column: spec-json|None} for a cached table into the
    directory's ``_manifest.json`` (alongside the integrity digests),
    so the encoding choice round-trips with the artifact."""
    from nds_tpu.io.integrity import MANIFEST_NAME, write_json_atomic
    path = os.path.join(dirpath, MANIFEST_NAME)
    doc: dict = {"version": 1, "files": {}}
    try:
        with open(path) as f:
            loaded = json.load(f)
        if isinstance(loaded, dict) and "files" in loaded:
            doc = loaded
    except (OSError, ValueError):
        pass
    from nds_tpu import columnar
    doc.setdefault("encodings", {})[table] = {
        "v": ENC_VERSION, "mode": columnar.mode(),
        "columns": {n: (spec_to_json(s) if s is not None else None)
                    for n, s in specs.items()}}
    write_json_atomic(path, doc)


def manifest_encodings(dirpath: str, table: str) -> "dict | None":
    """The persisted {column: EncSpec|None} for a cached table, or
    None when absent / written by a different encoder version or
    mode."""
    from nds_tpu.io.integrity import MANIFEST_NAME
    from nds_tpu import columnar
    try:
        with open(os.path.join(dirpath, MANIFEST_NAME)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    ent = (doc.get("encodings") or {}).get(table) \
        if isinstance(doc, dict) else None
    if (not isinstance(ent, dict) or ent.get("v") != ENC_VERSION
            or ent.get("mode") != columnar.mode()):
        return None
    out = {}
    for name, sj in (ent.get("columns") or {}).items():
        out[name] = None if sj is None else spec_from_json(sj)
    return out
