"""Traced decode of encoded column buffers (the late-materialization
half of nds_tpu/columnar/).

Every decode here runs INSIDE the consuming query's jax trace, so XLA
fuses the shift/mask (bitpack) or scatter+scan run-id rebuild (RLE)
into the one compiled program — encoded columns never round-trip
through HBM at full width. The contract with the scan (``device_exec._Trace``): decode
returns values in EXACTLY the dtype the raw upload would have produced
(``EncSpec.dtype``), so every downstream operator — joins on codes,
filters, group keys, semi-joins — is oblivious to the encoding and
string bytes still materialize only at the result compactor.
"""

from __future__ import annotations

import jax.numpy as jnp

from nds_tpu.columnar.encodings import EncSpec


def _unpack_words(words, n: int, bits: int):
    """Gather+shift+mask unpack of ``n`` fields of ``bits`` bits from
    int32 words (low field first). int32 arithmetic throughout: the
    arithmetic right shift's sign extension is masked off."""
    per = 32 // bits
    idx = jnp.arange(n, dtype=jnp.int32)
    w = jnp.take(words, idx // per)
    return (w >> ((idx % per) * bits)) & ((1 << bits) - 1)


def unpack_mask(words, n: int):
    return _unpack_words(words, n, 1).astype(bool)


def decode(spec: EncSpec, bufs: dict, key: str):
    """(values, validity) for one encoded scan column, traced. ``bufs``
    holds the encoded buffer set the executor uploaded under ``key``
    (+ ``#x``/``#v`` suffixes)."""
    n = spec.rows
    dt = jnp.dtype(spec.dtype)
    if spec.kind == "bitpack":
        words = bufs[key]
        if spec.bits >= 32:
            vals = (words.astype(jnp.int64) + spec.lo).astype(dt)
        else:
            field = _unpack_words(words, n, spec.bits)
            if -2**31 < spec.lo and spec.lo + (1 << spec.bits) < 2**31:
                # bias fits int32: stay on the native-width path
                vals = (field + spec.lo).astype(dt)
            else:
                vals = (field.astype(jnp.int64) + spec.lo).astype(dt)
    elif spec.kind == "rle":
        # run ids from run starts: scatter a 1 at each start, prefix-
        # sum, subtract 1 — linear work (a native scan on TPU), where
        # a searchsorted over run ends would pay a full sort of the
        # decoded length (measured 500x slower on XLA:CPU at 1M rows)
        starts = bufs[key + "#x"]
        seg = jnp.cumsum(jnp.zeros(n, jnp.int32).at[starts].add(
            jnp.int32(1))) - 1
        vals = jnp.take(bufs[key], seg)
    else:
        vals = bufs[key]
    from nds_tpu.analysis import plan_verify
    if plan_verify.verify_enabled() and vals.dtype != dt:
        # encoded-dtype propagation invariant: a decode that hands
        # downstream operators a different dtype than the raw upload
        # would silently change packing/compare semantics
        raise plan_verify.PlanVerifyError(
            [f"decoded dtype {vals.dtype} != declared {dt} "
             f"for {key!r}"], "columnar decode")
    valid = bufs.get(key + "#v")
    if valid is not None and spec.mask_packed:
        valid = unpack_mask(valid, n)
    return vals, valid
