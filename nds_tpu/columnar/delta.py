"""Delta segments + deleted-row bitmasks: writable warehouses over the
immutable encoded store.

The TPC maintenance phase (LF_* inserts, DF_* deletes) must not forfeit
what PR 7/12 bought: content-fingerprinted AOT programs and encoded
device buffers both assume table content is immutable. The old DML path
re-decoded every string column and re-ran np.unique over the whole
table on every insert — a full-table re-encode exactly when the TPC
metric charges for refresh time. This module makes mutation O(delta):

- **Inserts** land as append-only *segments*: the new rows concatenate
  onto the base arrays (a memcpy, never a decode). String dictionaries
  merge at DICTIONARY size — when the segment's values are already in
  the base dictionary the base codes are untouched; otherwise base
  codes remap through a dict-sized gather. Per-column encoding specs
  re-derive from EXACT merged statistics (``encodings.plan_from_stats``
  — the same decision procedure a fresh load runs, so merged-stats
  specs provably match what any other process plans from the same
  content) without an O(rows) re-scan.

- **Deletes** land as a deleted-row bitmask consulted by every scan
  keep-mask (device ``_run_scan`` row gate, reduced-scan-view keep,
  chunked ``_chunk_keep_mask``, CPU oracle context mask). Base columns
  are never gathered, so column objects — and their memoized encoding
  specs — survive a DF_* round untouched.

- **Digests** are segment-granular: a mutated table's content digest
  is a composition of (base digest, ordered segment digests, deleted
  bitmask digest), so ``cache/fingerprint.py`` invalidates only the
  programs that scan the touched table; every other table's stamp is
  bit-identical and its AOT entries keep hitting.

Segments are NORMALIZED through an arrow round-trip at append time so
the in-memory effective table is byte-identical to what a resumed
process reconstructs from the persisted parquet segments — digests and
merged-stats specs therefore agree across incarnations by construction
(the crash-safety contract maintenance's journal relies on).

No jax imports: mutation must run wherever the warehouse loads.
"""

from __future__ import annotations

import hashlib
import os
import re
from dataclasses import dataclass, field

import numpy as np

from nds_tpu.columnar import encodings
from nds_tpu.io.host_table import HostColumn, HostTable

ATTR = "_nds_delta"

# op-list sidecar committed with every delta version dir; CRC-stamped
# (io/integrity.py) and written BEFORE the snapshot manifest references
# the version, so a torn commit leaves the previous version readable
OPS_NAME = "ops.json"

_VDIR_RE = re.compile(r"(?:^|[\\/])_v(\d+)[\\/]")


def _count(name: str) -> None:
    from nds_tpu.obs import metrics as obs_metrics
    obs_metrics.counter(name).inc()


@dataclass
class Segment:
    """One committed insert: ``rows`` appended under ``seg_id`` with a
    content digest recorded at append time (recomputable from the
    persisted parquet — normalization makes them equal)."""

    seg_id: str
    rows: int
    digest: str
    # the segment table rides along until persisted so maintenance can
    # write exactly the rows that were appended; dropped after persist
    table: "HostTable | None" = None
    persisted: bool = False


@dataclass
class DeltaState:
    """Mutation lineage attached to a HostTable as ``_nds_delta``."""

    base_rows: int
    base_digest: str
    segments: list = field(default_factory=list)
    # True = deleted, over CURRENT physical rows; None = no deletes
    deleted: "np.ndarray | None" = None
    # exact per-column stats for spec merging: {col: {lo, hi, nvalid,
    # runs}} — lo/hi over VALID values (int columns), runs over all
    # physical values (mask-free non-float columns)
    col_stats: dict = field(default_factory=dict)
    # deletes since the last persist (maintenance persists one
    # cumulative mask per function)
    deleted_dirty: bool = False

    def clone(self) -> "DeltaState":
        return DeltaState(self.base_rows, self.base_digest,
                          list(self.segments),
                          None if self.deleted is None
                          else self.deleted,
                          {k: dict(v)
                           for k, v in self.col_stats.items()},
                          self.deleted_dirty)

    # ------------------------------------------------------- digesting

    def deleted_digest(self) -> str:
        if self.deleted is None or not self.deleted.any():
            return "none"
        h = hashlib.sha256()
        h.update(str(len(self.deleted)).encode())
        h.update(np.packbits(self.deleted).tobytes())
        return h.hexdigest()

    def content_digest(self) -> str:
        """Segment-granular content digest: a pure function of (base,
        ordered segments, deleted mask) — cache/fingerprint.py calls
        this instead of re-hashing the full concatenated arrays."""
        h = hashlib.sha256()
        h.update(b"delta|")
        h.update(self.base_digest.encode())
        for seg in self.segments:
            h.update(f"|seg:{seg.seg_id}:{seg.rows}:"
                     f"{seg.digest}".encode())
        h.update(f"|del:{self.deleted_digest()}".encode())
        return h.hexdigest()

    def deleted_count(self) -> int:
        return 0 if self.deleted is None else int(self.deleted.sum())


# ----------------------------------------------------------- accessors

def state_of(table) -> "DeltaState | None":
    return getattr(table, ATTR, None)


def live_mask(table) -> "np.ndarray | None":
    """Boolean True-=-live mask over physical rows, or None when every
    physical row is visible (the common case every scan fast-paths)."""
    st = state_of(table)
    if st is None or st.deleted is None or not st.deleted.any():
        return None
    return ~st.deleted


def visible_rows(table) -> int:
    """Logical row count: physical rows minus deleted rows (the number
    a COUNT(*) returns; ``table.nrows`` stays physical because buffer
    shapes derive from it)."""
    st = state_of(table)
    return table.nrows - (0 if st is None else st.deleted_count())


def segment_count(table) -> int:
    st = state_of(table)
    return 0 if st is None else len(st.segments)


def delta_report(table) -> "dict | None":
    """Per-table delta block for observability (ndsreport's delta
    column): segment count, appended rows, masked (deleted) rows."""
    st = state_of(table)
    if st is None:
        return None
    return {"segments": len(st.segments),
            "appended_rows": sum(s.rows for s in st.segments),
            "masked_rows": st.deleted_count()}


# -------------------------------------------------------- stats (exact)

def _col_stats(col: HostColumn) -> dict:
    """Exact stats for one column, the merge-able form of what
    ``plan_values`` measures: int bounds over valid values, run count
    over physical values (mask-free non-float columns only)."""
    vals = col.values
    lo = hi = runs = None
    nvalid = len(vals) if col.null_mask is None \
        else int(col.null_mask.sum())
    if np.issubdtype(vals.dtype, np.integer):
        lo, hi = encodings._int_bounds(vals, col.null_mask)
    if col.null_mask is None and not np.issubdtype(vals.dtype,
                                                   np.floating):
        runs = encodings._runs_of(vals)
    return {"lo": lo, "hi": hi, "nvalid": nvalid, "runs": runs}


def _merge_bounds(a: dict, b: dict) -> "tuple":
    """Exact merge of two parts' (lo, hi, nvalid): parts with zero
    valid values contribute nothing (matching ``_int_bounds`` over the
    concatenation)."""
    nvalid = a["nvalid"] + b["nvalid"]
    if a["nvalid"] == 0:
        return b["lo"], b["hi"], nvalid
    if b["nvalid"] == 0:
        return a["lo"], a["hi"], nvalid
    return min(a["lo"], b["lo"]), max(a["hi"], b["hi"]), nvalid


def _merge_runs(base_runs, seg_runs, base_last, seg_first,
                base_rows: int, seg_rows: int):
    """Exact run-count merge: boundary runs fuse when the base's last
    value equals the segment's first."""
    if base_runs is None or seg_runs is None:
        return None
    if base_rows == 0:
        return seg_runs
    if seg_rows == 0:
        return base_runs
    return base_runs + seg_runs - (1 if base_last == seg_first else 0)


# ------------------------------------------------------------- mutation

def _normalize_segment(seg: HostTable) -> HostTable:
    """Arrow round-trip the segment so its bytes (including masked
    slots) equal what a resumed process reads back from the persisted
    parquet — content digests and merged stats then agree across
    incarnations by construction."""
    from nds_tpu.io import csv_io
    return csv_io.from_arrow(seg.name, seg.schema, csv_io.to_arrow(seg))


def _ensure_state(table: HostTable) -> DeltaState:
    st = state_of(table)
    if st is not None:
        return st.clone()
    from nds_tpu.cache import fingerprint
    st = DeltaState(base_rows=table.nrows,
                    base_digest=fingerprint.table_digest(table))
    for name, col in table.columns.items():
        st.col_stats[name] = _col_stats(col)
    return st


def _merge_string_column(base: HostColumn, seg: HostColumn):
    """Merge a dictionary-encoded column without decoding a single
    base row. Returns (values, dictionary, base_remap, seg_remap) —
    remaps are dict-sized monotone gathers (or None when untouched)."""
    base_dict = base.dictionary.astype(str)
    seg_dict = seg.dictionary.astype(str) if seg.dictionary is not None \
        else np.array([], dtype=str)
    pos = np.searchsorted(base_dict, seg_dict)
    pos_c = np.clip(pos, 0, max(len(base_dict) - 1, 0))
    known = len(base_dict) > 0 and bool(
        np.all(base_dict[pos_c] == seg_dict)) if len(seg_dict) else True
    if known:
        # segment values ⊆ base dictionary: base codes byte-identical
        seg_codes = pos_c.astype(np.int32)[seg.values] \
            if len(seg_dict) else seg.values.astype(np.int32)
        values = np.concatenate([base.values, seg_codes])
        return values, base.dictionary, None, \
            pos_c.astype(np.int32) if len(seg_dict) else None
    merged = np.unique(np.concatenate([base_dict, seg_dict]))
    remap_base = np.searchsorted(merged, base_dict).astype(np.int32)
    remap_seg = np.searchsorted(merged, seg_dict).astype(np.int32)
    values = np.concatenate([remap_base[base.values],
                             remap_seg[seg.values]])
    return values, merged.astype(object), remap_base, remap_seg


def append_segment(table: HostTable, seg: HostTable,
                   seg_id: str = "") -> HostTable:
    """New effective HostTable with ``seg``'s rows appended as a delta
    segment: numeric columns concatenate, string dictionaries merge at
    dictionary size, encoding specs re-derive from exact merged stats
    and seed the new columns' memos — no base decode, no np.unique
    over rows, no re-encode of existing device buffers' source."""
    seg = _normalize_segment(seg)
    st = _ensure_state(table)
    from nds_tpu.cache import fingerprint
    seg_digest = fingerprint.table_digest(seg)
    n_old, n_new = table.nrows, seg.nrows
    cols: dict[str, HostColumn] = {}
    for f in table.schema:
        bcol = table.columns[f.name]
        scol = seg.columns[f.name]
        stats = st.col_stats.get(f.name) or _col_stats(bcol)
        seg_stats = _col_stats(scol)
        if bcol.is_string:
            values, dictionary, remap_base, _remap_seg = \
                _merge_string_column(bcol, scol)
            if remap_base is not None and stats["nvalid"] > 0:
                # monotone remap: bounds map through the gather
                stats = dict(stats,
                             lo=int(remap_base[stats["lo"]]),
                             hi=int(remap_base[stats["hi"]]))
            # seg codes changed dictionary space: re-measure the
            # appended slice (O(segment)) in the merged space
            seg_slice = values[n_old:]
            seg_stats = _col_stats(HostColumn(
                scol.dtype, seg_slice, dictionary, scol.null_mask))
        else:
            if scol.values.dtype != bcol.values.dtype:
                scol = HostColumn(scol.dtype,
                                  scol.values.astype(bcol.values.dtype),
                                  None, scol.null_mask)
                seg_stats = _col_stats(scol)
            values = np.concatenate([bcol.values, scol.values])
            dictionary = None
        mask = None
        if bcol.null_mask is not None or scol.null_mask is not None:
            mask = np.concatenate([
                bcol.null_mask if bcol.null_mask is not None
                else np.ones(n_old, dtype=bool),
                scol.null_mask if scol.null_mask is not None
                else np.ones(n_new, dtype=bool)])
            if mask.all():
                mask = None
        lo, hi, nvalid = _merge_bounds(stats, seg_stats)
        runs = None
        if mask is None and not np.issubdtype(values.dtype,
                                              np.floating):
            runs = _merge_runs(
                stats["runs"], seg_stats["runs"],
                values[n_old - 1] if n_old else None,
                values[n_old] if n_new else None, n_old, n_new)
            if runs is None and (stats["runs"] is not None
                                 or n_old == 0):
                runs = encodings._runs_of(values[n_old:]) \
                    if n_old == 0 else None
        merged_stats = {"lo": lo, "hi": hi, "nvalid": nvalid,
                        "runs": runs}
        col = HostColumn(bcol.dtype, values, dictionary, mask)
        spec = encodings.plan_from_stats(
            rows=len(values), dtype=values.dtype.name,
            raw=encodings.raw_nbytes(values, mask),
            lo=lo if np.issubdtype(values.dtype, np.integer) else None,
            hi=hi if np.issubdtype(values.dtype, np.integer) else None,
            runs=runs, has_mask=mask is not None,
            is_string=col.is_string)
        encodings.seed_column_spec(col, spec)
        _count("delta_spec_merges_total")
        st.col_stats[f.name] = merged_stats
        cols[f.name] = col
    if st.deleted is not None:
        st.deleted = np.concatenate(
            [st.deleted, np.zeros(n_new, dtype=bool)])
    st.segments.append(Segment(
        seg_id or f"seg-{len(st.segments)}", n_new, seg_digest,
        table=seg))
    out = HostTable(table.name, table.schema, cols)
    setattr(out, ATTR, st)
    _count("delta_segments_appended_total")
    return out


def apply_delete(table: HostTable, keep: np.ndarray) -> HostTable:
    """New effective HostTable with rows where ``keep`` is False marked
    deleted. Column objects are SHARED with the input table — their
    arrays, dictionaries and memoized encoding specs survive untouched;
    only the delta bitmask (and therefore the content digest) moves."""
    st = _ensure_state(table)
    dead = ~np.asarray(keep, dtype=bool)
    st.deleted = dead if st.deleted is None else (st.deleted | dead)
    st.deleted_dirty = True
    out = HostTable(table.name, table.schema, dict(table.columns))
    setattr(out, ATTR, st)
    _count("delta_rows_deleted_total")
    return out


_PHYSICAL_MEMO = "_nds_physical"


def physical(table: HostTable) -> HostTable:
    """Physically materialized copy: deleted rows gathered out, delta
    state dropped (compaction, SPMD sharding — packed words must align
    with the shard layout, so the sharded path materializes first).
    Memoized on the table object."""
    st = state_of(table)
    if st is None:
        return table
    memo = getattr(table, _PHYSICAL_MEMO, None)
    if memo is not None:
        return memo
    mask = live_mask(table)
    if mask is None:
        out = HostTable(table.name, table.schema, dict(table.columns))
    else:
        cols = {}
        for f in table.schema:
            col = table.columns[f.name]
            cols[f.name] = HostColumn(
                col.dtype, col.values[mask], col.dictionary,
                None if col.null_mask is None else col.null_mask[mask])
        out = HostTable(table.name, table.schema, cols)
    try:
        setattr(table, _PHYSICAL_MEMO, out)
    except Exception:  # noqa: BLE001 - slotted table: rebuild next time
        pass
    return out


# ---------------------------------------------------------- persistence

def persist_pending(table: HostTable, version_dir: str,
                    note: str = "") -> "list[str] | None":
    """Write every unpersisted segment (parquet) and, when deletes are
    pending, the cumulative deleted bitmask (npz) into ``version_dir``
    with a CRC-stamped op list + integrity digest manifest. Returns
    the written file paths (ops.json first) or None when nothing is
    pending. The caller commits the returned paths into the snapshot
    manifest — the ATOMIC commit point; a crash before that leaves an
    unreferenced version dir the reader never visits."""
    from nds_tpu.io import csv_io, integrity
    from nds_tpu.resilience import faults
    st = state_of(table)
    if st is None:
        return None
    ops, files = [], []
    for i, seg in enumerate(st.segments):
        if seg.persisted:
            continue
        fname = f"delta-{i}.parquet"
        path = os.path.join(version_dir, fname)
        csv_io.write_table(seg.table, path, "parquet")
        ops.append({"kind": "insert", "file": fname,
                    "seg_id": seg.seg_id, "rows": seg.rows,
                    "digest": seg.digest})
        files.append(path)
        seg.persisted = True
        seg.table = None
    if st.deleted_dirty and st.deleted is not None:
        fname = f"mask-{len(st.segments)}.npz"
        path = os.path.join(version_dir, fname)
        os.makedirs(version_dir, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, packed=np.packbits(st.deleted),
                     rows=np.int64(len(st.deleted)))
        os.replace(tmp, path)
        ops.append({"kind": "delete", "file": fname,
                    "rows": int(len(st.deleted)),
                    "deleted": st.deleted_count(),
                    "digest": st.deleted_digest()})
        files.append(path)
        st.deleted_dirty = False
    if not ops:
        return None
    ops_path = os.path.join(version_dir, OPS_NAME)
    integrity.write_json_atomic(
        ops_path, integrity.stamp_crc(
            {"version": 1, "table": table.name, "note": note,
             "ops": ops}))
    # per-segment digest manifest: delta files get the same re-hash-on-
    # load verification as transcode output (io.verify_digests)
    integrity.write_manifest(version_dir)
    # chaos site: a fault here models the torn commit — files written,
    # snapshot manifest never updated, reader serves the prior version
    faults.fault_point("store.commit", table=table.name,
                       version_dir=version_dir, note=note)
    return [ops_path] + files


def split_paths(paths) -> "tuple[list, dict]":
    """Partition a snapshot manifest's path list into (base files,
    {version -> version dir}) — delta artifacts live under
    ``<table>/_v<N>/`` and must not reach the format-sniffing reader."""
    base, versions = [], {}
    for p in paths:
        m = _VDIR_RE.search(p)
        if m is None:
            base.append(p)
        else:
            versions.setdefault(int(m.group(1)),
                                os.path.dirname(p))
    return base, versions


def load_versioned(name: str, schema, paths: list,
                   default_fmt: str) -> HostTable:
    """Rebuild the effective table from a snapshot lineage: read the
    base files, then replay each committed version's op list in order
    (inserts re-append their segments — re-deriving the same digests
    and merged-stats specs the writer had — and deletes restore the
    cumulative bitmask). Files re-hash against the version dir's
    digest manifest when io.verify_digests is on; a recorded-vs-
    recomputed segment digest mismatch is a CorruptArtifact."""
    import json

    from nds_tpu.cache import fingerprint
    from nds_tpu.io import csv_io, integrity
    base_paths, versions = split_paths(paths)
    table = csv_io.read_paths_auto(base_paths, name, schema,
                                   default_fmt)
    for v in sorted(versions):
        vdir = versions[v]
        ops_path = os.path.join(vdir, OPS_NAME)
        try:
            with open(ops_path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise integrity.CorruptArtifact(
                ops_path, "readable op list", f"unreadable: {e}")
        if not integrity.check_crc(doc):
            raise integrity.CorruptArtifact(
                ops_path, "valid crc", "crc mismatch")
        for op in doc.get("ops", []):
            path = os.path.join(vdir, op["file"])
            integrity.verify_paths([path], name)
            if op["kind"] == "insert":
                seg = csv_io.read_table_fmt(path, name, schema,
                                            "parquet")
                table = append_segment(table, seg,
                                       seg_id=op.get("seg_id", ""))
                st = state_of(table)
                got = st.segments[-1].digest
                if op.get("digest") and got != op["digest"]:
                    raise integrity.CorruptArtifact(
                        path, op["digest"], got)
                st.segments[-1].persisted = True
                st.segments[-1].table = None
            elif op["kind"] == "delete":
                with np.load(path) as z:
                    rows = int(z["rows"])
                    deleted = np.unpackbits(
                        z["packed"])[:rows].astype(bool)
                if rows != table.nrows:
                    raise integrity.CorruptArtifact(
                        path, f"{table.nrows} rows", f"{rows} rows")
                st = _ensure_state(table)
                st.deleted = deleted
                st.deleted_dirty = False
                new = HostTable(table.name, table.schema,
                                dict(table.columns))
                setattr(new, ATTR, st)
                st_digest = st.deleted_digest()
                if op.get("digest") and op["digest"] != st_digest:
                    raise integrity.CorruptArtifact(
                        path, op["digest"], st_digest)
                table = new
    # memoize the composed digest now (cheap; avoids a full re-hash on
    # tables that never mutated in this process)
    fingerprint.table_digest(table)
    return table


def has_delta_paths(paths) -> bool:
    return any(_VDIR_RE.search(p) for p in paths)
