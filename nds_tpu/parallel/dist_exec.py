"""Distributed (multi-chip) plan executor: shard_map over a device mesh.

The reference scales by Spark data parallelism with shuffle exchanges
delegated to the engine (SURVEY.md §2.6). The TPU-native equivalent here:

- fact tables are ROW-SHARDED across the 1-D mesh axis; dimension tables
  replicate (classic OLAP DP — the Spark broadcast-join analog);
- probe-side joins run device-local when the build side is replicated;
  when BOTH sides are sharded, both repartition by join key through the
  `exchange` all_to_all so matching keys colocate — shuffle over ICI,
  the deliverable the survey calls out (§5 "distributed communication
  backend");
- grouped aggregation exchanges rows by group-key hash, then aggregates
  locally: every group lands wholly on one device, so distinct/avg need
  no merge logic; global aggregates use psum/pmin/pmax;
- the whole query still compiles to ONE XLA program (shard_map under
  jit): collectives are inside the program, not host-driven.

Exchange overflow (static bucket exceeded) is counted in-program and
surfaced; execute() retries once with doubled slack — adaptive, never
silent (utils.report.TaskFailureCollector records the retry).
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P_

from nds_tpu.analysis import jitsan
from nds_tpu.engine import device_exec as dx
from nds_tpu.engine.device_exec import DCtx, DVal, DeviceExecError, _ok
from nds_tpu.io.host_table import HostTable
from nds_tpu.obs import costs as obs_costs
from nds_tpu.obs import memwatch
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs.trace import get_tracer
from nds_tpu.parallel.exchange import exchange, exchange_hierarchical
from nds_tpu.parallel.mesh import (
    DATA_AXIS, HOST_AXIS, make_mesh, pad_to_multiple,
)
from nds_tpu.resilience import faults
from nds_tpu.sql import plan as P
from nds_tpu.utils.report import TaskFailureCollector

if hasattr(jax, "shard_map"):  # jax>=0.8
    _shard_map = jax.shard_map
else:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(fn, **kw):
    """shard_map with replication checking off, across jax versions (the
    kwarg was renamed check_rep -> check_vma)."""
    import inspect
    params = inspect.signature(_shard_map).parameters
    if "check_vma" in params:
        kw["check_vma"] = False
    elif "check_rep" in params:
        kw["check_rep"] = False
    return _shard_map(fn, **kw)

# tables at or above this row count shard across the mesh; smaller ones
# replicate (the Spark broadcast threshold analog, but by rows)
DEFAULT_SHARD_THRESHOLD = 8192


class DistributedExecutor(dx.DeviceExecutor):
    """Session-compatible executor that runs plans SPMD over a mesh."""

    # survivor reduction applies to REPLICATED tables only (scan_view
    # below): filtered dimension scans shrink every device's copy and
    # all downstream gather-join capacities; sharded tables keep the
    # shard layout as their capacity story
    SCAN_REDUCE = True

    # columnar encoding (nds_tpu/columnar/) stays OFF on the sharded
    # path: packed words don't align with the shard/pad row layout
    # (a row's field may straddle a shard boundary word) and RLE run
    # ends are global offsets a per-shard trace can't interpret.
    # Sharded placements scan raw even when the mode is on — results
    # stay identical, only the bytes win is forfeit (ROADMAP item 3
    # owns making multi-host first-class)
    COLUMNAR_UPLOAD = False

    def __init__(self, tables: dict[str, HostTable], mesh=None,
                 n_devices: int | None = None,
                 shard_tables: set[str] | None = None,
                 shard_threshold: int = DEFAULT_SHARD_THRESHOLD,
                 slack: float = 2.0,
                 multiprocess: bool | None = None):
        super().__init__(tables)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        # multi-controller SPMD (one process per host): buffers must be
        # GLOBAL jax.Arrays, each process materializing only the shards
        # its devices own (parallel.multihost). Auto-detected.
        self.multiprocess = (jax.process_count() > 1
                             if multiprocess is None else multiprocess)
        self.n_dev = int(np.prod(self.mesh.devices.shape))
        # 2-D (host, lane) mesh: collectives span BOTH axes; the
        # exchange runs its hierarchical DCN-then-ICI form
        self.mesh_2d = self.mesh.devices.ndim == 2
        if self.mesh_2d:
            if tuple(self.mesh.axis_names) != (HOST_AXIS, DATA_AXIS):
                raise ValueError(
                    f"2-D mesh axes must be ({HOST_AXIS!r}, "
                    f"{DATA_AXIS!r}), got {self.mesh.axis_names} — "
                    f"build it with make_multihost_mesh")
            self.n_hosts, self.n_lanes = self.mesh.devices.shape
            self.axes = (HOST_AXIS, DATA_AXIS)
        else:
            self.n_hosts, self.n_lanes = 1, self.n_dev
            self.axes = DATA_AXIS
        self._explicit_shard = shard_tables
        self.shard_threshold = shard_threshold
        self.slack = slack
        from nds_tpu.analysis import plan_verify
        if plan_verify.verify_enabled():
            # exchange static-shape contract: a slack below 1.0 or a
            # degenerate mesh makes every all_to_all bucket undersized
            vs = plan_verify.check_exchange_invariants(
                max(t.nrows for t in tables.values()) if tables else 0,
                self.n_dev, self.slack)
            if vs:
                raise plan_verify.PlanVerifyError(vs, "DistributedExecutor")

    def _is_sharded(self, table: str) -> bool:
        if self._explicit_shard is not None:
            return table in self._explicit_shard
        return self.tables[table].nrows >= self.shard_threshold

    def grow_slack(self) -> None:
        """Scheduler ladder hook (engine/scheduler.py): an exchange
        overflow that persisted through the in-execute slack-doubling
        retries re-plans at a doubled BASE slack — every compiled
        program is invalidated (their exchange capacities baked in the
        old slack), and the next execute recompiles from the new
        floor. Collective-safe: the scheduler only calls this after a
        consensus round, so every rank re-plans together."""
        self.slack *= 2
        for key in list(self._compiled):
            self._evict_query_state(key)
        obs_metrics.counter("slack_replans_total").inc()

    def _dev(self, arr: np.ndarray, sharded: bool):
        """Host array -> device buffer. Single-process: plain upload
        (jit lays it out). Multi-process: a global jax.Array built
        shard-by-shard so each host only holds its own rows."""
        if not self.multiprocess:
            return jnp.asarray(arr)
        from nds_tpu.parallel.multihost import make_global_array
        spec = P_(self.axes) if sharded else P_()
        return make_global_array(self.mesh, spec, np.asarray(arr))

    # buffers: sharded tables pad to a multiple of n_dev
    def _upload(self, bufs: dict, table: str, name: str) -> None:
        key = f"{table}.{name}"
        if key not in self._buffers:
            col = self.tables[table].columns[name]
            vals = col.values
            sharded = self._is_sharded(table)
            if sharded:
                cap = pad_to_multiple(max(len(vals), self.n_dev),
                                      self.n_dev)
                pad = cap - len(vals)
                if pad:
                    vals = np.concatenate(
                        [vals, np.zeros(pad, dtype=vals.dtype)])
                if col.null_mask is not None:
                    m = np.concatenate(
                        [col.null_mask, np.zeros(pad, dtype=bool)])
                    self._buffers[key + "#v"] = self._dev(m, True)
            elif col.null_mask is not None:
                self._buffers[key + "#v"] = self._dev(
                    col.null_mask, False)
            self._buffers[key] = self._dev(vals, sharded)
        bufs[key] = self._buffers[key]
        if key + "#v" in self._buffers:
            bufs[key + "#v"] = self._buffers[key + "#v"]

    def _upload_live(self, bufs: dict, table: str) -> None:
        # delta deleted-row bitmask shards with the table's own pad
        # layout (False-padded, so padded slots stay dead) — the
        # sharded scan ANDs its local slice into the row gate exactly
        # like the single-chip path
        from nds_tpu.columnar import delta
        live = delta.live_mask(self.tables[table])
        if live is None:
            return
        key = f"{table}.__live"
        if key not in self._buffers:
            sharded = self._is_sharded(table)
            if sharded:
                cap = pad_to_multiple(max(len(live), self.n_dev),
                                      self.n_dev)
                pad = cap - len(live)
                if pad:
                    live = np.concatenate(
                        [live, np.zeros(pad, dtype=bool)])
            self._buffers[key] = self._dev(live, sharded)
        bufs[key] = self._buffers[key]

    def _compile(self, planned: P.PlannedQuery):
        side = {}

        def make(slack):
            def fn(shard_bufs, repl_bufs):
                tr = _DistTrace(self, {**shard_bufs, **repl_bufs}, slack)
                # collect per-shuffle destination-skew ratios at trace
                # time (parallel/exchange.skew_trace): the program
                # returns the worst one so the executor can publish
                # the exchange_skew_ratio gauge host-side — an output,
                # not a debug callback, so the executable still
                # serializes into the AOT plan cache
                from nds_tpu.parallel.exchange import skew_trace
                with skew_trace() as skews:
                    row, outs, dicts = tr.run_query(planned)
                side["dicts"] = dicts
                side["kernels"] = dict(tr.kernels)
                side["ops_est"] = int(tr.ops_est)
                overflow = tr.total_overflow()
                if skews:
                    skew = skews[0]
                    for s in skews[1:]:
                        skew = jnp.maximum(skew, s)
                    # every device sees every exchange; the fleet-wide
                    # worst is the gauge's value
                    skew = lax.pmax(skew, tr.axes)
                else:
                    skew = jnp.zeros((), jnp.float32)
                return row, outs, overflow, skew
            return fn

        def build(slack):
            sharded_keys, repl_keys = self._split_keys(planned)
            wrapped = shard_map(
                make(slack), mesh=self.mesh,
                in_specs=({k: P_(self.axes) for k in sharded_keys},
                          {k: P_() for k in repl_keys}),
                out_specs=P_())
            # ndslint: waive[NDS111] -- builds the traced callable only; AOT lower+compile routes through cache.aot in _execute_traced
            return jax.jit(wrapped), sharded_keys, repl_keys

        return build, side

    # ------------------------------------------------- plan cache (AOT)

    def _fingerprint_parts(self) -> dict:
        parts = super()._fingerprint_parts()
        parts.update({
            "mesh_shape": tuple(self.mesh.devices.shape),
            "mesh_axes": tuple(self.mesh.axis_names),
            "n_dev": self.n_dev,
            "shard_threshold": self.shard_threshold,
            "explicit_shard": (tuple(sorted(self._explicit_shard))
                               if self._explicit_shard is not None
                               else None),
        })
        return parts

    def _cache_for_sharded(self, planned, slack: float):
        """Plan-cache handle for the sharded program — single-process
        worlds only: a multi-controller executable spans every rank's
        devices, and per-rank deserialization against a local client
        is not a supported jax path. Multi-process runs fall back to
        jax's own persistent XLA cache (utils/xla_cache.py)."""
        if self.multiprocess:
            return None, None
        return self._plan_fingerprint(planned, slack)

    def _load_cached_sharded(self, planned, slack, state, side,
                             timings, tracer) -> bool:
        """Fill state[jitted/sk/rk] + side[dicts] from a verified
        plan-cache hit; False on miss (compile as always). The
        (cache, fingerprint) handle is stashed on ``state`` for
        ``_persist_sharded`` — the fingerprint hashes the whole plan
        tree, so a miss must not pay it twice."""
        from nds_tpu.cache import aot as cache_aot
        from nds_tpu.obs import metrics as obs_metrics
        pc, fp = self._cache_for_sharded(planned, slack)
        state["cache_handle"] = (pc, fp)
        if not fp:
            return False
        # the hit/miss verdict is counted HERE, after the sharded
        # key-split compat check load_cached cannot run itself
        with tracer.span("cache.load", fp=fp[:12]):
            bufs = self._collect_buffers(planned)
            hit = cache_aot.load_cached(pc, fp, type(self).__name__,
                                        timings, count=False)
        if hit is None:
            return False
        compiled, extra = hit
        sk, rk = extra.get("sk"), extra.get("rk")
        ok = sk is not None and rk is not None
        if ok and not cache_aot.call_compatible(
                compiled,
                {k: bufs[k] for k in sk if k in bufs},
                {k: bufs[k] for k in rk if k in bufs}):
            from nds_tpu.cache.store import _warn
            _warn(f"sharded entry {fp[:12]}… is "
                  f"signature-incompatible; recompiling fresh")
            ok = False
        obs_metrics.counter(
            "compile_cache_hits_total" if ok
            else "compile_cache_misses_total").inc()
        if not ok:
            return False
        state["jitted"], state["sk"], state["rk"] = compiled, sk, rk
        side["dicts"] = extra.get("dicts")
        side["kernels"] = extra.get("kernels")
        side["ops_est"] = extra.get("ops_est")
        return True

    def _persist_sharded(self, planned, slack, state, side) -> None:
        from nds_tpu.cache import aot as cache_aot
        pc, fp = state.pop("cache_handle", (None, None))
        if fp:
            cache_aot.persist(pc, fp, type(self).__name__,
                              state["jitted"],
                              {"sk": state["sk"], "rk": state["rk"],
                               "dicts": side.get("dicts"),
                               "kernels": side.get("kernels"),
                               "ops_est": side.get("ops_est")},
                              meta={"slack": slack})

    # survivor cap for turning a SHARDED filtered scan into a
    # replicated reduced build side (the broadcast-join move Spark AQE
    # makes under its broadcast threshold): survivors above this keep
    # the sharded layout — replicating them would cost more than the
    # exchange they avoid
    BROADCAST_ROWS = 1 << 18

    def scan_view(self, node):
        rv = super().scan_view(node)
        if rv is None or not self._is_sharded(node.table):
            return rv
        # sharded table: only take the reduced (replicated) form when
        # the survivor set is broadcast-sized
        if rv.nrows <= self.BROADCAST_ROWS:
            return rv
        # reject permanently: the decision is deterministic, and the
        # cached view's survivor idx is O(rows) host memory (multi-GB
        # for a half-surviving SF100 fact) that would otherwise be
        # retained without ever uploading a buffer
        for ck, v in self._scan_views.items():
            if v is rv:
                self._scan_views[ck] = "full"
                break
        return None

    def _reduced_to_device(self, arr):
        # multiprocess mode needs global (replicated) jax.Arrays
        return self._dev(arr, sharded=False)

    def _split_keys(self, planned):
        bufs = self._collect_buffers(planned)
        sharded, repl = [], []
        for k in bufs:
            table = k.split(".", 1)[0]
            if "@" in table:
                # reduced-scan buffers ("table@digest.col") are always
                # replicated — broadcast-sized by scan_view's cap even
                # when the base table is sharded
                repl.append(k)
            else:
                (sharded if self._is_sharded(table)
                 else repl).append(k)
        return sharded, repl

    # compiled shard_map programs are large (the 8-way virtual-CPU
    # forms of the big NDS plans run to GBs of executable + constant
    # memory each); a 99-query power run must not accumulate them
    # unboundedly — LRU-evict beyond this many entries
    MAX_COMPILED = 24

    # tighter than the single-chip default: 8-device shard_map compile
    # memory/time is the binding constraint (q64 traced to 54k jaxpr
    # eqns in ONE program and its 8-device compile exceeded 130 GB host
    # RAM before splitting — VERDICT r4 weak #2)
    STAGE_WEIGHT = int(os.environ.get("NDS_TPU_STAGE_DIST", "24"))

    def _plan_for_dispatch(self, planned):
        """Parameterized plans run INLINED on the sharded path (both
        execute() and the inherited execute_async): sharded programs
        bake literals into their traced collectives, and the
        multi-rank story (rank-local binding would have to agree
        across ranks) is not built yet."""
        from nds_tpu.sql import params as sqlparams
        return sqlparams.inline(planned)

    def execute(self, planned: P.PlannedQuery, key: object = None):
        """Multichip execute with the SAME timing contract as the
        single-chip executor: compile/execute/materialize wall-clock,
        bytes_scanned and the roofline fields land in last_timings and
        the query span, and the staged sub-program bill folds in after
        materialize (the round-5 advisor finding: multichip queries
        silently dropped their bill)."""
        faults.fault_point("device.execute",
                           executor=type(self).__name__)
        from nds_tpu.resilience import watchdog
        watchdog.beat("engine", phase="device.execute",
                      executor=type(self).__name__)
        planned = self._plan_for_dispatch(planned)
        key = key if key is not None else id(planned)
        orig = planned
        tracer = get_tracer()
        # a failed query must never inherit the previous query's span
        self.last_query_span = None
        qspan = tracer.begin("device.execute",
                             executor=type(self).__name__,
                             devices=self.n_dev)
        with tracer.attach(qspan):
            try:
                out, timings = self._execute_traced(planned, orig, key,
                                                    tracer)
            except BaseException as exc:
                # a staged sub's span must not survive as the failed
                # query's (subs set last_query_span on their success)
                self.last_query_span = None
                # release the attempt's accounted scan bytes (success
                # and overflow paths release inline by popping the same
                # token, so this covers ONLY raises between the add and
                # either release — never a second release)
                memwatch.sub_live(
                    (self.last_timings or {}).pop("__live_bytes", 0.0))
                qspan.set(error=f"{type(exc).__name__}: {exc}").end()
                raise
        qspan.set(timings=dict(timings)).end()
        self.last_query_span = qspan or None
        return out

    def _execute_traced(self, planned, orig, key, tracer):
        import time as _time
        planned = self._staged_effective(planned, key)
        timings = {"compile_ms": 0.0}
        self.last_timings = timings
        if key not in self._compiled:
            while len(self._compiled) >= self.MAX_COMPILED:
                old = next(iter(self._compiled))
                self._compiled.pop(old)
                # staged-plan state pins its plan through _compiled's
                # strong ref; evict them together or a recycled id()
                # can serve another query's staged split
                self._evict_query_state(old)
            # strong refs: the CALLER'S plan pins the id()-key, the
            # staged main plan is what actually compiled (base executor
            # rationale)
            self._compiled[key] = (self._compile(planned), {},
                                   (orig, planned))
        else:
            # LRU refresh: move the hit to the back of the dict order
            self._compiled[key] = self._compiled.pop(key)
        (build, side), state, _ref = self._compiled[key]
        slack = state.get("slack", self.slack)
        # the ad-hoc `for attempt in range(3)` slack loop, generalized
        # onto the shared resilience policy (no backoff sleep: each
        # retry already pays a full recompile; policy built by the
        # pipeline module — the single home of engine retry wiring)
        from nds_tpu.engine.scheduler import adaptive_policy
        for attempt in adaptive_policy(3).attempts():
            if "jitted" not in state or state.get("slack") != slack:
                # free the previous slack's executable BEFORE compiling
                # the bigger one: the 8-way compiled forms of wide
                # plans are GBs each, and holding both was the
                # difference between fitting and OOM on the virtual
                # mesh (q72's slack-2 -> slack-4 retry)
                state.pop("jitted", None)
                import gc
                gc.collect()
                if self._load_cached_sharded(planned, slack, state,
                                             side, timings, tracer):
                    # persisted AOT hit: zero compiles this process
                    # (compile_ms stays 0; cache_load_ms carries the
                    # deserialize cost)
                    state["slack"] = slack
                else:
                    from nds_tpu.cache import aot as cache_aot
                    # ndslint: waive[NDS102] -- raw bracket feeds compile_ms; the span records it too
                    t0 = _time.perf_counter()
                    with tracer.span("device.compile", slack=slack):
                        jitted, state["sk"], state["rk"] = build(slack)
                        bufs = self._collect_buffers(planned)
                        # AOT-compile (single-chip contract): compile
                        # cost must be attributed separately from the
                        # execute bracket, not hidden in the first
                        # timed call
                        state["jitted"] = cache_aot.lower_and_compile(
                            jitted,
                            {k: bufs[k] for k in state["sk"]},
                            {k: bufs[k] for k in state["rk"]},
                            fresh=cache_aot.fresh_for(*state.get(
                                "cache_handle", (None, None))),
                            kind=type(self).__name__)
                    state["slack"] = slack
                    timings["compile_ms"] += (
                        # ndslint: waive[NDS102] -- .compile() is synchronous; bracket ends when it returns
                        _time.perf_counter() - t0) * 1000
                    obs_metrics.counter(
                        "compiles_total" if attempt == 0
                        else "recompiles_total").inc()
                    self._persist_sharded(planned, slack, state, side)
            bufs = self._collect_buffers(planned)
            shard_bufs = {k: bufs[k] for k in state["sk"]}
            repl_bufs = {k: bufs[k] for k in state["rk"]}
            timings["bytes_scanned"] = float(
                sum(b.nbytes for b in bufs.values()))
            self._attach_delta(timings, planned)
            obs_metrics.counter("device_executions_total").inc()
            obs_metrics.counter("bytes_scanned_total").inc(
                timings["bytes_scanned"])
            # memory HWM (obs/memwatch): accounted scan bytes go live
            # for this attempt; device stats dominate when available.
            # __live_bytes is the pop-once release token (a failure
            # after an inline release must not release twice)
            memwatch.add_live(timings["bytes_scanned"])
            timings["__live_bytes"] = timings["bytes_scanned"]
            memwatch.sample_device()
            # compiler-truth cost billing (obs/costs): per dispatch,
            # outside the execute bracket
            obs_costs.record_program(type(self).__name__,
                                     state["jitted"])
            # ndslint: waive[NDS102] -- execute bracket start; closed below after device_get
            t1 = _time.perf_counter()
            with jitsan.dispatch(type(self).__name__):
                row, outs, overflow, skew = state["jitted"](shard_bufs,
                                                            repl_bufs)
            # one batched device->host round trip (see DeviceExecutor)
            row_h, outs_h, overflow_h, skew_h = jax.device_get(
                (row, outs, overflow, skew))
            if float(skew_h) > 0:
                # worst per-shuffle destination skew this program saw:
                # visible in live snapshots before it becomes a
                # straggler (README "Fleet & profiling")
                obs_metrics.gauge("exchange_skew_ratio").set(
                    round(float(skew_h), 4))
            # ndslint: waive[NDS102] -- bracket endpoint after device_get; becomes the device.run span
            t2 = _time.perf_counter()
            if int(overflow_h) == 0:
                tracer.begin("device.run", t0=t1).end(t=t2)
                with tracer.span("device.materialize"):
                    out = self._materialize(planned, row_h, outs_h,
                                            side)
                # ndslint: waive[NDS102] -- host materialize endpoint bracketed by the device.materialize span
                t3 = _time.perf_counter()
                memwatch.sample_device()
                memwatch.sub_live(timings.pop("__live_bytes", 0.0))
                timings["execute_ms"] = (t2 - t1) * 1000
                timings["materialize_ms"] = (t3 - t2) * 1000
                if side.get("ops_est"):
                    timings["ops_est"] = float(side["ops_est"])
                if side.get("kernels"):
                    timings["__kernels"] = dict(side["kernels"])
                self._finalize_timings(timings, key)
                return out, timings
            memwatch.sub_live(timings.pop("__live_bytes", 0.0))
            n_over = int(overflow_h)
            TaskFailureCollector.notify(
                f"exchange overflow ({n_over} rows) at slack="
                f"{slack}; retrying with slack={slack * 2}")
            obs_metrics.counter("exchange_overflow_retries_total").inc()
            obs_metrics.counter("exchange_overflow_rows_total").inc(
                n_over)
            obs_metrics.counter("slack_retries_total").inc()
            slack = slack * 2
        raise DeviceExecError("exchange overflow persisted after retries")


class _DistTrace(dx._Trace):
    def __init__(self, ex: DistributedExecutor, bufs: dict,
                 slack: float):
        super().__init__(ex, bufs, slack)
        self.n_dev = ex.n_dev
        self.axes = ex.axes

    def total_overflow(self):
        """Join-expansion + exchange overflow total (both append to
        _overflows; the executor's retry loop doubles whole-program
        slack and surfaces the event through the
        exchange_overflow_retries_total / exchange_overflow_rows_total
        metrics counters)."""
        if not self._overflows:
            return jnp.zeros((), jnp.int64)
        tot = self._overflows[0].astype(jnp.int64)
        for o in self._overflows[1:]:
            tot = tot + o.astype(jnp.int64)
        # every device sees every exchange; max across devices is enough
        return lax.pmax(tot, self.axes)

    # ------------------------------------------------------------- helpers

    def _replicate(self, ctx: DCtx) -> DCtx:
        if not getattr(ctx, "sharded", False):
            return ctx
        n = ctx.n * self.n_dev
        out = DCtx(n, lax.all_gather(ctx.row, self.axes, tiled=True))
        for k, dv in ctx.cols.items():
            arr = lax.all_gather(dv.arr, self.axes, tiled=True)
            valid = (None if dv.valid is None
                     else lax.all_gather(dv.valid, self.axes, tiled=True))
            out.cols[k] = dv.with_arrays(arr, valid)
        out.sharded = False
        return out

    def _exchange_ctx(self, ctx: DCtx, key, kok) -> tuple[DCtx, object]:
        """Repartition a sharded ctx by an int64 key; returns (ctx', key')
        both with capacity ctx.n * slack (rows colocated by key hash)."""
        names = list(ctx.cols)
        arrays = [ctx.cols[k].arr for k in names]
        valids = [ctx.cols[k].valid for k in names]
        vmask = [v is not None for v in valids]
        payload = arrays + [v for v in valids if v is not None] + [key]
        ok = ctx.row & kok
        if self.ex.mesh_2d:
            outs, out_ok, n_over = exchange_hierarchical(
                payload, key, ok, self.ex.n_hosts, self.ex.n_lanes,
                self.slack, HOST_AXIS, DATA_AXIS,
                key_index=len(payload) - 1)
        else:
            outs, out_ok, n_over = exchange(payload, key, ok,
                                            self.n_dev, self.slack)
        self._overflows.append(n_over)
        out_arrays = outs[:len(names)]
        vout = outs[len(names):-1]
        out_key = outs[-1]
        new = DCtx(out_ok.shape[0], out_ok)
        vi = 0
        for i, k in enumerate(names):
            dv = ctx.cols[k]
            valid = None
            if vmask[i]:
                valid = vout[vi]
                vi += 1
            new.cols[k] = dv.with_arrays(out_arrays[i], valid)
        new.sharded = True
        return new, out_key

    def _key_of(self, ctx: DCtx, exprs) -> tuple:
        """Pack a list of key exprs into one int64 per row (bounds
        required beyond the first key), plus validity."""
        vals = [self.eval(e, ctx) for e in exprs]
        ok = ctx.row
        for v in vals:
            ok = _ok(v, ok)
        if len(vals) == 1:
            return vals[0].arr.astype(jnp.int64), ok
        parts = []
        widths = []
        for v in vals:
            lo, hi = v.lo, v.hi
            if v.sdict is not None:
                lo, hi = 0, max(len(v.sdict) - 1, 0)
            if lo is None or hi is None:
                raise DeviceExecError("cannot pack key without bounds")
            parts.append((v.arr, lo, hi))
            widths.append(max((hi - lo).bit_length(), 1))
        if sum(widths) > 62:
            raise DeviceExecError("distributed key too wide")
        acc = None
        for (arr, lo, hi), w in zip(parts, widths):
            norm = jnp.clip(arr.astype(jnp.int64) - lo, 0, hi - lo)
            acc = norm if acc is None else ((acc << w) | norm)
        return acc, ok

    # ---------------------------------------------------------- plan nodes

    def _run_scan(self, node: P.Scan) -> DCtx:
        if (not self.ex._is_sharded(node.table)
                or self.ex.scan_view(node) is not None):
            # replicated table, or a sharded one whose filtered
            # survivors broadcast as a reduced replicated build side
            ctx = super()._run_scan(node)
            ctx.sharded = False
            return ctx
        t = self.ex.tables[node.table]
        cap = pad_to_multiple(max(t.nrows, self.n_dev), self.n_dev)
        local = cap // self.n_dev
        dev_i = lax.axis_index(DATA_AXIS)
        if self.ex.mesh_2d:
            dev_i = (lax.axis_index(HOST_AXIS) * self.ex.n_lanes
                     + dev_i)
        gidx = dev_i.astype(jnp.int64) * local + jnp.arange(local)
        row = gidx < t.nrows
        live = self.bufs.get(f"{node.table}.__live")
        if live is not None:
            # delta deleted-row bitmask (local shard slice, padded
            # False): deleted rows leave the shard's row population
            row = row & live
        ctx = DCtx(local, row)
        ctx.sharded = True
        for name, _dt in node.output:
            col = t.columns[name]
            arr = self.bufs[f"{node.table}.{name}"]
            valid = self.bufs.get(f"{node.table}.{name}#v")
            lo, hi = self.ex.col_bounds(node.table, name)
            sdict = col.dictionary if col.is_string else None
            ctx.cols[(node.binding, name)] = DVal(arr, valid, sdict, lo, hi)
        for pred in node.filters:
            ctx2 = self._apply_filter(ctx, pred)
            ctx2.sharded = True
            ctx = ctx2
        return ctx

    def _run_derivedscan(self, node: P.DerivedScan) -> DCtx:
        ctx = super()._run_derivedscan(node)
        ctx.sharded = getattr(self.run(node.child), "sharded", False)
        return ctx

    def _run_filter(self, node: P.Filter) -> DCtx:
        child = self.run(node.child)
        ctx = self._apply_filter(child, node.predicate)
        ctx.sharded = getattr(child, "sharded", False)
        return ctx

    def _run_project(self, node: P.Project) -> DCtx:
        child = self.run(node.child)
        ctx = super()._run_project(node)
        ctx.sharded = getattr(child, "sharded", False)
        return ctx

    def _run_join(self, node: P.Join) -> DCtx:
        lctx, rctx = self.run(node.left), self.run(node.right)
        ls = getattr(lctx, "sharded", False)
        rs = getattr(rctx, "sharded", False)
        if not node.left_keys:
            out = self._cross_replicated(node, lctx, rctx, ls, rs)
            return out
        if node.right_unique:
            probe_sharded = ls
            if rs and ls:
                # both sharded: colocate by join key over ICI. Keys must
                # be packed with PAIR-aligned bounds/dictionaries (the
                # single-device _align_pair rules) or identical logical
                # keys would hash differently per side
                lkey, lok, rkey, rok, _span = self._join_key_arrays(
                    [self.eval(k, lctx) for k in node.left_keys],
                    [self.eval(k, rctx) for k in node.right_keys],
                    lctx, rctx)
                if node.kind == "left":
                    # NULL-key left rows must SURVIVE the exchange to be
                    # null-extended: route them by a sentinel key (can't
                    # match — local probe re-checks key validity)
                    lkey = jnp.where(lok, lkey,
                                     jnp.zeros((), lkey.dtype))
                    lctx, _lk = self._exchange_ctx(lctx, lkey, lctx.row)
                else:
                    lctx, _lk = self._exchange_ctx(lctx, lkey, lok)
                rctx, _rk = self._exchange_ctx(rctx, rkey, rok)
            elif rs:
                rctx = self._replicate(rctx)
            out = self._join_cached(node, lctx, rctx)
            out.sharded = probe_sharded
            return out
        # probe side is the right: left must be visible in full
        if ls and rs:
            lkey, lok, rkey, rok, _span = self._join_key_arrays(
                [self.eval(k, lctx) for k in node.left_keys],
                [self.eval(k, rctx) for k in node.right_keys],
                lctx, rctx)
            if node.kind == "left":
                # block B emits unmatched LEFT rows: NULL-key left rows
                # must survive the exchange (see gather-join path above)
                lkey = jnp.where(lok, lkey, jnp.zeros((), lkey.dtype))
                lctx, _ = self._exchange_ctx(lctx, lkey, lctx.row)
            else:
                lctx, _ = self._exchange_ctx(lctx, lkey, lok)
            rctx, _ = self._exchange_ctx(rctx, rkey, rok)
            # after the exchange all matches are device-local, so the
            # base expanding join (incl. left-outer block B) is exact:
            # exchanged shards are disjoint across devices
            out = self._join_cached(node, lctx, rctx)
            out.sharded = True
            return out
        if ls:
            lctx = self._replicate(lctx)
        if rs and node.kind == "left":
            # left outer with replicated left + sharded right: the base
            # join computes 'matched' per device, so a left row matched
            # only on another device would ALSO null-extend from every
            # device's block B (duplicates). Replicate the right side —
            # correctness over memory until a pmax-matched path lands.
            rctx = self._replicate(rctx)
            rs = False
        out = self._join_cached(node, lctx, rctx)
        out.sharded = rs
        return out

    def _join_cached(self, node, lctx, rctx):
        """Run the single-device join logic on prepared child contexts."""
        self.stash(node.left, lctx)
        self.stash(node.right, rctx)
        self._cache.pop(id(node), None)
        return super()._run_join(node)

    def _cross_replicated(self, node, lctx, rctx, ls, rs):
        lctx = self._replicate(lctx) if ls else lctx
        rctx = self._replicate(rctx) if rs else rctx
        self.stash(node.left, lctx)
        self.stash(node.right, rctx)
        out = self._cross_join(node, lctx, rctx)
        out.sharded = False
        return out

    def _run_semijoin(self, node: P.SemiJoin) -> DCtx:
        lctx, rctx = self.run(node.left), self.run(node.right)
        ls = getattr(lctx, "sharded", False)
        if getattr(rctx, "sharded", False):
            rctx = self._replicate(rctx)
        self.stash(node.left, lctx)
        self.stash(node.right, rctx)
        self._cache.pop(id(node), None)
        out = super()._run_semijoin(node)
        out.sharded = ls
        return out

    def _run_aggregate(self, node: P.Aggregate) -> DCtx:
        ctx = self.run(node.child)
        if not getattr(ctx, "sharded", False):
            out = super()._run_aggregate(node)
            out.sharded = False
            return out
        if not node.group_keys:
            return self._global_agg_sharded(node, ctx)
        # repartition by group key so each group is wholly local, then the
        # single-device aggregate is exact (distinct/avg included)
        try:
            key, kok = self._key_of(ctx, [e for _, e in node.group_keys])
        except DeviceExecError:
            self.stash(node.child, self._replicate(ctx))
            self._cache.pop(id(node), None)
            out = super()._run_aggregate(node)
            out.sharded = False
            return out
        # NULL group keys: kok False would keep rows home — fine, they
        # still form their own (local) group only if all-null; TPC group
        # keys are non-null so route by key, keep row presence as-is
        new, _ = self._exchange_ctx(ctx, key, ctx.row)
        self.stash(node.child, new)
        self._cache.pop(id(node), None)
        out = super()._run_aggregate(node)
        out.sharded = True
        return out

    def _global_agg_sharded(self, node: P.Aggregate, ctx: DCtx) -> DCtx:
        b = node.binding
        if any(spec.distinct for _, spec in node.aggs):
            self.stash(node.child, self._replicate(ctx))
            self._cache.pop(id(node), None)
            out = super()._run_aggregate(node)
            out.sharded = False
            return out
        out = DCtx(1, jnp.ones(1, dtype=bool))
        out.sharded = False
        for name, spec in node.aggs:
            arr, valid, sdict = self._psum_agg(spec, ctx)
            out.cols[(b, name)] = DVal(arr, valid, sdict)
        return out

    def _psum_agg(self, spec: P.AggSpec, ctx: DCtx):
        import jax.numpy as jnp
        from nds_tpu.engine.device_exec import I64_MAX, I64_MIN, _to_float
        from nds_tpu.engine.types import FloatType
        dv = self._agg_arg(spec, ctx)
        if spec.func == "count" and dv is None:
            cnt = lax.psum(jnp.sum(ctx.row), self.axes)
            return cnt.reshape(1).astype(jnp.int64), jnp.ones(1, bool), None
        w = _ok(dv, ctx.row)
        cnt = lax.psum(jnp.sum(w), self.axes)
        valid = (cnt > 0).reshape(1)
        if spec.func == "count":
            return cnt.reshape(1).astype(jnp.int64), jnp.ones(1, bool), None
        if spec.func == "sum":
            if isinstance(spec.dtype, FloatType):
                s = jnp.sum(jnp.where(w, dv.arr.astype(jnp.float64), 0.0))
            else:
                s = jnp.sum(jnp.where(w, dv.arr.astype(jnp.int64), 0))
            return lax.psum(s, self.axes).reshape(1), valid, None
        if spec.func == "avg":
            f = _to_float(dv.arr, spec.arg.dtype)
            s = lax.psum(jnp.sum(jnp.where(w, f, 0.0)), self.axes)
            return (s / jnp.maximum(cnt, 1)).reshape(1), valid, None
        if spec.func in ("min", "max"):
            isf = jnp.issubdtype(dv.arr.dtype, jnp.floating)
            if isf:
                fill = jnp.inf if spec.func == "min" else -jnp.inf
                masked = jnp.where(w, dv.arr, fill)
            else:
                fill = I64_MAX if spec.func == "min" else I64_MIN
                masked = jnp.where(w, dv.arr.astype(jnp.int64), fill)
            red = jnp.min(masked) if spec.func == "min" else jnp.max(masked)
            red = (lax.pmin(red, self.axes) if spec.func == "min"
                   else lax.pmax(red, self.axes))
            return red.reshape(1), valid, dv.sdict
        raise DeviceExecError(spec.func)

    def _run_sort(self, node: P.Sort) -> DCtx:
        child = self.run(node.child)
        if getattr(child, "sharded", False):
            self.stash(node.child, self._replicate(child))
            self._cache.pop(id(node), None)
        out = super()._run_sort(node)
        out.sharded = False
        return out

    def _run_limit(self, node: P.Limit) -> DCtx:
        child = self.run(node.child)
        if getattr(child, "sharded", False):
            self.stash(node.child, self._replicate(child))
            self._cache.pop(id(node), None)
        out = super()._run_limit(node)
        out.sharded = False
        return out

    def _run_distinct(self, node: P.Distinct) -> DCtx:
        child = self.run(node.child)
        if getattr(child, "sharded", False):
            self.stash(node.child, self._replicate(child))
            self._cache.pop(id(node), None)
        out = super()._run_distinct(node)
        out.sharded = False
        return out

    def _run_setop(self, node: P.SetOp) -> DCtx:
        for side in (node.left, node.right):
            c = self.run(side)
            if getattr(c, "sharded", False):
                self.stash(side, self._replicate(c))
        self._cache.pop(id(node), None)
        out = super()._run_setop(node)
        out.sharded = False
        return out

    def _run_window(self, node: P.Window) -> DCtx:
        # windows run post-aggregation on small relations; replicate
        # (an exchange-by-partition-key path can land later)
        child = self.run(node.child)
        if getattr(child, "sharded", False):
            self.stash(node.child, self._replicate(child))
            self._cache.pop(id(node), None)
        out = super()._run_window(node)
        out.sharded = False
        return out

    def run_query(self, planned: P.PlannedQuery):
        for i, sub in enumerate(planned.scalar_subplans):
            ctx = self._replicate(self.run(sub))
            self.stash(sub, ctx)
            name, dt = sub.output[0]
            dv = ctx.cols[(sub.binding, name)]
            pos = jnp.argmax(ctx.row)
            v = dv.arr[pos]
            ok = ctx.row[pos]
            if dv.valid is not None:
                ok = ok & dv.valid[pos]
            self.scalars[i] = (v, ok, dv.sdict, dt)
        ctx = self._replicate(self.run(planned.root))
        root = planned.root
        outs, dicts = [], []
        for name, _dt in root.output:
            dv = ctx.cols[(root.binding, name)]
            valid = dv.valid if dv.valid is not None else jnp.ones(
                ctx.n, dtype=bool)
            outs.append((dv.arr, valid))
            dicts.append(dv.sdict)
        return ctx.row, outs, dicts


def make_distributed_factory(mesh=None, n_devices=None,
                             shard_tables=None,
                             shard_threshold=DEFAULT_SHARD_THRESHOLD,
                             multiprocess=None):
    """Session executor factory for the distributed engine (one executor
    per table registry, like `device_exec.make_device_factory`)."""
    holder: dict = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            ex = DistributedExecutor(
                tables, mesh=mesh, n_devices=n_devices,
                shard_tables=shard_tables,
                shard_threshold=shard_threshold,
                multiprocess=multiprocess)
            holder["ex"] = ex
        return ex

    # DML invalidation hooks (Session.invalidate), as in
    # device_exec.make_device_factory — the scoped form keeps warm
    # buffers and compiled programs for every unmutated table
    factory.invalidate = holder.clear

    def invalidate_tables(names):
        ex = holder.get("ex")
        if ex is not None:
            ex.invalidate_tables(names)

    factory.invalidate_tables = invalidate_tables
    return factory
