"""Hash-partition exchange over ICI: the engine's shuffle operator.

This is the component the reference leaves entirely to Spark's
block-based shuffle (config-only, `spark.sql.shuffle.partitions`,
SURVEY.md §2.6): here it is first-class and TPU-native — rows hash to a
destination device and move in ONE `lax.all_to_all` across the mesh axis
(ICI within a pod, DCN across slices; XLA picks the transport).

Static-shape contract: each device sends a fixed-capacity bucket of
``ceil(local_rows / n_dev * slack)`` rows to every peer. Hash
partitioning spreads keys uniformly, so slack=2 covers real skew; rows
that overflow a bucket are dropped AND counted — the executor surfaces
the count so the host can retry with a bigger slack (adaptive, one
recompile, never silent).
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax import lax

from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.parallel.mesh import DATA_AXIS

# trace-time skew collector: while a sink is active (the distributed
# executor's program build opens one around run_query), every
# exchange_by_dest appends its per-shuffle destination-skew ratio
# (max/mean destination rows, a TRACED scalar) so the program can
# return the worst skew alongside the overflow count and the executor
# can publish the ``exchange_skew_ratio`` gauge host-side. NOT a
# debug callback on purpose: callback-bearing executables cannot
# serialize into the persistent AOT plan cache (PyCapsule pickling).
_SKEW_SINK: "list | None" = None


@contextlib.contextmanager
def skew_trace():
    """Collect per-shuffle skew ratios appended during one program
    trace; yields the list the traced scalars land in."""
    global _SKEW_SINK
    prev, _SKEW_SINK = _SKEW_SINK, []
    try:
        yield _SKEW_SINK
    finally:
        _SKEW_SINK = prev


def _mix64(x):
    """splitmix64 finalizer: avalanche int64 keys before bucketing (raw
    TPC keys are sequential — modulo alone would stripe, not spread)."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> 31)
    return x


def exchange(arrays: list, key, ok, n_dev: int, slack: float = 2.0,
             axis: str = DATA_AXIS):
    """Repartition rows by hash(key) across the mesh axis.

    arrays: per-row payload arrays (local shard). key: int64 per row.
    ok: bool per row (invalid rows don't travel).
    Returns (out_arrays, out_ok, overflow_count) where out_* have
    capacity n_dev * bucket ( = local_n * slack rounded up).
    """
    dest = (_mix64(key) % jnp.uint64(n_dev)).astype(jnp.int32)
    return exchange_by_dest(arrays, dest, ok, n_dev, slack, axis)


def exchange_by_dest(arrays: list, dest, ok, n_dev: int,
                     slack: float = 2.0, axis: str = DATA_AXIS,
                     bucket: int | None = None):
    """Exchange core routed by an explicit per-row destination index in
    [0, n_dev) along ``axis`` (the hierarchical DCN/ICI exchange routes
    each stage with a different destination derivation). ``bucket``
    overrides the per-peer capacity — hierarchical stage 2 sizes it
    from the LOGICAL row count, not the stage-1 padded length."""
    n = dest.shape[0]
    # chaos site (trace time, like the counter below): an injected
    # fault here surfaces during compile, where the executor's retry
    # loop classifies and handles it like a real capacity failure
    from nds_tpu.resilience import faults, watchdog
    faults.fault_point("exchange", n_dev=n_dev)
    # trace-time heartbeat: big multi-exchange programs show liveness
    # to the hang watchdog per exchange traced, not just per query
    watchdog.beat("engine", phase="exchange")
    # trace-time count: how many exchange ops the compiled programs
    # contain (runtime executions multiply by program runs; in-program
    # counting would cost a collective per query for a vanity number)
    obs_metrics.counter("exchanges_traced_total").inc()
    if bucket is None:
        bucket = max(1, int(-(-n * slack // n_dev)))
    # dead rows get a sentinel dest PAST every real bucket so they never
    # consume rank slots (a heavily filtered shard must not overflow its
    # own bucket with corpses)
    dest = jnp.where(ok, dest, jnp.int32(n_dev))
    # stable-group rows by destination. Explicit int32 iota operand:
    # jnp.argsort would carry an int64 index operand under x64, pushing
    # the whole shuffle-grouping sort onto the TPU's emulated 64-bit
    # path (NDS112 — same trap as device_exec._build_lookup)
    iota = jnp.arange(n, dtype=jnp.int32)
    _, order = lax.sort([dest, iota], num_keys=1, is_stable=True)
    dest_s = jnp.take(dest, order)
    ok_s = jnp.take(ok, order)
    # per-destination boundaries: [:-1] are the bucket starts the rank
    # derivation needs; the full fencepost vector also yields the
    # per-destination row COUNTS behind the skew gauge below
    bounds = jnp.searchsorted(dest_s,
                              jnp.arange(n_dev + 1, dtype=jnp.int32))
    first_of_dest = bounds[:-1]
    if _SKEW_SINK is not None:
        # partition-skew visibility (README "Fleet & profiling"):
        # max/mean valid rows per destination for THIS shuffle — the
        # signal that a key distribution is loading one device before
        # it becomes a straggler. bounds[-1] counts the valid rows
        # (dead rows carry the sentinel dest and sort past every
        # real bucket)
        counts = (bounds[1:] - bounds[:-1]).astype(jnp.float32)
        total = bounds[-1].astype(jnp.float32)
        ratio = jnp.where(
            total > 0,
            jnp.max(counts) / jnp.maximum(total / n_dev, 1e-9),
            jnp.float32(1.0))
        _SKEW_SINK.append(ratio)
    rank = iota - jnp.take(first_of_dest,
                           jnp.clip(dest_s, 0, n_dev - 1))
    overflow = ok_s & (rank >= bucket)
    n_overflow = jnp.sum(overflow)
    keep = ok_s & (rank < bucket)
    # kept rows get unique slots; everything else lands in a trash slot
    # past the buffer (sliced off below) so it can't clobber a kept row
    trash = n_dev * bucket
    slot = jnp.where(keep, dest_s * bucket + jnp.clip(rank, 0, bucket - 1),
                     trash)

    def scatter(vals_sorted, fill):
        buf = jnp.full((n_dev * bucket + 1,), fill, dtype=vals_sorted.dtype)
        return buf.at[slot].set(vals_sorted)[:-1]

    send_ok = jnp.zeros((n_dev * bucket + 1,), dtype=bool).at[slot].set(
        keep)[:-1]
    out_ok = lax.all_to_all(
        send_ok.reshape(n_dev, bucket), axis, 0, 0).reshape(-1)
    outs = []
    for a in arrays:
        a_s = jnp.take(a, order, axis=0)
        sent = scatter(a_s, jnp.zeros((), a.dtype))
        outs.append(lax.all_to_all(
            sent.reshape(n_dev, bucket), axis, 0, 0).reshape(-1))
    return outs, out_ok, n_overflow


def exchange_hierarchical(arrays: list, key, ok, n_hosts: int,
                          n_lanes: int, slack: float = 2.0,
                          host_axis: str = "h",
                          lane_axis: str = DATA_AXIS,
                          key_index: int | None = None):
    """Two-stage shuffle for multi-host meshes (SURVEY.md §7 hard part
    4: the ICI-instead-of-UCX deliverable at DCN scale): rows first move
    to their destination HOST over the ``host_axis`` (DCN — one
    all_to_all of host-sized buckets, minimizing cross-slice bytes),
    then to their destination LANE over ``lane_axis`` (ICI within the
    slice). The destination device for a key is stable:
    g = hash(key) % (hosts * lanes); host = g // lanes; lane = g %
    lanes — so downstream grouped operators see the same colocation
    contract as the flat 1-D exchange.

    Returns (out_arrays, out_ok, overflow_count) with the overflow
    counts of both stages summed (the executor's retry-with-bigger-slack
    loop treats them uniformly).
    """
    n = key.shape[0]
    g = (_mix64(key) % jnp.uint64(n_hosts * n_lanes)).astype(jnp.int32)
    dest_h = g // n_lanes
    # stage 1 (DCN): deliver rows to the right host. When the caller's
    # payload already carries the key (key_index), reuse it for stage 2
    # instead of shipping a second copy over the cross-slice link
    payload = list(arrays)
    appended = key_index is None
    if appended:
        payload = payload + [key]
        key_index = len(payload) - 1
    outs1, ok1, over1 = exchange_by_dest(
        payload, dest_h, ok, n_hosts, slack, host_axis)
    key1 = outs1[key_index]
    # stage 2 (ICI): recompute the lane from the carried key. Bucket is
    # sized from the LOGICAL rows (expected ~n per device after a
    # uniform hash), not the stage-1 padded capacity — otherwise every
    # downstream operator pays n * slack^2
    g1 = (_mix64(key1) % jnp.uint64(n_hosts * n_lanes)).astype(jnp.int32)
    dest_d = g1 % n_lanes
    bucket2 = max(1, int(-(-n * slack // n_lanes)))
    outs2, ok2, over2 = exchange_by_dest(
        outs1[:-1] if appended else outs1, dest_d, ok1, n_lanes, slack,
        lane_axis, bucket=bucket2)
    return outs2, ok2, over1 + over2
