"""Multi-host (multi-process) runtime entry point.

The reference scales out by Spark executor topology — a static config of
instances x cores on a cluster (`nds/base.template:29-31`). The
TPU-native equivalent is jax's multi-controller SPMD runtime: every host
runs the SAME driver process, `jax.distributed.initialize` wires them
into one global device world (gRPC coordination over DCN), and the
engine's shard_map programs span the global mesh — XLA routes
collectives over ICI within a slice and DCN across slices.

Launch contract (env-driven, one process per host):

    NDS_TPU_COORDINATOR=host0:12355   coordinator address
    NDS_TPU_NUM_PROCESSES=4           world size
    NDS_TPU_PROCESS_ID=0..3           this process's rank

On a real TPU pod slice all three are auto-detected by jax and may be
omitted. ``python -m nds_tpu.nds.power --backend distributed`` calls
``maybe_initialize()`` at session construction, so the same CLI works
single-process (no env vars, virtual or single-chip mesh) and
multi-process (env vars set by the launcher) — the analog of the same
spark-submit working on local[*] and a cluster.
"""

from __future__ import annotations

import os

import numpy as np


_initialized = False


def maybe_initialize() -> bool:
    """Initialize jax's distributed runtime when the env asks for it
    (idempotent — jax.distributed.initialize may run only once per
    process, and one driver builds several sessions, e.g. maintenance
    then power). Returns True when running multi-process."""
    global _initialized
    import jax
    coord = os.environ.get("NDS_TPU_COORDINATOR")
    nproc = os.environ.get("NDS_TPU_NUM_PROCESSES")
    if coord and nproc and int(nproc) > 1 and not _initialized:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(os.environ.get("NDS_TPU_PROCESS_ID", "0")))
        _initialized = True
        return True
    return jax.process_count() > 1


def is_primary() -> bool:
    """True on the process that owns report/log writing (rank 0) —
    every process computes, one records (the reference's driver/executor
    split collapses to rank-0-writes in multi-controller SPMD)."""
    import jax
    return jax.process_index() == 0


def global_mesh(shards: int | None = None):
    """1-D data mesh over the GLOBAL device world (all processes).

    Single-process: ``shards`` restricts the mesh to that many devices
    (validated). Multi-process: the mesh must span every process's
    devices — a device subset would leave some ranks with nothing
    addressable at the first collective — so any ``shards`` other than
    the world size is an error, not a silent slice."""
    import jax
    from nds_tpu.parallel.mesh import make_mesh
    devices = jax.devices()
    if jax.process_count() > 1:
        if shards not in (None, len(devices)):
            raise ValueError(
                f"engine.mesh.shards={shards} but the multi-process "
                f"world has {len(devices)} devices; the mesh must span "
                f"all of them (omit the knob or set it to "
                f"{len(devices)})")
        return make_mesh(devices=devices)
    return make_mesh(shards if shards and shards > 1 else None)


def gather_votes(vote: int) -> "list[int] | None":
    """Allgather one small int from every process (DCN) — the
    transport under the scheduler's placement-consensus step
    (engine/scheduler.py): every rank calls this at the same decision
    point, reads back all votes, and applies the same deterministic
    rule, so placement switches are all-or-none across the SPMD world.
    Returns None when the gather fails (a dead coordinator / lagging
    rank) — the caller keeps its placement rather than diverging."""
    import jax
    if jax.process_count() == 1:
        return [int(vote)]
    try:
        from jax.experimental import multihost_utils
        votes = multihost_utils.process_allgather(
            np.asarray([vote], dtype=np.int32))
        return [int(v) for v in np.asarray(votes).reshape(-1)]
    except Exception:  # noqa: BLE001 - consensus must degrade, not hang
        return None


# per-process sequence for coordination-service keys/barriers: every
# rank performs the SAME number of handshakes (one per power run), so
# the derived ids agree across the world; a drifted count times out
# the barrier and degrades instead of mispairing
_kv_seq = 0

_KV_TIMEOUT_MS = 15_000


def coordination_client():
    """The jax.distributed coordination-service client (gRPC KV store
    + named barriers), or None single-process / when the private API
    moved. This is the fleet-handshake transport: it works on EVERY
    backend — XLA collectives (process_allgather) are unavailable on
    the multi-process CPU backend that tier-1's virtual fleets run
    on — and a barrier/KV round costs no device compilation."""
    import jax
    if jax.process_count() == 1:
        return None
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001 - private-API drift: degrade
        return None


def barrier(tag: str) -> bool:
    """Fleet-wide named barrier (True when every rank arrived; False
    on timeout/failure — the caller degrades, never hangs). Trivially
    True single-process."""
    import jax
    if jax.process_count() == 1:
        return True
    client = coordination_client()
    if client is None:
        return False
    try:
        client.wait_at_barrier(tag, timeout_in_ms=_KV_TIMEOUT_MS)
        return True
    except Exception:  # noqa: BLE001 - alignment must degrade, not hang
        return False


def gather_floats(value: float) -> "list[float] | None":
    """Allgather one float from every process over the coordination
    service — the transport under the fleet clock handshake
    (obs/fleet.py): a barrier releases every rank at (approximately)
    one instant, each rank publishes its clock reading under its rank
    key, and every rank reads all of them back. Returns None when the
    round fails (dead coordinator, lagging rank) — the caller degrades
    to unaligned (offset-0) shards rather than hanging the run."""
    global _kv_seq
    import jax
    if jax.process_count() == 1:
        return [float(value)]
    client = coordination_client()
    if client is None:
        return None
    _kv_seq += 1
    prefix = f"nds_tpu/gatherf/{_kv_seq}"
    try:
        client.key_value_set(f"{prefix}/{jax.process_index()}",
                             repr(float(value)))
        return [float(client.blocking_key_value_get(
                    f"{prefix}/{r}", _KV_TIMEOUT_MS))
                for r in range(jax.process_count())]
    except Exception:  # noqa: BLE001 - alignment must degrade, not hang
        return None


def make_global_array(mesh, spec, full_value: np.ndarray):
    """Build a global jax.Array laid out per (mesh, spec) from host data.

    Per-host shard loading: the callback materializes ONLY the global
    row ranges owned by this process's addressable devices — a host
    never holds device buffers for rows another host owns. (Row-range
    -> parquet-file mapping lets the IO layer skip reading forever-
    remote rows; device memory is the contract enforced here.)
    """
    import jax
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        full_value.shape, sharding, lambda idx: full_value[idx])
