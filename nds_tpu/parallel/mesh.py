"""Device mesh helpers.

The reference's executor topology is fixed Spark config
(`nds/base.template:29-31`); ours is a jax.sharding.Mesh. The benchmark
workload is data-parallel over rows with explicit exchanges, so the mesh
is 1-D ("d"); multi-host TPU slices extend the same axis over DCN —
collectives are inserted by XLA per the sharding, not hand-coded
(SURVEY.md §2.6 TPU-native mapping).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "d"
HOST_AXIS = "h"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def make_multihost_mesh(n_hosts: int, n_lanes: int,
                        devices=None) -> Mesh:
    """2-D (host, lane) mesh for DCN-scale runs: the ``h`` axis crosses
    slices (DCN), the ``d`` axis stays within a slice (ICI). The
    hierarchical exchange (`exchange.exchange_hierarchical`) routes its
    DCN stage over ``h`` and its ICI stage over ``d``, so cross-slice
    traffic is one host-bucketed all_to_all instead of a flat
    (hosts*lanes)-way shuffle."""
    if devices is None:
        devices = jax.devices()
    need = n_hosts * n_lanes
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_hosts, n_lanes)
    return Mesh(grid, (HOST_AXIS, DATA_AXIS))


def pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m
