"""Device mesh helpers.

The reference's executor topology is fixed Spark config
(`nds/base.template:29-31`); ours is a jax.sharding.Mesh. The benchmark
workload is data-parallel over rows with explicit exchanges, so the mesh
is 1-D ("d"); multi-host TPU slices extend the same axis over DCN —
collectives are inserted by XLA per the sharding, not hand-coded
(SURVEY.md §2.6 TPU-native mapping).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "d"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m
