"""Static-analysis layer: plan invariants and codebase lint.

Two subsystems live here, both gated into tier-1 by
``tools/static_checks.py``:

- ``plan_verify``: walks every ``PlannedQuery`` post-planning and checks
  the structural invariants the executors silently rely on (ColRef
  resolution, dtype propagation, join-key dtype agreement, staged-scan
  integrity). Enabled automatically under ``NDS_TPU_VERIFY_PLANS=1``
  and always in tests.
- ``lint_rules``: ast-based rules over the codebase encoding the
  mechanical hazard classes advisor rounds kept rediscovering by hand
  (id()-keyed caches without a pinning ref, raw timing calls in the
  engine, prefix-only content fingerprints, dead dataclass fields, ...),
  driven by ``tools/ndslint.py``.
- ``concurrency``: cross-module lock-discipline auditor (guard
  inference, the static lock-order graph, signal-handler safety,
  thread-shared mutation), driven by ``tools/ndsraces.py``.
- ``locksan``: the opt-in runtime lock-order sanitizer
  (``NDS_TPU_LOCKSAN=1``) witnessing the order graph on the real
  chaos/soak/serve workloads.

The package ``__init__`` is deliberately lazy (PEP 562 re-exports):
``locksan`` must be importable by ``obs/metrics.py`` at interpreter
start without dragging the plan verifier's sql/engine import chain in
behind it.
"""

_PLAN_VERIFY_NAMES = frozenset(
    ("PlanVerifyError", "Violation", "assert_valid", "verify",
     "verify_enabled"))


def __getattr__(name):
    if name in _PLAN_VERIFY_NAMES:
        from nds_tpu.analysis import plan_verify
        return getattr(plan_verify, name)
    raise AttributeError(name)
