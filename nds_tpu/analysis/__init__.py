"""Static-analysis layer: plan invariants and codebase lint.

Two subsystems live here, both gated into tier-1 by
``tools/static_checks.py``:

- ``plan_verify``: walks every ``PlannedQuery`` post-planning and checks
  the structural invariants the executors silently rely on (ColRef
  resolution, dtype propagation, join-key dtype agreement, staged-scan
  integrity). Enabled automatically under ``NDS_TPU_VERIFY_PLANS=1``
  and always in tests.
- ``lint_rules``: ast-based rules over the codebase encoding the
  mechanical hazard classes advisor rounds kept rediscovering by hand
  (id()-keyed caches without a pinning ref, raw timing calls in the
  engine, prefix-only content fingerprints, dead dataclass fields, ...),
  driven by ``tools/ndslint.py``.
"""

from nds_tpu.analysis.plan_verify import (  # noqa: F401
    PlanVerifyError, Violation, assert_valid, verify, verify_enabled,
)
