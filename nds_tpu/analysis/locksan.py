"""Runtime lock-order sanitizer: witness the order graph at runtime.

The static auditor (``nds_tpu/analysis/concurrency.py``) PROPOSES the
lock acquisition graph from the ast; this module WITNESSES it on real
concurrent workloads. Under ``NDS_TPU_LOCKSAN=1`` every lock the
engine's threaded modules create through the :func:`lock` /
:func:`rlock` / :func:`condition` factories is a thin wrapper that
records, per thread, the stack of currently-held lock NAMES plus the
Python traceback of each first-witnessed acquisition edge:

- acquiring B while holding A adds the directed edge ``A -> B``; the
  first time an edge closes a cycle (``B ⇝ A`` already witnessed) an
  INVERSION is recorded with both witness stacks, counted on
  ``lock_order_inversions_total``, and printed loudly — the exact
  interleaving evidence a post-hoc deadlock leaves nowhere;
- re-acquiring a non-reentrant lock the same thread already holds (the
  ``request_stall_capture`` bug class) raises ``RuntimeError``
  immediately instead of deadlocking the process under test;
- at process exit the graph + inversions are reported: written as JSON
  to ``$NDS_TPU_LOCKSAN_REPORT/locksan-<pid>.json`` (via
  ``io.integrity.write_json_atomic`` — whose tmp names are
  thread-unique, our own NDS109 dogfood) when the env names a
  directory, else printed to stderr when inversions exist.

Disabled (the default), the factories return plain ``threading``
primitives — zero overhead, zero behavior change. Tests enable it
process-wide (tests/conftest.py) and ``tools/static_checks.py`` runs
the chaos/soak/serve gates under it, asserting the real workloads stay
inversion-free while a seeded inversion (``selftest``) proves the
detector actually fires. Lock identity is the NAME (one per creation
site), so every instance of a class shares one node in the order graph
— which is the discipline being checked; self-deadlock detection uses
object identity, so two instances of one class never false-positive.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
import traceback

ENV = "NDS_TPU_LOCKSAN"
REPORT_ENV = "NDS_TPU_LOCKSAN_REPORT"

# witness stacks are trimmed to this many frames (deepest first): deep
# jax/pytest frames bury the engine frame the report exists to show
_STACK_FRAMES = 12


def enabled() -> bool:
    return os.environ.get(ENV, "0") == "1"


def _stack() -> "list[str]":
    frames = traceback.format_stack()[:-2]
    return [ln.rstrip("\n") for ln in frames[-_STACK_FRAMES:]]


class OrderGraph:
    """One acquisition-order graph: edges, inversions, per-thread held
    stacks. The global instance backs every factory-made lock; tests
    and the selftest build private instances so seeded inversions never
    pollute the process verdict."""

    def __init__(self, metric: bool = True):
        # the sanitizer's own lock is a PLAIN lock: it must be
        # invisible to itself, and nothing is ever acquired inside it
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.edges: dict = {}        # (a, b) -> {count, stack}
        self.inversions: list = []
        self.metric = metric

    # ------------------------------------------------------- held state

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_names(self) -> "list[str]":
        return [name for name, _ident in self._held()]

    def holds(self, ident: int) -> bool:
        return any(i == ident for _n, i in self._held())

    # -------------------------------------------------------- recording

    def on_acquired(self, name: str, ident: int) -> None:
        held = self._held()
        new_inversion = None
        if held:
            prior = {n for n, _i in held if n != name}
            with self._lock:
                for h in prior:
                    edge = self.edges.get((h, name))
                    if edge is not None:
                        edge["count"] += 1
                        continue
                    self.edges[(h, name)] = {"count": 1,
                                             "stack": _stack()}
                    if self._reaches_locked(name, h):
                        new_inversion = {
                            "cycle": [h, name],
                            "stack": self.edges[(h, name)]["stack"],
                            "prior_stack": self._witness_locked(name,
                                                                h),
                            "thread": threading.current_thread().name,
                            "ts": time.time(),
                        }
                        self.inversions.append(new_inversion)
        held.append((name, ident))
        if new_inversion is not None:
            # metric + print OUTSIDE the graph lock: the counter's own
            # (sanitized) lock would re-enter on_acquired
            self._announce(new_inversion)

    def on_reacquired(self, name: str, ident: int) -> None:
        """A legal reentrant re-acquire (RLock depth > 1): push the
        held record so release stays symmetric, but record NO edges —
        a re-acquire of a lock this thread already owns can never
        block, so it must never synthesize an inversion."""
        self._held().append((name, ident))

    def on_released(self, name: str, ident: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == (name, ident):
                del held[i]
                return

    def drop_all(self, ident: int) -> int:
        """Remove every held record for ``ident`` (RLock fully
        releasing inside Condition.wait); returns how many were held
        so the restore can push them back."""
        held = self._held()
        n = len([1 for _name, i in held if i == ident])
        held[:] = [(nm, i) for nm, i in held if i != ident]
        return n

    def on_self_deadlock(self, name: str) -> None:
        rec = {"cycle": [name, name], "stack": _stack(),
               "prior_stack": [],
               "thread": threading.current_thread().name,
               "ts": time.time()}
        with self._lock:
            self.inversions.append(rec)
        self._announce(rec)

    def _reaches_locked(self, src: str, dst: str) -> bool:
        stack, seen = [src], set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(b for (a, b) in self.edges if a == n)
        return False

    def _witness_locked(self, a: str, b: str) -> "list[str]":
        edge = self.edges.get((a, b))
        return edge["stack"] if edge else []

    def _announce(self, rec: dict) -> None:
        if self.metric:
            try:
                from nds_tpu.obs import metrics as obs_metrics
                obs_metrics.counter(
                    "lock_order_inversions_total").inc()
            except Exception:  # noqa: BLE001 - detector must not crash
                pass
        a, b = rec["cycle"]
        kind = ("re-entrant acquire of non-reentrant lock"
                if a == b else "lock-order inversion")
        print(f"[locksan] {kind}: {a} -> {b} "
              f"(thread {rec['thread']})", file=sys.stderr)

    # --------------------------------------------------------- readout

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "pid": os.getpid(),
                "ts": time.time(),
                "edges": {f"{a} -> {b}": dict(e)
                          for (a, b), e in self.edges.items()},
                "inversions": [dict(i) for i in self.inversions],
            }

    def inversion_count(self) -> int:
        with self._lock:
            return len(self.inversions)

    def reset(self) -> None:
        with self._lock:
            self.edges.clear()
            self.inversions.clear()


class SanLock:
    """Order-recording wrapper around ``threading.Lock``."""

    reentrant = False

    def __init__(self, name: str, graph: "OrderGraph | None" = None):
        self._name = name
        self._graph = graph if graph is not None else _GRAPH
        self._inner = self._make_inner()
        self._ident = id(self._inner)

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if (blocking and timeout < 0 and not self.reentrant
                and self._graph.holds(self._ident)):
            self._graph.on_self_deadlock(self._name)
            raise RuntimeError(
                f"locksan: re-entrant acquire of non-reentrant lock "
                f"{self._name} would deadlock")
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._graph.on_acquired(self._name, self._ident)
        return got

    def release(self) -> None:
        self._graph.on_released(self._name, self._ident)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class SanRLock(SanLock):
    """Order-recording wrapper around ``threading.RLock``: recursion is
    legal, and only the outermost acquire records ORDER EDGES — a
    re-acquire of a lock the thread already owns can never block, so it
    must never synthesize an inversion (nested re-acquires still push
    held records, keeping release symmetric)."""

    reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        recursing = self._graph.holds(self._ident)
        got = self._inner.acquire(blocking, timeout)
        if got:
            if recursing:
                self._graph.on_reacquired(self._name, self._ident)
            else:
                self._graph.on_acquired(self._name, self._ident)
        return got

    # Condition-wait protocol: a Condition backed by this lock must
    # FULLY release the recursion on wait() and restore it after
    # (threading.Condition uses these when present; its fallbacks call
    # bare release()/acquire(), which only drop one recursion level)
    def _release_save(self):
        depth = self._graph.drop_all(self._ident)
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        self._graph.on_acquired(self._name, self._ident)
        for _ in range(depth - 1):
            self._graph.on_reacquired(self._name, self._ident)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


_GRAPH = OrderGraph()


def graph() -> OrderGraph:
    return _GRAPH


def inversion_count() -> int:
    return _GRAPH.inversion_count()


def reset() -> None:
    _GRAPH.reset()


# ------------------------------------------------------------ factories

def lock(name: str):
    """A mutex for ``name`` (one name per creation site, e.g.
    ``"serve.QueryServer._lock"``): sanitized under NDS_TPU_LOCKSAN=1,
    a plain ``threading.Lock`` otherwise."""
    if enabled():
        _ensure_exit_report()
        return SanLock(name)
    return threading.Lock()


def rlock(name: str):
    if enabled():
        _ensure_exit_report()
        return SanRLock(name)
    return threading.RLock()


def condition(name: str):
    """A ``threading.Condition`` whose underlying mutex is sanitized:
    ``wait()`` releases and re-acquires through the wrapper, so the
    order graph sees exactly what the threads do. Backed by a
    SanRLock — ``threading.Condition()``'s default lock is an RLock,
    and the sanitized primitive must keep the same reentrancy
    semantics, not just observe."""
    if enabled():
        _ensure_exit_report()
        return threading.Condition(lock=SanRLock(name))
    return threading.Condition()


# ---------------------------------------------------------- exit report

_exit_registered = False


def write_report(path: "str | None" = None) -> "str | None":
    """Write the global graph's snapshot as JSON (atomic, thread-unique
    tmp via io.integrity). Default path comes from
    ``$NDS_TPU_LOCKSAN_REPORT`` (a directory; the file is
    ``locksan-<pid>.json``); returns the path written, or None when no
    destination is configured."""
    if path is None:
        d = os.environ.get(REPORT_ENV)
        if not d:
            return None
        path = os.path.join(d, f"locksan-{os.getpid()}.json")
    from nds_tpu.io.integrity import write_json_atomic
    write_json_atomic(path, _GRAPH.snapshot())
    return path


def _at_exit() -> None:
    try:
        wrote = write_report()
    except Exception:  # noqa: BLE001 - exit path, best effort
        wrote = None
    n = _GRAPH.inversion_count()
    if n and not wrote:
        print(f"[locksan] exiting with {n} unreported lock-order "
              f"inversion(s) — set {REPORT_ENV} to capture them",
              file=sys.stderr)


def _ensure_exit_report() -> None:
    global _exit_registered
    if not _exit_registered:
        _exit_registered = True
        atexit.register(_at_exit)


# -------------------------------------------------------------- selftest

def selftest() -> bool:
    """Seed a deliberate AB/BA inversion on a PRIVATE graph and return
    whether the detector fired — the tier-1 proof that the sanitizer
    catches what it claims to (static_checks ``locksan`` section)."""
    g = OrderGraph(metric=False)
    a, b = SanLock("selftest.A", g), SanLock("selftest.B", g)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    seeded = g.inversion_count() == 1
    # and the re-entrant-acquire guard: must raise, not deadlock
    try:
        with a:
            a.acquire()
        reentry = False
    except RuntimeError:
        reentry = True
    return seeded and reentry and g.inversion_count() == 2
