"""Concurrency auditor: lock-discipline static analysis over nds_tpu/.

Every concurrency bug shipped in PRs 9-11 — the FlightRecorder pid-tmp
truncation race, the QueryJournal write outside its lock, the profiler
stall-hook self-deadlock, the engine thread's double-resolved batch
futures — was found by human review AFTER landing. This module codifies
those bug classes as cross-module ast rules the way plan bugs got
``plan_verify``: ``tools/ndsraces.py`` drives it, ``static_checks.py``
gates it in tier-1, and the opt-in runtime lock-order sanitizer
(``nds_tpu/analysis/locksan.py``, ``NDS_TPU_LOCKSAN=1``) witnesses at
runtime the order graph this module proposes statically.

Rules (waiver grammar: ``# ndsraces: waive[NDSR2xx] -- why``, same
semantics as ndslint's — mandatory justification, stale waivers fail):

- NDSR201 unguarded-shared-attr  **guard inference**: per class, every
                                 ``self._*`` attribute mutated under a
                                 ``with self._lock`` (any lock attr of
                                 the class) in ANY method is inferred
                                 lock-guarded; a read or write of it in
                                 the same class holding none of its
                                 guard locks flags (the QueryJournal
                                 bug: readout methods touching
                                 ``self.state`` lock-free while the
                                 drain thread mutates it). Methods
                                 named ``*_locked`` declare the
                                 caller-holds-the-guard contract and
                                 are exempt; ``__init__`` is exempt
                                 (construction happens-before
                                 publication).
- NDSR202 lock-order-cycle       **static acquisition graph**: lock A
                                 held while acquiring B — directly
                                 nested ``with``s or across resolved
                                 call edges within nds_tpu/ — builds a
                                 directed graph whose cycles are
                                 potential deadlocks; acquiring a
                                 non-reentrant lock already held (via a
                                 call edge) is the degenerate cycle
                                 (the ``request_stall_capture``
                                 self-deadlock bug).
- NDSR203 signal-unsafe          functions reachable from a
                                 ``signal.signal`` registration must
                                 not take locks (the interrupted frame
                                 may hold them — unbounded
                                 self-deadlock), block on a
                                 timeout-less ``join()``/``wait()``/
                                 ``acquire()``, or spawn subprocesses.
                                 A ``waive[NDSR203]`` on a function's
                                 ``def`` line declares it a BOUNDED
                                 signal boundary (e.g. lock-taking work
                                 delegated to a worker thread joined
                                 with a timeout) and prunes traversal.
- NDSR204 thread-shared-mutation objects whose methods run as a
                                 ``threading.Thread(target=self.X)``
                                 while other methods mutate the same
                                 attributes lock-free (both sides
                                 unguarded — rule 201 can't see them
                                 because no lock discipline exists to
                                 infer); plus ``.tmp`` names in atomic
                                 writes that embed ``os.getpid()`` but
                                 not ``threading.get_ident()`` in
                                 threading modules — the flight-dump
                                 truncation race, where two THREADS of
                                 one pid interleave one tmp file.

The call graph is best-effort by construction (``self.m()``, same-
module and imported nds_tpu functions, plus attribute calls whose
method name is defined by exactly one audited class); what it cannot
resolve it skips, which under-reports rather than drowning the gate —
the runtime sanitizer exists for exactly the dynamic remainder.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from nds_tpu.analysis.lint_rules import (
    LintResult, LintViolation, parse_waivers,
)

RULE_IDS = ("NDSR201", "NDSR202", "NDSR203", "NDSR204")
META_RULE = "NDSR200"
TOOL = "ndsraces"

_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": False}
_LOCKSAN_CTORS = {"lock": False, "rlock": True, "condition": False}
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|cv|cond|mutex)s?$")
_INIT_NAMES = ("__init__", "__new__", "__post_init__", "__del__")
# method calls that mutate the receiver's container in place
_MUTATORS = {"append", "appendleft", "extend", "insert", "remove",
             "pop", "popleft", "popitem", "clear", "update",
             "setdefault", "add", "discard"}


# --------------------------------------------------------------- index

@dataclass
class FuncNode:
    key: str                    # "path::Qual.name"
    path: str
    name: str
    node: object
    cls: "ClassNode | None" = None
    # (attr, is_write, frozenset(held lock ids), lineno)
    accesses: list = field(default_factory=list)
    # (lock id, reentrant, lineno)
    acquires: list = field(default_factory=list)
    # (held id, acquired id, acquired reentrant, lineno)
    direct_edges: list = field(default_factory=list)
    # (frozenset(callee keys), frozenset(held ids), lineno)
    calls: list = field(default_factory=list)
    # (lineno, description) — blocking primitives for the signal rule
    blocking: list = field(default_factory=list)


@dataclass
class ClassNode:
    key: str                    # "path::Name"
    path: str
    name: str
    methods: "dict[str, FuncNode]" = field(default_factory=dict)
    lock_attrs: "dict[str, bool]" = field(default_factory=dict)
    event_attrs: set = field(default_factory=set)
    thread_targets: set = field(default_factory=set)  # method names


@dataclass
class Index:
    funcs: "dict[str, FuncNode]" = field(default_factory=dict)
    classes: "dict[str, ClassNode]" = field(default_factory=dict)
    # per-path: bare func name -> key (module-level + nested defs)
    mod_funcs: "dict[str, dict[str, str]]" = field(default_factory=dict)
    # method name -> set of keys, for the unique-method fallback
    methods_by_name: "dict[str, set]" = field(default_factory=dict)
    # per-path: handler func keys registered via signal.signal
    handlers: "dict[str, list]" = field(default_factory=dict)
    # per-path: module uses threading at all (scopes the tmp-name rule)
    uses_threading: "dict[str, bool]" = field(default_factory=dict)
    # per-path: tmp-name findings (lineno)
    tmp_findings: "dict[str, list]" = field(default_factory=dict)


def _ctor_kind(call: ast.AST) -> "bool | None":
    """reentrant flag when ``call`` constructs a lock (threading.Lock/
    RLock/Condition or locksan.lock/rlock/condition), else None."""
    if not (isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)):
        return None
    mod, attr = call.func.value.id, call.func.attr
    if mod == "threading" and attr in _LOCK_CTORS:
        return _LOCK_CTORS[attr]
    if mod == "locksan" and attr in _LOCKSAN_CTORS:
        return _LOCKSAN_CTORS[attr]
    return None


def _is_event_ctor(call: ast.AST) -> bool:
    return (isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "Event"
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "threading")


def _self_base_attr(expr: ast.AST) -> "str | None":
    """``self.a`` / ``self.a.b`` / ``self.a[k].c`` -> ``a``."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr
        expr = expr.value
    return None


class _ModuleInfo:
    """Per-module import maps and module-level locks."""

    def __init__(self, path: str, tree: ast.AST, all_paths: set):
        self.path = path
        self.tree = tree
        self.aliases: dict[str, str] = {}   # name -> nds module path
        self.imported_funcs: dict[str, str] = {}  # name -> func key
        self.foreign: set = set()           # non-nds imported names
        self.module_locks: dict[str, bool] = {}   # name -> reentrant
        self._collect(all_paths)

    @staticmethod
    def _mod_path(dotted: str, all_paths: set) -> "str | None":
        base = dotted.replace(".", "/")
        for cand in (base + ".py", base + "/__init__.py"):
            if cand in all_paths:
                return cand
        return None

    def _collect(self, all_paths: set) -> None:
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Import):
                for a in n.names:
                    name = a.asname or a.name.split(".")[0]
                    p = self._mod_path(a.name, all_paths)
                    if p:
                        self.aliases[a.asname or a.name] = p
                    else:
                        self.foreign.add(name)
            elif isinstance(n, ast.ImportFrom) and n.module:
                for a in n.names:
                    name = a.asname or a.name
                    sub = self._mod_path(f"{n.module}.{a.name}",
                                         all_paths)
                    if sub:
                        self.aliases[name] = sub
                        continue
                    p = self._mod_path(n.module, all_paths)
                    if p:
                        self.imported_funcs[name] = f"{p}::{a.name}"
                    else:
                        self.foreign.add(name)
        for n in self.tree.body:
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)):
                kind = _ctor_kind(n.value)
                if kind is not None:
                    self.module_locks[n.targets[0].id] = kind


class _FuncScanner:
    """One function's body walk: accesses, acquisitions, call sites and
    blocking primitives, tracking the held-lock set through ``with``
    regions. Nested defs get their own FuncNode (empty held set — their
    execution time is unknown)."""

    def __init__(self, idx: Index, mod: _ModuleInfo,
                 cls: "ClassNode | None", out: FuncNode):
        self.idx = idx
        self.mod = mod
        self.cls = cls
        self.out = out

    # ------------------------------------------------- lock expressions

    def _lock_id(self, expr: ast.AST) -> "tuple[str, bool] | None":
        if isinstance(expr, ast.Name):
            if expr.id in self.mod.module_locks:
                return (f"{self.mod.path}::{expr.id}",
                        self.mod.module_locks[expr.id])
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        owner = None
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls"):
                owner = self.cls
            else:
                owner = self.idx.classes.get(
                    f"{self.mod.path}::{base.id}")
        if owner is None:
            return None
        reent = owner.lock_attrs.get(expr.attr)
        if reent is None and _LOCK_NAME_RE.search(expr.attr):
            # param-passed locks (obs/metrics instruments): the attr is
            # USED as a lock and NAMED one — infer non-reentrant
            owner.lock_attrs.setdefault(expr.attr, False)
            reent = owner.lock_attrs[expr.attr]
        if reent is None:
            return None
        return (f"{owner.key}.{expr.attr}", reent)

    # ------------------------------------------------- call resolution

    def _resolve_call(self, call: ast.Call) -> set:
        f = call.func
        keys: set = set()
        if isinstance(f, ast.Name):
            local = self.idx.mod_funcs.get(self.mod.path, {})
            if f.id in local:
                keys.add(local[f.id])
            elif f.id in self.mod.imported_funcs:
                keys.add(self.mod.imported_funcs[f.id])
            return keys
        if not isinstance(f, ast.Attribute):
            return keys
        base = f.value
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls") and self.cls is not None:
                if f.attr in self.cls.methods:
                    keys.add(self.cls.methods[f.attr].key)
                return keys
            if base.id in self.mod.foreign:
                return keys
            mp = self.mod.aliases.get(base.id)
            if mp is not None:
                k = f"{mp}::{f.attr}"
                if k in self.idx.funcs:
                    keys.add(k)
                return keys
            c = self.idx.classes.get(f"{self.mod.path}::{base.id}")
            if c is not None and f.attr in c.methods:
                keys.add(c.methods[f.attr].key)
                return keys
        # unique-method fallback: exactly one audited class defines
        # this method name -> resolve the attribute call to it
        cands = self.idx.methods_by_name.get(f.attr, set())
        if len(cands) == 1:
            keys.add(next(iter(cands)))
        return keys

    # ------------------------------------------------------ specials

    def _thread_target(self, call: ast.Call) -> None:
        f = call.func
        is_thread = (isinstance(f, ast.Attribute) and f.attr == "Thread"
                     and isinstance(f.value, ast.Name)
                     and f.value.id == "threading") or (
                         isinstance(f, ast.Name) and f.id == "Thread")
        if not is_thread or self.cls is None:
            return
        for kw in call.keywords:
            if (kw.arg == "target"
                    and isinstance(kw.value, ast.Attribute)
                    and isinstance(kw.value.value, ast.Name)
                    and kw.value.value.id == "self"):
                self.cls.thread_targets.add(kw.value.attr)

    def _signal_reg(self, call: ast.Call) -> None:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "signal"
                and isinstance(f.value, ast.Name)
                and f.value.id.lstrip("_") == "signal"
                and len(call.args) >= 2):
            return
        h = call.args[1]
        key = None
        if isinstance(h, ast.Name):
            key = self.idx.mod_funcs.get(self.mod.path, {}).get(h.id)
        elif (isinstance(h, ast.Attribute)
              and isinstance(h.value, ast.Name)
              and h.value.id == "self" and self.cls is not None
              and h.attr in self.cls.methods):
            key = self.cls.methods[h.attr].key
        if key is not None:
            self.idx.handlers.setdefault(self.mod.path, []).append(key)

    def _blocking(self, call: ast.Call) -> None:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        if f.attr == "acquire":
            lid = self._lock_id(f.value)
            lockish = lid is not None or (
                isinstance(f.value, ast.Name)
                and _LOCK_NAME_RE.search(f.value.id))
            blocking_arg = not call.args or (
                isinstance(call.args[0], ast.Constant)
                and call.args[0].value is True)
            nonblocking_kw = any(
                kw.arg == "blocking"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in call.keywords)
            if (lockish and blocking_arg and not has_timeout
                    and not nonblocking_kw):
                self.out.blocking.append(
                    (call.lineno, "timeout-less blocking acquire()"))
        elif f.attr in ("join", "wait"):
            if not call.args and not has_timeout:
                # str.join always takes an iterable arg, so a no-arg
                # join() is a thread join; a no-arg wait() is an
                # unbounded Event/Condition wait
                self.out.blocking.append(
                    (call.lineno, f"timeout-less {f.attr}()"))
        elif (isinstance(f.value, ast.Name)
              and f.value.id == "subprocess"):
            self.out.blocking.append(
                (call.lineno, f"subprocess.{f.attr}() on the signal "
                              f"path"))

    def _tmp_name(self, node: ast.JoinedStr) -> None:
        text_parts = [v.value for v in node.values
                      if isinstance(v, ast.Constant)
                      and isinstance(v.value, str)]
        if not any(".tmp" in t for t in text_parts):
            return
        calls = [c.func.attr for c in ast.walk(node)
                 if isinstance(c, ast.Call)
                 and isinstance(c.func, ast.Attribute)]
        if "getpid" in calls and not (
                {"get_ident", "get_native_id"} & set(calls)):
            self.idx.tmp_findings.setdefault(
                self.mod.path, []).append(node.lineno)

    # ----------------------------------------------------------- walk

    def scan(self) -> None:
        for stmt in self.out.node.body:
            self._walk(stmt, ())

    def _record_target(self, t: ast.AST, held: tuple,
                       lineno: int) -> None:
        attr = _self_base_attr(t)
        if attr is not None:
            self.out.accesses.append((attr, True, frozenset(held),
                                      lineno))
        # slices/values inside the target still read
        if isinstance(t, ast.Subscript):
            self._walk(t.slice, held)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._record_target(el, held, lineno)

    def _walk(self, node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = FuncNode(
                key=f"{self.out.key}.<locals>.{node.name}",
                path=self.mod.path, name=node.name, node=node,
                cls=self.cls)
            self.idx.funcs[sub.key] = sub
            self.idx.mod_funcs.setdefault(self.mod.path, {}) \
                .setdefault(node.name, sub.key)
            _FuncScanner(self.idx, self.mod, self.cls, sub).scan()
            return
        if isinstance(node, (ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                lid = self._lock_id(item.context_expr)
                if lid is None:
                    self._walk(item.context_expr, inner)
                    continue
                lock, reent = lid
                self.out.acquires.append((lock, reent, node.lineno))
                for h in inner:
                    self.out.direct_edges.append(
                        (h, lock, reent, node.lineno))
                inner = inner + (lock,)
            for stmt in node.body:
                self._walk(stmt, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                self._record_target(t, held, node.lineno)
            if node.value is not None:
                self._walk(node.value, held)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._record_target(t, held, node.lineno)
            return
        if isinstance(node, ast.Call):
            self._thread_target(node)
            self._signal_reg(node)
            self._blocking(node)
            f = node.func
            mutated = None
            if (isinstance(f, ast.Attribute)
                    and f.attr in _MUTATORS):
                mutated = _self_base_attr(f.value)
                if mutated is not None:
                    self.out.accesses.append(
                        (mutated, True, frozenset(held), node.lineno))
            keys = self._resolve_call(node)
            if keys:
                self.out.calls.append((frozenset(keys),
                                       frozenset(held), node.lineno))
            for child in ast.iter_child_nodes(node):
                # the mutator branch already recorded this access as a
                # write; re-walking the receiver would double-report it
                # as a read at the same line
                if mutated is not None and child is f:
                    continue
                self._walk(child, held)
            return
        if isinstance(node, ast.JoinedStr):
            if self.idx.uses_threading.get(self.mod.path):
                self._tmp_name(node)
            for child in ast.iter_child_nodes(node):
                self._walk(child, held)
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)):
            if (self.cls is None
                    or node.attr not in self.cls.lock_attrs):
                self.out.accesses.append(
                    (node.attr, False, frozenset(held), node.lineno))
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)


def build_index(sources: "dict[str, str]") -> "tuple[Index, list]":
    """Parse every source and populate the cross-module index; returns
    (index, syntax-error violations)."""
    idx = Index()
    errors: list[LintViolation] = []
    trees: dict[str, ast.AST] = {}
    for path, src in sorted(sources.items()):
        try:
            trees[path] = ast.parse(src)
        except SyntaxError as exc:
            errors.append(LintViolation(
                META_RULE, path, exc.lineno or 0,
                f"syntax error: {exc.msg}"))
            continue
        idx.uses_threading[path] = ("threading" in src
                                    or "locksan" in src)
    mods = {path: _ModuleInfo(path, tree, set(trees))
            for path, tree in trees.items()}
    # pass 1: classes, their lock/event attrs, func skeletons
    for path, tree in trees.items():
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                cls = ClassNode(key=f"{path}::{node.name}", path=path,
                                name=node.name)
                idx.classes[cls.key] = cls
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)):
                        kind = _ctor_kind(stmt.value)
                        if kind is not None:
                            cls.lock_attrs[stmt.targets[0].id] = kind
                    if not isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    fn = FuncNode(
                        key=f"{cls.key}.{stmt.name}", path=path,
                        name=stmt.name, node=stmt, cls=cls)
                    idx.funcs[fn.key] = fn
                    cls.methods[stmt.name] = fn
                    idx.methods_by_name.setdefault(
                        stmt.name, set()).add(fn.key)
                    for n in ast.walk(stmt):
                        if not (isinstance(n, ast.Assign)
                                and len(n.targets) == 1
                                and isinstance(n.targets[0],
                                               ast.Attribute)):
                            continue
                        t = n.targets[0]
                        if not (isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            continue
                        kind = _ctor_kind(n.value)
                        if kind is not None:
                            cls.lock_attrs[t.attr] = kind
                        elif _is_event_ctor(n.value):
                            cls.event_attrs.add(t.attr)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                fn = FuncNode(key=f"{path}::{node.name}", path=path,
                              name=node.name, node=node)
                idx.funcs[fn.key] = fn
                idx.mod_funcs.setdefault(path, {})[node.name] = fn.key
    # pass 2: body scans (lock regions need every class known first)
    for key, fn in list(idx.funcs.items()):
        if "<locals>" in key:
            continue  # nested defs are scanned by their parent
        _FuncScanner(idx, mods[fn.path], fn.cls, fn).scan()
    return idx, errors


# --------------------------------------------------------------- rules

def _rule_unguarded(idx: Index, enabled) -> list:
    if "NDSR201" not in enabled:
        return []
    out = []
    for cls in idx.classes.values():
        if not cls.lock_attrs:
            continue
        own_locks = {f"{cls.key}.{a}" for a in cls.lock_attrs}
        guard_locks: dict[str, set] = {}
        for m in cls.methods.values():
            for attr, write, held, _ln in m.accesses:
                if write and held & own_locks:
                    guard_locks.setdefault(attr, set()).update(
                        held & own_locks)
        for attr in list(guard_locks):
            if attr in cls.lock_attrs or attr in cls.event_attrs:
                del guard_locks[attr]
        if not guard_locks:
            continue
        for key, fn in idx.funcs.items():
            if (fn.cls is not cls or fn.name in _INIT_NAMES
                    or fn.name.endswith("_locked")):
                continue
            for attr, write, held, ln in fn.accesses:
                locks = guard_locks.get(attr)
                if locks is None or held & locks:
                    continue
                names = ", ".join(sorted(
                    lk.rsplit(".", 1)[-1] for lk in locks))
                out.append(LintViolation(
                    "NDSR201", fn.path, ln,
                    f"{cls.name}.{attr} is guarded by {names} "
                    f"(mutated under it elsewhere in the class) but "
                    f"{'written' if write else 'read'} lock-free in "
                    f"{fn.name}() — take the lock, or waive with why "
                    f"this access cannot race"))
    return out


def _may_acquire(idx: Index) -> dict:
    """Fixpoint closure: every lock a function may acquire directly or
    through resolved callees."""
    may = {k: {(lid, r) for lid, r, _ln in f.acquires}
           for k, f in idx.funcs.items()}
    changed = True
    while changed:
        changed = False
        for k, f in idx.funcs.items():
            cur = may[k]
            before = len(cur)
            for callees, _held, _ln in f.calls:
                for c in callees:
                    cur |= may.get(c, set())
            if len(cur) != before:
                changed = True
    return may


def _rule_lock_order(idx: Index, enabled) -> list:
    if "NDSR202" not in enabled:
        return []
    may = _may_acquire(idx)
    # edge: (held, acquired) -> (reentrant, path, line) first witness
    edges: dict = {}
    for f in idx.funcs.values():
        for h, lock, reent, ln in f.direct_edges:
            edges.setdefault((h, lock), (reent, f.path, ln))
        for callees, held, ln in f.calls:
            if not held:
                continue
            for c in callees:
                for lock, reent in may.get(c, set()):
                    for h in held:
                        edges.setdefault((h, lock),
                                         (reent, f.path, ln))
    out = []
    seen_self: set = set()
    graph: dict[str, set] = {}
    for (a, b), (reent, path, ln) in sorted(
            edges.items(), key=lambda kv: (kv[1][1], kv[1][2])):
        if a == b:
            if not reent and (path, ln) not in seen_self:
                seen_self.add((path, ln))
                out.append(LintViolation(
                    "NDSR202", path, ln,
                    f"non-reentrant lock {a.rsplit('::', 1)[-1]} "
                    f"acquired while already held (self-deadlock; "
                    f"the request_stall_capture bug class) — hoist "
                    f"the inner acquisition out, use an RLock, or "
                    f"waive with why re-entry is impossible"))
            continue
        graph.setdefault(a, set()).add(b)
    # cycles: report once per unordered lock set, at the first witness
    def _reach(src: str, dst: str) -> bool:
        stack, seen = [src], set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(graph.get(n, ()))
        return False

    reported: set = set()
    for (a, b), (_reent, path, ln) in sorted(
            edges.items(), key=lambda kv: (kv[1][1], kv[1][2])):
        if a == b or not _reach(b, a):
            continue
        key = frozenset((a, b))
        if key in reported:
            continue
        reported.add(key)
        short = [x.rsplit("::", 1)[-1] for x in (a, b)]
        out.append(LintViolation(
            "NDSR202", path, ln,
            f"lock-order cycle: {short[0]} is held while acquiring "
            f"{short[1]} here, and elsewhere {short[1]} is held "
            f"while (transitively) acquiring {short[0]} — a "
            f"potential deadlock; pick one order, or waive with why "
            f"the two paths cannot interleave"))
    return out


def _rule_signal_safety(idx: Index, enabled, waiver_lines) -> list:
    if "NDSR203" not in enabled:
        return []
    out = []
    queue = [k for keys in idx.handlers.values() for k in keys]
    seen: set = set()
    while queue:
        key = queue.pop()
        if key in seen:
            continue
        seen.add(key)
        fn = idx.funcs.get(key)
        if fn is None:
            continue
        defline = fn.node.lineno
        waived = waiver_lines.get(fn.path, {}).get(defline)
        if waived and "NDSR203" in waived:
            # declared signal boundary: its blocking work is bounded
            # (worker thread + timeout) — emit the boundary finding so
            # the waiver registers used, and prune traversal
            out.append(LintViolation(
                "NDSR203", fn.path, defline,
                f"signal path enters {fn.name}() (declared bounded "
                f"boundary)"))
            continue
        for lock, _reent, ln in fn.acquires:
            out.append(LintViolation(
                "NDSR203", fn.path, ln,
                f"{fn.name}() acquires {lock.rsplit('::', 1)[-1]} on "
                f"a signal-handler path — the interrupted frame may "
                f"hold it (unbounded self-deadlock absorbing the "
                f"signal); move the lock-taking work to a bounded "
                f"worker thread, or waive the def line as a bounded "
                f"boundary"))
        for ln, why in fn.blocking:
            out.append(LintViolation(
                "NDSR203", fn.path, ln,
                f"{why} in {fn.name}() on a signal-handler path — "
                f"bound it with a timeout, or waive with why it "
                f"cannot block"))
        for callees, _held, _ln in fn.calls:
            queue.extend(callees)
    return out


def _rule_thread_shared(idx: Index, enabled) -> list:
    if "NDSR204" not in enabled:
        return []
    out = []
    for cls in idx.classes.values():
        if not cls.thread_targets:
            continue

        def _closure(entry_names) -> set:
            todo = [cls.methods[n].key for n in entry_names
                    if n in cls.methods]
            seen: set = set()
            while todo:
                k = todo.pop()
                if k in seen:
                    continue
                seen.add(k)
                fn = idx.funcs.get(k)
                if fn is None:
                    continue
                for callees, _held, _ln in fn.calls:
                    todo.extend(c for c in callees
                                if c.startswith(cls.key + "."))
            return seen

        thread_keys = _closure(cls.thread_targets)

        def _unguarded_writes(keys) -> dict:
            w: dict[str, int] = {}
            for k in keys:
                fn = idx.funcs.get(k)
                if fn is None:
                    continue
                for attr, write, held, ln in fn.accesses:
                    if write and not held:
                        w.setdefault(attr, ln)
            return w

        thread_writes = _unguarded_writes(thread_keys)
        other = [m.name for m in cls.methods.values()
                 if m.key not in thread_keys
                 and m.name not in _INIT_NAMES]
        off_writes = _unguarded_writes(_closure(other))
        skip = (set(cls.lock_attrs) | cls.event_attrs
                | set(cls.thread_targets))
        for attr in sorted(set(thread_writes) & set(off_writes)
                           - skip):
            entry = "/".join(sorted(cls.thread_targets))
            out.append(LintViolation(
                "NDSR204", cls.path, off_writes[attr],
                f"{cls.name}.{attr} is mutated lock-free both on the "
                f"{entry} thread and from other methods — guard it, "
                f"or waive with why the race is benign"))
    for path, lines in idx.tmp_findings.items():
        for ln in sorted(set(lines)):
            out.append(LintViolation(
                "NDSR204", path, ln,
                "atomic-write tmp name embeds os.getpid() but not "
                "threading.get_ident(): two threads of one process "
                "truncate each other's stream (the flight-dump race) "
                "— add the thread ident, or route through "
                "io.integrity.write_json_atomic"))
    return out


# -------------------------------------------------------------- driver

def audit_sources(sources: "dict[str, str]",
                  enabled: "set[str] | None" = None) -> LintResult:
    """Audit {path: source}; returns the same LintResult shape ndslint
    uses (violations / waived / errors), with the ``ndsraces`` waiver
    marker."""
    enabled = set(RULE_IDS) if enabled is None else enabled
    res = LintResult()
    idx, errors = build_index(sources)
    res.errors.extend(errors)
    waivers_by_path: dict = {}
    waiver_lines: dict = {}
    for path, src in sources.items():
        waivers, werrs = parse_waivers(src, tool=TOOL,
                                       meta_rule=META_RULE)
        for w in werrs:
            w.path = path
            res.errors.append(w)
        waivers_by_path[path] = waivers
        waiver_lines[path] = {ln: set(w.rules)
                              for ln, w in waivers.items()}
    violations = (_rule_unguarded(idx, enabled)
                  + _rule_lock_order(idx, enabled)
                  + _rule_signal_safety(idx, enabled, waiver_lines)
                  + _rule_thread_shared(idx, enabled))
    for v in sorted(violations, key=lambda v: (v.path, v.line, v.msg)):
        w = waivers_by_path.get(v.path, {}).get(v.line)
        if w is not None and v.rule in w.rules:
            w.used = True
            v.waived = True
            v.waiver_note = w.note
            res.waived.append(v)
        else:
            res.violations.append(v)
    for path, waivers in waivers_by_path.items():
        for w in waivers.values():
            if not w.used:
                res.errors.append(LintViolation(
                    META_RULE, path, w.line,
                    f"waiver for {','.join(w.rules)} matches no "
                    f"violation — stale, remove it"))
    return res
