"""ndsjit rules: the JAX-specific recompile & transfer hazard classes.

The engine's serving claim is "0 compiles warm" (README "Plan cache")
and its perf claim is that dispatch never hides a host<->device sync
inside the hot path. Both die silently: a traced value leaking into
Python control flow retraces per distinct value, a closure capture the
plan fingerprint doesn't cover mints unbounded cache entries, an
``.item()`` in dispatch code stalls the pipeline, and a bare Python
literal at a jit boundary weak-types into a fresh cache key. This
module encodes each as an ast check over ``nds_tpu/`` (driven by
``tools/ndsjit.py``; the runtime witness is ``analysis/jitsan.py``):

- NDSJ301 traced-leak       ``if``/``while``/``assert`` on a value
                            DERIVED from jnp/lax ops inside a traced
                            function (one decorated/wrapped with
                            ``jax.jit``/``donate_jit`` or built for
                            the AOT cache): each branch on a traced
                            value is a TracerBoolConversionError at
                            trace time or — via static args — a
                            retrace per distinct value. Branch on host
                            config instead, or ``lax.cond``/``where``.
- NDSJ302 fingerprint-blind-capture
                            a traced builder in ``engine/`` /
                            ``parallel/`` closes over an enclosing
                            function's LOCAL variable that the plan
                            fingerprint never folds in (not mentioned
                            in a ``try_fingerprint``/
                            ``_fingerprint_parts``/``fingerprint``
                            site in the same file): two queries
                            differing only in that capture would
                            collide on one cache entry — or mint
                            unbounded ones. Fold it into ``parts`` (or
                            waive with why it cannot vary per query).
- NDSJ303 implicit-transfer ``float()``/``int()``/``bool()``/
                            ``np.asarray()``/``.item()``/``.tolist()``
                            on a device-derived value in ``engine/`` /
                            ``serve/`` / ``parallel/`` dispatch code:
                            each is a blocking device->host sync the
                            timing bills never see. Sync at sanctioned
                            read-back points via ``jax.device_get``
                            (which batches and is attributed), or
                            waive the site as a sanctioned sync.
                            In ``serve/``, additionally flags a
                            blocking ``block_until_ready``/
                            ``device_get`` reachable from an ``async
                            def`` coroutine through same-module sync
                            helpers — one stalled coroutine stalls
                            every in-flight request.
- NDSJ304 weak-literal-dispatch
                            a bare Python numeric literal passed
                            positionally to a compiled/jitted callable
                            (``compiled(bufs, 5)``): weak-typed
                            scalars re-promote per call site and each
                            distinct literal can key a fresh
                            executable — stage it
                            (``jnp.int32(n)``/``device_put``) so the
                            dtype is pinned and the transfer explicit.

Waivers share lint_rules' grammar under the ``ndsjit`` marker —
``ndsjit: waive[NDSJ3xx] -- why`` (note mandatory) or
``ndsjit: disable=NDSJ3xx`` (lightweight form), as a line comment;
malformed/stale markers report under NDSJ300. File roots come from ``[tool.ndsjit]``
in pyproject.toml (tools/ndsjit.py loads it).
"""

from __future__ import annotations

import ast

from nds_tpu.analysis.lint_rules import (
    LintResult, LintViolation, Rule, _walk_funcs, lint_sources,
)

TOOL = "ndsjit"
META_RULE = "NDSJ300"

#: names a compiled/AOT executable commonly binds to in this tree —
#: the jit-boundary callables NDSJ303/304 treat as device sources
_COMPILED_NAMES = {"compiled", "jitted", "cf", "entry", "state"}

#: module aliases whose calls produce device values
_DEVICE_MODULES = {"jnp", "lax"}

#: jit wrappers that mark a function argument as traced
_JIT_WRAPPERS = {"jit", "donate_jit"}


def _call_name(func: ast.AST) -> "str | None":
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_device_call(node: ast.AST) -> bool:
    """A call whose result lives on device: ``jnp.*``/``lax.*`` ops,
    ``jax.device_put``, or an invocation of a compiled executable
    (``compiled(...)``, ``entry["compiled"](...)``)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name) and v.id in _DEVICE_MODULES:
            return True
        if (isinstance(v, ast.Name) and v.id == "jax"
                and f.attr == "device_put"):
            return True
    if isinstance(f, ast.Name) and f.id in ("compiled", "jitted", "cf"):
        return True
    if (isinstance(f, ast.Subscript)
            and isinstance(f.slice, ast.Constant)
            and f.slice.value in ("compiled", "jitted")):
        return True
    return False


def _is_host_call(node: ast.AST) -> bool:
    """A call whose result is host-resident even when fed device
    values: ``jax.device_get`` / ``np.asarray`` (its OUTPUT is host —
    the call itself is judged separately as a sink)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute)
            and ((isinstance(f.value, ast.Name)
                  and f.value.id == "jax" and f.attr == "device_get")
                 or (isinstance(f.value, ast.Name)
                     and f.value.id in ("np", "numpy")
                     and f.attr == "asarray")))


def _assigned_names(target: ast.AST):
    """Flatten assignment targets: Name, tuple/list unpack, starred."""
    stack = [target]
    while stack:
        t = stack.pop()
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)


def _device_taint(fn: ast.AST) -> set:
    """Names in ``fn`` bound (directly or transitively through
    assignments) to device-call results, minus names re-bound through
    the host escapes (device_get/np.asarray outputs are host)."""
    tainted: set = set()
    host: set = set()
    assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
    for _ in range(4):  # bounded fixpoint: chains are short
        changed = False
        for a in assigns:
            rhs = a.value
            is_host = _is_host_call(rhs) or (
                isinstance(rhs, ast.Call)
                and any(_is_host_call(x) for x in ast.walk(rhs.func)))
            derives = any(_is_device_call(x) for x in ast.walk(rhs))
            refs_taint = any(isinstance(x, ast.Name)
                             and x.id in tainted
                             for x in ast.walk(rhs))
            for name in [n for t in a.targets
                         for n in _assigned_names(t)]:
                if is_host:
                    if name not in host:
                        host.add(name)
                        changed = True
                    tainted.discard(name)
                elif (derives or refs_taint) and name not in tainted:
                    tainted.add(name)
                    changed = True
        if not changed:
            break
    return tainted - host


def _traced_functions(tree: ast.AST) -> "list[ast.AST]":
    """Function defs that become XLA programs: decorated with a jit
    wrapper, or passed by name/lambda into ``jax.jit``/``donate_jit``
    anywhere in the module (the AOT builders' shape)."""
    funcs = list(_walk_funcs(tree))
    by_name = {f.name: f for f in funcs}
    traced: list = []

    def _add(f):
        # identity (not ==) membership: ast nodes hash/compare by
        # object, and the tree is small enough for the linear scan
        if all(f is not g for g in traced):
            traced.append(f)

    for f in funcs:
        for d in f.decorator_list:
            target = d.func if isinstance(d, ast.Call) else d
            if _call_name(target) in _JIT_WRAPPERS:
                _add(f)
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call)
                and _call_name(n.func) in _JIT_WRAPPERS):
            continue
        for arg in n.args:
            if isinstance(arg, ast.Name) and arg.id in by_name:
                _add(by_name[arg.id])
            elif isinstance(arg, ast.Lambda):
                _add(arg)
    return traced


class TracedLeakRule(Rule):
    """NDSJ301: Python control flow on a traced-derived value inside a
    traced function. Only values DERIVED from jnp/lax calls within the
    function taint — branching on captured host config is static at
    trace time and legal."""

    id = "NDSJ301"
    name = "traced-leak"
    paths = ("nds_tpu/",)

    def check(self, tree, src, path):
        out = []
        for fn in _traced_functions(tree):
            if isinstance(fn, ast.Lambda):
                continue  # lambdas cannot contain statements
            tainted = _device_taint(fn)

            def _leaks(test: ast.AST) -> bool:
                if any(isinstance(x, ast.Name) and x.id in tainted
                       for x in ast.walk(test)):
                    return True
                return any(_is_device_call(x) for x in ast.walk(test))

            for n in ast.walk(fn):
                test = None
                kind = None
                if isinstance(n, ast.If):
                    test, kind = n.test, "if"
                elif isinstance(n, ast.While):
                    test, kind = n.test, "while"
                elif isinstance(n, ast.Assert):
                    test, kind = n.test, "assert"
                if test is None or not _leaks(test):
                    continue
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    f"`{kind}` on a traced value inside traced "
                    f"function {fn.name}(): a branch on device data "
                    f"either fails at trace time or forces a host "
                    f"sync + retrace per distinct value — use "
                    f"lax.cond/jnp.where, or hoist the decision to "
                    f"host config"))
        return out


class FingerprintBlindCaptureRule(Rule):
    """NDSJ302: a traced builder closing over an enclosing function's
    local that no fingerprint site in the file mentions. Module
    globals, params, ALL_CAPS constants, and self-attributes are out
    of scope — the hazard is the per-query-varying LOCAL the cache key
    can't see."""

    id = "NDSJ302"
    name = "fingerprint-blind-capture"
    paths = ("nds_tpu/engine/", "nds_tpu/parallel/")

    _FP_MARKERS = ("try_fingerprint", "_fingerprint_parts",
                   "fingerprint", "_plan_fingerprint")

    @staticmethod
    def _locals_of(fn: ast.AST) -> set:
        names = set()
        for n in ast.walk(fn):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not fn:
                continue
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    names.update(_assigned_names(t))
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                names.update(_assigned_names(n.target))
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                names.update(_assigned_names(n.target))
            elif isinstance(n, ast.withitem) and n.optional_vars:
                names.update(_assigned_names(n.optional_vars))
        a = getattr(fn, "args", None)
        if a is not None:
            for arg in (a.args + a.kwonlyargs + a.posonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                names.add(arg.arg)
        return names

    def _fp_covered(self, src: str) -> set:
        """Names mentioned anywhere inside a fingerprint call's source
        segment in this file — textual on purpose: the parts dict
        spells captures as strings and expressions alike."""
        covered: set = set()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            return covered
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call)
                    and _call_name(n.func) in self._FP_MARKERS):
                continue
            for x in ast.walk(n):
                if isinstance(x, ast.Name):
                    covered.add(x.id)
                elif (isinstance(x, ast.Constant)
                      and isinstance(x.value, str)):
                    covered.add(x.value)
        # a `parts` dict assembled before the call covers its values
        for n in ast.walk(tree):
            if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n.name in ("_fingerprint_parts",
                                   "_fingerprint_roots")):
                for x in ast.walk(n):
                    if isinstance(x, ast.Name):
                        covered.add(x.id)
                    elif isinstance(x, ast.Attribute):
                        covered.add(x.attr)
        return covered

    def check(self, tree, src, path):
        out = []
        covered = self._fp_covered(src)
        traced = _traced_functions(tree)
        funcs = list(_walk_funcs(tree))
        for outer in funcs:
            outer_locals = self._locals_of(outer)
            for inner in ast.walk(outer):
                if inner is outer or all(inner is not f
                                         for f in traced):
                    continue
                if not isinstance(inner, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                    continue
                inner_bound = self._locals_of(inner)
                loads = {x.id for x in ast.walk(inner)
                         if isinstance(x, ast.Name)
                         and isinstance(x.ctx, ast.Load)}
                captures = (loads & outer_locals) - inner_bound
                for name in sorted(captures):
                    if name.isupper() or name == "self":
                        continue
                    if name in covered:
                        continue
                    out.append(LintViolation(
                        self.id, path, inner.lineno,
                        f"traced builder {inner.name}() captures "
                        f"enclosing local {name!r} that no "
                        f"fingerprint site in this file folds in: "
                        f"a per-query-varying capture makes cache "
                        f"entries collide or mint unboundedly — add "
                        f"it to the fingerprint parts, or waive with "
                        f"why it cannot vary"))
        return out


class ImplicitTransferRule(Rule):
    """NDSJ303: blocking host syncs on device-derived values in the
    dispatch layers, plus blocking calls reachable from serve
    coroutines through same-module sync helpers."""

    id = "NDSJ303"
    name = "implicit-transfer"
    paths = ("nds_tpu/engine/", "nds_tpu/serve/", "nds_tpu/parallel/")

    _SCALARIZERS = {"float", "int", "bool"}
    _METHOD_SINKS = {"item", "tolist"}
    _BLOCKING = {"block_until_ready", "device_get"}

    def _sink_hits(self, fn: ast.AST, path: str) -> list:
        tainted = _device_taint(fn)
        out = []
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            name = _call_name(f)
            hit = None
            if (isinstance(f, ast.Name) and name in self._SCALARIZERS
                    and n.args):
                a = n.args[0]
                if (isinstance(a, ast.Name) and a.id in tainted) \
                        or _is_device_call(a):
                    hit = f"{name}() on a device value"
            elif (isinstance(f, ast.Attribute)
                  and f.attr in self._METHOD_SINKS):
                v = f.value
                if (isinstance(v, ast.Name) and v.id in tainted) \
                        or _is_device_call(v):
                    hit = f".{f.attr}() on a device value"
            elif (isinstance(f, ast.Attribute) and f.attr == "asarray"
                  and isinstance(f.value, ast.Name)
                  and f.value.id in ("np", "numpy") and n.args):
                a = n.args[0]
                if (isinstance(a, ast.Name) and a.id in tainted) \
                        or _is_device_call(a):
                    hit = "np.asarray() on a device value"
            if hit is None:
                continue
            out.append(LintViolation(
                self.id, path, n.lineno,
                f"{hit} is a blocking implicit device->host sync in "
                f"dispatch code — batch it through jax.device_get at "
                f"a sanctioned read-back point, or waive with why "
                f"this sync is the site's product"))
        return out

    def _serve_reachable(self, tree: ast.AST, path: str) -> list:
        """serve/ only: a coroutine calling (transitively, same
        module) a sync function containing block_until_ready /
        device_get stalls the shared event loop."""
        if "serve/" not in path.replace("\\", "/"):
            return []
        funcs = list(_walk_funcs(tree))
        by_name = {f.name: f for f in funcs}

        def blocking_sites(f):
            return [n for n in ast.walk(f)
                    if isinstance(n, ast.Call)
                    and _call_name(n.func) in self._BLOCKING]

        calls = {f.name: {_call_name(n.func) for n in ast.walk(f)
                          if isinstance(n, ast.Call)} - {None}
                 for f in funcs}
        out = []
        for f in funcs:
            if not isinstance(f, ast.AsyncFunctionDef):
                continue
            seen, stack = set(), [f.name]
            while stack:
                cur = stack.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                target = by_name.get(cur)
                if target is None:
                    continue
                if cur != f.name:
                    for site in blocking_sites(target):
                        out.append(LintViolation(
                            self.id, path, site.lineno,
                            f"{_call_name(site.func)}() reachable "
                            f"from coroutine {f.name}() via "
                            f"{target.name}(): a blocking device "
                            f"sync on the event loop stalls every "
                            f"in-flight request — hand it to the "
                            f"engine thread"))
                stack.extend(calls.get(cur, ()))
        # dedupe: one site may be reachable from several coroutines
        uniq = {}
        for v in out:
            uniq.setdefault(v.line, v)
        return list(uniq.values())

    def check(self, tree, src, path):
        out = []
        for fn in _walk_funcs(tree):
            out.extend(self._sink_hits(fn, path))
        out.extend(self._serve_reachable(tree, path))
        return out


class WeakLiteralDispatchRule(Rule):
    """NDSJ304: a bare numeric literal passed positionally into a
    compiled/jitted callable. Weak-typed scalars promote per call and
    distinct literals can key distinct executables — the classic
    cache-miss multiplier at serving time."""

    id = "NDSJ304"
    name = "weak-literal-dispatch"
    paths = ("nds_tpu/engine/", "nds_tpu/parallel/")

    @staticmethod
    def _is_compiled_callee(f: ast.AST) -> bool:
        if isinstance(f, ast.Name) and f.id in ("compiled", "jitted",
                                                "cf"):
            return True
        return (isinstance(f, ast.Subscript)
                and isinstance(f.slice, ast.Constant)
                and f.slice.value in ("compiled", "jitted"))

    def check(self, tree, src, path):
        out = []
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call)
                    and self._is_compiled_callee(n.func)):
                continue
            for a in n.args:
                lit = a
                if (isinstance(lit, ast.UnaryOp)
                        and isinstance(lit.op, ast.USub)):
                    lit = lit.operand
                if (isinstance(lit, ast.Constant)
                        and isinstance(lit.value, (int, float))
                        and not isinstance(lit.value, bool)):
                    seg = ast.get_source_segment(src, a) or "?"
                    out.append(LintViolation(
                        self.id, path, n.lineno,
                        f"bare literal {seg} "
                        f"passed positionally to a compiled callable: "
                        f"weak-typed scalars re-key the executable "
                        f"per distinct value — stage it explicitly "
                        f"(jnp.int32(...)/device_put) so dtype and "
                        f"transfer are pinned"))
        return out


def default_rules() -> "list[Rule]":
    return [TracedLeakRule(), FingerprintBlindCaptureRule(),
            ImplicitTransferRule(), WeakLiteralDispatchRule()]


def scan_sources(sources: "dict[str, str]",
                 enabled: "set[str] | None" = None) -> LintResult:
    """Run the ndsjit catalog over {path: source} with the shared
    waiver/disable semantics under the ``ndsjit`` marker."""
    return lint_sources(sources, rules=default_rules(),
                        enabled=enabled, tool=TOOL,
                        meta_rule=META_RULE)
