"""Runtime recompile & transfer sanitizer for the jit dispatch path.

The static auditor (``nds_tpu/analysis/jit_hazards.py``, driven by
``tools/ndsjit.py``) PROPOSES where recompiles and hidden host<->device
syncs could happen; this module WITNESSES that they don't, on the real
serving workloads. "0 compiles warm" is the engine's core serving
claim (README "Plan cache"), and PR 16's cost ledger made compiles
countable — jitsan promotes the count from a bench observation to an
enforced runtime invariant:

- :func:`arm` opens a measurement window (serve_check arms after its
  warmup phase; cost_check arms its warm run). While armed, every
  compile that reaches the engine's single lower/compile funnel
  (``cache/aot.py lower_and_compile``, which calls :func:`on_compile`)
  is recorded with its Python stack — a post-warmup compile is the
  recompile the plan cache exists to prevent.
- While armed, implicit device->host transfers are interposed at the
  array type itself: ``ArrayImpl.__array__`` / ``.item()`` /
  ``.tolist()`` and the scalar dunders (``float()``/``int()``/
  ``bool()``) on a live device array each force a blocking sync, and
  each firing outside a :func:`declared` scope records an UNDECLARED
  transfer with its stack. (CPU caveat: ``np.asarray`` on a local
  array shares the buffer zero-copy without consulting ``__array__``,
  so that one route is witnessed only on real accelerators —
  scalarization and the dunders fire everywhere, and the static rule
  NDSJ303 covers ``np.asarray`` textually.) The explicit APIs — ``jax.device_get`` /
  ``jax.device_put`` — stay legal and are merely counted (they are
  the engine's sanctioned, attributed transfer points; device_get
  delegates through ``np.asarray`` internally, so the wrapper marks
  its own scope declared to avoid self-flagging).
- :func:`dispatch` scopes the five executor dispatch sites (the
  ``obs_costs.record_program`` call sites in device_exec /
  chunked_exec / dist_exec). While armed it additionally raises jax's
  ``transfer_guard_host_to_device("disallow")`` around the compiled
  call: dispatch buffers are staged device-resident ahead of time, so
  an implicit h2d here means a host buffer leaked into the hot path.
  (The symmetric d2h guard is useless on CPU — zero-copy transfers
  never consult it — which is why the interposition above exists.)
- :func:`disarm` closes the window and returns a verdict; every
  window is kept for the process-wide ``static_checks`` ``jitsan``
  section, and an exit report lands in
  ``$NDS_TPU_JITSAN_REPORT/jitsan-<pid>.json`` when that names a
  directory (same contract as locksan's).

Disabled (``NDS_TPU_JITSAN`` unset/0), nothing is patched and
:func:`arm` is a no-op returning an inactive window — zero overhead,
zero behavior change. The hooks never alter behavior even when armed:
they record and delegate, so a violating workload still completes and
the gate fails on the evidence, not on a mid-query crash.
``selftest()`` (run by ``tools/ndsjit.py --jitsan-selftest`` and the
static_checks section) seeds a deliberate post-warmup recompile and a
hidden ``.item()`` on a PRIVATE sanitizer and proves both are caught.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import sys
import threading
import time
import traceback

ENV = "NDS_TPU_JITSAN"
REPORT_ENV = "NDS_TPU_JITSAN_REPORT"

# witness stacks are trimmed like locksan's: the engine frame matters,
# the jax/pytest frames above it don't
_STACK_FRAMES = 12


def enabled() -> bool:
    return os.environ.get(ENV, "0") == "1"


def _stack() -> "list[str]":
    frames = traceback.format_stack()[:-2]
    return [ln.rstrip("\n") for ln in frames[-_STACK_FRAMES:]]


def _ledger_compiles() -> int:
    """The cost ledger's compile counters (PR 16): the cross-check
    that catches a compile which somehow bypassed the aot funnel."""
    try:
        from nds_tpu.obs import metrics as obs_metrics
        c = obs_metrics.snapshot().get("counters", {})
        return int(c.get("compiles_total", 0)
                   + c.get("recompiles_total", 0))
    except Exception:  # noqa: BLE001 - detector must not crash
        return 0


class Sanitizer:
    """One measurement state: armed window, recorded events, verdicts.

    The global instance backs the installed hooks; tests and the
    selftest swap in PRIVATE instances (:func:`swapped`) so seeded
    hazards never pollute the process verdict."""

    def __init__(self, metric: bool = True):
        # plain lock on purpose: the sanitizer must be invisible to
        # locksan and nothing is ever acquired inside it
        self._lock = threading.Lock()
        self.metric = metric
        self.armed = False
        self.label = ""
        self.compiles: list = []      # post-arm compiles (stacks)
        self.undeclared: list = []    # implicit transfers (stacks)
        self.declared = 0             # device_get/device_put count
        self.dispatches = 0           # dispatch sites crossed armed
        self._ledger0 = 0
        self.windows: list = []       # closed-window verdicts

    # ----------------------------------------------------------- window

    def arm(self, label: str) -> None:
        with self._lock:
            self.armed = True
            self.label = label
            self.compiles = []
            self.undeclared = []
            self.declared = 0
            self.dispatches = 0
            self._ledger0 = _ledger_compiles()

    def disarm(self) -> dict:
        with self._lock:
            v = {
                "label": self.label,
                "active": True,
                "compiles": list(self.compiles),
                "ledger_compiles": _ledger_compiles() - self._ledger0,
                "undeclared_transfers": list(self.undeclared),
                "declared_transfers": self.declared,
                "dispatches": self.dispatches,
                "ts": time.time(),
            }
            self.armed = False
            self.label = ""
            self.windows.append(v)
            return v

    # -------------------------------------------------------- recording

    def on_compile(self, kind: str) -> None:
        if not self.armed:  # ndsraces: waive[NDSR201] -- benign racy fast-path gate: runs on every compile even disarmed; the authoritative re-check is under _lock below and disarm() closes accounting under the same lock
            return
        rec = {"kind": kind, "stack": _stack(),
               "thread": threading.current_thread().name,
               "ts": time.time()}
        with self._lock:
            if not self.armed:
                return
            self.compiles.append(rec)
        self._announce(f"post-warmup compile of {kind!r}")

    def on_transfer(self, what: str, declared: bool) -> None:
        if not self.armed:  # ndsraces: waive[NDSR201] -- benign racy fast-path gate: interposed on every scalarization tree-wide; both branches re-check under _lock before recording
            return
        if declared:
            with self._lock:
                if not self.armed:
                    return
                self.declared += 1
            return
        rec = {"what": what, "stack": _stack(),
               "thread": threading.current_thread().name,
               "ts": time.time()}
        with self._lock:
            if not self.armed:
                return
            self.undeclared.append(rec)
        self._announce(f"undeclared implicit transfer via {what}")

    def on_dispatch(self, kind: str) -> None:
        del kind
        if not self.armed:  # ndsraces: waive[NDSR201] -- benign racy fast-path gate: per-dispatch hot path; the count mutates only under the _lock re-check below
            return
        with self._lock:
            if not self.armed:
                return
            self.dispatches += 1

    def _announce(self, msg: str) -> None:
        if self.metric:
            try:
                from nds_tpu.obs import metrics as obs_metrics
                obs_metrics.counter("jitsan_violations_total").inc()
            except Exception:  # noqa: BLE001 - detector must not crash
                pass
        print(f"[jitsan] {msg} "
              f"(thread {threading.current_thread().name})",
              file=sys.stderr)

    # --------------------------------------------------------- readout

    def violation_count(self) -> int:
        """Violations across CLOSED windows plus the open one."""
        with self._lock:
            n = len(self.compiles) + len(self.undeclared)
            for w in self.windows:
                n += len(w["compiles"]) + len(w["undeclared_transfers"])
            return n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "pid": os.getpid(),
                "ts": time.time(),
                "armed": self.armed,
                "windows": [dict(w) for w in self.windows],
                "open_compiles": list(self.compiles),
                "open_undeclared": list(self.undeclared),
            }

    def reset(self) -> None:
        with self._lock:
            self.armed = False
            self.compiles = []
            self.undeclared = []
            self.declared = 0
            self.dispatches = 0
            self.windows = []


_SAN = Sanitizer()
_ACTIVE = _SAN


def sanitizer() -> Sanitizer:
    return _SAN


def _active() -> Sanitizer:
    return _ACTIVE


@contextlib.contextmanager
def swapped(san: Sanitizer):
    """Route the installed hooks to a PRIVATE sanitizer (selftest and
    tests): seeded hazards must never pollute the process verdict."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = san
    try:
        yield san
    finally:
        _ACTIVE = prev


# --------------------------------------------------------- interposition

_tls = threading.local()


def _declared_depth() -> int:
    return getattr(_tls, "declared", 0)


@contextlib.contextmanager
def declared(why: str = ""):
    """Scope in which implicit device->host syncs are sanctioned (the
    engine's attributed read-back points). ``why`` documents the site;
    it is not recorded — the scope IS the declaration."""
    del why
    _tls.declared = _declared_depth() + 1
    try:
        yield
    finally:
        _tls.declared = _declared_depth() - 1


_installed = False
_originals: dict = {}


def _hook_method(cls, name: str, what: str) -> bool:
    orig = getattr(cls, name, None)
    if orig is None:
        return False

    def hooked(self, *args, **kwargs):
        san = _active()
        if san.armed and _declared_depth() == 0:
            san.on_transfer(what, declared=False)
        # delegate under a declared scope: np.asarray(x) reaching
        # __array__ must not double-count through nested dunders
        with declared():
            return orig(self, *args, **kwargs)

    hooked.__name__ = getattr(orig, "__name__", name)
    try:
        setattr(cls, name, hooked)
    except (TypeError, AttributeError):
        return False
    _originals[(cls, name)] = orig
    return True


def install() -> bool:
    """Patch the array interposition + wrap the explicit transfer
    APIs. Idempotent; returns whether the hooks are live. Lazy on
    purpose: nothing is touched until a window is armed (or a test
    installs explicitly), so the disabled path never pays."""
    global _installed
    if _installed:
        return True
    import jax
    try:
        from jaxlib.xla_extension import ArrayImpl
    except ImportError:  # jaxlib layout drift: sanitizer degrades
        return False
    for name, what in (("__array__", "np.asarray()/__array__"),
                       ("item", ".item()"),
                       ("tolist", ".tolist()"),
                       ("__float__", "float()"),
                       ("__int__", "int()"),
                       ("__bool__", "bool()"),
                       ("__index__", "__index__")):
        _hook_method(ArrayImpl, name, what)

    dg, dp = jax.device_get, jax.device_put

    def device_get(*args, **kwargs):
        san = _active()
        if san.armed:
            san.on_transfer("jax.device_get", declared=True)
        with declared():
            return dg(*args, **kwargs)

    def device_put(*args, **kwargs):
        san = _active()
        if san.armed:
            san.on_transfer("jax.device_put", declared=True)
        with declared():
            return dp(*args, **kwargs)

    jax.device_get, jax.device_put = device_get, device_put
    _originals[("jax", "device_get")] = dg
    _originals[("jax", "device_put")] = dp
    _installed = True
    return True


def uninstall() -> None:
    """Restore every patched attribute (tests only; production leaves
    the hooks in place for the life of the process)."""
    global _installed
    if not _installed:
        return
    import jax
    for (owner, name), orig in list(_originals.items()):
        if owner == "jax":
            setattr(jax, name, orig)
        else:
            setattr(owner, name, orig)
    _originals.clear()
    _installed = False


# ------------------------------------------------------------ engine API

def arm(label: str, force: bool = False) -> bool:
    """Open a measurement window on the GLOBAL sanitizer. Returns
    whether the window is live: under ``NDS_TPU_JITSAN=1`` (or
    ``force=True``) the hooks install and recording starts; otherwise
    this is a no-op and :func:`disarm` reports an inactive window —
    gates degrade to unenforced, never to wrong."""
    if not (enabled() or force):
        return False
    if not install():
        return False
    _ensure_exit_report()
    _SAN.arm(label)
    return True


def disarm() -> dict:
    if not _SAN.armed:
        return {"active": False, "label": "", "compiles": [],
                "ledger_compiles": 0, "undeclared_transfers": [],
                "declared_transfers": 0, "dispatches": 0}
    return _SAN.disarm()


def on_compile(kind: str) -> None:
    """Called by ``cache/aot.py lower_and_compile`` — the engine's
    single compile funnel — on EVERY lower+compile, counted or not.
    Armed windows record it; disarmed, this is a branch and a return."""
    san = _active()
    if san.armed:
        san.on_compile(kind)


@contextlib.contextmanager
def dispatch(kind: str):
    """Scope one executor dispatch (the five record_program sites).
    Disarmed: a no-op. Armed: counts the crossing and raises jax's
    h2d transfer guard — dispatch buffers are device-resident by
    contract, so an implicit h2d inside the compiled call is a host
    buffer leaking into the hot path (the guard raises, the retry
    policy classifies it deterministic, and the gate shows the site)."""
    san = _active()
    if not san.armed:
        yield
        return
    san.on_dispatch(kind)
    import jax
    with jax.transfer_guard_host_to_device("disallow"):
        yield


def windows() -> "list[dict]":
    return [dict(w) for w in _SAN.windows]


def violation_count() -> int:
    return _SAN.violation_count()


def reset() -> None:
    _SAN.reset()


# ------------------------------------------------------------ exit report

_exit_registered = False


def write_report(path: "str | None" = None) -> "str | None":
    if path is None:
        d = os.environ.get(REPORT_ENV)
        if not d:
            return None
        path = os.path.join(d, f"jitsan-{os.getpid()}.json")
    from nds_tpu.io.integrity import write_json_atomic
    write_json_atomic(path, _SAN.snapshot())
    return path


def _at_exit() -> None:
    try:
        wrote = write_report()
    except Exception:  # noqa: BLE001 - exit path, best effort
        wrote = None
    n = _SAN.violation_count()
    if n and not wrote:
        print(f"[jitsan] exiting with {n} unreported violation(s) — "
              f"set {REPORT_ENV} to capture them", file=sys.stderr)


def _ensure_exit_report() -> None:
    global _exit_registered
    if not _exit_registered:
        _exit_registered = True
        atexit.register(_at_exit)


# -------------------------------------------------------------- selftest

def selftest() -> bool:
    """Seed a deliberate post-warmup recompile and a hidden ``.item()``
    on a PRIVATE sanitizer and return whether BOTH were caught — the
    tier-1 proof the detector fires (static_checks ``jitsan`` section;
    ``tools/ndsjit.py --jitsan-selftest``)."""
    if not install():
        return False
    import jax
    import jax.numpy as jnp
    from nds_tpu.cache import aot as cache_aot
    g = Sanitizer(metric=False)
    with swapped(g):
        g.arm("selftest")
        # the seeded recompile: a compile through the engine's funnel
        # INSIDE the armed window — exactly what a fingerprint gap
        # would cause after warmup
        jitted = jax.jit(lambda x: x + 1)
        buf = jnp.ones((4,), jnp.float32)
        compiled = cache_aot.lower_and_compile(jitted, buf)
        with dispatch("selftest"):
            out = compiled(buf)
        # the hidden sync: an implicit d2h outside any declared scope
        _ = out[0].item()
        # and the sanctioned path must NOT flag: explicit device_get
        _ = jax.device_get(out)
        v = g.disarm()
    caught_compile = len(v["compiles"]) == 1
    caught_sync = len(v["undeclared_transfers"]) >= 1
    counted_declared = v["declared_transfers"] >= 1
    return caught_compile and caught_sync and counted_declared
