"""Plan verifier: structural invariants every executor assumes.

The executors (cpu_exec / device_exec / dist_exec) walk planned trees
and address columns as ``(binding, name)`` pairs in a runtime context;
nothing re-checks at execution time that those addresses exist, that
dtypes propagated consistently, or that join keys agree across sides —
a planner bug surfaces as a KeyError deep inside a compiled program (or
worse, as silently wrong rows). This module proves those invariants
right after planning, the typed-plan validation discipline the tensor-
runtime lowering papers rely on (PAPERS.md: Query Processing on Tensor
Computation Runtimes; Flare's staged compilation checks).

Checked per node (namespaces mirror each ``_run_*``'s context
construction in cpu_exec / the DCtx construction in device_exec):

- every ``ColRef`` resolves in the namespace of the child it is
  evaluated against, with the dtype recorded there;
- expression dtypes are consistent: ``Arith`` matches
  ``ir.arith_type``, aggregate specs match ``ir.agg_type``, predicates
  are BOOL;
- join / set-op key dtypes agree across sides (joinable, not merely
  present);
- ``AggRef`` / ``WindowRef`` / ``GroupingRef`` never survive planning
  (the planner remaps them onto concrete columns; one escaping — or
  carrying an out-of-range index — would crash or misbind at runtime);
- ``ScalarRef.plan_id`` indexes a real scalar subplan;
- Sort / Limit / Distinct binding invariants (passthrough output stays
  addressable, limit count non-negative);
- ``StagedScan`` integrity: mangled columns bijective with the backing
  temp-table scan, and (when an executor's table registry is supplied)
  the temp is actually registered;
- exchange slack / partition-capacity consistency for the distributed
  path (``check_exchange_invariants``).

Gate: ``NDS_TPU_VERIFY_PLANS=1`` turns verification on inside
``Session.plan`` and the device executors; tests force it on
(tests/conftest.py). ``tools/ndsverify.py`` runs it over every NDS /
NDS-H statement with no accelerator.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from nds_tpu.engine.types import (
    BOOL, BoolType, DateType, DecimalType, DType, FloatType, IntType,
    StringType,
)
from nds_tpu.sql import ir
from nds_tpu.sql import plan as P

ENV_FLAG = "NDS_TPU_VERIFY_PLANS"


def verify_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "0") not in ("", "0")


@dataclass
class Violation:
    rule: str       # short stable id, e.g. "colref-unresolved"
    node: str       # plan-node type the violation anchors to
    detail: str

    def __str__(self) -> str:
        return f"[{self.rule}] {self.node}: {self.detail}"


class PlanVerifyError(ValueError):
    def __init__(self, violations: list[Violation], label: str = ""):
        self.violations = violations
        head = f"plan verification failed{' for ' + label if label else ''}"
        super().__init__(
            "\n  ".join([f"{head} ({len(violations)} violation(s)):"]
                        + [str(v) for v in violations]))


# --------------------------------------------------------------- dtypes

def _joinable(lt: DType, rt: DType) -> bool:
    """Key dtypes that compare correctly on both engines. Integer
    widths may differ (the executors widen); everything else must match
    exactly — a decimal-scale or string/int mismatch would compare raw
    representations and silently drop matches."""
    if lt is None or rt is None:
        return False
    if lt == rt:
        return True
    if isinstance(lt, IntType) and isinstance(rt, IntType):
        return True
    # epoch-day dates are int32 on device; planner may emit either side
    # as the raw int (EXTRACT output, d_date + N arithmetic)
    date_int = (DateType, IntType)
    if isinstance(lt, date_int) and isinstance(rt, date_int):
        return True
    return False


def _union_compatible(lt: DType, rt: DType) -> bool:
    """Branch output dtypes a SetOp may concatenate: exact match, any
    integer pair, any float pair, or string/string (the cpu engine
    concatenates decoded values; dictionary codes never cross a union).
    Decimals must agree on scale — concatenating scaled ints of
    different scales is a value corruption."""
    if lt is None or rt is None:
        return False
    if lt == rt:
        return True
    if isinstance(lt, IntType) and isinstance(rt, IntType):
        return True
    if isinstance(lt, FloatType) and isinstance(rt, FloatType):
        return True
    if isinstance(lt, StringType) and isinstance(rt, StringType):
        return True
    if isinstance(lt, DecimalType) and isinstance(rt, DecimalType):
        return lt.scale == rt.scale
    return False


# ----------------------------------------------------------- namespaces

def _namespace(node: P.Node, memo: dict) -> dict:
    """{(binding, name): dtype} the node's runtime context exposes to
    its parent — mirrors cpu_exec's Context keys per node type (and
    staging._exposed, which encodes the same contract for cuts)."""
    nid = id(node)
    if nid in memo:
        return memo[nid]
    # ndslint: waive[NDS101] -- memo lives for one verify() pass; the plan pins nodes
    memo[nid] = {}  # cycle guard; real value set below
    if isinstance(node, P.Scan):
        ns = {(node.binding, n): dt for n, dt in node.output}
    elif isinstance(node, P.DerivedScan):
        ns = {(node.binding, n): dt for n, dt in node.child.output}
    elif isinstance(node, P.StagedScan):
        ns = {(b, n): dt for b, n, _m, dt in node.cols}
    elif isinstance(node, P.Project):
        ns = {(node.binding, n): e.dtype for n, e in node.exprs}
    elif isinstance(node, P.Aggregate):
        ns = {(node.binding, n): dt for n, dt in node.output}
    elif isinstance(node, P.Join):
        ns = dict(_namespace(node.left, memo))
        ns.update(_namespace(node.right, memo))
    elif isinstance(node, P.SemiJoin):
        ns = dict(_namespace(node.left, memo))
    elif isinstance(node, P.Window):
        ns = dict(_namespace(node.child, memo))
        ns.update({(node.binding, n): s.dtype for n, s in node.specs})
    elif isinstance(node, P.SetOp):
        if node.kind.startswith("union"):
            # _run_setop materializes ONLY the left output columns
            # under the left binding; sibling columns do not survive
            lb = node.left.binding
            ns = {(lb, n): dt for n, dt in node.left.output}
        else:  # intersect/except keep the left context wholesale
            ns = dict(_namespace(node.left, memo))
    elif isinstance(node, (P.Filter, P.Sort, P.Limit, P.Distinct)):
        ns = dict(_namespace(node.child, memo))
    else:
        ns = {}
    # ndslint: waive[NDS101] -- memo lives for one verify() pass; the plan pins nodes
    memo[nid] = ns
    return ns


# ---------------------------------------------------------- expressions

_PREDICATE_IRS = (ir.Cmp, ir.BoolOp, ir.Not, ir.LikeIR, ir.InListIR,
                  ir.IsNullIR)


class _Verifier:
    def __init__(self, planned: P.PlannedQuery,
                 tables: "dict | None" = None,
                 catalog=None):
        self.planned = planned
        self.tables = tables
        self.catalog = catalog
        self.out: list[Violation] = []
        self.ns_memo: dict = {}

    def fail(self, rule: str, node, detail: str) -> None:
        name = type(node).__name__ if isinstance(node, (P.Node, ir.IR)) \
            else str(node)
        self.out.append(Violation(rule, name, detail))

    # ------------------------------------------------- expression checks

    def check_expr(self, e: ir.IR, ns: dict, node: P.Node) -> None:
        for x in ir.walk(e):
            if isinstance(x, ir.ColRef):
                key = (x.binding, x.name)
                if key not in ns:
                    self.fail("colref-unresolved", node,
                              f"{x!r} not in the evaluation namespace "
                              f"(bindings in scope: "
                              f"{sorted({b for b, _ in ns})})")
                elif x.dtype is None:
                    self.fail("colref-untyped", node, f"{x!r} has no dtype")
                elif x.dtype != ns[key]:
                    self.fail("colref-dtype", node,
                              f"{x!r} typed {x.dtype} but the child "
                              f"exposes {ns[key]}")
            elif isinstance(x, (ir.AggRef, ir.WindowRef, ir.GroupingRef)):
                # the planner remaps every one of these onto concrete
                # columns; any survivor (in-range or not) would misbind
                idx = getattr(x, "index", getattr(x, "key_index", None))
                self.fail("ref-unresolved", node,
                          f"unresolved {type(x).__name__}(#{idx}) "
                          f"escaped planning")
            elif isinstance(x, ir.ScalarRef):
                nsub = len(self.planned.scalar_subplans)
                if not (0 <= x.plan_id < nsub):
                    self.fail("scalarref-range", node,
                              f"scalar#{x.plan_id} out of range "
                              f"({nsub} subplan(s))")
            elif isinstance(x, (ir.ParamRef, ir.DictParamIR,
                                ir.InListParamIR)):
                # hoisted literals (sql/params.py): the slot must bind
                # against the plan's value list, and dict/inlist
                # predicates must stay boolean
                vals = getattr(self.planned, "param_values", None)
                idx = getattr(x, "index", 0)
                if vals is None or not (0 <= idx < len(vals)):
                    self.fail("paramref-range", node,
                              f"{x!r} has no value slot "
                              f"({0 if vals is None else len(vals)} "
                              f"value(s) attached)")
                if isinstance(x, ir.ParamRef):
                    if x.dtype is None:
                        self.fail("expr-untyped", node,
                                  f"{x!r} has no dtype")
                elif not isinstance(x.dtype, BoolType):
                    self.fail("predicate-dtype", node,
                              f"{type(x).__name__} typed {x.dtype}, "
                              f"not bool")
                if (isinstance(x, ir.InListParamIR) and vals is not None
                        and 0 <= idx < len(vals)
                        and len(vals[idx]) != x.width):
                    self.fail("paramref-width", node,
                              f"{x!r} declares width {x.width} but the "
                              f"slot holds {len(vals[idx])} value(s)")
            elif isinstance(x, ir.Arith):
                lt, rt = x.left.dtype, x.right.dtype
                if lt is None or rt is None:
                    self.fail("arith-untyped", node,
                              f"{x.op} operand missing dtype")
                else:
                    try:
                        want = ir.arith_type(x.op, lt, rt)
                    except TypeError as exc:
                        self.fail("arith-illegal", node, str(exc))
                        continue
                    if x.dtype != want:
                        self.fail("arith-dtype", node,
                                  f"{lt} {x.op} {rt} must produce "
                                  f"{want}, plan says {x.dtype}")
            elif isinstance(x, _PREDICATE_IRS):
                if not isinstance(x.dtype, BoolType):
                    self.fail("predicate-dtype", node,
                              f"{type(x).__name__} typed {x.dtype}, "
                              f"not bool")
            elif isinstance(x, (ir.Neg, ir.CastIR, ir.CaseIR, ir.Lit,
                                ir.SubstrIR, ir.StrMapIR, ir.ConcatIR,
                                ir.ExtractIR)):
                if x.dtype is None:
                    self.fail("expr-untyped", node,
                              f"{type(x).__name__} has no dtype")

    # ------------------------------------------------------- node checks

    def check_node(self, node: P.Node) -> None:
        m = getattr(self, "_check_" + type(node).__name__.lower(), None)
        if m is not None:
            m(node)

    def _check_scan(self, node: P.Scan) -> None:
        ns = _namespace(node, self.ns_memo)
        for f in node.filters:
            self.check_expr(f, ns, node)
            if f.dtype is not None and not isinstance(f.dtype, BoolType):
                self.fail("filter-dtype", node,
                          f"pushed-down filter typed {f.dtype}, not bool")
        schema = None
        if self.tables is not None:
            t = self.tables.get(node.table)
            if t is None:
                # at execution time EVERY scan must resolve in the
                # registry — this would otherwise die as a KeyError
                # inside buffer collection
                self.fail("scan-unregistered", node,
                          f"table {node.table!r} not in the executor "
                          f"registry")
                return
            schema = getattr(t, "schema", None)
        elif self.catalog is not None:
            if not self.catalog.has_table(node.table):
                self.fail("scan-unknown-table", node,
                          f"table {node.table!r} not in catalog")
                return
            schema = self.catalog.schemas[node.table]
        if schema is not None:
            for n, dt in node.output:
                if n not in schema:
                    self.fail("scan-unknown-column", node,
                              f"{node.table}.{n} not in schema")
                elif schema.field(n).dtype != dt:
                    self.fail("scan-column-dtype", node,
                              f"{node.table}.{n} is "
                              f"{schema.field(n).dtype} in the schema, "
                              f"{dt} in the plan")

    def _check_stagedscan(self, node: P.StagedScan) -> None:
        if not isinstance(node.child, P.Scan):
            self.fail("staged-child", node,
                      f"child is {type(node.child).__name__}, not a "
                      f"temp-table Scan")
            return
        child_cols = dict(node.child.output)
        mangled = [m for _b, _n, m, _dt in node.cols]
        if sorted(mangled) != sorted(child_cols):
            self.fail("staged-mangle", node,
                      f"cols mapping {sorted(mangled)} is not a "
                      f"bijection with the temp scan's "
                      f"{sorted(child_cols)}")
        else:
            for _b, n, m, dt in node.cols:
                if child_cols[m] != dt:
                    self.fail("staged-dtype", node,
                              f"{m} staged as {child_cols[m]} but "
                              f"re-exposed as {dt} ({n})")
        if self.tables is not None and node.child.table not in self.tables:
            self.fail("staged-unregistered", node,
                      f"temp table {node.child.table!r} is not "
                      f"registered with the executor")

    def _check_filter(self, node: P.Filter) -> None:
        ns = _namespace(node.child, self.ns_memo)
        self.check_expr(node.predicate, ns, node)
        if (node.predicate.dtype is not None
                and not isinstance(node.predicate.dtype, BoolType)):
            self.fail("filter-dtype", node,
                      f"predicate typed {node.predicate.dtype}, not bool")

    def _check_project(self, node: P.Project) -> None:
        ns = _namespace(node.child, self.ns_memo)
        seen = set()
        for n, e in node.exprs:
            if n in seen:
                self.fail("project-dup", node,
                          f"duplicate output column {n!r}")
            seen.add(n)
            self.check_expr(e, ns, node)

    def _check_join_like(self, node) -> None:
        lns = _namespace(node.left, self.ns_memo)
        rns = _namespace(node.right, self.ns_memo)
        if len(node.left_keys) != len(node.right_keys):
            self.fail("join-key-arity", node,
                      f"{len(node.left_keys)} left vs "
                      f"{len(node.right_keys)} right keys")
        for k in node.left_keys:
            self.check_expr(k, lns, node)
        for k in node.right_keys:
            self.check_expr(k, rns, node)
        for lk, rk in zip(node.left_keys, node.right_keys):
            if not _joinable(lk.dtype, rk.dtype):
                self.fail("join-key-dtype", node,
                          f"key pair {lk!r}:{lk.dtype} vs "
                          f"{rk!r}:{rk.dtype} is not joinable")
        if node.residual is not None:
            both = dict(lns)
            both.update(rns)
            self.check_expr(node.residual, both, node)
            if (node.residual.dtype is not None
                    and not isinstance(node.residual.dtype, BoolType)):
                self.fail("residual-dtype", node,
                          f"residual typed {node.residual.dtype}")

    def _check_join(self, node: P.Join) -> None:
        self._check_join_like(node)
        if node.kind not in ("inner", "left", "full"):
            self.fail("join-kind", node, f"unknown kind {node.kind!r}")
        # kernel-choice invariants (engine/kernels.py): the planner's
        # stamp must name a real kernel AND one the trace can lower for
        # this node shape — a direct/matmul probe needs the unique-
        # build gather path, radix partitioning only exists for the
        # M:N inner expansion
        from nds_tpu.engine import kernels as KX
        if node.kernel not in KX.JOIN_KERNELS:
            self.fail("kernel-unknown", node,
                      f"unknown join kernel {node.kernel!r} "
                      f"(known: {[k for k in KX.JOIN_KERNELS if k]})")
        elif (node.kernel in (KX.JOIN_DIRECT, KX.JOIN_MATMUL)
                and not node.right_unique):
            self.fail("kernel-shape", node,
                      f"{node.kernel!r} requires a unique build side "
                      f"(right_unique)")
        elif node.kernel == KX.JOIN_PARTITIONED and (
                node.right_unique or node.kind != "inner"):
            self.fail("kernel-shape", node,
                      f"{node.kernel!r} only lowers the M:N inner "
                      f"expansion (kind={node.kind!r}, "
                      f"right_unique={node.right_unique})")

    def _check_semijoin(self, node: P.SemiJoin) -> None:
        self._check_join_like(node)
        from nds_tpu.engine import kernels as KX
        if node.kernel not in KX.SEMI_KERNELS:
            self.fail("kernel-unknown", node,
                      f"unknown semi-join kernel {node.kernel!r} "
                      f"(known: {[k for k in KX.SEMI_KERNELS if k]})")

    def _check_aggregate(self, node: P.Aggregate) -> None:
        from nds_tpu.engine import kernels as KX
        if node.kernel not in KX.AGG_KERNELS:
            self.fail("kernel-unknown", node,
                      f"unknown aggregate kernel {node.kernel!r} "
                      f"(known: {[k for k in KX.AGG_KERNELS if k]})")
        ns = _namespace(node.child, self.ns_memo)
        for _n, e in node.group_keys:
            self.check_expr(e, ns, node)
        for n, spec in node.aggs:
            if spec.arg is not None:
                self.check_expr(spec.arg, ns, node)
            arg_t = spec.arg.dtype if spec.arg is not None else None
            try:
                want = ir.agg_type(spec.func, arg_t)
            except TypeError as exc:
                self.fail("agg-illegal", node, f"{n}: {exc}")
                continue
            if spec.dtype != want:
                self.fail("agg-dtype", node,
                          f"{spec.func}({arg_t}) must produce {want}, "
                          f"plan says {spec.dtype} for {n!r}")

    def _check_window(self, node: P.Window) -> None:
        ns = _namespace(node.child, self.ns_memo)
        for n, s in node.specs:
            if s.dtype is None:
                self.fail("window-untyped", node, f"{n} has no dtype")
            if s.arg is not None:
                self.check_expr(s.arg, ns, node)
            for p in s.partition:
                self.check_expr(p, ns, node)
            for e, _asc, _nf in s.order:
                self.check_expr(e, ns, node)

    def _check_sort(self, node: P.Sort) -> None:
        ns = _namespace(node.child, self.ns_memo)
        for e, asc, nf in node.keys:
            self.check_expr(e, ns, node)
            # nulls_first is Optional: None = SQL default (nulls last),
            # the encoding both engines' sort paths treat as falsy
            if not isinstance(asc, bool) or not isinstance(nf,
                                                           (bool,
                                                            type(None))):
                self.fail("sort-flags", node,
                          f"non-bool sort flags ({asc!r}, {nf!r})")

    def _check_limit(self, node: P.Limit) -> None:
        if not isinstance(node.count, int) or node.count < 0:
            self.fail("limit-count", node,
                      f"count {node.count!r} is not a non-negative int")

    def _check_distinct(self, node: P.Distinct) -> None:
        ns = _namespace(node.child, self.ns_memo)
        for n, _dt in node.output:
            if (node.binding, n) not in ns:
                self.fail("distinct-binding", node,
                          f"output column ({node.binding!r}, {n!r}) not "
                          f"addressable in the child context")

    def _check_setop(self, node: P.SetOp) -> None:
        kinds = ("union", "union all", "intersect", "except")
        if node.kind not in kinds:
            self.fail("setop-kind", node, f"unknown kind {node.kind!r}")
        lo, ro = node.left.output, node.right.output
        if len(lo) != len(ro):
            self.fail("setop-arity", node,
                      f"{len(lo)} vs {len(ro)} output columns")
            return
        for (ln, lt), (rn, rt) in zip(lo, ro):
            if not _union_compatible(lt, rt):
                self.fail("setop-dtype", node,
                          f"column pair {ln!r}:{lt} vs {rn!r}:{rt} "
                          f"cannot combine")

    # ------------------------------------------------------------ driver

    def run(self) -> list[Violation]:
        planned = self.planned
        roots = [("root", planned.root)]
        for i, sub in enumerate(planned.scalar_subplans):
            roots.append((f"scalar#{i}", sub))
            if not isinstance(sub, P.Node):
                self.fail("subplan-type", sub,
                          f"scalar subplan #{i} is not a plan Node")
                continue
            if len(sub.output) != 1:
                self.fail("subplan-arity", sub,
                          f"scalar subplan #{i} produces "
                          f"{len(sub.output)} columns, not 1")
        if planned.column_names and len(planned.column_names) != len(
                planned.root.output):
            self.fail("result-arity", planned.root,
                      f"{len(planned.column_names)} result names for "
                      f"{len(planned.root.output)} output columns")
        # the session/driver reads the root's output through its binding
        root_ns = _namespace(planned.root, self.ns_memo)
        for n, _dt in planned.root.output:
            if (planned.root.binding, n) not in root_ns:
                self.fail("root-binding", planned.root,
                          f"result column ({planned.root.binding!r}, "
                          f"{n!r}) not addressable at the root")
        seen: set = set()
        for _label, root in roots:
            if not isinstance(root, P.Node):
                continue
            for node in P.walk_plan(root):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                self.check_node(node)
        return self.out


# -------------------------------------------------------------- frontend

def verify(planned: P.PlannedQuery, tables: "dict | None" = None,
           catalog=None) -> list[Violation]:
    """All invariant violations in one planned statement ([] = valid).

    ``tables`` (an executor's name -> HostTable registry) additionally
    proves Scan columns against real schemas and StagedScan temps
    against registration; ``catalog`` (planner CatalogInfo) does the
    schema half when no executor exists yet."""
    if not isinstance(planned, P.PlannedQuery):
        return [Violation("not-a-plan", type(planned).__name__,
                          "verify() expects a PlannedQuery")]
    return _Verifier(planned, tables, catalog).run()


def assert_valid(planned: P.PlannedQuery, tables: "dict | None" = None,
                 catalog=None, label: str = "") -> P.PlannedQuery:
    """verify() that raises PlanVerifyError on any violation; returns
    the plan unchanged so call sites can wrap in-line."""
    violations = verify(planned, tables, catalog)
    if violations:
        raise PlanVerifyError(violations, label)
    return planned


def check_exchange_invariants(n_rows: int, n_dev: int,
                              slack: float) -> list[Violation]:
    """Distributed-path consistency: the static-shape exchange contract
    (parallel/exchange.py) only holds when every device's per-peer
    bucket of ceil(n * slack / n_dev) rows gives total capacity >= the
    rows actually present. slack < 1 breaks that bound even with a
    perfectly uniform hash; non-positive mesh sizes are configuration
    corruption."""
    out: list[Violation] = []
    if n_dev < 1:
        out.append(Violation("exchange-mesh", "exchange",
                             f"n_dev={n_dev} must be >= 1"))
    if slack < 1.0:
        out.append(Violation("exchange-slack", "exchange",
                             f"slack={slack} < 1.0 cannot cover even a "
                             f"uniform partition"))
    if n_rows < 0:
        out.append(Violation("exchange-rows", "exchange",
                             f"negative row count {n_rows}"))
    if out:
        return out
    bucket = max(1, -(-int(n_rows * slack) // n_dev))
    if n_rows and bucket * n_dev < n_rows:
        out.append(Violation(
            "exchange-capacity", "exchange",
            f"bucket {bucket} x {n_dev} devices = {bucket * n_dev} "
            f"slots < {n_rows} rows"))
    return out


# ------------------------------------------------------- size estimates

def _dtype_width(dt: DType) -> int:
    """Estimated bytes per value as the engine materializes it on
    device: ints by declared width, decimals as scaled int64, dates as
    epoch-day int32, strings as int32 dictionary codes (the dictionary
    itself stays on host and is small next to the column)."""
    if isinstance(dt, IntType):
        return dt.bits // 8
    if isinstance(dt, FloatType):
        return dt.bits // 8
    if isinstance(dt, DecimalType):
        return 8
    if isinstance(dt, DateType):
        return 4
    if isinstance(dt, StringType):
        return 4
    return 8


def _scan_bytes(table, output, nrows: int,
                encoded: "bool | None" = None) -> int:
    """Bytes a device scan of these columns moves. With a real
    HostTable and an active columnar mode (nds_tpu/columnar/) the
    per-column ENCODED widths apply — so the scheduler cost model and
    the MemoryGovernor's pre-admission budget both see the compressed
    working set (an SF that only fits encoded must not be demoted off
    device on raw arithmetic). ``encoded=False`` forces raw widths —
    the scheduler passes it when costing a placement that uploads raw
    (the sharded SPMD path opts out of columnar upload, so shrinking
    ITS working-set math by the compression ratio would under-admit).
    Catalog-only estimates (and mode off) keep the raw device-width
    formula."""
    cols = getattr(table, "columns", None)
    if cols is not None:
        from nds_tpu import columnar
        if columnar.enabled() and encoded is not False:
            total = 0
            for name, dt in output:
                col = cols.get(name)
                total += (columnar.scan_nbytes(col)
                          if col is not None
                          else _dtype_width(dt) * nrows)
            return total
    return nrows * sum(_dtype_width(dt) for _n, dt in output)


def check_encoding_spec(spec, values, mask, nrows=None) -> list:
    """Invariants for one column-encoding choice (nds_tpu/columnar/):
    violations mean the spec cannot faithfully reproduce the column.
    Run at encode time under the verify gate (always on in tests).
    ``nrows`` bounds the LIVE prefix — pad rows past it are gated by
    the row mask at trace time and may clip freely."""
    import numpy as np
    out = []
    kind = getattr(spec, "kind", None)
    if kind not in ("bitpack", "rle", "raw"):
        out.append(f"unknown encoding kind {kind!r}")
        return out
    if spec.rows != len(values):
        out.append(f"{kind}: spec rows {spec.rows} != column rows "
                   f"{len(values)}")
    if spec.dtype != values.dtype.name:
        out.append(f"{kind}: spec dtype {spec.dtype!r} != column "
                   f"dtype {values.dtype.name!r}")
    if kind == "bitpack":
        if spec.bits not in (1, 2, 4, 8, 16, 32):
            out.append(f"bitpack: unsupported width {spec.bits}")
        else:
            live = values if nrows is None else values[:nrows]
            lmask = mask if nrows is None or mask is None \
                else mask[:nrows]
            live = live if lmask is None else live[lmask]
            if len(live):
                lo, hi = int(live.min()), int(live.max())
                top = spec.lo + ((2**31 - 1) if spec.bits >= 32
                                 else (1 << spec.bits) - 1)
                if lo < spec.lo or hi > top:
                    out.append(
                        f"bitpack: values [{lo},{hi}] exceed packed "
                        f"range [{spec.lo},{top}] — decode would "
                        f"clip live data")
    elif kind == "rle":
        if mask is not None:
            out.append("rle: null-masked column cannot RLE (runs "
                       "would splice null and live values)")
        if np.issubdtype(values.dtype, np.floating):
            out.append("rle: float column cannot RLE (value-equality "
                       "runs splice -0.0/+0.0; decode would flip "
                       "signbits vs the raw upload)")
        live = values if nrows is None else values[:nrows]
        if len(live) >= 2:
            actual = int(np.count_nonzero(
                live[1:] != live[:-1])) + 1
        else:
            actual = len(live)
        if spec.runs != actual:
            out.append(f"rle: spec runs {spec.runs} != actual "
                       f"{actual}")
    if spec.mask_packed and mask is None:
        out.append(f"{kind}: mask_packed without a null mask")
    return out


@dataclass
class PlanEstimate:
    """Static size estimate for one planned statement — the cost-model
    input the scheduler (engine/scheduler.py) seeds placement from.
    ``tables`` maps each scanned base table to its (rows, bytes)
    estimate; bytes count only the columns the plan's scans actually
    read, at device materialization widths. Estimates come from real
    HostTables when an executor registry is supplied, else from the
    planner catalog's relative size statistics — both paths need no
    accelerator (tools/ndsverify.py assigns placements on bare CPU)."""
    rows: int = 0
    bytes: int = 0
    widest_table_bytes: int = 0
    tables: dict = None  # type: ignore[assignment]
    joins: int = 0
    aggregates: int = 0
    sorts: int = 0
    windows: int = 0


def estimate_plan(planned: P.PlannedQuery, tables: "dict | None" = None,
                  catalog=None,
                  encoded: "bool | None" = None) -> PlanEstimate:
    """Scan-level size estimate over every root (scalar subplans
    included). Row counts prefer the executor's registered HostTables
    (exact); the catalog's ``sizes`` statistics (relative row weights)
    are the planning-time fallback. Unknown tables estimate as 0 rows —
    the scheduler treats an all-unknown plan as small, which is the
    conservative direction for placement (the ladder recovers from an
    underestimate; overestimating would pin small queries off-device).
    ``encoded=False`` forces raw scan widths even under an active
    columnar mode (see ``_scan_bytes``)."""
    est = PlanEstimate(tables={})
    if not isinstance(planned, P.PlannedQuery):
        return est
    seen: set = set()
    for root in [planned.root, *planned.scalar_subplans]:
        if not isinstance(root, P.Node):
            continue
        for node in P.walk_plan(root):
            if id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, P.Join):
                est.joins += 1
            elif isinstance(node, P.Aggregate):
                est.aggregates += 1
            elif isinstance(node, P.Sort):
                est.sorts += 1
            elif isinstance(node, P.Window):
                est.windows += 1
            if not isinstance(node, P.Scan):
                continue
            nrows = 0
            t = tables.get(node.table) if tables is not None else None
            if t is not None:
                nrows = t.nrows
            elif catalog is not None:
                nrows = int(catalog.sizes.get(node.table, 0))
            nbytes = _scan_bytes(t, node.output, nrows, encoded)
            rows0, bytes0 = est.tables.get(node.table, (0, 0))
            # one table scanned by several Scan nodes: rows count once,
            # bytes accumulate per scan (each scan uploads its columns)
            # ndslint: waive[NDS119] -- est.tables is a local cost-estimate accumulator, not a session catalog
            est.tables[node.table] = (max(rows0, nrows),
                                      bytes0 + nbytes)
    for nrows, nbytes in est.tables.values():
        est.rows += nrows
        est.bytes += nbytes
        est.widest_table_bytes = max(est.widest_table_bytes, nbytes)
    return est
