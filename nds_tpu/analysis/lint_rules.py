"""ndslint rules: the repo's recurring hazard classes as ast checks.

Every rule here encodes a bug class an advisor round actually found by
hand (ADVICE.md rounds 1-5) — the linter exists so the NEXT instance
fails CI instead of waiting for a human audit:

- NDS101 id-keyed-cache     storing under ``id(obj)`` without the value
                            pinning the object: a recycled address
                            serves another object's cached state
                            (round-5 `_stage_plans` finding).
- NDS102 raw-timing         ``time.time()/perf_counter()/monotonic()``
                            inside ``engine/`` / ``parallel/``: timing
                            bills belong to ``obs`` spans so traces and
                            CSVs can never drift apart.
- NDS103 unsynced-timing    a perf-counter delta in a function that
                            touches jax but never syncs
                            (``block_until_ready``/``device_get``):
                            async dispatch makes the bracket measure
                            dispatch, not execution.
- NDS104 prefix-hash        content fingerprint over a sliced prefix
                            (``arr[:n].tobytes()``): same-shape changes
                            past the prefix serve stale cache entries
                            (round-5 `_register_staged` finding).
- NDS105 dead-field         dataclass field written but never read
                            anywhere in the tree (round-5 `_DistTrace`
                            finding).
- NDS106 mutable-default    mutable function-argument default.
- NDS107 bare-except        ``except:`` catching SystemExit/
                            KeyboardInterrupt.
- NDS108 naked-retry        a retry loop (loop + except handler) that
                            sleeps a CONSTANT between attempts (no
                            backoff) or spins ``while True`` (no
                            attempt cap): under real contention a
                            fixed-interval uncapped retry herd is the
                            outage amplifier — use
                            ``resilience.retry.RetryPolicy``.
- NDS109 non-atomic-json    ``json.dump`` into a handle opened ``"w"``
                            on the final path, in a function that never
                            calls ``os.replace``/``os.rename``: a crash
                            mid-write leaves a TORN report/journal/
                            manifest a later reader crashes on or —
                            worse — half-trusts. Write via
                            ``io.integrity.write_json_atomic`` (tmp +
                            rename), or waive with why a torn read is
                            impossible for that artifact.

- NDS110 direct-executor    constructing a placement executor
                            (``DeviceExecutor(`` / ``ChunkedExecutor(``
                            / ``DistributedExecutor(`` /
                            ``CpuExecutor(``) in engine/suite code
                            outside ``engine/scheduler.py`` or the
                            executor's own defining module: placement
                            is a scheduling decision owned by the
                            unified pipeline, and a stray direct
                            construction silently regresses the
                            unification (no shared retry/ladder/
                            consensus wiring runs for it).

- NDS111 uncached-compile   ``jax.jit(...)`` or a ``.lower(args)``
                            AOT-lowering call inside ``engine/`` /
                            ``parallel/``: every lower+compile must
                            route through ``nds_tpu/cache/aot.py`` so
                            the persistent plan cache sees it — a
                            stray inline compile is invisible to the
                            cache and pays the full XLA bill in every
                            process. Sites that only BUILD the traced
                            callable (the ``jax.jit(fn)`` handed to
                            ``cache.aot``) carry waivers saying so.

- NDS112 int64-emulation-hazard
                            ``jnp.argsort`` / ``jnp.sort`` /
                            ``jnp.searchsorted`` in ``engine/`` /
                            ``parallel/`` with no explicit int32 cast
                            in the call: under x64 these carry int64
                            operands (argsort's implicit iota is the
                            canonical trap — see ``_build_lookup``),
                            and TPU emulates 64-bit sorts at ~4-8x the
                            native i32 cost. Narrow explicitly
                            (``_narrow_key`` / ``.astype(jnp.int32)``)
                            or waive with why the width is required.

- NDS113 direct-profiler    ``jax.profiler.start_trace`` outside
                            ``obs/profile.py``: profiler captures must
                            route through the trigger policy so the
                            single-active-trace invariant holds, the
                            capture lands in the BenchReport
                            ``profile`` block, and the on-stall hook
                            can always grab the profiler — a stray
                            start_trace wedges all of that.

- NDS114 unchained-signal-handler
                            ``signal.signal(...)`` installing a real
                            handler without the enclosing scope ever
                            calling ``signal.getsignal``: the install
                            silently DISCARDS whatever handler was
                            there — the flight-dump chain
                            (obs/fleet._install_sigterm) or the
                            preemption drain
                            (resilience/drain.DrainManager), both of
                            which capture and chain/restore the
                            previous handler (the blessed pattern).
                            Restores to ``SIG_DFL``/``SIG_IGN`` are
                            clean; anything else needs the chain or a
                            waiver saying why replacement is intended.
- NDS119 unjournaled-mutation
                            a direct store into a ``.tables[...]`` /
                            ``.columns[...]`` catalog (subscript
                            assign/del, or ``.pop/.setdefault/
                            .update/.clear`` on it) outside the
                            journaled machinery (engine/session.py,
                            engine/dml.py, columnar/delta.py,
                            io/host_table.py). Warehouse mutation
                            must flow through Session.register_table
                            or the DML path so the maintenance commit
                            journal, delta segments and table-scoped
                            plan invalidation all observe it — a raw
                            catalog write is invisible to crash
                            recovery and serves stale cached plans.

Waivers are per-line: ``# ndslint: waive[NDS1xx] -- justification`` on
the offending line or the line directly above. The justification is
mandatory; a waiver without one, or one that matches no violation, is
itself an error. The lightweight ``# ndslint: disable=NDS1xx`` form
(note optional, same staleness rules) suppresses per rule at sites
whose exemption is obvious in context — test helpers mainly; both
forms are shared verbatim by ndsraces and ndsjit markers. The marker
and file roots come from ``[tool.ndslint]`` in pyproject.toml
(tools/ndslint.py loads it).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field


@dataclass
class LintViolation:
    rule: str
    path: str
    line: int
    msg: str
    waived: bool = False
    waiver_note: str = ""

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.msg}"


@dataclass
class Waiver:
    line: int           # line the waiver covers
    rules: list
    note: str
    used: bool = False


# ------------------------------------------------------------- waivers

# one waiver grammar, three tools: ndslint (this module's rules),
# ndsraces (nds_tpu/analysis/concurrency.py), and ndsjit
# (nds_tpu/analysis/jit_hazards.py) share the marker syntax differing
# only in the tool name, so the waiver-report and the stale-waiver
# semantics stay identical across all gates. Two per-line forms:
#
#   <line>  # <tool>: waive[NDS1xx] -- justification   (note mandatory)
#   <line>  # <tool>: disable=NDS1xx[,NDSyyy]          (note optional)
#
# ``waive[...]`` is the audited form — the justification is part of
# the record; ``disable=`` is the lightweight per-rule suppression for
# sites whose exemption is obvious in context (test helpers,
# fixtures). Both cover the next line when standalone, both go stale
# (and fail the gate) when they match no live finding.
WAIVER_RE = re.compile(
    r"#\s*ndslint:\s*waive\[(?P<rules>[A-Z0-9, ]+)\]"
    r"(?:\s*--\s*(?P<note>.*\S))?")

_WAIVER_RES: dict = {"ndslint": WAIVER_RE}
_DISABLE_RES: dict = {}


def waiver_re(tool: str) -> "re.Pattern":
    pat = _WAIVER_RES.get(tool)
    if pat is None:
        pat = _WAIVER_RES[tool] = re.compile(
            r"#\s*" + re.escape(tool)
            + r":\s*waive\[(?P<rules>[A-Z0-9, ]+)\]"
            r"(?:\s*--\s*(?P<note>.*\S))?")
    return pat


def disable_re(tool: str) -> "re.Pattern":
    pat = _DISABLE_RES.get(tool)
    if pat is None:
        pat = _DISABLE_RES[tool] = re.compile(
            r"#\s*" + re.escape(tool)
            + r":\s*disable=(?P<rules>[A-Z0-9]+(?:\s*,\s*[A-Z0-9]+)*)"
            r"(?:\s*--\s*(?P<note>.*\S))?")
    return pat


def _comment_tokens(src: str):
    """[(line, standalone, comment_text)] for each COMMENT token, or
    None when the source does not tokenize (caller falls back to the
    raw line scan — a best-effort net for broken sources the ast
    parse will report anyway)."""
    try:
        out = []
        for t in tokenize.generate_tokens(io.StringIO(src).readline):
            if t.type != tokenize.COMMENT:
                continue
            row, col = t.start
            line = t.line if t.line else ""
            out.append((row, line[:col].strip() == "", t.string))
        return out
    except (tokenize.TokenError, IndentationError, SyntaxError,
            ValueError):
        return None


def parse_waivers(src: str, tool: str = "ndslint",
                  meta_rule: str = "NDS100"
                  ) -> "tuple[dict, list[LintViolation]]":
    """{covered_line: Waiver} plus violations for malformed waivers.
    A marker on its own line covers the next line; an end-of-line
    marker covers its own. ``tool`` picks the marker (``ndslint`` /
    ``ndsraces`` / ``ndsjit``); ``meta_rule`` is the rule id
    malformed-waiver errors report under. ``waive[...]`` requires a
    ``-- justification``; ``disable=NDS1xx`` does not (its note is
    optional) — both forms share staleness accounting."""
    waivers: dict[int, Waiver] = {}
    errors: list[LintViolation] = []
    lines = src.splitlines()
    # only genuine COMMENT tokens carry markers: a marker spelled
    # inside a string literal (linter test fixtures embed whole
    # sources, markers included) must not parse as a waiver of the
    # embedding file — tokenize separates the two exactly. Sources
    # that don't tokenize fall back to the raw per-line scan.
    candidates = _comment_tokens(src)
    if candidates is None:
        candidates = [(i, None, text)
                      for i, text in enumerate(lines, 1)]
    for lineno, standalone, text in candidates:
        m = waiver_re(tool).search(text)
        need_note = True
        if not m:
            m = disable_re(tool).search(text)
            need_note = False
        if not m:
            continue
        rules = [r.strip() for r in m.group("rules").split(",")
                 if r.strip()]
        note = (m.group("note") or "").strip()
        if standalone is None:
            standalone = text[: m.start()].strip() == ""
        covered = lineno + 1 if standalone else lineno
        if need_note and not note:
            errors.append(LintViolation(
                meta_rule, "", lineno,
                f"waiver without justification (use "
                f"'# {tool}: waive[...] -- why', or the per-rule "
                f"'# {tool}: disable=NDS1xx' form)"))
            continue
        waivers[covered] = Waiver(covered, rules, note)
    return waivers, errors


def waiver_report(results: "dict[str, LintResult]",
                  verbose: bool = False) -> "list[str]":
    """Tree-wide waiver hygiene report shared by ``ndslint
    --waiver-report`` and ``ndsraces --waiver-report``: per-rule waiver
    counts per tool, each waiver's site + note under ``verbose``, and
    every STALE waiver (one matching no live finding — already a gate
    error) flagged explicitly so audits see exactly what to drop."""
    lines: list[str] = []
    for tool in sorted(results):
        res = results[tool]
        by_rule: dict[str, list] = {}
        for v in res.waived:
            by_rule.setdefault(v.rule, []).append(v)
        total = sum(len(vs) for vs in by_rule.values())
        lines.append(f"{tool}: {total} waiver(s) across "
                     f"{len(by_rule)} rule(s)")
        for rule in sorted(by_rule):
            vs = by_rule[rule]
            lines.append(f"  {rule}: {len(vs)}")
            if verbose:
                for v in sorted(vs, key=lambda x: (x.path, x.line)):
                    lines.append(f"    {v.path}:{v.line}: "
                                 f"{v.waiver_note}")
        stale = [e for e in res.errors
                 if "matches no violation" in e.msg]
        for e in sorted(stale, key=lambda x: (x.path, x.line)):
            lines.append(f"  STALE: {e.path}:{e.line}: {e.msg}")
    return lines


# --------------------------------------------------------------- rules

class Rule:
    id = "NDS000"
    name = "base"
    #: path substrings this rule is restricted to ([] = everywhere)
    paths: tuple = ()

    def applies(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return not self.paths or any(p in norm for p in self.paths)

    def check(self, tree: ast.AST, src: str,
              path: str) -> "list[LintViolation]":
        raise NotImplementedError


def _walk_funcs(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _attrs_in(node: ast.AST) -> set:
    return {n.attr for n in ast.walk(node)
            if isinstance(n, ast.Attribute)}


def _is_id_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "id")


class IdKeyedCacheRule(Rule):
    """NDS101: ``cache[id(x)] = v`` / ``cache.setdefault(id(x), v)``
    where nothing guarantees ``x`` outlives the entry. Detected
    syntactically (any id()-derived subscript store); sites that DO pin
    the object in the stored value carry a waiver saying so."""

    id = "NDS101"
    name = "id-keyed-cache"

    def check(self, tree, src, path):
        out = []
        # names assigned from a bare id(...) call anywhere in the file:
        # `nid = id(node); cache[nid] = v` is the same hazard spelled
        # in two statements (name collisions across scopes only widen
        # the net, which is the right failure mode for a linter)
        id_vars = {t.id for n in ast.walk(tree)
                   if isinstance(n, ast.Assign) and _is_id_call(n.value)
                   for t in n.targets if isinstance(t, ast.Name)}

        def keyed_by_id(expr: ast.AST) -> bool:
            return (any(_is_id_call(x) for x in ast.walk(expr))
                    or (isinstance(expr, ast.Name)
                        and expr.id in id_vars))

        for n in ast.walk(tree):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                tgts = (n.targets if isinstance(n, ast.Assign)
                        else [n.target])
                for t in tgts:
                    if (isinstance(t, ast.Subscript)
                            and keyed_by_id(t.slice)):
                        out.append(LintViolation(
                            self.id, path, n.lineno,
                            "store keyed by id(): a recycled address "
                            "can serve another object's entry unless "
                            "the value pins the object"))
            elif (isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)
                  and n.func.attr == "setdefault" and n.args
                  and keyed_by_id(n.args[0])):
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    "id()-keyed setdefault: the stored value must pin "
                    "the keyed object (or waive with the pinning "
                    "argument)"))
        return out


class RawTimingRule(Rule):
    """NDS102: raw wall-clock reads in the engine/parallel layers."""

    id = "NDS102"
    name = "raw-timing"
    paths = ("nds_tpu/engine/", "nds_tpu/parallel/")
    _FUNCS = {"time", "perf_counter", "monotonic", "process_time"}

    def check(self, tree, src, path):
        out = []
        for n in ast.walk(tree):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in self._FUNCS
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id.lstrip("_") == "time"):
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    f"raw time.{n.func.attr}() in the engine layer — "
                    f"use an obs span (or waive with why the raw "
                    f"bracket is required)"))
        return out


class UnsyncedTimingRule(Rule):
    """NDS103: a perf-counter delta inside a function that references
    jax but never calls block_until_ready/device_get — with async
    dispatch the bracket closes before the device work does."""

    id = "NDS103"
    name = "unsynced-timing"
    paths = ("nds_tpu/engine/", "nds_tpu/parallel/")
    _JAX = {"jax", "jnp", "lax", "jitted", "shard_map"}
    _SYNC = {"block_until_ready", "device_get"}

    def check(self, tree, src, path):
        out = []
        for fn in _walk_funcs(tree):
            names = _names_in(fn)
            attrs = _attrs_in(fn)
            if not (names & self._JAX):
                continue
            if (names | attrs) & self._SYNC:
                continue
            timer_vars = set()
            for n in ast.walk(fn):
                if (isinstance(n, ast.Assign)
                        and isinstance(n.value, ast.Call)
                        and isinstance(n.value.func, ast.Attribute)
                        and n.value.func.attr == "perf_counter"):
                    timer_vars |= {t.id for t in n.targets
                                   if isinstance(t, ast.Name)}
            for n in ast.walk(fn):
                if not (isinstance(n, ast.BinOp)
                        and isinstance(n.op, ast.Sub)):
                    continue
                ends_bracket = any(
                    (isinstance(x, ast.Name) and x.id in timer_vars)
                    or (isinstance(x, ast.Call)
                        and isinstance(x.func, ast.Attribute)
                        and x.func.attr == "perf_counter")
                    for x in (n.left, n.right))
                if ends_bracket and timer_vars:
                    out.append(LintViolation(
                        self.id, path, n.lineno,
                        f"timing bracket in {fn.name}() closes without "
                        f"block_until_ready/device_get — async "
                        f"dispatch makes this measure dispatch, not "
                        f"execution"))
        return out


class PrefixHashRule(Rule):
    """NDS104: hashing a sliced array prefix (``arr[:n].tobytes()``)
    as a content fingerprint."""

    id = "NDS104"
    name = "prefix-hash"

    def check(self, tree, src, path):
        out = []
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "tobytes"):
                continue
            sliced = any(isinstance(x, ast.Subscript)
                         and isinstance(x.slice, ast.Slice)
                         for x in ast.walk(n.func.value))
            if sliced:
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    "content fingerprint over a sliced prefix: "
                    "same-shape changes past the slice serve stale "
                    "cache entries — hash the full buffer"))
        return out


class DeadDataclassFieldRule(Rule):
    """NDS105: a dataclass field no code ever reads. Reads counted
    tree-wide: attribute loads, keyword-free getattr-style string
    constants (``getattr(n, "child")`` walks via string names), so only
    fields dead under BOTH access styles flag. Needs the whole-tree
    index built by ``build_read_index``."""

    id = "NDS105"
    name = "dead-field"

    def __init__(self):
        self.reads: set = set()
        self.strings: set = set()

    def build_read_index(self, trees: "list[ast.AST]") -> None:
        for tree in trees:
            for n in ast.walk(tree):
                if (isinstance(n, ast.Attribute)
                        and isinstance(n.ctx, ast.Load)):
                    self.reads.add(n.attr)
                elif (isinstance(n, ast.Constant)
                      and isinstance(n.value, str)):
                    self.strings.add(n.value)

    @staticmethod
    def _is_dataclass(cls: ast.ClassDef) -> bool:
        for d in cls.decorator_list:
            target = d.func if isinstance(d, ast.Call) else d
            name = (target.attr if isinstance(target, ast.Attribute)
                    else getattr(target, "id", ""))
            if name == "dataclass":
                return True
        return False

    def check(self, tree, src, path):
        out = []
        for n in ast.walk(tree):
            if not (isinstance(n, ast.ClassDef)
                    and self._is_dataclass(n)):
                continue
            for stmt in n.body:
                if not (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    continue
                fname = stmt.target.id
                if fname.startswith("__"):
                    continue
                if fname in self.reads or fname in self.strings:
                    continue
                out.append(LintViolation(
                    self.id, path, stmt.lineno,
                    f"dataclass field {n.name}.{fname} is written but "
                    f"never read anywhere in the tree"))
        return out


class MutableDefaultRule(Rule):
    """NDS106: mutable default argument shared across calls."""

    id = "NDS106"
    name = "mutable-default"
    _CTORS = {"list", "dict", "set"}

    def check(self, tree, src, path):
        out = []
        for fn in _walk_funcs(tree):
            for d in list(fn.args.defaults) + [
                    x for x in fn.args.kw_defaults if x is not None]:
                bad = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in self._CTORS)
                if bad:
                    out.append(LintViolation(
                        self.id, path, d.lineno,
                        f"mutable default argument in {fn.name}()"))
        return out


class BareExceptRule(Rule):
    """NDS107: ``except:`` swallows SystemExit/KeyboardInterrupt."""

    id = "NDS107"
    name = "bare-except"

    def check(self, tree, src, path):
        return [LintViolation(self.id, path, n.lineno,
                              "bare except: catches SystemExit and "
                              "KeyboardInterrupt — name the exception")
                for n in ast.walk(tree)
                if isinstance(n, ast.ExceptHandler) and n.type is None]


class NakedRetryRule(Rule):
    """NDS108: hand-rolled retry loops. A loop whose body contains an
    ``except`` handler (the retry shape) flags when it either sleeps a
    constant interval (no backoff) or is ``while True`` with a sleep
    (no attempt cap). ``resilience.retry.RetryPolicy`` provides capped
    attempts + exponential backoff + jitter; loops that delegate to it
    (``policy.attempts()``, computed delays) don't match."""

    id = "NDS108"
    name = "naked-retry"

    @staticmethod
    def _is_sleep(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Name):
            return f.id == "sleep"
        return (isinstance(f, ast.Attribute) and f.attr == "sleep"
                and isinstance(f.value, ast.Name)
                and f.value.id.lstrip("_") == "time")

    def check(self, tree, src, path):
        out = []
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            has_except = any(isinstance(x, ast.ExceptHandler)
                             for x in ast.walk(loop))
            if not has_except:
                continue
            sleeps = [x for x in ast.walk(loop) if self._is_sleep(x)]
            if not sleeps:
                continue
            uncapped = (isinstance(loop, ast.While)
                        and isinstance(loop.test, ast.Constant)
                        and loop.test.value is True)
            if uncapped:
                out.append(LintViolation(
                    self.id, path, loop.lineno,
                    "while True retry loop with no attempt cap — use "
                    "resilience.retry.RetryPolicy (capped attempts + "
                    "backoff)"))
                continue
            for s in sleeps:
                if any(isinstance(a, ast.Constant) for a in s.args):
                    out.append(LintViolation(
                        self.id, path, s.lineno,
                        "retry loop sleeps a constant interval (no "
                        "backoff) — use resilience.retry.RetryPolicy "
                        "(exponential backoff + jitter)"))
        return out


class NonAtomicJsonWriteRule(Rule):
    """NDS109: ``json.dump(obj, f)`` where ``f`` was opened ``"w"``
    directly on the destination path and the enclosing function never
    calls ``os.replace``/``os.rename`` — the torn-artifact shape.
    Functions that DO rename are presumed to be writing a tmp file
    first (the journal/snapshot/integrity writers), so they don't
    flag."""

    id = "NDS109"
    name = "non-atomic-json-write"
    paths = ("nds_tpu/",)

    @staticmethod
    def _renames_atomically(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("replace", "rename")
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "os"):
                return True
        return False

    @staticmethod
    def _write_handles(fn: ast.AST) -> set:
        """Names bound by ``with open(path, "w"...) as f``."""
        out = set()
        for n in ast.walk(fn):
            if not isinstance(n, ast.With):
                continue
            for item in n.items:
                c = item.context_expr
                if not (isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Name)
                        and c.func.id == "open"):
                    continue
                mode = None
                if len(c.args) > 1 and isinstance(c.args[1],
                                                  ast.Constant):
                    mode = c.args[1].value
                for kw in c.keywords:
                    if (kw.arg == "mode"
                            and isinstance(kw.value, ast.Constant)):
                        mode = kw.value.value
                if (isinstance(mode, str) and "w" in mode
                        and isinstance(item.optional_vars, ast.Name)):
                    out.add(item.optional_vars.id)
        return out

    def check(self, tree, src, path):
        out = []
        for fn in _walk_funcs(tree):
            if self._renames_atomically(fn):
                continue
            handles = self._write_handles(fn)
            if not handles:
                continue
            for n in ast.walk(fn):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "dump"
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "json"):
                    continue
                fp = (n.args[1] if len(n.args) > 1
                      else next((kw.value for kw in n.keywords
                                 if kw.arg == "fp"), None))
                if isinstance(fp, ast.Name) and fp.id in handles:
                    out.append(LintViolation(
                        self.id, path, n.lineno,
                        "non-atomic JSON artifact write: json.dump "
                        "into open(.., 'w') without tmp+os.replace — "
                        "a crash leaves a torn file; use "
                        "io.integrity.write_json_atomic (or waive "
                        "with why a torn read is impossible)"))
        return out


class DirectExecutorRule(Rule):
    """NDS110: direct placement-executor construction outside the
    scheduler. The unified pipeline (engine/scheduler.py) is the one
    place executors are built — it wires the cost model, the
    degradation ladder, retries, and SPMD consensus around them. A
    direct ``DeviceExecutor(...)`` call elsewhere in nds_tpu/ runs none
    of that and silently regresses the unification. Each executor's own
    defining module is exempt (its ``make_*_factory`` helpers and
    subclass internals construct legitimately); tests and tools are out
    of scope by path."""

    id = "NDS110"
    name = "direct-executor"
    paths = ("nds_tpu/",)

    EXECUTORS = {
        "CpuExecutor": "cpu_exec",
        "DeviceExecutor": "device_exec",
        "ChunkedExecutor": "chunked_exec",
        "DistributedExecutor": "dist_exec",
    }
    ALLOWED = ("engine/scheduler.py",)

    def check(self, tree, src, path):
        norm = path.replace("\\", "/")
        if any(a in norm for a in self.ALLOWED):
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else None)
            home = self.EXECUTORS.get(name or "")
            if home is None or norm.endswith(f"{home}.py"):
                continue
            out.append(LintViolation(
                self.id, path, node.lineno,
                f"direct {name} construction outside "
                f"engine/scheduler.py — placement is a scheduling "
                f"decision; route through the ExecutionPipeline (or "
                f"waive with why this site must bypass it)"))
        return out


class UncachedCompileRule(Rule):
    """NDS111: an XLA compile entry point — ``jax.jit(...)`` or an AOT
    ``.lower(args)`` chain — inside ``engine/``/``parallel/`` outside
    the cache module. The persistent plan cache (nds_tpu/cache/) can
    only serve a program it saw compiled: ``cache.aot`` is the single
    lower/compile site, so every executor program gets the
    consult-hit-or-persist treatment. ``.lower()`` with no arguments
    is string-lowercasing, never flagged; ``jax.jit(fn)`` used purely
    to build the traced callable handed to ``cache.aot`` is
    legitimate and carries a waiver saying so."""

    id = "NDS111"
    name = "uncached-compile"
    paths = ("nds_tpu/engine/", "nds_tpu/parallel/")

    def check(self, tree, src, path):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "jit"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "jax"):
                out.append(LintViolation(
                    self.id, path, node.lineno,
                    "jax.jit in engine/parallel code — compiles must "
                    "route through nds_tpu/cache/aot.py so the "
                    "persistent plan cache sees them (waive sites "
                    "that only build the traced callable)"))
            elif (isinstance(f, ast.Attribute) and f.attr == "lower"
                    and (node.args or node.keywords)
                    and not self._string_module(f.value)):
                # .lower(bufs) is jax AOT lowering; bare .lower() is a
                # string method
                out.append(LintViolation(
                    self.id, path, node.lineno,
                    ".lower(args) AOT chain in engine/parallel code — "
                    "use cache.aot.lower_and_compile / cached_compile "
                    "so the plan cache can serve and persist the "
                    "executable"))
        return out

    @staticmethod
    def _string_module(value: ast.AST) -> bool:
        """``np.char.lower(a)`` / ``str.lower(s)`` are string ops, not
        AOT lowering — a function call THROUGH a string-handling
        module, distinguishable syntactically from a method on a
        jitted object."""
        if isinstance(value, ast.Name):
            return value.id == "str"
        if isinstance(value, ast.Attribute):
            return value.attr == "char"
        return False


class Int64EmulationHazardRule(Rule):
    """NDS112: ``jnp.argsort``/``jnp.sort``/``jnp.searchsorted`` call
    in the engine/parallel layers whose call text carries no explicit
    int32 narrowing. Under ``jax_enable_x64`` the default integer (and
    argsort's implicit index operand) is int64, which TPU sorts via
    emulation at a multiple of the native i32 cost — the trap
    ``_build_lookup``'s explicit-iota comment documents, promoted to a
    rule. The check is textual-per-call on purpose: an ``int32``
    mention anywhere in the call (an ``astype``, a ``dtype=``, a
    ``_narrow_key``-produced name is NOT enough — narrowing helpers
    live a line above) signals the author handled the width; anything
    else needs a waiver explaining why 64-bit operands are required."""

    id = "NDS112"
    name = "int64-emulation-hazard"
    paths = ("nds_tpu/engine/", "nds_tpu/parallel/")
    _FUNCS = {"argsort", "sort", "searchsorted"}

    def check(self, tree, src, path):
        out = []
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in self._FUNCS
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "jnp"):
                continue
            seg = ast.get_source_segment(src, n) or ""
            if "int32" in seg:
                continue
            out.append(LintViolation(
                self.id, path, n.lineno,
                f"jnp.{n.func.attr}() without an explicit int32 cast: "
                f"int64 operands under x64 push the sort/search onto "
                f"TPU's emulated 64-bit path (narrow via _narrow_key/"
                f".astype(jnp.int32), or waive with why the width is "
                f"required)"))
        return out


class DirectProfilerRule(Rule):
    """NDS113: a ``jax.profiler.start_trace`` call outside
    ``obs/profile.py``. The profiler allows one active trace per
    process; the profile module owns that invariant (trigger policy,
    BenchReport ``profile`` block, the watchdog's on-stall capture),
    and a stray start_trace elsewhere wedges every managed capture
    after it. Route through ``obs.profile`` (``stream_trace`` /
    ``Profiler.capture``) instead."""

    id = "NDS113"
    name = "direct-profiler"
    paths = ("nds_tpu/", "tools/")
    ALLOWED = ("obs/profile.py",)

    def check(self, tree, src, path):
        norm = path.replace("\\", "/")
        if any(a in norm for a in self.ALLOWED):
            return []
        out = []
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "start_trace"):
                continue
            v = n.func.value
            via_profiler = (
                (isinstance(v, ast.Attribute) and v.attr == "profiler")
                or (isinstance(v, ast.Name) and v.id == "profiler"))
            if via_profiler:
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    "direct jax.profiler.start_trace outside "
                    "obs/profile.py — captures must route through the "
                    "profile trigger policy (obs.profile.stream_trace "
                    "/ Profiler.capture), or waive with why this site "
                    "must own the profiler"))
        return out


class UnchainedSignalHandlerRule(Rule):
    """NDS114: a ``signal.signal(sig, handler)`` call whose enclosing
    scope never calls ``signal.getsignal``. Installing a handler
    without capturing the previous one silently discards it — in this
    tree that means losing the SIGTERM flight-dump chain
    (obs/fleet.py) or the preemption drain (resilience/drain.py),
    whose chaining installs are the blessed pattern. Restoring
    ``SIG_DFL``/``SIG_IGN`` (the re-raise idiom inside a handler) is
    clean by design."""

    id = "NDS114"
    name = "unchained-signal-handler"
    paths = ("nds_tpu/",)

    @staticmethod
    def _is_restore(arg: ast.AST) -> bool:
        if isinstance(arg, ast.Attribute):
            return arg.attr in ("SIG_DFL", "SIG_IGN")
        return (isinstance(arg, ast.Name)
                and arg.id in ("SIG_DFL", "SIG_IGN"))

    @staticmethod
    def _has_getsignal(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if (isinstance(f, ast.Attribute)
                    and f.attr == "getsignal") \
                    or (isinstance(f, ast.Name)
                        and f.id == "getsignal"):
                return True
        return False

    def check(self, tree, src, path):
        out = []
        funcs = list(_walk_funcs(tree))
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "signal"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id.lstrip("_") == "signal"
                    and len(n.args) >= 2):
                continue
            if self._is_restore(n.args[1]):
                continue
            # chained when ANY enclosing function (nearest or an
            # ancestor closure that captured prev) calls getsignal;
            # module-level installs check the whole module
            enclosing = [f for f in funcs
                         if any(ch is n for ch in ast.walk(f))]
            if enclosing:
                if any(self._has_getsignal(f) for f in enclosing):
                    continue
            elif self._has_getsignal(tree):
                continue
            out.append(LintViolation(
                self.id, path, n.lineno,
                "signal.signal() discards the previous handler (no "
                "signal.getsignal in scope): chain it like the "
                "flight-dump/drain installs (obs/fleet.py, "
                "resilience/drain.py), or waive with why replacement "
                "is intended"))
        return out


class BlockingInAsyncRule(Rule):
    """NDS115: blocking calls inside a coroutine of the serving layer
    (``nds_tpu/serve/``). The asyncio front shares ONE event loop
    across every connection: a ``time.sleep``, a synchronous ``open``,
    a ``subprocess``/``socket``/``requests`` call, or a concurrent
    ``Future.result()`` inside an ``async def`` stalls every in-flight
    request at once. Engine work belongs on the engine thread; a
    coroutine may only enqueue and ``await`` (``asyncio.wrap_future``
    is the blessed bridge)."""

    id = "NDS115"
    name = "blocking-in-async"
    paths = ("nds_tpu/serve/",)
    _MODULE_CALLS = {"subprocess": {"run", "call", "check_output",
                                    "check_call", "Popen"},
                     "socket": {"socket", "create_connection"},
                     "requests": {"get", "post", "put", "delete",
                                  "request"},
                     "time": {"sleep"}}

    def _violation_for(self, n: ast.Call) -> "str | None":
        f = n.func
        if isinstance(f, ast.Name) and f.id == "open":
            return "synchronous open() blocks the event loop"
        if isinstance(f, ast.Attribute):
            if (isinstance(f.value, ast.Name)
                    and f.attr in self._MODULE_CALLS.get(
                        f.value.id.lstrip("_"), ())):
                return (f"{f.value.id}.{f.attr}() blocks the event "
                        f"loop")
            if f.attr == "result":
                return ("Future.result() blocks the event loop — "
                        "await asyncio.wrap_future(fut) instead")
        return None

    @staticmethod
    def _body_nodes(fn: ast.AST):
        """The coroutine's own statements: nested defs run wherever
        they're CALLED, not on the loop, so their bodies are pruned
        (nested ASYNC defs get their own check via _walk_funcs)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def check(self, tree, src, path):
        out = []
        for fn in _walk_funcs(tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for n in self._body_nodes(fn):
                if not isinstance(n, ast.Call):
                    continue
                why = self._violation_for(n)
                if why:
                    out.append(LintViolation(
                        self.id, path, n.lineno,
                        f"{why} (in coroutine {fn.name!r}): hand the "
                        f"work to the engine thread, or waive with "
                        f"why blocking here is safe"))
        return out


class EarlyMaterializationRule(Rule):
    """NDS116: decoding dictionary codes to string bytes inside the
    engine/parallel dataflow outside the result compactor. The
    columnar contract (nds_tpu/columnar/; README "Compressed columnar
    store") is LATE materialization: operators consume int32 codes /
    packed words end-to-end and values materialize exactly once, at
    ``_materialize``. A ``col.decode()`` call or a
    ``something.dictionary[...]`` gather anywhere else in the engine
    re-inflates a column to full width mid-plan — the exact bytes the
    compressed store exists to never move. The CPU oracle
    (``engine/cpu_exec.py``) and host-side DML (``engine/dml.py``)
    materialize BY CONTRACT (they are the host reference semantics,
    not device dataflow) and are exempt by path; host-side *planning*
    uses elsewhere carry waivers saying so."""

    id = "NDS116"
    name = "early-materialization"
    paths = ("nds_tpu/engine/", "nds_tpu/parallel/")
    ALLOWED = ("engine/cpu_exec.py", "engine/dml.py")

    @staticmethod
    def _in_materialize(funcs: list, node: ast.AST) -> bool:
        for f in funcs:
            if f.name in ("_materialize", "materialize") and any(
                    ch is node for ch in ast.walk(f)):
                return True
        return False

    def check(self, tree, src, path):
        norm = path.replace("\\", "/")
        if any(a in norm for a in self.ALLOWED):
            return []
        out = []
        funcs = list(_walk_funcs(tree))
        for n in ast.walk(tree):
            hit = None
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "decode"
                    and not n.args and not n.keywords):
                hit = (".decode() materializes a dictionary column "
                       "to python values")
            elif (isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Attribute)
                    and n.value.attr == "dictionary"):
                hit = (".dictionary[...] gathers string bytes "
                       "through the dictionary")
            if hit is None or self._in_materialize(funcs, n):
                continue
            out.append(LintViolation(
                self.id, path, n.lineno,
                f"{hit} outside the result compactor — the engine "
                f"operates on codes end-to-end (late "
                f"materialization, nds_tpu/columnar/); decode at "
                f"_materialize, or waive with why this site is "
                f"host-side planning, not dataflow"))
        return out


class BlockingTransferInStreamLoopRule(Rule):
    """NDS117: a blocking device->host transfer inside the chunked
    engine's phase-A stream loops or the prefetch worker. The pipelined
    executor (``engine/pipeline_io.py``; README "Pipelined execution")
    exists so host staging overlaps device compute; a stray
    ``jax.device_get(...)``, ``.block_until_ready()``, or
    ``np.asarray(<device result>)`` inside a chunk loop serializes the
    pipeline right back to the pre-overlap behavior — silently, since
    results stay correct and only occupancy collapses. The two
    SANCTIONED per-chunk sync points (the partial-agg overflow verdict,
    the keep-mask readback — each IS the loop's product) carry waivers
    saying so; anything new must justify why its sync cannot move to a
    chunk boundary."""

    id = "NDS117"
    name = "blocking-transfer-in-stream-loop"
    paths = ("engine/chunked_exec.py", "engine/pipeline_io.py")

    def check(self, tree, src, path):
        out = []
        seen: set = set()
        loops = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.For, ast.While))]
        for loop in loops:
            for n in ast.walk(loop):
                if id(n) in seen or not isinstance(n, ast.Call):
                    continue
                f = n.func
                hit = None
                if isinstance(f, ast.Attribute):
                    if f.attr == "device_get":
                        hit = "jax.device_get(...)"
                    elif f.attr == "block_until_ready":
                        hit = ".block_until_ready()"
                    elif (f.attr == "asarray"
                          and isinstance(f.value, ast.Name)
                          and f.value.id in ("np", "numpy")
                          and n.args
                          and isinstance(n.args[0], ast.Call)):
                        # np.asarray over a CALL result (a device
                        # computation) syncs; slicing host arrays
                        # (np.asarray(col.values[...])) does not
                        hit = "np.asarray(<device result>)"
                if hit is None:
                    continue
                seen.add(id(n))
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    f"{hit} inside a chunk-stream loop blocks the "
                    f"prefetch pipeline (transfers must stay async — "
                    f"jax.device_put — with syncs only at sanctioned "
                    f"per-chunk read-back points); move the sync to a "
                    f"chunk boundary or waive with why this sync is "
                    f"the loop's product"))
        return out


class UndeadlinedAwaitRule(Rule):
    """NDS118: an ``await`` on a cross-process send/recv/drain inside
    the serving layer (``nds_tpu/serve/``) without an enclosing
    deadline. The fleet router and the TCP front await sockets owned
    by OTHER processes — a replica that was SIGKILLed mid-response, a
    client that stopped reading — and an unbounded ``await
    reader.readline()`` / ``writer.drain()`` / ``wait_closed()`` /
    ``asyncio.open_connection()`` pins a coroutine (and whatever
    request it carries) on that dead peer forever. Every such await
    must sit under ``asyncio.wait_for(...)`` or an enclosing ``async
    with asyncio.timeout(...)`` block, so failover latency is a
    config knob, not a hang."""

    id = "NDS118"
    name = "undeadlined-await"
    paths = ("nds_tpu/serve/",)
    _STREAM_ATTRS = {"readline", "readexactly", "readuntil", "read",
                     "drain", "wait_closed"}

    @classmethod
    def _stream_call(cls, call: ast.Call) -> "str | None":
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in cls._STREAM_ATTRS:
                return f".{f.attr}()"
            if (f.attr == "open_connection"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "asyncio"):
                return "asyncio.open_connection()"
        return None

    @staticmethod
    def _under_timeout(node: ast.AST) -> bool:
        """An enclosing ``async with asyncio.timeout(...)`` (or
        ``timeout_at``) bounds every await in its body; the search
        stops at the coroutine boundary — an outer function's timeout
        does not cover a nested def that runs elsewhere."""
        cur = getattr(node, "_nds118_parent", None)
        while cur is not None:
            if isinstance(cur, ast.AsyncWith):
                for item in cur.items:
                    c = item.context_expr
                    if (isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and c.func.attr in ("timeout",
                                                "timeout_at")
                            and isinstance(c.func.value, ast.Name)
                            and c.func.value.id == "asyncio"):
                        return True
            if isinstance(cur, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                break
            cur = getattr(cur, "_nds118_parent", None)
        return False

    def check(self, tree, src, path):
        out = []
        for n in ast.walk(tree):
            for ch in ast.iter_child_nodes(n):
                ch._nds118_parent = n
        for fn in _walk_funcs(tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for n in BlockingInAsyncRule._body_nodes(fn):
                if (not isinstance(n, ast.Await)
                        or not isinstance(n.value, ast.Call)):
                    continue
                what = self._stream_call(n.value)
                if what is None or self._under_timeout(n):
                    continue
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    f"await {what} without a deadline (in coroutine "
                    f"{fn.name!r}): one dead peer must never hang "
                    f"the serving front — wrap in "
                    f"asyncio.wait_for(...) or an enclosing "
                    f"asyncio.timeout(...) block, or waive with why "
                    f"this await is bounded elsewhere"))
        return out


class UnjournaledMutationRule(Rule):
    """NDS119: a raw store into a ``.tables[...]`` / ``.columns[...]``
    catalog outside the journaled machinery. The writable warehouse
    keeps three views consistent — the session catalog, the delta
    segments/deleted-masks (columnar/delta.py) and the maintenance
    commit journal (nds/maintenance.py) — and ALL of them hang off the
    blessed mutation paths: ``Session.register_table``, the DML
    ``sess.sql`` route and the delta append/delete helpers. A direct
    subscript write (or ``.pop``/``.update``/``.setdefault``/
    ``.clear`` on the catalog dict) bypasses table-scoped plan
    invalidation and crash recovery: cached plans keep serving the old
    table and a resumed run can double-apply or lose the mutation."""

    id = "NDS119"
    name = "unjournaled-mutation"
    paths = ("nds_tpu/",)
    _CATALOGS = ("tables", "columns")
    #: the machinery the journal/invalidation contract is BUILT from —
    #: mutation here is the blessed path itself
    _ALLOWED = ("nds_tpu/engine/session.py", "nds_tpu/engine/dml.py",
                "nds_tpu/columnar/delta.py", "nds_tpu/io/host_table.py")
    _MUTATORS = {"pop", "setdefault", "update", "clear"}

    def applies(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        if any(norm.endswith(a) for a in self._ALLOWED):
            return False
        return super().applies(path)

    @classmethod
    def _catalog_attr(cls, node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and node.attr in cls._CATALOGS)

    def check(self, tree, src, path):
        out = []
        for n in ast.walk(tree):
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            elif isinstance(n, ast.Delete):
                targets = n.targets
            for t in targets:
                if (isinstance(t, ast.Subscript)
                        and self._catalog_attr(t.value)):
                    out.append(LintViolation(
                        self.id, path, n.lineno,
                        f"direct .{t.value.attr}[...] catalog write "
                        f"bypasses the DML journal and table-scoped "
                        f"invalidation: route through "
                        f"Session.register_table / the sess.sql DML "
                        f"path / columnar.delta, or waive with why "
                        f"this store is journal-invisible by design"))
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in self._MUTATORS
                    and self._catalog_attr(n.func.value)):
                out.append(LintViolation(
                    self.id, path, n.lineno,
                    f".{n.func.value.attr}.{n.func.attr}(...) mutates "
                    f"a catalog dict outside the journaled machinery: "
                    f"route through Session.register_table / the "
                    f"sess.sql DML path / columnar.delta, or waive "
                    f"with why this store is journal-invisible by "
                    f"design"))
        return out


def default_rules() -> "list[Rule]":
    return [IdKeyedCacheRule(), RawTimingRule(), UnsyncedTimingRule(),
            PrefixHashRule(), DeadDataclassFieldRule(),
            MutableDefaultRule(), BareExceptRule(), NakedRetryRule(),
            NonAtomicJsonWriteRule(), DirectExecutorRule(),
            UncachedCompileRule(), Int64EmulationHazardRule(),
            DirectProfilerRule(), UnchainedSignalHandlerRule(),
            BlockingInAsyncRule(), EarlyMaterializationRule(),
            BlockingTransferInStreamLoopRule(),
            UndeadlinedAwaitRule(), UnjournaledMutationRule()]


# -------------------------------------------------------------- driver

@dataclass
class LintResult:
    violations: list = field(default_factory=list)  # unwaived, to fix
    waived: list = field(default_factory=list)      # waived, informational
    errors: list = field(default_factory=list)      # malformed/unused waivers


def lint_sources(sources: "dict[str, str]",
                 rules: "list[Rule] | None" = None,
                 enabled: "set[str] | None" = None,
                 tool: str = "ndslint",
                 meta_rule: str = "NDS100") -> LintResult:
    """Lint {path: source}. Rules needing a whole-tree read index (dead
    fields) see every file; violations and waiver bookkeeping are
    per-file. ``enabled`` filters by rule id (None = all). ``tool`` /
    ``meta_rule`` select the waiver marker and the id malformed/stale
    waivers report under — ndsjit (jit_hazards.py) drives this same
    loop with its own catalog."""
    rules = default_rules() if rules is None else rules
    if enabled is not None:
        rules = [r for r in rules if r.id in enabled]
    res = LintResult()
    trees: dict[str, ast.AST] = {}
    for path, src in sorted(sources.items()):
        try:
            trees[path] = ast.parse(src)
        except SyntaxError as exc:
            res.errors.append(LintViolation(
                "NDS000", path, exc.lineno or 0,
                f"syntax error: {exc.msg}"))
    for r in rules:
        if isinstance(r, DeadDataclassFieldRule):
            r.build_read_index(list(trees.values()))
    for path, tree in trees.items():
        src = sources[path]
        waivers, werrs = parse_waivers(src, tool=tool,
                                       meta_rule=meta_rule)
        for w in werrs:
            w.path = path
            res.errors.append(w)
        for r in rules:
            if not r.applies(path):
                continue
            for v in r.check(tree, src, path):
                w = waivers.get(v.line)
                if w is not None and v.rule in w.rules:
                    w.used = True
                    v.waived = True
                    v.waiver_note = w.note
                    res.waived.append(v)
                else:
                    res.violations.append(v)
        for w in waivers.values():
            if not w.used:
                res.errors.append(LintViolation(
                    meta_rule, path, w.line,
                    f"waiver for {','.join(w.rules)} matches no "
                    f"violation — stale, remove it"))
    return res
