"""Built-in deterministic TPC-H-like data generator.

The reference shells out to the TPC-licensed ``dbgen`` tool, downloaded by
the user and patched at build time (`nds-h/nds_h_gen_data.py:90-115`,
`nds-h/tpch-gen/Makefile`). Those tools stay external here too (see
``nds_tpu.datagen.toolwrap``); this module additionally provides what the
reference cannot ship: a hermetic, pure-numpy generator with TPC-H's
documented value distributions (TPC-H v3 spec §4.2, public), so the suite
can be tested and benchmarked end-to-end with zero external downloads.

Chunked generation mirrors dbgen's ``-C parallel -S step`` contract
(`nds-h/nds_h_gen_data.py:90-95`): ``gen_table(table, sf, parallel, step)``
produces exactly the rows of that chunk, deterministically — per-chunk
seeds derive from (seed, table, step) so chunks can be generated on any
host in any order (the reference achieves this with one Hadoop mapper per
chunk, `nds-h/tpch-gen/.../GenTable.java:209-277`; here any process/host
fan-out works).

Correlations the queries depend on are honored:
- l_extendedprice = l_quantity * retailprice(l_partkey) (spec formula);
- o_custkey % 3 != 0, leaving 1/3 of customers order-less (q13/q22);
- l_returnflag/l_linestatus derive from receipt/ship dates vs 1995-06-17;
- o_orderstatus derives from its lineitems' linestatus;
- comments occasionally embed 'special ... requests' (q13) and
  'Customer ... Complaints' (q16) phrases.
"""

from __future__ import annotations

import hashlib

import numpy as np

# --- fixed small tables (public TPC-H spec §4.2.3) -------------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

# (nation name, region index) in nationkey order 0..24
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONT_S1 = ["SM", "MED", "LG", "JUMBO", "WRAP"]
CONT_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod",
    "green", "grey", "honeydew", "hot", "hazel", "indian", "ivory", "khaki",
    "lace", "lavender", "lawn", "lemon", "light", "lime", "linen", "magenta",
    "maroon", "medium", "metallic", "midnight", "mint", "misty", "moccasin",
    "navajo", "navy", "olive", "orange", "orchid", "pale", "papaya", "peach",
    "peru", "pink", "plum", "powder", "puff", "purple", "red", "rose",
    "rosy", "royal", "saddle", "salmon", "sandy", "seashell", "sienna",
    "sky", "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
    "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]
_WORDS = [
    "furiously", "quickly", "carefully", "blithely", "slyly", "ideas",
    "deposits", "accounts", "packages", "foxes", "pinto", "beans",
    "requests", "instructions", "theodolites", "dependencies", "platelets",
    "excuses", "asymptotes", "somas", "final", "regular", "express", "bold",
    "even", "silent", "pending", "ironic", "dogged", "sleep", "wake",
    "haggle", "nag", "among", "above", "along", "after", "across",
]

# epoch-day helpers ---------------------------------------------------------

_EPOCH = np.datetime64("1970-01-01", "D")


def days(iso: str) -> int:
    """ISO date -> int32 days since epoch."""
    return int((np.datetime64(iso, "D") - _EPOCH).astype(np.int64))

STARTDATE = days("1992-01-01")          # spec: O_ORDERDATE uniform range
ENDDATE_ORDERS = days("1998-08-02")     # STARTDATE .. ENDDATE-151
CURRENTDATE_SPLIT = days("1995-06-17")  # returnflag/linestatus split


def _rng(seed: int, table: str, step: int) -> np.random.Generator:
    h = hashlib.sha256(f"{seed}:{table}:{step}".encode()).digest()
    return np.random.Generator(np.random.Philox(int.from_bytes(h[:8], "little")))


def _chunk_range(total: int, parallel: int, step: int) -> tuple[int, int]:
    """Row range [start, end) for 1-based chunk ``step`` of ``parallel``."""
    if not 1 <= step <= parallel:
        raise ValueError(f"step {step} not in [1, {parallel}]")
    base, rem = divmod(total, parallel)
    start = (step - 1) * base + min(step - 1, rem)
    end = start + base + (1 if step <= rem else 0)
    return start, end


def retailprice_cents(partkey: np.ndarray) -> np.ndarray:
    """Spec formula: (90000 + ((partkey/10) mod 20001) + 100*(partkey mod 1000))."""
    pk = partkey.astype(np.int64)
    return 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)


def _comments(rng: np.random.Generator, n: int, nwords: int,
              phrase: tuple[str, str] | None = None,
              phrase_prob: float = 0.0) -> np.ndarray:
    """Random word-salad comments, optionally embedding 'A ... B' phrases."""
    idx = rng.integers(0, len(_WORDS), size=(n, nwords))
    words = np.array(_WORDS, dtype=object)[idx]
    out = np.array([" ".join(row) for row in words], dtype=object)
    if phrase is not None and phrase_prob > 0:
        hit = rng.random(n) < phrase_prob
        if hit.any():
            mid = np.array(_WORDS, dtype=object)[rng.integers(0, len(_WORDS), hit.sum())]
            out[hit] = [f"{phrase[0]} {m} {phrase[1]}" for m in mid]
    return out


def _phones(rng: np.random.Generator, nationkey: np.ndarray) -> np.ndarray:
    n = len(nationkey)
    a = rng.integers(100, 1000, n)
    b = rng.integers(100, 1000, n)
    c = rng.integers(1000, 10000, n)
    cc = nationkey + 10
    return np.array([f"{cc[i]}-{a[i]}-{b[i]}-{c[i]}" for i in range(n)], dtype=object)


# --- per-table row counts (spec §4.2.5) ------------------------------------

def table_rows(table: str, sf: float) -> int:
    base = {
        "customer": 150_000,
        "orders": 1_500_000,
        "part": 200_000,
        "partsupp": 800_000,
        "supplier": 10_000,
    }
    if table == "nation":
        return 25
    if table == "region":
        return 5
    if table == "lineitem":
        # lineitem rows derive from orders (1-7 lines each); callers get the
        # actual count from gen_table. This is the spec's nominal estimate.
        return int(6_000_000 * sf)
    if table not in base:
        raise KeyError(table)
    # floor supplier at 4 so the partsupp 4-supplier spread keeps distinct
    # (ps_partkey, ps_suppkey) primary keys at degenerate scale factors
    floor = 4 if table == "supplier" else 1
    return max(floor, int(base[table] * sf))


def num_customers(sf: float) -> int:
    return table_rows("customer", sf)


# --- order-side deterministic attributes -----------------------------------

def _order_attrs(seed: int, sf: float, o_start: int, o_end: int):
    """Order attributes for order indices [o_start, o_end) (0-based).

    Deterministic in the order index regardless of chunking, so lineitem
    chunks can re-derive their parent orders' dates and line counts.
    """
    # Per-order randomness comes from splitmix-style integer hashing of the
    # order index (vectorized, reproducible for any slice), not a sequential
    # RNG, so any chunk can derive any order's attributes independently.
    idx = np.arange(o_start, o_end, dtype=np.uint64)

    def h(k: int) -> np.ndarray:
        x = idx + np.uint64((k * 0x9E3779B97F4A7C15) % (1 << 64)) + np.uint64(seed)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))

    ncust = num_customers(sf)
    # ENDDATE_ORDERS is already ENDDATE-151 (latest date leaving room for
    # ship/receipt offsets), so the modulus spans the full order-date range.
    orderdate = STARTDATE + (h(1) % np.uint64(ENDDATE_ORDERS - STARTDATE + 1)).astype(np.int32)
    nlines = 1 + (h(2) % np.uint64(7)).astype(np.int32)
    custkey = 1 + (h(3) % np.uint64(ncust)).astype(np.int64)
    # spec: custkey % 3 != 0 -> shift offenders to a neighbor (never 0)
    bad = custkey % 3 == 0
    custkey = np.where(bad, np.maximum(custkey - 1, 1), custkey)
    custkey = np.where(custkey % 3 == 0, custkey + 1, custkey)
    return orderdate, nlines, custkey, h


def gen_table(table: str, sf: float, parallel: int = 1, step: int = 1,
              seed: int = 0) -> dict[str, np.ndarray]:
    """Generate one chunk of one table as {column: numpy array}.

    Dates are int32 epoch days; decimals are int64 cents-style scaled ints
    (scale matches the schema, i.e. value * 100); strings are object arrays.
    """
    if table == "region":
        rng = _rng(seed, table, step)
        if step != 1:
            return {k: v[:0] for k, v in gen_table("region", sf, 1, 1, seed).items()}
        return {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(REGIONS, dtype=object),
            "r_comment": _comments(rng, 5, 8),
        }
    if table == "nation":
        rng = _rng(seed, table, step)
        if step != 1:
            return {k: v[:0] for k, v in gen_table("nation", sf, 1, 1, seed).items()}
        return {
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": np.array([n for n, _ in NATIONS], dtype=object),
            "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int64),
            "n_comment": _comments(rng, 25, 10),
        }

    if table == "supplier":
        total = table_rows(table, sf)
        start, end = _chunk_range(total, parallel, step)
        n = end - start
        rng = _rng(seed, table, step)
        suppkey = np.arange(start + 1, end + 1, dtype=np.int64)
        nationkey = rng.integers(0, 25, n).astype(np.int64)
        return {
            "s_suppkey": suppkey,
            "s_name": np.array([f"Supplier#{k:09d}" for k in suppkey], dtype=object),
            "s_address": _comments(rng, n, 3),
            "s_nationkey": nationkey,
            "s_phone": _phones(rng, nationkey),
            "s_acctbal": rng.integers(-99999, 999999, n).astype(np.int64),
            # q16: ~0.05% of suppliers carry 'Customer ... Complaints'
            "s_comment": _comments(rng, n, 10, ("Customer", "Complaints"), 0.005),
        }

    if table == "customer":
        total = table_rows(table, sf)
        start, end = _chunk_range(total, parallel, step)
        n = end - start
        rng = _rng(seed, table, step)
        custkey = np.arange(start + 1, end + 1, dtype=np.int64)
        nationkey = rng.integers(0, 25, n).astype(np.int64)
        return {
            "c_custkey": custkey,
            "c_name": np.array([f"Customer#{k:09d}" for k in custkey], dtype=object),
            "c_address": _comments(rng, n, 3),
            "c_nationkey": nationkey,
            "c_phone": _phones(rng, nationkey),
            "c_acctbal": rng.integers(-99999, 999999, n).astype(np.int64),
            "c_mktsegment": np.array(SEGMENTS, dtype=object)[rng.integers(0, 5, n)],
            "c_comment": _comments(rng, n, 12),
        }

    if table == "part":
        total = table_rows(table, sf)
        start, end = _chunk_range(total, parallel, step)
        n = end - start
        rng = _rng(seed, table, step)
        partkey = np.arange(start + 1, end + 1, dtype=np.int64)
        s1 = np.array(TYPE_S1, dtype=object)[rng.integers(0, len(TYPE_S1), n)]
        s2 = np.array(TYPE_S2, dtype=object)[rng.integers(0, len(TYPE_S2), n)]
        s3 = np.array(TYPE_S3, dtype=object)[rng.integers(0, len(TYPE_S3), n)]
        c1 = np.array(CONT_S1, dtype=object)[rng.integers(0, len(CONT_S1), n)]
        c2 = np.array(CONT_S2, dtype=object)[rng.integers(0, len(CONT_S2), n)]
        m = rng.integers(1, 6, n)
        b = rng.integers(1, 6, n)
        colors = np.array(COLORS, dtype=object)
        name_idx = rng.integers(0, len(COLORS), size=(n, 5))
        return {
            "p_partkey": partkey,
            "p_name": np.array([" ".join(colors[r]) for r in name_idx], dtype=object),
            "p_mfgr": np.array([f"Manufacturer#{v}" for v in m], dtype=object),
            "p_brand": np.array([f"Brand#{m[i]}{b[i]}" for i in range(n)], dtype=object),
            "p_type": np.array([f"{s1[i]} {s2[i]} {s3[i]}" for i in range(n)], dtype=object),
            "p_size": rng.integers(1, 51, n).astype(np.int32),
            "p_container": np.array([f"{c1[i]} {c2[i]}" for i in range(n)], dtype=object),
            "p_retailprice": retailprice_cents(partkey),
            "p_comment": _comments(rng, n, 4),
        }

    if table == "partsupp":
        # 4 suppliers per part, deterministic spec-style spread
        nparts = table_rows("part", sf)
        nsupp = table_rows("supplier", sf)
        start, end = _chunk_range(nparts, parallel, step)
        n = end - start
        rng = _rng(seed, table, step)
        partkey = np.repeat(np.arange(start + 1, end + 1, dtype=np.int64), 4)
        j = np.tile(np.arange(4, dtype=np.int64), n)
        suppkey = _supplier_spread(partkey, j, nsupp)
        return {
            "ps_partkey": partkey,
            "ps_suppkey": suppkey,
            "ps_availqty": rng.integers(1, 10000, 4 * n).astype(np.int32),
            "ps_supplycost": rng.integers(100, 100001, 4 * n).astype(np.int64),
            "ps_comment": _comments(rng, 4 * n, 12),
        }

    if table == "orders":
        total = table_rows(table, sf)
        start, end = _chunk_range(total, parallel, step)
        n = end - start
        rng = _rng(seed, table, step)
        orderdate, nlines, custkey, h = _order_attrs(seed, sf, start, end)
        orderkey = np.arange(start + 1, end + 1, dtype=np.int64)
        # orderstatus: F if all lines shipped before split, O if all after,
        # else P. Derive from the same hashes lineitem uses.
        all_f, all_o = _order_status_parts(orderdate, nlines, start, end, seed)
        status = np.where(all_f, "F", np.where(all_o, "O", "P")).astype(object)
        totalprice = _order_totalprice(h, nlines)
        return {
            "o_orderkey": orderkey,
            "o_custkey": custkey,
            "o_orderstatus": status,
            "o_totalprice": totalprice,
            "o_orderdate": orderdate.astype(np.int32),
            "o_orderpriority": np.array(PRIORITIES, dtype=object)[rng.integers(0, 5, n)],
            "o_clerk": np.array(
                [f"Clerk#{v:09d}" for v in rng.integers(1, max(2, int(sf * 1000)) + 1, n)],
                dtype=object),
            "o_shippriority": np.zeros(n, dtype=np.int32),
            "o_comment": _comments(rng, n, 8, ("special", "requests"), 0.01),
        }

    if table == "lineitem":
        # chunked by parent order range so each chunk is self-contained
        n_orders = table_rows("orders", sf)
        o_start, o_end = _chunk_range(n_orders, parallel, step)
        rng = _rng(seed, table, step)
        orderdate, nlines, _custkey, h = _order_attrs(seed, sf, o_start, o_end)
        total_lines = int(nlines.sum())
        okey = np.repeat(np.arange(o_start + 1, o_end + 1, dtype=np.int64), nlines)
        odate = np.repeat(orderdate, nlines)
        # line number within order
        offs = np.concatenate([[0], np.cumsum(nlines)[:-1]])
        linenumber = (np.arange(total_lines, dtype=np.int64)
                      - np.repeat(offs, nlines) + 1).astype(np.int32)
        # per-line randomness: hash on (global order idx, linenumber)
        lidx = np.repeat(np.arange(o_start, o_end, dtype=np.uint64), nlines)

        def lh(k: int) -> np.ndarray:
            return _line_hash(lidx, linenumber.astype(np.uint64), k, seed)

        nparts = table_rows("part", sf)
        nsupp = table_rows("supplier", sf)
        partkey = 1 + (lh(1) % np.uint64(nparts)).astype(np.int64)
        # one of the part's 4 suppliers, same spread as partsupp
        j = (lh(2) % np.uint64(4)).astype(np.int64)
        suppkey = _supplier_spread(partkey, j, nsupp)
        quantity = 1 + (lh(3) % np.uint64(50)).astype(np.int64)
        extprice = quantity * retailprice_cents(partkey)
        discount = (lh(4) % np.uint64(11)).astype(np.int64)          # 0.00-0.10
        tax = (lh(5) % np.uint64(9)).astype(np.int64)                # 0.00-0.08
        shipdate = odate + 1 + (lh(6) % np.uint64(121)).astype(np.int32)
        commitdate = odate + 30 + (lh(7) % np.uint64(61)).astype(np.int32)
        receiptdate = shipdate + 1 + (lh(8) % np.uint64(30)).astype(np.int32)
        returned = receiptdate <= CURRENTDATE_SPLIT
        rf_r = (lh(9) % np.uint64(2)).astype(bool)
        returnflag = np.where(returned, np.where(rf_r, "R", "A"), "N").astype(object)
        linestatus = np.where(shipdate > CURRENTDATE_SPLIT, "O", "F").astype(object)
        return {
            "l_orderkey": okey,
            "l_partkey": partkey,
            "l_suppkey": suppkey,
            "l_linenumber": linenumber,
            "l_quantity": quantity * 100,            # scale-2 cents
            "l_extendedprice": extprice,
            "l_discount": discount,
            "l_tax": tax,
            "l_returnflag": returnflag,
            "l_linestatus": linestatus,
            "l_shipdate": shipdate.astype(np.int32),
            "l_commitdate": commitdate.astype(np.int32),
            "l_receiptdate": receiptdate.astype(np.int32),
            "l_shipinstruct": np.array(INSTRUCTIONS, dtype=object)[
                (lh(10) % np.uint64(4)).astype(np.int64)],
            "l_shipmode": np.array(SHIPMODES, dtype=object)[
                (lh(11) % np.uint64(7)).astype(np.int64)],
            "l_comment": _comments(rng, total_lines, 5),
        }

    raise KeyError(f"unknown TPC-H table {table!r}")


def _supplier_spread(partkey: np.ndarray, j: np.ndarray, nsupp: int) -> np.ndarray:
    """Supplier j (0-3) of a part. Spec §4.2.5.4 spread for realistic supplier
    counts; plain +j at degenerate counts where the spec step can share a
    factor with nsupp and collapse the 4 suppliers together."""
    if nsupp >= 100:
        step = nsupp // 4 + (partkey - 1 + nsupp) // nsupp
        return ((partkey + j * step) % nsupp) + 1
    return ((partkey + j) % nsupp) + 1


def _line_hash(o_idx: np.ndarray, linenumber: np.ndarray, k: int,
               seed: int) -> np.ndarray:
    """The per-lineitem splitmix hash, shared by lineitem gen and
    _order_status_parts so o_orderstatus matches actual line statuses."""
    x = (o_idx * np.uint64(8) + linenumber.astype(np.uint64)
         + np.uint64((k * 0x9E3779B97F4A7C15) % (1 << 64)) + np.uint64(seed))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _order_status_parts(orderdate, nlines, o_start, o_end, seed):
    """Whether all / none of an order's lines have linestatus F.

    Computes each order's actual per-line shipdates with the identical hash
    lineitem generation uses (k=6), so o_orderstatus is exactly consistent
    with the joined lineitem rows (q21 filters o_orderstatus='F').
    """
    idx = np.arange(o_start, o_end, dtype=np.uint64)
    n = len(idx)
    min_ship = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
    max_ship = np.full(n, np.iinfo(np.int64).min, dtype=np.int64)
    for j in range(1, 8):
        has_line = nlines >= j
        ship = orderdate.astype(np.int64) + 1 + (
            _line_hash(idx, np.full(n, j, dtype=np.uint64), 6, seed)
            % np.uint64(121)).astype(np.int64)
        min_ship = np.where(has_line, np.minimum(min_ship, ship), min_ship)
        max_ship = np.where(has_line, np.maximum(max_ship, ship), max_ship)
    all_f = max_ship <= CURRENTDATE_SPLIT
    all_o = min_ship > CURRENTDATE_SPLIT
    return all_f, all_o


def _order_totalprice(h, nlines):
    """Approximate totalprice from hashed per-line prices (scale-2 int)."""
    # Deterministic but decoupled from exact line sums; queries never join
    # o_totalprice against line sums (only q18 uses it as output).
    base = (h(12) % np.uint64(50_000_000)).astype(np.int64) + 100_000
    return base * nlines.astype(np.int64) // 4
