"""External TPC tool wrappers: dsdgen/dsqgen (TPC-DS), dbgen/qgen (TPC-H).

The TPC toolkits are licensed and are NOT vendored — the user downloads
them and this module builds/patches/drives them, mirroring the
reference's stance (`nds/tpcds-gen/Makefile:30-38` patches then builds;
`nds/nds_gen_data.py:211-222` shells out per chunk;
`nds/nds_gen_query_stream.py:57-70` drives dsqgen). Hadoop-MR fan-out
(`GenTable.java:188-279`) is replaced by local process fan-out — same
per-(chunk, table) command lines, no cluster dependency.

Patches are applied from a caller-supplied directory (e.g. the
spark-rapids-benchmarks checkout's ``tpcds-gen/patches``); they are not
shipped here for the same licensing reason the tools aren't.
"""

from __future__ import annotations

import os
import subprocess


class ToolError(RuntimeError):
    pass


def apply_patches(tools_dir: str, patches_dir: str) -> list[str]:
    """Apply every .patch in patches_dir to the TPC toolkit source with
    ``patch -p1`` (idempotent: already-applied patches are skipped via
    ``--forward``). Returns the list of applied patch files."""
    applied = []
    for fname in sorted(os.listdir(patches_dir)):
        if not fname.endswith(".patch"):
            continue
        path = os.path.join(patches_dir, fname)
        proc = subprocess.run(
            ["patch", "-p1", "--forward", "-i", path],
            cwd=tools_dir, capture_output=True, text=True)
        if proc.returncode == 0:
            applied.append(fname)
        elif "Reversed (or previously applied)" not in proc.stdout:
            raise ToolError(
                f"patch {fname} failed:\n{proc.stdout}\n{proc.stderr}")
    return applied


def build_tools(tools_dir: str, patches_dir: str | None = None) -> None:
    """Patch (optionally) and ``make`` the toolkit in its tools/ dir."""
    if patches_dir:
        apply_patches(tools_dir, patches_dir)
    make_dir = os.path.join(tools_dir, "tools")
    if not os.path.isdir(make_dir):
        make_dir = tools_dir
    proc = subprocess.run(["make"], cwd=make_dir, capture_output=True,
                          text=True)
    if proc.returncode != 0:
        raise ToolError(f"make failed in {make_dir}:\n{proc.stderr[-2000:]}")


def _fan_out(cmds: list[list[str]], cwd: str, env: dict) -> None:
    procs = [subprocess.Popen(c, cwd=cwd, env=env) for c in cmds]
    rcs = [p.wait() for p in procs]
    if any(rcs):
        raise ToolError(f"tool chunks failed: {rcs}")


def run_dsdgen(dsdgen_path: str, scale: int, parallel: int, data_dir: str,
               update: int | None = None) -> None:
    """One dsdgen process per child chunk (the reference mapper command,
    `GenTable.java:233-279`: ``dsdgen -scale N -parallel P -child C``)."""
    os.makedirs(data_dir, exist_ok=True)
    tool_dir = os.path.dirname(os.path.abspath(dsdgen_path))
    env = dict(os.environ)
    cmds = []
    for child in range(1, parallel + 1):
        cmd = [dsdgen_path, "-scale", str(scale), "-dir", data_dir,
               "-force", "Y"]
        if parallel > 1:
            cmd += ["-parallel", str(parallel), "-child", str(child)]
        if update is not None:
            cmd += ["-update", str(update)]
        cmds.append(cmd)
    _fan_out(cmds, tool_dir, env)
    _move_into_table_dirs(data_dir)


def run_dbgen(dbgen_path: str, scale: int, parallel: int,
              data_dir: str) -> None:
    """One dbgen process per chunk (`nds-h/nds_h_gen_data.py:90-95`:
    ``dbgen -s N -C P -S C``)."""
    os.makedirs(data_dir, exist_ok=True)
    tool_dir = os.path.dirname(os.path.abspath(dbgen_path))
    env = dict(os.environ, DSS_PATH=data_dir)
    cmds = []
    for step in range(1, parallel + 1):
        cmd = [dbgen_path, "-s", str(scale), "-f"]
        if parallel > 1:
            cmd += ["-C", str(parallel), "-S", str(step)]
        cmds.append(cmd)
    _fan_out(cmds, tool_dir, env)
    _move_into_table_dirs(data_dir)


def _move_into_table_dirs(data_dir: str) -> None:
    """dsdgen/dbgen drop table_N_M.dat / table.tbl.N files flat; the
    harness layout is one directory per table
    (`nds/nds_gen_data.py:86-117` move step)."""
    for fname in sorted(os.listdir(data_dir)):
        path = os.path.join(data_dir, fname)
        if not os.path.isfile(path):
            continue
        base = fname.split(".")[0]          # table.tbl.3 -> table
        parts = base.split("_")
        while parts and parts[-1].isdigit():  # table_3_8 -> table
            parts.pop()
        table = "_".join(parts)
        if not table:
            continue
        tdir = os.path.join(data_dir, table)
        os.makedirs(tdir, exist_ok=True)
        os.replace(path, os.path.join(tdir, fname))


def run_dsqgen(dsqgen_path: str, template_dir: str, output_dir: str,
               scale: int = 1, streams: int | None = None,
               template: str | None = None,
               dialect: str = "spark",
               rngseed: int | None = None) -> None:
    """Drive dsqgen to emit one query or N permuted streams
    (`nds/nds_gen_query_stream.py:57-88`)."""
    os.makedirs(output_dir, exist_ok=True)
    cmd = [dsqgen_path,
           "-template_dir", template_dir,
           "-input", os.path.join(template_dir, "templates.lst"),
           "-scale", str(scale),
           "-directory", template_dir,
           "-dialect", dialect,
           "-output_dir", output_dir]
    if template:
        cmd += ["-template", template]
    else:
        cmd += ["-streams", str(streams or 1)]
    if rngseed is not None:
        cmd += ["-rngseed", str(rngseed)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise ToolError(f"dsqgen failed:\n{proc.stderr[-2000:]}")


def run_qgen(qgen_path: str, query_dir: str, output_dir: str,
             scale: int = 1, streams: int = 1) -> None:
    """Drive TPC-H qgen per stream with DSS_QUERY pointing at the patched
    query templates (`nds-h/nds_h_gen_query_stream.py:60-81`)."""
    os.makedirs(output_dir, exist_ok=True)
    env = dict(os.environ, DSS_QUERY=query_dir)
    tool_dir = os.path.dirname(os.path.abspath(qgen_path))
    for i in range(streams):
        cmd = [qgen_path, "-s", str(scale)]
        if i:
            cmd += ["-p", str(i)]
        proc = subprocess.run(cmd, env=env, cwd=tool_dir,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise ToolError(f"qgen stream {i} failed:\n{proc.stderr}")
        out = os.path.join(output_dir, f"stream_{i}.sql")
        with open(out, "w") as f:
            f.write(proc.stdout)
