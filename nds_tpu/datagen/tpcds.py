"""Built-in deterministic TPC-DS-like data generator.

Counterpart of `nds_tpu.datagen.tpch` for the NDS suite: the reference
drives the TPC-licensed dsdgen via Hadoop-MR
(`nds/tpcds-gen/.../GenTable.java:233-279`); the licensed tool stays
external here too, while this module gives the suite a hermetic generator
with the public spec's schema shapes (TPC-DS v3.2 §3): the star-schema FK
structure, the item brand/class/category hierarchy, the demographic
cross-product dimensions, the 1998-2002 sales calendar, multi-line
tickets/orders, returns as ~10% subsets of sales keyed by
(item, ticket/order), weekly inventory snapshots, and NULLable FK
columns. Distribution *parameters* are public spec §3 facts; value
synthesis is hash-based (splitmix-style), chunk-parallel with the same
(seed, table, step) determinism contract as the TPC-H generator.

Internal consistency is the correctness bar: the differential oracle
compares engine-vs-engine on identical inputs (`nds/nds_validate.py`
compares two runs of the same data), not engine-vs-dsdgen bytes.
"""

from __future__ import annotations

import numpy as np

from nds_tpu.nds.schema import table_rows

# ---- public spec §3 value domains -----------------------------------------

CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES_PER_CAT = 16
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
MARITAL = ["S", "M", "D", "W", "U"]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000",
                 ">10000", "Unknown"]
GENDERS = ["M", "F"]
STATES = ["AL", "CA", "CO", "FL", "GA", "IL", "IN", "KS", "KY", "LA",
          "MI", "MN", "MO", "MS", "NC", "NE", "NY", "OH", "OK", "PA",
          "SD", "TN", "TX", "VA", "WA", "WI"]
COUNTIES = [f"{w} County" for w in
            ["Williamson", "Walker", "Ziebach", "Franklin", "Bronx",
             "Orange", "Fairfield", "Jackson", "Barrow", "Daviess",
             "Luce", "Richland", "Furnas", "Maverick", "Huron",
             "Kittitas", "Mobile", "Coal", "Lunenburg", "Ferry"]]
CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Oakland",
          "Riverside", "Salem", "Georgetown", "Greenfield", "Liberty",
          "Bethel", "Pleasant Hill", "Lebanon", "Springdale", "Shiloh",
          "Mount Olive", "Glendale", "Marion", "Greenville", "Union"]
STREET_TYPES = ["Street", "Ave", "Blvd", "Way", "Ct", "Dr", "Ln",
                "Pkwy", "Rd", "Cir"]
SHIFT = ["first", "second", "third"]
MEAL = ["breakfast", "lunch", "dinner", ""]
SM_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
SM_CODES = ["AIR", "SURFACE", "SEA"]
SM_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
               "ZOUROS", "MSC", "LATVIAN", "DIAMOND", "ALLIANCE",
               "ORIENTAL", "BARIAN", "BOXBUNDLES", "HARMSTORF",
               "PRIVATECARRIER", "GERMA", "RUPEKSA", "GREAT EASTERN"]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blue", "blush", "brown", "burlywood", "chartreuse",
          "chiffon", "chocolate", "coral", "cornflower", "cream",
          "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
          "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod",
          "green", "grey", "honeydew", "hot", "indian", "ivory", "khaki",
          "lace", "lavender", "lawn", "lemon", "light", "lime", "linen",
          "magenta", "maroon", "medium", "metallic", "midnight", "mint",
          "misty", "moccasin", "navajo", "navy", "olive", "orange",
          "orchid", "pale", "papaya", "peach", "peru", "pink", "plum",
          "powder", "puff", "purple", "red", "rose", "rosy", "royal",
          "saddle", "salmon", "sandy", "seashell", "sienna", "sky",
          "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
          "tomato", "turquoise", "violet", "wheat", "white", "yellow"]
UNITS = ["Unknown", "Each", "Dozen", "Case", "Pallet", "Gross", "Lb",
         "Oz", "Ton", "Bunch", "Bundle", "Box", "Carton", "Cup",
         "Dram", "Gram", "N/A", "Pound", "Tbl", "Tsp"]
CONTAINERS = ["Unknown"]
SIZES_DOM = ["small", "medium", "large", "extra large", "economy",
             "N/A", "petite"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
COUNTRY = "United States"

# d_date_sk convention: julian-style, 2415022 == 1900-01-02 (dsdgen's
# base); date_dim spans 73049 days from 1900-01-02
DATE_SK_BASE = 2415022
EPOCH_1900 = -25567  # 1900-01-02 as days since 1970-01-01 is -25566
DATE_DIM_START_EPOCH = -25566
SALES_DATE_LO = 2450815  # 1998-01-01
SALES_DATE_HI = 2452642  # 2002-12-31


def sk_to_epoch(sk):
    return sk - DATE_SK_BASE + DATE_DIM_START_EPOCH


def epoch_to_sk(d):
    return d - DATE_DIM_START_EPOCH + DATE_SK_BASE


def _stable_base(seed: int, table: str, k: int) -> int:
    import hashlib
    digest = hashlib.md5(f"{seed}/{table}/{k}".encode()).digest()
    return int.from_bytes(digest[:8], "little")


def _h(seed: int, table: str, k: int, idx: np.ndarray) -> np.ndarray:
    """Deterministic uint64 stream #k over row indices (stable across
    processes — python's salted hash() must NOT leak in here, chunks are
    generated by independent workers)."""
    base = np.uint64((_stable_base(seed, table, k)
                      & 0x7FFFFFFFFFFFFFFF) | 1)
    x = idx.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15) + base
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _choice(h: np.ndarray, options: list) -> np.ndarray:
    return np.array(options, dtype=object)[
        (h % np.uint64(len(options))).astype(np.int64)]


def _uniform(h: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Integer uniform in [lo, hi]."""
    return (lo + (h % np.uint64(hi - lo + 1)).astype(np.int64))


def _chunk(total: int, parallel: int, step: int) -> tuple[int, int]:
    per = -(-total // parallel)
    lo = (step - 1) * per
    return lo, min(lo + per, total)


def _ids(prefix: str, sk: np.ndarray, width: int = 16) -> np.ndarray:
    return np.array([f"{prefix}{int(v):0{width - len(prefix)}d}"
                     for v in sk], dtype=object)


def _null_out(arr: np.ndarray, h: np.ndarray, pct: int) -> np.ndarray:
    """~pct% of values become the -1 NULL sentinel (dsdgen's NULLable FK
    sks). Inside the generators the sentinel stays -1 so derived columns
    can branch on it; ``gen_table`` converts sentinels to genuine null
    masks (``col#null`` companion arrays) before handing data to IO, so
    IS NULL / join / aggregate NULL semantics match dsdgen output."""
    mask = (h % np.uint64(100)) < np.uint64(pct)
    out = arr.copy()
    out[mask] = -1
    return out


def _is_sentinel_nullable(name: str) -> bool:
    """Columns whose -1 values are NULL sentinels: surrogate keys (domain
    starts at 1) and the one nulled measure, inv_quantity_on_hand."""
    return name.endswith("_sk") or name == "inv_quantity_on_hand"


SEED = 20260729


def gen_table(table: str, sf: float, parallel: int = 1, step: int = 1,
              seed: int = SEED) -> dict[str, np.ndarray]:
    fn = _GENERATORS.get(table)
    if fn is None:
        raise ValueError(f"unknown TPC-DS table {table!r}")
    total = table_rows(table, sf)
    lo, hi = _chunk(total, parallel, step)
    idx = np.arange(lo, hi, dtype=np.int64)
    out = fn(idx, sf, seed, total)
    # -1 sentinels -> genuine null masks ('<col>#null' companion arrays,
    # True = valid), consumed by io.host_table.from_arrays
    masks = {}
    for name, arr in out.items():
        if (isinstance(arr, np.ndarray) and arr.dtype.kind == "i"
                and _is_sentinel_nullable(name)):
            isnull = arr == -1
            if isnull.any():
                masks[name + "#null"] = ~isnull
                fixed = arr.copy()
                fixed[isnull] = 0
                out[name] = fixed
    out.update(masks)
    return out


# ---- dimensions -----------------------------------------------------------

def _gen_date_dim(idx, sf, seed, total):
    sk = DATE_SK_BASE + idx
    epoch = sk_to_epoch(sk)
    dt = (np.datetime64("1970-01-01", "D") + epoch)
    Y = dt.astype("datetime64[Y]")
    M = dt.astype("datetime64[M]")
    year = Y.astype(np.int64) + 1970
    moy = (M.astype(np.int64) % 12) + 1
    dom = (dt - M).astype(np.int64) + 1
    dow = ((epoch + 4) % 7).astype(np.int64)  # 1970-01-01 = Thursday
    month_seq = (year - 1900) * 12 + moy - 1
    week_seq = ((epoch - DATE_DIM_START_EPOCH) // 7) + 1
    qoy = (moy - 1) // 3 + 1
    quarter_seq = (year - 1900) * 4 + qoy - 1
    month_start_epoch = (M.astype("datetime64[D]")
                         - np.datetime64("1970-01-01", "D")
                         ).astype(np.int64)
    first_dom = epoch_to_sk(month_start_epoch)
    last_dom = first_dom + 27  # approximation, unused by the query set
    holiday = np.where((moy == 12) & (dom == 25), "Y", "N").astype(object)
    weekend = np.where((dow == 0) | (dow == 6), "Y", "N").astype(object)
    return {
        "d_date_sk": sk.astype(np.int32),
        "d_date_id": _ids("AAAAAAAA", sk),
        "d_date": epoch.astype(np.int32),
        "d_month_seq": month_seq.astype(np.int32),
        "d_week_seq": week_seq.astype(np.int32),
        "d_quarter_seq": quarter_seq.astype(np.int32),
        "d_year": year.astype(np.int32),
        "d_dow": dow.astype(np.int32),
        "d_moy": moy.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_qoy": qoy.astype(np.int32),
        "d_fy_year": year.astype(np.int32),
        "d_fy_quarter_seq": quarter_seq.astype(np.int32),
        "d_fy_week_seq": week_seq.astype(np.int32),
        "d_day_name": np.array(DAY_NAMES, dtype=object)[dow],
        "d_quarter_name": np.array(
            [f"{y}Q{q}" for y, q in zip(year, qoy)], dtype=object),
        "d_holiday": holiday,
        "d_weekend": weekend,
        "d_following_holiday": np.roll(holiday, -1),
        "d_first_dom": first_dom.astype(np.int32),
        "d_last_dom": last_dom.astype(np.int32),
        "d_same_day_ly": (sk - 365).astype(np.int32),
        "d_same_day_lq": (sk - 91).astype(np.int32),
        "d_current_day": np.full(len(idx), "N", dtype=object),
        "d_current_week": np.full(len(idx), "N", dtype=object),
        "d_current_month": np.full(len(idx), "N", dtype=object),
        "d_current_quarter": np.full(len(idx), "N", dtype=object),
        "d_current_year": np.full(len(idx), "N", dtype=object),
    }


def _gen_time_dim(idx, sf, seed, total):
    t = idx
    hour = t // 3600
    minute = (t % 3600) // 60
    second = t % 60
    shift = np.array(SHIFT, dtype=object)[
        np.minimum(hour // 8, 2).astype(np.int64)]
    meal = np.where(
        (hour >= 6) & (hour <= 8), "breakfast",
        np.where((hour >= 11) & (hour <= 13), "lunch",
                 np.where((hour >= 17) & (hour <= 19), "dinner", "")))
    return {
        "t_time_sk": t.astype(np.int32),
        "t_time_id": _ids("AAAAAAAA", t),
        "t_time": t.astype(np.int32),
        "t_hour": hour.astype(np.int32),
        "t_minute": minute.astype(np.int32),
        "t_second": second.astype(np.int32),
        "t_am_pm": np.where(hour < 12, "AM", "PM").astype(object),
        "t_shift": shift,
        "t_sub_shift": shift,
        "t_meal_time": meal.astype(object),
    }


def _address_cols(prefix, idx, seed, table):
    h = lambda k: _h(seed, table, k, idx)
    num = _uniform(h(90), 1, 999)
    return {
        f"{prefix}street_number": np.array(
            [str(v) for v in num], dtype=object),
        f"{prefix}street_name": _choice(h(91), CITIES),
        f"{prefix}street_type": _choice(h(92), STREET_TYPES),
        f"{prefix}suite_number": np.array(
            [f"Suite {int(v)}" for v in _uniform(h(93), 0, 99)],
            dtype=object),
        f"{prefix}city": _choice(h(94), CITIES),
        f"{prefix}county": _choice(h(95), COUNTIES),
        f"{prefix}state": _choice(h(96), STATES),
        f"{prefix}zip": np.array(
            [f"{int(v):05d}" for v in _uniform(h(97), 10000, 99999)],
            dtype=object),
        f"{prefix}country": np.full(len(idx), COUNTRY, dtype=object),
        f"{prefix}gmt_offset": (-(_uniform(h(98), 5, 8)) * 100
                                ).astype(np.int64),
    }


def _gen_customer_address(idx, sf, seed, total):
    sk = idx + 1
    out = {"ca_address_sk": sk.astype(np.int32),
           "ca_address_id": _ids("AAAAAAAA", sk)}
    out.update(_address_cols("ca_", idx, seed, "customer_address"))
    out["ca_location_type"] = _choice(
        _h(seed, "customer_address", 99, idx),
        ["apartment", "condo", "single family"])
    return out


def _gen_customer_demographics(idx, sf, seed, total):
    # exact cross product, spec order: gender x marital x education x
    # purchase_estimate x credit x dep x dep_employed x dep_college
    sk = idx + 1
    i = idx
    g = i % 2
    i = i // 2
    m = i % 5
    i = i // 5
    e = i % 7
    i = i // 7
    pe = i % 20
    i = i // 20
    cr = i % 4
    i = i // 4
    dep = i % 7
    i = i // 7
    depe = i % 7
    i = i // 7
    depc = i % 7
    return {
        "cd_demo_sk": sk.astype(np.int32),
        "cd_gender": np.array(GENDERS, dtype=object)[g],
        "cd_marital_status": np.array(MARITAL, dtype=object)[m],
        "cd_education_status": np.array(EDUCATION, dtype=object)[e],
        "cd_purchase_estimate": ((pe + 1) * 500).astype(np.int32),
        "cd_credit_rating": np.array(CREDIT, dtype=object)[cr],
        "cd_dep_count": dep.astype(np.int32),
        "cd_dep_employed_count": depe.astype(np.int32),
        "cd_dep_college_count": depc.astype(np.int32),
    }


def _gen_household_demographics(idx, sf, seed, total):
    sk = idx + 1
    i = idx
    ib = i % 20
    i = i // 20
    bp = i % 6
    i = i // 6
    dep = i % 10
    i = i // 10
    veh = i % 6
    return {
        "hd_demo_sk": sk.astype(np.int32),
        "hd_income_band_sk": (ib + 1).astype(np.int32),
        "hd_buy_potential": np.array(BUY_POTENTIAL, dtype=object)[bp],
        "hd_dep_count": dep.astype(np.int32),
        "hd_vehicle_count": (veh - 1).astype(np.int32),
    }


def _gen_income_band(idx, sf, seed, total):
    sk = idx + 1
    return {
        "ib_income_band_sk": sk.astype(np.int32),
        "ib_lower_bound": (idx * 10000).astype(np.int32),
        "ib_upper_bound": ((idx + 1) * 10000).astype(np.int32),
    }


def _gen_reason(idx, sf, seed, total):
    sk = idx + 1
    reasons = ["Package was damaged", "Stopped working",
               "Did not get it on time", "Not the product that was ordred",
               "Parts missing", "Does not work with a product that I have",
               "Gift exchange", "Did not like the color",
               "Did not like the model", "Did not like the make",
               "Did not fit", "Found a better price in a store",
               "Found a better extended warranty in a store",
               "No service location in my area", "duplicate purchase",
               "its is a boy", "its is a girl", "reason 18", "reason 19",
               "reason 20", "reason 21", "reason 22", "reason 23",
               "reason 24", "reason 25", "reason 26", "reason 27",
               "reason 28", "reason 29", "reason 30", "reason 31",
               "reason 32", "reason 33", "reason 34", "reason 35"]
    return {
        "r_reason_sk": sk.astype(np.int32),
        "r_reason_id": _ids("AAAAAAAA", sk),
        "r_reason_desc": np.array(reasons, dtype=object)[
            idx % len(reasons)],
    }


def _gen_ship_mode(idx, sf, seed, total):
    sk = idx + 1
    return {
        "sm_ship_mode_sk": sk.astype(np.int32),
        "sm_ship_mode_id": _ids("AAAAAAAA", sk),
        "sm_type": np.array(SM_TYPES, dtype=object)[idx % 5],
        "sm_code": np.array(SM_CODES, dtype=object)[idx % 3],
        "sm_carrier": np.array(SM_CARRIERS, dtype=object)[
            idx % len(SM_CARRIERS)],
        "sm_contract": _ids("", idx + 1, 16),
    }


_BRAND_WORDS = ["amalg", "edu pack", "exporti", "importo", "scholar",
                "corp", "brand", "univ", "namel", "maxi"]


def _gen_item(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "item", k, idx)
    cat_id = (idx % 10).astype(np.int64)
    class_id = _uniform(h(1), 1, CLASSES_PER_CAT)
    manufact_id = _uniform(h(2), 1, 1000)
    brand_id = cat_id * 1000000 + class_id * 1000 + manufact_id % 1000
    price = _uniform(h(3), 99, 9999)  # cents
    cat = np.array(CATEGORIES, dtype=object)[cat_id]
    return {
        "i_item_sk": sk.astype(np.int32),
        "i_item_id": _ids("AAAAAAAA", (sk + 1) // 2),  # ids repeat (SCD)
        "i_rec_start_date": np.full(len(idx), 10227, dtype=np.int64),
        "i_rec_end_date": np.where(sk % 2 == 0, 11322, 0),
        "i_rec_end_date#null": sk % 2 == 0,
        "i_item_desc": np.array(
            [f"Item description {int(v)} promising results"
             for v in sk], dtype=object),
        "i_current_price": price.astype(np.int64),
        "i_wholesale_cost": (price * 6 // 10).astype(np.int64),
        "i_brand_id": brand_id.astype(np.int32),
        "i_brand": np.array(
            [_BRAND_WORDS[int(c)] + f" #{int(b) % 10 + 1}"
             for c, b in zip(cat_id, brand_id)], dtype=object),
        "i_class_id": class_id.astype(np.int32),
        "i_class": np.array(
            [f"{c.lower()}class{int(k)}" for c, k
             in zip(cat, class_id)], dtype=object),
        "i_category_id": (cat_id + 1).astype(np.int32),
        "i_category": cat,
        "i_manufact_id": manufact_id.astype(np.int32),
        "i_manufact": np.array(
            [f"manufact#{int(v)}" for v in manufact_id], dtype=object),
        "i_size": _choice(h(4), SIZES_DOM),
        "i_formulation": _ids("", _uniform(h(5), 1, 10 ** 9), 20),
        "i_color": _choice(h(6), COLORS),
        "i_units": _choice(h(7), UNITS),
        "i_container": np.full(len(idx), "Unknown", dtype=object),
        "i_manager_id": _uniform(h(8), 1, 100).astype(np.int32),
        "i_product_name": np.array(
            [f"product{int(v)}" for v in sk], dtype=object),
    }


def _gen_customer(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "customer", k, idx)
    n_addr = table_rows("customer_address", sf)
    n_cd = table_rows("customer_demographics", sf)
    n_hd = table_rows("household_demographics", sf)
    first = ["James", "Mary", "John", "Patricia", "Robert", "Jennifer",
             "Michael", "Linda", "William", "Elizabeth", "David",
             "Barbara", "Richard", "Susan", "Joseph", "Jessica",
             "Thomas", "Sarah", "Charles", "Karen"]
    last = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
            "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez",
            "Lopez", "Gonzalez", "Wilson", "Anderson", "Thomas",
            "Taylor", "Moore", "Jackson", "Martin"]
    bday = _uniform(h(5), 1, 28)
    bmonth = _uniform(h(6), 1, 12)
    byear = _uniform(h(7), 1924, 1992)
    fsales = _uniform(h(8), SALES_DATE_LO - 1460, SALES_DATE_LO + 1000)
    return {
        "c_customer_sk": sk.astype(np.int32),
        "c_customer_id": _ids("AAAAAAAA", sk),
        "c_current_cdemo_sk": _null_out(
            _uniform(h(1), 1, n_cd), h(21), 4).astype(np.int32),
        "c_current_hdemo_sk": _null_out(
            _uniform(h(2), 1, n_hd), h(22), 4).astype(np.int32),
        "c_current_addr_sk": _uniform(h(3), 1, n_addr).astype(np.int32),
        "c_first_shipto_date_sk": (fsales + 30).astype(np.int32),
        "c_first_sales_date_sk": fsales.astype(np.int32),
        "c_salutation": _choice(h(9), ["Mr.", "Mrs.", "Ms.", "Dr.",
                                       "Miss", "Sir"]),
        "c_first_name": _choice(h(10), first),
        "c_last_name": _choice(h(11), last),
        "c_preferred_cust_flag": _choice(h(12), ["Y", "N"]),
        "c_birth_day": bday.astype(np.int32),
        "c_birth_month": bmonth.astype(np.int32),
        "c_birth_year": byear.astype(np.int32),
        "c_birth_country": _choice(
            h(13), ["UNITED STATES", "CANADA", "MEXICO", "GERMANY",
                    "FRANCE", "JAPAN", "CHINA", "BRAZIL", "INDIA",
                    "ITALY", "SPAIN", "NIGERIA", "KENYA", "EGYPT",
                    "PERU", "CHILE", "GREECE", "POLAND", "NORWAY",
                    "TOGO"]),
        "c_login": np.full(len(idx), "", dtype=object),
        "c_email_address": np.array(
            [f"c{int(v)}@example.com" for v in sk], dtype=object),
        "c_last_review_date_sk": _uniform(
            h(14), SALES_DATE_LO, SALES_DATE_HI).astype(np.int32),
    }


def _simple_named_dim(idx, seed, table, prefix, names, with_addr=True,
                      extra=None):
    sk = idx + 1
    h = lambda k: _h(seed, table, k, idx)
    out = {f"{prefix}{k}": v for k, v in (extra or {}).items()}
    return sk, h, out


def _gen_store(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "store", k, idx)
    out = {
        "s_store_sk": sk.astype(np.int32),
        "s_store_id": _ids("AAAAAAAA", (sk + 1) // 2),
        "s_rec_start_date": np.full(len(idx), 10227, dtype=np.int64),
        "s_rec_end_date": np.where(sk % 2 == 0, 11322, 0),
        "s_rec_end_date#null": sk % 2 == 0,
        "s_closed_date_sk": _null_out(
            _uniform(h(1), SALES_DATE_LO, SALES_DATE_HI), h(2), 70
        ).astype(np.int32),
        "s_store_name": _choice(h(3), ["ought", "able", "pri", "ese",
                                       "anti", "cally", "ation", "eing",
                                       "bar", "ought"]),
        "s_number_employees": _uniform(h(4), 200, 300).astype(np.int32),
        "s_floor_space": _uniform(h(5), 5000000, 10000000
                                  ).astype(np.int32),
        "s_hours": _choice(h(6), ["8AM-4PM", "8AM-8AM", "8AM-12AM"]),
        "s_manager": _choice(h(7), ["William Ward", "Scott Smith",
                                    "Edwin Adams", "David Thomas",
                                    "Charles Bartley", "Robert Thompson"]),
        "s_market_id": _uniform(h(8), 1, 10).astype(np.int32),
        "s_geography_class": np.full(len(idx), "Unknown", dtype=object),
        "s_market_desc": np.array(
            [f"Market description {int(v)}" for v in sk], dtype=object),
        "s_market_manager": _choice(
            h(9), ["Charles Bartley", "Mark Hightower", "Larry Mccray",
                   "Dean Morrison", "David Thomas"]),
        "s_division_id": np.ones(len(idx), dtype=np.int32),
        "s_division_name": np.full(len(idx), "Unknown", dtype=object),
        "s_company_id": np.ones(len(idx), dtype=np.int32),
        "s_company_name": np.full(len(idx), "Unknown", dtype=object),
    }
    out.update({k.replace("ca_", "s_"): v for k, v in
                _address_cols("ca_", idx, seed, "store").items()})
    out["s_tax_precentage"] = _uniform(h(10), 0, 11).astype(np.int64)
    return out


def _gen_warehouse(idx, sf, seed, total):
    sk = idx + 1
    out = {
        "w_warehouse_sk": sk.astype(np.int32),
        "w_warehouse_id": _ids("AAAAAAAA", sk),
        "w_warehouse_name": _choice(
            _h(seed, "warehouse", 1, idx),
            ["Conventional childr", "Important issues liv",
             "Doors canno", "Bad cards must make.", "Rooms cook "]),
        "w_warehouse_sq_ft": _uniform(
            _h(seed, "warehouse", 2, idx), 50000, 1000000
        ).astype(np.int32),
    }
    out.update({k.replace("ca_", "w_"): v for k, v in
                _address_cols("ca_", idx, seed, "warehouse").items()})
    return out


def _gen_call_center(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "call_center", k, idx)
    out = {
        "cc_call_center_sk": sk.astype(np.int32),
        "cc_call_center_id": _ids("AAAAAAAA", (sk + 1) // 2),
        "cc_rec_start_date": np.full(len(idx), 10227, dtype=np.int64),
        "cc_rec_end_date": np.where(sk % 2 == 0, 11322, 0),
        "cc_rec_end_date#null": sk % 2 == 0,
        "cc_closed_date_sk": np.full(len(idx), -1, dtype=np.int32),
        "cc_open_date_sk": _uniform(
            h(1), SALES_DATE_LO - 3000, SALES_DATE_LO).astype(np.int32),
        "cc_name": np.array([f"call center {int(v)}" for v in sk],
                            dtype=object),
        "cc_class": _choice(h(2), ["small", "medium", "large"]),
        "cc_employees": _uniform(h(3), 1, 7).astype(np.int32),
        "cc_sq_ft": _uniform(h(4), 1000, 40000000).astype(np.int32),
        "cc_hours": _choice(h(5), ["8AM-4PM", "8AM-8AM", "8AM-12AM"]),
        "cc_manager": _choice(h(6), ["Bob Belcher", "Felipe Perkins",
                                     "Mark Hightower", "Larry Mccray"]),
        "cc_mkt_id": _uniform(h(7), 1, 6).astype(np.int32),
        "cc_mkt_class": np.full(len(idx), "Unknown", dtype=object),
        "cc_mkt_desc": np.array(
            [f"Call center market {int(v)}" for v in sk], dtype=object),
        "cc_market_manager": _choice(
            h(8), ["Julius Tran", "Gary Colburn", "Evan Zimmerman"]),
        "cc_division": np.ones(len(idx), dtype=np.int32),
        "cc_division_name": np.full(len(idx), "pri", dtype=object),
        "cc_company": np.ones(len(idx), dtype=np.int32),
        "cc_company_name": np.full(len(idx), "Unknown", dtype=object),
    }
    out.update({k.replace("ca_", "cc_"): v for k, v in
                _address_cols("ca_", idx, seed, "call_center").items()})
    out["cc_tax_percentage"] = _uniform(h(9), 0, 11).astype(np.int64)
    return out


def _gen_web_site(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "web_site", k, idx)
    out = {
        "web_site_sk": sk.astype(np.int32),
        "web_site_id": _ids("AAAAAAAA", (sk + 1) // 2),
        "web_rec_start_date": np.full(len(idx), 10227, dtype=np.int64),
        "web_rec_end_date": np.where(sk % 2 == 0, 11322, 0),
        "web_rec_end_date#null": sk % 2 == 0,
        "web_name": np.array([f"site_{int(v) % 10}" for v in sk],
                             dtype=object),
        "web_open_date_sk": _uniform(
            h(1), SALES_DATE_LO - 3000, SALES_DATE_LO).astype(np.int32),
        "web_close_date_sk": np.full(len(idx), -1, dtype=np.int32),
        "web_class": np.full(len(idx), "Unknown", dtype=object),
        "web_manager": _choice(h(2), ["Raymond Jacobs", "Ronald Barnes",
                                      "Albert Leung", "Zachery Oneil"]),
        "web_mkt_id": _uniform(h(3), 1, 6).astype(np.int32),
        "web_mkt_class": np.full(len(idx), "Unknown", dtype=object),
        "web_mkt_desc": np.array(
            [f"Web market {int(v)}" for v in sk], dtype=object),
        "web_market_manager": _choice(
            h(4), ["Albert Leung", "Zachery Oneil", "Lawrence Fox"]),
        "web_company_id": np.ones(len(idx), dtype=np.int32),
        "web_company_name": _choice(h(5), ["pri", "able", "ought",
                                           "ation", "bar", "ese"]),
    }
    out.update({k.replace("ca_", "web_"): v for k, v in
                _address_cols("ca_", idx, seed, "web_site").items()})
    out["web_tax_percentage"] = _uniform(h(6), 0, 11).astype(np.int64)
    return out


def _gen_web_page(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "web_page", k, idx)
    return {
        "wp_web_page_sk": sk.astype(np.int32),
        "wp_web_page_id": _ids("AAAAAAAA", (sk + 1) // 2),
        "wp_rec_start_date": np.full(len(idx), 10227, dtype=np.int64),
        "wp_rec_end_date": np.where(sk % 2 == 0, 11322, 0),
        "wp_rec_end_date#null": sk % 2 == 0,
        "wp_creation_date_sk": _uniform(
            h(1), SALES_DATE_LO - 1000, SALES_DATE_LO).astype(np.int32),
        "wp_access_date_sk": _uniform(
            h(2), SALES_DATE_HI - 100, SALES_DATE_HI).astype(np.int32),
        "wp_autogen_flag": _choice(h(3), ["Y", "N"]),
        "wp_customer_sk": _null_out(
            _uniform(h(4), 1, max(table_rows("customer", sf), 1)),
            h(5), 70).astype(np.int32),
        "wp_url": np.full(len(idx), "http://www.foo.com", dtype=object),
        "wp_type": _choice(h(6), ["ad", "bio", "dynamic", "feedback",
                                  "general", "order", "protected",
                                  "welcome"]),
        "wp_char_count": _uniform(h(7), 100, 8000).astype(np.int32),
        "wp_link_count": _uniform(h(8), 2, 25).astype(np.int32),
        "wp_image_count": _uniform(h(9), 1, 7).astype(np.int32),
        "wp_max_ad_count": _uniform(h(10), 0, 4).astype(np.int32),
    }


def _gen_promotion(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "promotion", k, idx)
    yn = lambda k: _choice(h(k), ["N", "N", "N", "N", "N", "N", "N",
                                  "N", "N", "Y"])
    start = _uniform(h(1), SALES_DATE_LO, SALES_DATE_HI - 60)
    return {
        "p_promo_sk": sk.astype(np.int32),
        "p_promo_id": _ids("AAAAAAAA", sk),
        "p_start_date_sk": start.astype(np.int32),
        "p_end_date_sk": (start + _uniform(h(2), 10, 60)
                          ).astype(np.int32),
        "p_item_sk": _uniform(h(3), 1, max(table_rows("item", sf), 1)
                              ).astype(np.int32),
        "p_cost": np.full(len(idx), 100000, dtype=np.int64),
        "p_response_target": np.ones(len(idx), dtype=np.int32),
        "p_promo_name": _choice(h(4), ["anti", "ought", "able", "pri",
                                       "ese", "cally", "ation", "eing",
                                       "bar"]),
        "p_channel_dmail": yn(5), "p_channel_email": yn(6),
        "p_channel_catalog": yn(7), "p_channel_tv": yn(8),
        "p_channel_radio": yn(9), "p_channel_press": yn(10),
        "p_channel_event": yn(11), "p_channel_demo": yn(12),
        "p_channel_details": np.array(
            [f"promo details {int(v)}" for v in sk], dtype=object),
        "p_purpose": np.full(len(idx), "Unknown", dtype=object),
        "p_discount_active": _choice(h(13), ["N", "Y"]),
    }


def _gen_catalog_page(idx, sf, seed, total):
    sk = idx + 1
    h = lambda k: _h(seed, "catalog_page", k, idx)
    start = _uniform(h(1), SALES_DATE_LO - 1000, SALES_DATE_HI - 100)
    return {
        "cp_catalog_page_sk": sk.astype(np.int32),
        "cp_catalog_page_id": _ids("AAAAAAAA", sk),
        "cp_start_date_sk": start.astype(np.int32),
        "cp_end_date_sk": (start + 90).astype(np.int32),
        "cp_department": np.full(len(idx), "DEPARTMENT", dtype=object),
        "cp_catalog_number": (idx // 108 + 1).astype(np.int32),
        "cp_catalog_page_number": (idx % 108 + 1).astype(np.int32),
        "cp_description": np.array(
            [f"Catalog page description {int(v)}" for v in sk],
            dtype=object),
        "cp_type": _choice(h(2), ["bi-annual", "quarterly", "monthly"]),
    }


def _gen_inventory(idx, sf, seed, total):
    n_item = table_rows("item", sf)
    n_wh = table_rows("warehouse", sf)
    # weekly snapshots over the sales window
    i = idx
    item = i % n_item + 1
    i = i // n_item
    wh = i % n_wh + 1
    week = i // n_wh
    date_sk = SALES_DATE_LO + (week % 261) * 7
    h = _h(seed, "inventory", 1, idx)
    qty = _uniform(h, 0, 1000)
    qty = _null_out(qty, _h(seed, "inventory", 2, idx), 5)
    return {
        "inv_date_sk": date_sk.astype(np.int32),
        "inv_item_sk": item.astype(np.int32),
        "inv_warehouse_sk": wh.astype(np.int32),
        "inv_quantity_on_hand": qty.astype(np.int32),
    }


# ---- fact channels --------------------------------------------------------

# tickets repeat a [4, 8, 12, 16]-line pattern (40 rows / 4 tickets):
# group-size variety with O(1) row -> (ticket, line) indexing, so any
# chunk generates independently
_TICKET_PATTERN = np.array([4, 8, 12, 16])
_PATTERN_ROWS = int(_TICKET_PATTERN.sum())
_PATTERN_STARTS = np.concatenate([[0], np.cumsum(_TICKET_PATTERN)[:-1]])


def _ticket_of(idx):
    block = idx // _PATTERN_ROWS
    off = idx % _PATTERN_ROWS
    within = np.searchsorted(_PATTERN_STARTS, off, side="right") - 1
    ticket = block * 4 + within
    line = off - _PATTERN_STARTS[within]
    return ticket + 1, line + 1


def _sales_money(h, qty):
    """Consistent money ladder (cents): wholesale -> list -> sales ->
    ext_* -> net_*; discounts/coupons derived from hash streams."""
    wholesale = _uniform(h(20), 100, 10000)
    list_p = wholesale * _uniform(h(21), 110, 240) // 100
    disc_pct = _uniform(h(22), 0, 90)
    sales_p = list_p * (100 - disc_pct) // 100
    coupon = np.where(_h_pct(h(23), 15), sales_p * qty // 10, 0)
    ext_disc = (list_p - sales_p) * qty
    ext_sales = sales_p * qty
    ext_whole = wholesale * qty
    ext_list = list_p * qty
    tax_pct = _uniform(h(24), 0, 9)
    ext_tax = (ext_sales - coupon) * tax_pct // 100
    net_paid = ext_sales - coupon
    ship = ext_whole * _uniform(h(25), 0, 20) // 100
    return dict(wholesale=wholesale, list=list_p, sales=sales_p,
                coupon=coupon, ext_disc=ext_disc, ext_sales=ext_sales,
                ext_whole=ext_whole, ext_list=ext_list, ext_tax=ext_tax,
                net_paid=net_paid, ship=ship)


def _h_pct(h, pct):
    return (h % np.uint64(100)) < np.uint64(pct)


def _fact_common(idx, sf, seed, table):
    h = lambda k: _h(seed, table, k, idx)
    ticket, line = _ticket_of(idx)
    # per-ticket attributes come from ticket-indexed hash streams so all
    # lines of a ticket agree (date, customer, store)
    th = lambda k: _h(seed, table + "#t", k, ticket)
    date_sk = _uniform(th(1), SALES_DATE_LO, SALES_DATE_HI)
    time_sk = _uniform(th(2), 0, 86399)
    cust = _uniform(th(3), 1, max(table_rows("customer", sf), 1))
    # items are DISTINCT within a ticket (dsdgen invariant backing the
    # (item, ticket) primary key): per-ticket random base + line offset
    n_item = max(table_rows("item", sf), 1)
    item = (_uniform(th(12), 0, n_item - 1) + line - 1) % n_item + 1
    qty = _uniform(h(5), 1, 100)
    return h, th, ticket, line, date_sk, time_sk, cust, item, qty


def _gen_store_sales(idx, sf, seed, total):
    h, th, ticket, line, date_sk, time_sk, cust, item, qty = \
        _fact_common(idx, sf, seed, "store_sales")
    m = _sales_money(h, qty)
    net_profit = m["net_paid"] - m["ext_whole"]
    return {
        "ss_sold_date_sk": _null_out(date_sk, h(40), 4).astype(np.int32),
        "ss_sold_time_sk": _null_out(time_sk, h(41), 4).astype(np.int32),
        "ss_item_sk": item.astype(np.int32),
        "ss_customer_sk": _null_out(cust, h(42), 4).astype(np.int32),
        "ss_cdemo_sk": _null_out(_uniform(
            th(6), 1, table_rows("customer_demographics", sf)),
            h(43), 4).astype(np.int32),
        "ss_hdemo_sk": _null_out(_uniform(
            th(7), 1, table_rows("household_demographics", sf)),
            h(44), 4).astype(np.int32),
        "ss_addr_sk": _null_out(_uniform(
            th(8), 1, max(table_rows("customer_address", sf), 1)),
            h(45), 4).astype(np.int32),
        "ss_store_sk": _null_out(_uniform(
            th(9), 1, max(table_rows("store", sf), 1)),
            h(46), 4).astype(np.int32),
        "ss_promo_sk": _null_out(_uniform(
            h(10), 1, max(table_rows("promotion", sf), 1)),
            h(47), 4).astype(np.int32),
        "ss_ticket_number": ticket.astype(np.int64),
        "ss_quantity": qty.astype(np.int32),
        "ss_wholesale_cost": m["wholesale"].astype(np.int64),
        "ss_list_price": m["list"].astype(np.int64),
        "ss_sales_price": m["sales"].astype(np.int64),
        "ss_ext_discount_amt": m["ext_disc"].astype(np.int64),
        "ss_ext_sales_price": m["ext_sales"].astype(np.int64),
        "ss_ext_wholesale_cost": m["ext_whole"].astype(np.int64),
        "ss_ext_list_price": m["ext_list"].astype(np.int64),
        "ss_ext_tax": m["ext_tax"].astype(np.int64),
        "ss_coupon_amt": m["coupon"].astype(np.int64),
        "ss_net_paid": m["net_paid"].astype(np.int64),
        "ss_net_paid_inc_tax": (m["net_paid"] + m["ext_tax"]
                                ).astype(np.int64),
        "ss_net_profit": net_profit.astype(np.int64),
    }


def _returns_base(idx, sf, seed, sales_table, ratio):
    """Returns row i corresponds to sales row i*ratio (+jitter): gives the
    (item, ticket) FK back-reference the maintenance/delete flows and
    return-join queries need."""
    sales_total = table_rows(sales_table, sf)
    jitter = (_h(seed, sales_table + "#r", 1, idx)
              % np.uint64(ratio)).astype(np.int64)
    return (idx * ratio + jitter) % max(sales_total, 1)


def _gen_store_returns(idx, sf, seed, total):
    sales_idx = _returns_base(idx, sf, seed, "store_sales", 10)
    s = _gen_store_sales(sales_idx, sf, seed, None)
    h = lambda k: _h(seed, "store_returns", k, idx)
    rdate = np.where(
        s["ss_sold_date_sk"] > 0,
        s["ss_sold_date_sk"] + _uniform(h(1), 1, 90),
        _uniform(h(2), SALES_DATE_LO, SALES_DATE_HI)).astype(np.int64)
    rqty = np.minimum(_uniform(h(3), 1, 100), s["ss_quantity"])
    amt = s["ss_sales_price"].astype(np.int64) * rqty
    tax = amt * _uniform(h(4), 0, 9) // 100
    fee = _uniform(h(5), 50, 10000)
    shipcost = s["ss_wholesale_cost"].astype(np.int64) * rqty // 2
    refunded = amt * _uniform(h(6), 0, 100) // 100
    reversed_ = amt - refunded
    return {
        "sr_returned_date_sk": rdate.astype(np.int32),
        "sr_return_time_sk": _uniform(h(7), 28800, 61200
                                      ).astype(np.int32),
        "sr_item_sk": s["ss_item_sk"],
        "sr_customer_sk": _null_out(
            s["ss_customer_sk"].astype(np.int64), h(8), 4
        ).astype(np.int32),
        "sr_cdemo_sk": s["ss_cdemo_sk"],
        "sr_hdemo_sk": s["ss_hdemo_sk"],
        "sr_addr_sk": s["ss_addr_sk"],
        "sr_store_sk": s["ss_store_sk"],
        "sr_reason_sk": _uniform(h(9), 1, 35).astype(np.int32),
        "sr_ticket_number": s["ss_ticket_number"],
        "sr_return_quantity": rqty.astype(np.int32),
        "sr_return_amt": amt.astype(np.int64),
        "sr_return_tax": tax.astype(np.int64),
        "sr_return_amt_inc_tax": (amt + tax).astype(np.int64),
        "sr_fee": fee.astype(np.int64),
        "sr_return_ship_cost": shipcost.astype(np.int64),
        "sr_refunded_cash": refunded.astype(np.int64),
        "sr_reversed_charge": reversed_.astype(np.int64),
        "sr_store_credit": np.zeros(len(idx), dtype=np.int64),
        "sr_net_loss": (fee + shipcost + tax).astype(np.int64),
    }


def _gen_catalog_sales(idx, sf, seed, total):
    h, th, order, line, date_sk, time_sk, cust, item, qty = \
        _fact_common(idx, sf, seed, "catalog_sales")
    m = _sales_money(h, qty)
    ship_date = date_sk + _uniform(h(30), 2, 120)
    net_profit = m["net_paid"] - m["ext_whole"]
    return {
        "cs_sold_date_sk": _null_out(date_sk, h(40), 4).astype(np.int32),
        "cs_sold_time_sk": time_sk.astype(np.int32),
        "cs_ship_date_sk": _null_out(ship_date, h(41), 4
                                     ).astype(np.int32),
        "cs_bill_customer_sk": cust.astype(np.int32),
        "cs_bill_cdemo_sk": _uniform(
            th(6), 1, table_rows("customer_demographics", sf)
        ).astype(np.int32),
        "cs_bill_hdemo_sk": _uniform(
            th(7), 1, table_rows("household_demographics", sf)
        ).astype(np.int32),
        "cs_bill_addr_sk": _uniform(
            th(8), 1, max(table_rows("customer_address", sf), 1)
        ).astype(np.int32),
        "cs_ship_customer_sk": _null_out(
            _uniform(th(9), 1, max(table_rows("customer", sf), 1)),
            h(42), 4).astype(np.int32),
        "cs_ship_cdemo_sk": _uniform(
            th(10), 1, table_rows("customer_demographics", sf)
        ).astype(np.int32),
        "cs_ship_hdemo_sk": _uniform(
            th(11), 1, table_rows("household_demographics", sf)
        ).astype(np.int32),
        "cs_ship_addr_sk": _uniform(
            th(12), 1, max(table_rows("customer_address", sf), 1)
        ).astype(np.int32),
        "cs_call_center_sk": _null_out(_uniform(
            th(13), 1, max(table_rows("call_center", sf), 1)),
            h(43), 4).astype(np.int32),
        "cs_catalog_page_sk": _uniform(
            h(14), 1, max(table_rows("catalog_page", sf), 1)
        ).astype(np.int32),
        "cs_ship_mode_sk": _uniform(h(15), 1, 20).astype(np.int32),
        "cs_warehouse_sk": _null_out(_uniform(
            h(16), 1, max(table_rows("warehouse", sf), 1)),
            h(44), 4).astype(np.int32),
        "cs_item_sk": item.astype(np.int32),
        "cs_promo_sk": _null_out(_uniform(
            h(17), 1, max(table_rows("promotion", sf), 1)),
            h(45), 4).astype(np.int32),
        "cs_order_number": order.astype(np.int64),
        "cs_quantity": qty.astype(np.int32),
        "cs_wholesale_cost": m["wholesale"].astype(np.int64),
        "cs_list_price": m["list"].astype(np.int64),
        "cs_sales_price": m["sales"].astype(np.int64),
        "cs_ext_discount_amt": m["ext_disc"].astype(np.int64),
        "cs_ext_sales_price": m["ext_sales"].astype(np.int64),
        "cs_ext_wholesale_cost": m["ext_whole"].astype(np.int64),
        "cs_ext_list_price": m["ext_list"].astype(np.int64),
        "cs_ext_tax": m["ext_tax"].astype(np.int64),
        "cs_coupon_amt": m["coupon"].astype(np.int64),
        "cs_ext_ship_cost": m["ship"].astype(np.int64),
        "cs_net_paid": m["net_paid"].astype(np.int64),
        "cs_net_paid_inc_tax": (m["net_paid"] + m["ext_tax"]
                                ).astype(np.int64),
        "cs_net_paid_inc_ship": (m["net_paid"] + m["ship"]
                                 ).astype(np.int64),
        "cs_net_paid_inc_ship_tax": (
            m["net_paid"] + m["ship"] + m["ext_tax"]).astype(np.int64),
        "cs_net_profit": net_profit.astype(np.int64),
    }


def _gen_catalog_returns(idx, sf, seed, total):
    sales_idx = _returns_base(idx, sf, seed, "catalog_sales", 10)
    s = _gen_catalog_sales(sales_idx, sf, seed, None)
    h = lambda k: _h(seed, "catalog_returns", k, idx)
    rdate = np.where(
        s["cs_sold_date_sk"] > 0,
        s["cs_sold_date_sk"].astype(np.int64) + _uniform(h(1), 1, 90),
        _uniform(h(2), SALES_DATE_LO, SALES_DATE_HI))
    rqty = np.minimum(_uniform(h(3), 1, 100), s["cs_quantity"])
    amt = s["cs_sales_price"].astype(np.int64) * rqty
    tax = amt * _uniform(h(4), 0, 9) // 100
    fee = _uniform(h(5), 50, 10000)
    shipcost = s["cs_wholesale_cost"].astype(np.int64) * rqty // 2
    refunded = amt * _uniform(h(6), 0, 100) // 100
    return {
        "cr_returned_date_sk": rdate.astype(np.int32),
        "cr_returned_time_sk": _uniform(h(7), 0, 86399).astype(np.int32),
        "cr_item_sk": s["cs_item_sk"],
        "cr_refunded_customer_sk": s["cs_bill_customer_sk"],
        "cr_refunded_cdemo_sk": s["cs_bill_cdemo_sk"],
        "cr_refunded_hdemo_sk": s["cs_bill_hdemo_sk"],
        "cr_refunded_addr_sk": s["cs_bill_addr_sk"],
        "cr_returning_customer_sk": _null_out(
            s["cs_ship_customer_sk"].astype(np.int64), h(8), 4
        ).astype(np.int32),
        "cr_returning_cdemo_sk": s["cs_ship_cdemo_sk"],
        "cr_returning_hdemo_sk": s["cs_ship_hdemo_sk"],
        "cr_returning_addr_sk": s["cs_ship_addr_sk"],
        "cr_call_center_sk": s["cs_call_center_sk"],
        "cr_catalog_page_sk": s["cs_catalog_page_sk"],
        "cr_ship_mode_sk": s["cs_ship_mode_sk"],
        "cr_warehouse_sk": s["cs_warehouse_sk"],
        "cr_reason_sk": _uniform(h(9), 1, 35).astype(np.int32),
        "cr_order_number": s["cs_order_number"],
        "cr_return_quantity": rqty.astype(np.int32),
        "cr_return_amount": amt.astype(np.int64),
        "cr_return_tax": tax.astype(np.int64),
        "cr_return_amt_inc_tax": (amt + tax).astype(np.int64),
        "cr_fee": fee.astype(np.int64),
        "cr_return_ship_cost": shipcost.astype(np.int64),
        "cr_refunded_cash": refunded.astype(np.int64),
        "cr_reversed_charge": (amt - refunded).astype(np.int64),
        "cr_store_credit": np.zeros(len(idx), dtype=np.int64),
        "cr_net_loss": (fee + shipcost + tax).astype(np.int64),
    }


def _gen_web_sales(idx, sf, seed, total):
    h, th, order, line, date_sk, time_sk, cust, item, qty = \
        _fact_common(idx, sf, seed, "web_sales")
    m = _sales_money(h, qty)
    ship_date = date_sk + _uniform(h(30), 2, 120)
    return {
        "ws_sold_date_sk": _null_out(date_sk, h(40), 4).astype(np.int32),
        "ws_sold_time_sk": time_sk.astype(np.int32),
        "ws_ship_date_sk": ship_date.astype(np.int32),
        "ws_item_sk": item.astype(np.int32),
        "ws_bill_customer_sk": cust.astype(np.int32),
        "ws_bill_cdemo_sk": _uniform(
            th(6), 1, table_rows("customer_demographics", sf)
        ).astype(np.int32),
        "ws_bill_hdemo_sk": _uniform(
            th(7), 1, table_rows("household_demographics", sf)
        ).astype(np.int32),
        "ws_bill_addr_sk": _uniform(
            th(8), 1, max(table_rows("customer_address", sf), 1)
        ).astype(np.int32),
        "ws_ship_customer_sk": _uniform(
            th(9), 1, max(table_rows("customer", sf), 1)
        ).astype(np.int32),
        "ws_ship_cdemo_sk": _uniform(
            th(10), 1, table_rows("customer_demographics", sf)
        ).astype(np.int32),
        "ws_ship_hdemo_sk": _uniform(
            th(11), 1, table_rows("household_demographics", sf)
        ).astype(np.int32),
        "ws_ship_addr_sk": _uniform(
            th(12), 1, max(table_rows("customer_address", sf), 1)
        ).astype(np.int32),
        "ws_web_page_sk": _uniform(
            h(13), 1, max(table_rows("web_page", sf), 1)
        ).astype(np.int32),
        "ws_web_site_sk": _uniform(
            th(14), 1, max(table_rows("web_site", sf), 1)
        ).astype(np.int32),
        "ws_ship_mode_sk": _uniform(h(15), 1, 20).astype(np.int32),
        "ws_warehouse_sk": _uniform(
            h(16), 1, max(table_rows("warehouse", sf), 1)
        ).astype(np.int32),
        "ws_promo_sk": _uniform(
            h(17), 1, max(table_rows("promotion", sf), 1)
        ).astype(np.int32),
        "ws_order_number": order.astype(np.int64),
        "ws_quantity": qty.astype(np.int32),
        "ws_wholesale_cost": m["wholesale"].astype(np.int64),
        "ws_list_price": m["list"].astype(np.int64),
        "ws_sales_price": m["sales"].astype(np.int64),
        "ws_ext_discount_amt": m["ext_disc"].astype(np.int64),
        "ws_ext_sales_price": m["ext_sales"].astype(np.int64),
        "ws_ext_wholesale_cost": m["ext_whole"].astype(np.int64),
        "ws_ext_list_price": m["ext_list"].astype(np.int64),
        "ws_ext_tax": m["ext_tax"].astype(np.int64),
        "ws_coupon_amt": m["coupon"].astype(np.int64),
        "ws_ext_ship_cost": m["ship"].astype(np.int64),
        "ws_net_paid": m["net_paid"].astype(np.int64),
        "ws_net_paid_inc_tax": (m["net_paid"] + m["ext_tax"]
                                ).astype(np.int64),
        "ws_net_paid_inc_ship": (m["net_paid"] + m["ship"]
                                 ).astype(np.int64),
        "ws_net_paid_inc_ship_tax": (
            m["net_paid"] + m["ship"] + m["ext_tax"]).astype(np.int64),
        "ws_net_profit": (m["net_paid"] - m["ext_whole"]
                          ).astype(np.int64),
    }


def _gen_web_returns(idx, sf, seed, total):
    sales_idx = _returns_base(idx, sf, seed, "web_sales", 10)
    s = _gen_web_sales(sales_idx, sf, seed, None)
    h = lambda k: _h(seed, "web_returns", k, idx)
    rdate = np.where(
        s["ws_sold_date_sk"] > 0,
        s["ws_sold_date_sk"].astype(np.int64) + _uniform(h(1), 1, 90),
        _uniform(h(2), SALES_DATE_LO, SALES_DATE_HI))
    rqty = np.minimum(_uniform(h(3), 1, 100), s["ws_quantity"])
    amt = s["ws_sales_price"].astype(np.int64) * rqty
    tax = amt * _uniform(h(4), 0, 9) // 100
    fee = _uniform(h(5), 50, 10000)
    shipcost = s["ws_wholesale_cost"].astype(np.int64) * rqty // 2
    refunded = amt * _uniform(h(6), 0, 100) // 100
    return {
        "wr_returned_date_sk": rdate.astype(np.int32),
        "wr_returned_time_sk": _uniform(h(7), 0, 86399).astype(np.int32),
        "wr_item_sk": s["ws_item_sk"],
        "wr_refunded_customer_sk": _null_out(
            s["ws_bill_customer_sk"].astype(np.int64), h(8), 4
        ).astype(np.int32),
        "wr_refunded_cdemo_sk": s["ws_bill_cdemo_sk"],
        "wr_refunded_hdemo_sk": s["ws_bill_hdemo_sk"],
        "wr_refunded_addr_sk": s["ws_bill_addr_sk"],
        "wr_returning_customer_sk": s["ws_ship_customer_sk"],
        "wr_returning_cdemo_sk": s["ws_ship_cdemo_sk"],
        "wr_returning_hdemo_sk": s["ws_ship_hdemo_sk"],
        "wr_returning_addr_sk": s["ws_ship_addr_sk"],
        "wr_web_page_sk": s["ws_web_page_sk"],
        "wr_reason_sk": _uniform(h(9), 1, 35).astype(np.int32),
        "wr_order_number": s["ws_order_number"],
        "wr_return_quantity": rqty.astype(np.int32),
        "wr_return_amt": amt.astype(np.int64),
        "wr_return_tax": tax.astype(np.int64),
        "wr_return_amt_inc_tax": (amt + tax).astype(np.int64),
        "wr_fee": fee.astype(np.int64),
        "wr_return_ship_cost": shipcost.astype(np.int64),
        "wr_refunded_cash": refunded.astype(np.int64),
        "wr_reversed_charge": (amt - refunded).astype(np.int64),
        "wr_account_credit": np.zeros(len(idx), dtype=np.int64),
        "wr_net_loss": (fee + shipcost + tax).astype(np.int64),
    }


_GENERATORS = {
    "date_dim": _gen_date_dim,
    "time_dim": _gen_time_dim,
    "customer_address": _gen_customer_address,
    "customer_demographics": _gen_customer_demographics,
    "household_demographics": _gen_household_demographics,
    "income_band": _gen_income_band,
    "reason": _gen_reason,
    "ship_mode": _gen_ship_mode,
    "item": _gen_item,
    "customer": _gen_customer,
    "store": _gen_store,
    "warehouse": _gen_warehouse,
    "call_center": _gen_call_center,
    "web_site": _gen_web_site,
    "web_page": _gen_web_page,
    "promotion": _gen_promotion,
    "catalog_page": _gen_catalog_page,
    "inventory": _gen_inventory,
    "store_sales": _gen_store_sales,
    "store_returns": _gen_store_returns,
    "catalog_sales": _gen_catalog_sales,
    "catalog_returns": _gen_catalog_returns,
    "web_sales": _gen_web_sales,
    "web_returns": _gen_web_returns,
}
